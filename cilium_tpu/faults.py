# policyd: hot
"""Deterministic fault injection for the verdict path (policyd-failsafe).

The pipeline is deep and stateful — bounded in-flight FIFO, CT epochs,
pinned staging free-lists, a verdict mesh — and none of that state is
exercised by tests unless something actually fails mid-batch. This
module is the failure source: a process-wide registry of NAMED
injection sites wired into the hot path (h2d staging, XLA dispatch,
completion pull, CT-epoch advance, kvstore pump, TPU attach, the
admission gate's queue-full probe, the watchdog's stall sweep, the
state-dir CT-snapshot write) that raises classified faults on demand,
deterministically.

Cost model (the hub's ``active`` pattern, observe/tracer.py): the hot
path reads ONE attribute per site visit — ``hub.active`` — and skips
the call entirely when injection is off. The OFF path must stay
byte-identical to pre-faults behavior; tests/test_failsafe.py pins the
compiled program set and verdict outputs with the hub disabled.

Determinism: every site owns its own ``random.Random`` seeded with
``crc32(site) ^ seed`` — NOT ``hash(site)``, which is salted per
process — so a chaos round at a fixed seed injects the same faults at
the same sites in the same order, independent of dict order, thread
interleaving, or which other sites were probed in between.

Taxonomy (mirrors how the pipeline classifies REAL errors):

- ``transient``  — worth a bounded retry (a flaky interconnect, a
  kvstore partition, a wedged attach that recovers on reconnect).
- ``poisoned``   — retry cannot help (device state corrupted, program
  miscompiled); the batch is quarantined and the circuit breaker
  counts toward a degradation-ladder descent.
- ``error``      — NOT a fault: programmer/control errors (TypeError,
  KeyError, assertion) classified out so self-healing never swallows
  a bug; callers re-raise these raw.

Stdlib-only by design: the registry must be importable (and armable)
before jax, from the bench watchdog, and inside the proxy."""

from __future__ import annotations

import random
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

# Stable site names (wired into the hot path; bench --chaos and the
# failsafe tests key on these)
SITE_H2D = "h2d"            # staging write + host→device upload
SITE_DISPATCH = "dispatch"  # async XLA enqueue of the fused program
SITE_COMPLETE = "complete"  # host pull of un-pulled device results
SITE_CT_EPOCH = "ct_epoch"  # conntrack basis advance in rebuild()
SITE_KVSTORE = "kvstore"    # SharedStore.pump event drain
SITE_ATTACH = "attach"      # backend handshake / first compile
SITE_QUEUE_FULL = "queue_full"  # admission gate: forces over-budget
SITE_STALL = "stall"        # watchdog sweep: synthesizes a stuck batch
SITE_STATE_WRITE = "state_write"  # state-dir persistence (CT snapshot)

SITES: Tuple[str, ...] = (
    SITE_H2D, SITE_DISPATCH, SITE_COMPLETE,
    SITE_CT_EPOCH, SITE_KVSTORE, SITE_ATTACH,
    SITE_QUEUE_FULL, SITE_STALL, SITE_STATE_WRITE,
)

KIND_TRANSIENT = "transient"
KIND_POISONED = "poisoned"
KIND_ERROR = "error"  # classification-only: never injected


class FaultError(RuntimeError):
    """Base of injected faults. Carries ``site``/``kind`` so the
    pipeline's classification is exact (no string matching)."""

    kind = KIND_TRANSIENT

    def __init__(self, site: str, msg: Optional[str] = None) -> None:
        super().__init__(msg or f"injected {self.kind} fault at {site!r}")
        self.site = site


class TransientFault(FaultError):
    kind = KIND_TRANSIENT


class PoisonedFault(FaultError):
    kind = KIND_POISONED


# Native exception classes treated as transient: environmental errors
# a reconnect/retry can plausibly clear (the axon tunnel surfaces
# wedges as timeouts and socket errors).
_TRANSIENT_NATIVE = (TimeoutError, ConnectionError, InterruptedError, OSError)
# Programmer/control errors: never "faults" — self-healing must not
# swallow a bug or a shutdown signal.
_ERROR_NATIVE = (
    TypeError, ValueError, KeyError, IndexError, AttributeError,
    AssertionError, NameError, NotImplementedError, StopIteration,
    KeyboardInterrupt, SystemExit, GeneratorExit, MemoryError,
)


def classify(exc: BaseException) -> str:
    """→ ``transient`` | ``poisoned`` | ``error``.

    Injected faults carry their kind; native environmental errors are
    transient; programmer/control errors are surfaced raw (``error``);
    everything else (XLA runtime errors, unknown RuntimeErrors) is
    poisoned — retrying an unknown device failure risks repeating it
    against corrupted state, so the safe default is quarantine."""
    if isinstance(exc, FaultError):
        return exc.kind
    if isinstance(exc, _ERROR_NATIVE):
        return KIND_ERROR
    if isinstance(exc, _TRANSIENT_NATIVE):
        return KIND_TRANSIENT
    return KIND_POISONED


class _Rule:
    """One explicit injection rule: skip ``after`` visits, then fire
    ``times`` faults of ``kind``."""

    __slots__ = ("kind", "times", "after")

    def __init__(self, kind: str, times: int, after: int) -> None:
        self.kind = kind
        self.times = int(times)
        self.after = int(after)


class FaultHub:
    """Process-wide injection registry.

    Disabled cost is one ``hub.active`` attribute read per site visit.
    Enabled, each visit takes the hub lock, consumes explicit rules
    (``fail()``) first, then rolls the site's seeded RNG against the
    armed probability (``arm()``). Counts per (site, kind) accumulate
    in ``injected`` and in ``pipeline_faults_total{site,kind}``."""

    def __init__(self) -> None:
        self.active = False
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._seed = 0
        self._rate = 0.0
        self._armed_sites: frozenset = frozenset()
        self._poison_every = 0  # every Nth probabilistic fault poisons
        self._prob_fired = 0
        self.injected: Dict[Tuple[str, str], int] = {}

    # -- configuration -------------------------------------------------
    # `active` writes take the hub lock so every mutation is ordered
    # with the guarded state; hot-path READS stay bare by design (a
    # GIL-atomic bool read — the whole point of the hub pattern)
    def enable(self) -> None:
        with self._lock:
            self.active = True

    def disable(self) -> None:
        """Stop injecting. Rules/arming are kept (re-enable resumes);
        use reset() to drop them."""
        with self._lock:
            self.active = False

    def reset(self) -> None:
        with self._lock:
            self.active = False
            self._rules.clear()
            self._rngs.clear()
            self._rate = 0.0
            self._armed_sites = frozenset()
            self._poison_every = 0
            self._prob_fired = 0
            self.injected = {}

    def fail(
        self, site: str, kind: str = KIND_TRANSIENT,
        times: int = 1, after: int = 0,
    ) -> None:
        """Queue an explicit fault: the next visit to ``site`` (after
        skipping ``after`` visits) raises ``times`` faults of ``kind``.
        Enables the hub — an explicit rule always means "inject"."""
        if kind not in (KIND_TRANSIENT, KIND_POISONED):
            raise ValueError(f"kind must be transient|poisoned, got {kind!r}")
        with self._lock:
            self._rules.setdefault(site, []).append(_Rule(kind, times, after))
            self.active = True

    def arm(
        self, seed: int, rate: float,
        sites: Optional[Iterable[str]] = None,
        poison_every: int = 0,
    ) -> None:
        """Probabilistic chaos mode: each visit to an armed site fires
        a fault with probability ``rate``, from a per-site RNG seeded
        ``crc32(site) ^ seed``. ``poison_every=N`` makes every Nth
        probabilistic fault poisoned (0 = all transient)."""
        with self._lock:
            self._seed = int(seed)
            self._rate = float(rate)
            self._armed_sites = frozenset(sites if sites is not None else SITES)
            self._poison_every = int(poison_every)
            self._prob_fired = 0
            self._rngs = {
                s: random.Random(zlib.crc32(s.encode("utf-8")) ^ int(seed))
                for s in self._armed_sites
            }
            self.active = True

    # -- hot-path probe ------------------------------------------------
    def check(self, site: str) -> None:
        """Visit ``site``: raise the due fault, if any. Callers gate on
        ``hub.active`` so the disabled path never reaches here."""
        kind = None
        with self._lock:
            rules = self._rules.get(site)
            if rules:
                r = rules[0]
                if r.after > 0:
                    r.after -= 1
                else:
                    kind = r.kind
                    r.times -= 1
                    if r.times <= 0:
                        rules.pop(0)
            if kind is None and site in self._armed_sites and self._rate > 0.0:
                if self._rngs[site].random() < self._rate:
                    self._prob_fired += 1
                    kind = (
                        KIND_POISONED
                        if self._poison_every
                        and self._prob_fired % self._poison_every == 0
                        else KIND_TRANSIENT
                    )
            if kind is not None:
                k = (site, kind)
                self.injected[k] = self.injected.get(k, 0) + 1
        if kind is None:
            return
        # metric outside the hub lock; imported lazily so the registry
        # stays importable before the package (bench watchdog, proxy)
        from . import metrics as _metrics

        _metrics.pipeline_faults_total.inc({"site": site, "kind": kind})
        raise (PoisonedFault if kind == KIND_POISONED else TransientFault)(site)

    def snapshot(self) -> Dict:
        """Introspection for /healthz, traces, and bench --chaos."""
        with self._lock:
            return {
                "active": self.active,
                "injected": {
                    f"{s}:{k}": n for (s, k), n in sorted(self.injected.items())
                },
                "pending_rules": {
                    s: len(rs) for s, rs in self._rules.items() if rs
                },
                "armed_sites": sorted(self._armed_sites),
                "rate": self._rate,
                "seed": self._seed,
            }


# The process-wide hub (the tracer-singleton pattern): sites import
# this module once and read ``hub.active`` per visit.
hub = FaultHub()
