"""policyd-fed: cluster federation — one identity plane and policy
epoch across N daemon nodes.

The subsystem that makes N daemon processes behave as ONE policy
plane (PAPER.md layer 5, pkg/allocator + pkg/clustermesh roles):

- :mod:`identity_plane` — cluster-wide identity allocation over the
  kvstore with a reserve/confirm CAS protocol, per-node leases with
  heartbeat renewal, and a local read-through cache. Two nodes
  labeling the same label set always converge to the same small
  integer; a partition can stall an allocation but never fork one.
- :mod:`epochs` — node registry + policy-epoch exchange: every node
  publishes its descriptor and current ``policy_epoch`` (the EpochSwap
  counter) under a lease, watches peers, and exposes the
  ``wait_cluster_epoch`` convergence barrier.
- :mod:`member` — one daemon's membership: composes the allocator and
  the exchange, bridges the identity registry, and drives heartbeats
  from the controller pump.
- :mod:`bootstrap` — multi-process mesh bring-up:
  ``jax.distributed.initialize`` keyed off ``mesh_process_index``
  feeding ``PlacementConfig.process_index`` so MeshPlan spans hosts.

See README.md in this package for the lease/CAS protocol and its
failure modes.
"""

from .bootstrap import mesh_bootstrap, placement_config
from .epochs import EpochExchange
from .identity_plane import ClusterIdentityAllocator, FederationError
from .member import FederationMember

__all__ = [
    "ClusterIdentityAllocator",
    "EpochExchange",
    "FederationError",
    "FederationMember",
    "mesh_bootstrap",
    "placement_config",
]
