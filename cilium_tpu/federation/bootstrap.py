"""Multi-process mesh bootstrap: jax.distributed keyed off the daemon's
``mesh_process_index``.

PR 9 made placement an explicit MeshPlan and plumbed
``PlacementConfig.process_index`` through option.py/daemon.py, but past
one host the field was dead code. This module is the missing bring-up:
``mesh_bootstrap`` runs ``jax.distributed.initialize`` so ``jax.
devices()`` spans every participating host, and ``placement_config``
builds the PlacementConfig whose ``process_index`` filter
(placement.eligible_devices) then selects exactly this host's local
devices out of the global complement.

CPU dryrun recipe (what tests/test_mesh_bootstrap.py subprocesses):
each process sets ``JAX_PLATFORMS=cpu`` and ``XLA_FLAGS=
--xla_force_host_platform_device_count=K``, then calls
``mesh_bootstrap("127.0.0.1:<port>", num_processes=N,
process_index=i)``. Every process sees N*K global devices, K local
ones, and per-process ``resolve_plan`` yields the same generation and
axis layout — the MeshPlan spans hosts.

Initialization is process-global in jax, hence idempotent here: a
second call returns the first call's summary (coordinator mismatch
raises — silently reusing a different fleet would be worse).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..option import get_config

_lock = threading.Lock()
_summary: Optional[Dict] = None


def mesh_bootstrap(
    coordinator_address: str,
    num_processes: int,
    process_index: Optional[int] = None,
) -> Dict:
    """Join (or found) the multi-process jax mesh; returns a summary of
    the resulting device complement. ``process_index`` defaults to the
    daemon config's ``mesh_process_index``."""
    if process_index is None:
        process_index = get_config().mesh_process_index
    global _summary
    with _lock:
        if _summary is not None:
            if _summary["coordinator"] != coordinator_address:
                raise RuntimeError(
                    "mesh already initialized against "
                    f"{_summary['coordinator']!r}, refusing "
                    f"{coordinator_address!r}"
                )
            return dict(_summary)
        try:
            import jax
        except ImportError as e:  # container without the toolchain
            raise RuntimeError(f"jax unavailable for mesh bootstrap: {e}")
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_index,
            )
        except (RuntimeError, ValueError) as e:
            raise RuntimeError(
                f"jax.distributed.initialize failed for process "
                f"{process_index}/{num_processes} at "
                f"{coordinator_address}: {e}"
            )
        _summary = {
            "initialized": True,
            "coordinator": coordinator_address,
            "num_processes": int(num_processes),
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
        }
        return dict(_summary)


def bootstrap_state() -> Optional[Dict]:
    """The last successful bootstrap summary (None standalone)."""
    with _lock:
        return dict(_summary) if _summary is not None else None


def placement_config(process_index: Optional[int] = None):
    """The PlacementConfig for this host's slice of the fleet mesh —
    same construction the daemon ctor uses, with ``process_index``
    resolvable from the live bootstrap instead of static config."""
    from ..datapath.placement import PlacementConfig

    cfg = get_config()
    if process_index is None:
        state = bootstrap_state()
        process_index = (
            state["process_index"] if state else cfg.mesh_process_index
        )
    return PlacementConfig(
        device_ids=(
            tuple(int(x) for x in cfg.mesh_devices.split(","))
            if cfg.mesh_devices
            else None
        ),
        ident_axis=cfg.mesh_ident_axis,
        process_index=process_index,
    )
