"""Node registry + policy-epoch exchange over the kvstore watch fabric.

# policyd: hot

Every federated node publishes one lease-bound record — its node
descriptor plus its current ``policy_epoch`` (the EpochSwap counter a
full rebuild bumps when the shadow generation swaps in, PR 7) — under
``CLUSTER_EPOCHS_PATH`` and watches every peer's record through a
:class:`SharedStore` (pkg/kvstore/store role, as the node registry
does for connectivity).

The *cluster epoch* is the convergence floor: the minimum published
``policy_epoch`` across every known node. A rule pushed at one node is
provably enforced fleet-wide once the cluster epoch reaches the epoch
of the rebuild that installed it — that is exactly what the
``wait_cluster_epoch`` barrier polls for (bounded, ROBUST002: every
wait in here carries a timeout).

Failure modes: a dead node's record dies with its lease, so it stops
holding the floor down; a partitioned node keeps serving its LAST
converged tables (the exchange is an observability/barrier plane, not
an enforcement gate) and its staleness is visible to every peer as a
rising ``cluster_epoch_lag``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from .. import metrics as _metrics
from ..kvstore.backend import BackendOperations
from ..kvstore.paths import CLUSTER_EPOCHS_PATH
from ..kvstore.store import SharedStore


class EpochExchange:
    """One node's view of the fleet's policy epochs."""

    def __init__(
        self,
        backend: BackendOperations,
        node_name: str,
        *,
        cluster: str = "default",
        descriptor: Optional[dict] = None,
        epoch_source: Optional[Callable[[], int]] = None,
        base_path: str = CLUSTER_EPOCHS_PATH,
    ) -> None:
        self.node_name = node_name
        self.cluster = cluster
        self.key_name = f"{cluster}/{node_name}"
        self._descriptor = dict(descriptor or {})
        self._epoch_source = epoch_source or (lambda: 0)
        self._last_published: Optional[int] = None
        self._seq = 0
        self.store = SharedStore(backend, base_path)

    # ------------------------------------------------------------------
    def local_epoch(self) -> int:
        return int(self._epoch_source())

    def publish(self, epoch: Optional[int] = None, *, force: bool = False) -> bool:
        """Publish (descriptor, policy_epoch) when the epoch moved (or
        ``force`` — anti-entropy resync after a lease loss). True when
        a write happened."""
        e = self.local_epoch() if epoch is None else int(epoch)
        if not force and e == self._last_published:
            return False
        self._seq += 1
        rec = dict(self._descriptor)
        rec.update(
            {
                "node": self.node_name,
                "cluster": self.cluster,
                "policy_epoch": e,
                "seq": self._seq,
            }
        )
        self.store.update_local_key_sync(self.key_name, rec)
        self._last_published = e
        return True

    def pump(self) -> int:
        """Apply pending peer events; refresh the cluster gauges."""
        n = self.store.pump()
        view = self.view()
        _metrics.cluster_nodes.set(float(len(view)))
        _metrics.cluster_epoch_lag.set(float(self.epoch_lag(view)))
        return n

    # -- fleet view ------------------------------------------------------
    def view(self) -> Dict[str, dict]:
        """name → published record for every node of this cluster
        (including self once the watch round-tripped)."""
        return {
            name: rec
            for name, rec in dict(self.store.shared).items()
            if rec.get("cluster") == self.cluster
        }

    def cluster_epoch(self, view: Optional[Dict[str, dict]] = None) -> int:
        """The convergence floor: min published policy_epoch across
        every known node (self included — an unpublished local bump
        cannot claim fleet convergence)."""
        v = self.view() if view is None else view
        epochs = [int(r.get("policy_epoch", 0)) for r in v.values()]
        local = self.local_epoch()
        if not epochs:
            return local
        return min(epochs + [local])

    def epoch_lag(self, view: Optional[Dict[str, dict]] = None) -> int:
        return max(0, self.local_epoch() - self.cluster_epoch(view))

    # -- the barrier -----------------------------------------------------
    def wait_cluster_epoch(
        self,
        epoch: Optional[int] = None,
        timeout: float = 10.0,
        *,
        poll: float = 0.02,
        min_nodes: int = 1,
        pump: Optional[Callable[[], object]] = None,
    ) -> bool:
        """Convergence barrier: True once at least ``min_nodes`` nodes
        are publishing and EVERY one of them reports ``policy_epoch >=
        epoch`` (default: this node's current local epoch). Bounded
        poll — returns False at the deadline; a caller-supplied
        ``pump`` runs each round (in-process multi-node tests drive
        their peers' controllers through it)."""
        target = self.local_epoch() if epoch is None else int(epoch)
        deadline = time.monotonic() + timeout
        while True:
            self.publish()
            if pump is not None:
                pump()
            self.pump()
            view = self.view()
            if len(view) >= min_nodes and all(
                int(r.get("policy_epoch", 0)) >= target for r in view.values()
            ):
                return True
            now = time.monotonic()
            if now >= deadline:
                return False
            time.sleep(min(poll, deadline - now))

    # ------------------------------------------------------------------
    def sync(self) -> int:
        """Anti-entropy: re-write our lease-bound record (heartbeat
        path; self-heals a lease loss)."""
        return self.store.sync_local_keys()

    def close(self) -> None:
        try:
            self.store.delete_local_key(self.key_name)
        except (ConnectionError, TimeoutError, OSError, RuntimeError):
            pass  # backend gone; the lease reaps our record
        self.store.close()
