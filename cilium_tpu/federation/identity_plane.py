"""Cluster-wide identity allocator: reserve/confirm CAS over the kvstore.

# policyd: hot

Extends the master/slave scheme of :mod:`cilium_tpu.kvstore.allocator`
(allocator.go:80-106) with the federation protocol PR 14 needs to hold
its no-double-assign guarantee under partitions and node death:

    <base>/id/<id>              = key   (master: THE allocation, durable)
    <base>/value/<key>/<node>   = id    (slave: per-node use, lease-bound)
    <base>/reserve/<id>         = node  (reserve: candidate claim, lease-bound)
    <base>/locks/<key>          =       (per-key CAS lock)

Reserve/confirm: before CAS-creating the durable master key, a node
CAS-creates a *lease-bound* reserve key on its candidate id. Two
federated nodes that both computed the same smallest-unused id diverge
at the reserve instead of burning a master-CAS round, and a node that
crashes between picking an id and confirming it leaks nothing — the
reserve evaporates with its lease. The master ``create_only`` remains
the single arbiter, so the protocol stays wire-compatible with
pre-federation nodes running the plain :class:`Allocator` on the same
path: a legacy node racing on the same id simply wins or loses at the
master CAS.

Partitions: every kvstore round-trip may raise ``ConnectionError``
(FlakyBackend, a real etcd outage). ``allocate`` folds both CAS races
and partitions into one retry loop riding ``utils/backoff`` with FULL
jitter (decorrelates the post-partition thundering herd) and a
``max_elapsed_s`` cap so callers get a :class:`FederationError` instead
of an unbounded stall. Nothing is retried *inside* a CAS — an attempt
either fully confirms or changes nothing durable, so a retry after a
mid-attempt partition converges onto the adopt path.

Lease expiry: slave keys (and reserves) die with the node's lease.
``heartbeat()`` is the renewal side — it re-creates this node's
slave/master keys after a lease loss (so GC cannot reap identities
still in local use) and reaps any of this node's orphaned reserves.
The release-on-lease-expiry side needs no code here: a dead node's
slave keys vanish, and ``run_gc`` reaps masters with no slaves left.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Set, Tuple

from .. import metrics as _metrics
from ..kvstore.allocator import Allocator, AllocatorError
from ..kvstore.backend import BackendOperations
from ..utils.backoff import Backoff

# what a kvstore partition looks like from here: FlakyBackend raises
# ConnectionError, a real client surfaces timeouts/socket errors, and
# a lease-expired write raises RuntimeError (transient under the
# FileBackend keepalive; permanent loss exhausts the backoff into a
# FederationError instead of leaking a raw backend error)
_KV_DOWN = (ConnectionError, TimeoutError, OSError, RuntimeError)


class FederationError(Exception):
    """Allocation failed after the backoff budget (partition outlasted
    ``max_elapsed_s``) — the caller decides whether to degrade."""


def _default_backoff() -> Backoff:
    # ms-scale floors: identity allocation sits on the endpoint-create
    # path, and the contended case is CAS races between a handful of
    # nodes, not a 60s-class outage ladder
    return Backoff(
        min_s=0.005, max_s=0.25, full_jitter=True, max_elapsed_s=2.0
    )


class ClusterIdentityAllocator(Allocator):
    """Federated id↔key allocation with reserve/confirm + heartbeats.

    Drop-in for :class:`Allocator` (same ``allocate(key) -> (id,
    is_new)`` contract and key scheme); ``node_name`` takes the slave
    suffix role and names this node in reserve keys.
    """

    def __init__(
        self,
        backend: BackendOperations,
        base_path: str,
        *,
        node_name: str,
        min_id: int = 1,
        max_id: int = 1 << 16,
        on_event: Optional[Callable[[str, int, Optional[str]], None]] = None,
        backoff_factory: Optional[Callable[[], Backoff]] = None,
    ) -> None:
        self.node_name = node_name
        self.reserve_prefix = base_path.rstrip("/") + "/reserve/"
        self._backoff_factory = backoff_factory or _default_backoff
        # reserves this node holds for allocations in flight RIGHT NOW
        # (API threads) — heartbeat's orphan sweep must not reap them
        self._inflight_reserves: Set[int] = set()
        # per-instance outcome counts: the metric family is process-
        # global, but in-process multi-node tests/bench want per-node
        self._counts: dict = {}
        super().__init__(
            backend,
            base_path,
            suffix=node_name,
            min_id=min_id,
            max_id=max_id,
            on_event=on_event,
        )

    # ------------------------------------------------------------------
    def _reserve_key(self, id_: int) -> str:
        return f"{self.reserve_prefix}{id_}"

    def _account(self, result: str) -> None:
        with self._lock:
            self._counts[result] = self._counts.get(result, 0) + 1
        _metrics.cluster_identity_allocations_total.inc({"result": result})

    def _select_candidate(self) -> int:
        """Smallest id unused by both the master list AND live reserves
        (a peer mid-confirm holds only a reserve; skipping it saves the
        master-CAS round both would otherwise burn)."""
        used = set(self._cache)
        for k in self.backend.list_prefix(self.id_prefix):
            try:
                used.add(int(k[len(self.id_prefix):]))
            except ValueError:
                pass
        for k in self.backend.list_prefix(self.reserve_prefix):
            try:
                used.add(int(k[len(self.reserve_prefix):]))
            except ValueError:
                pass
        for cand in range(self.min_id, self.max_id + 1):
            if cand not in used:
                return cand
        return 0

    # -- allocation -----------------------------------------------------
    def _allocate_once(self, key: str) -> Optional[Tuple[int, bool]]:
        """One adopt-or-reserve/confirm attempt. Returns (id, is_new),
        or None when a CAS race demands a retry; kvstore partitions
        surface as ``_KV_DOWN`` to the caller's backoff loop."""
        self.pump()
        value = self.get_no_cache(key)
        if value == 0:
            # a peer may have confirmed the master without our watch
            # having delivered a slave key yet
            for id_, k in self.cache_items().items():
                if k == key:
                    value = id_
                    break
        if value != 0:
            # adopt: serialize with GC via the per-key lock, slave write
            # conditioned on the master still existing
            lock = self.backend.lock_path(self.lock_prefix + key)
            try:
                if not self._create_slave(key, value):
                    return None  # master reaped mid-adopt; re-resolve
            finally:
                lock.unlock()
            self._local_ref(key, value)
            return value, False

        id_ = self._select_candidate()
        if id_ == 0:
            self._account("error")
            raise AllocatorError("no more available IDs in configured space")
        # reserve: lease-bound claim on the candidate. Loss here means a
        # federated peer is mid-confirm on this id — re-select, nothing
        # durable happened.
        if not self.backend.create_only(
            self._reserve_key(id_), self.node_name.encode(), lease=True
        ):
            return None
        with self._lock:
            self._inflight_reserves.add(id_)
        try:
            lock = self.backend.lock_path(self.lock_prefix + key)
            try:
                if self.get_no_cache(key) != 0:
                    return None  # lost the key race; adopt on retry
                if not self.backend.create_only(
                    self._master_key(id_), key.encode(), lease=False
                ):
                    # a legacy (non-reserving) node won the master CAS
                    return None
                self._create_slave(key, id_)
            finally:
                lock.unlock()
        finally:
            with self._lock:
                self._inflight_reserves.discard(id_)
            # confirm (or abandon): the reserve's job is done either
            # way; if THIS delete rides a partition, the lease reaps it
            self.backend.delete(self._reserve_key(id_))
        with self._lock:
            self._cache[id_] = key
        self._local_ref(key, id_)
        if self._on_event:
            self._on_event("upsert", id_, key)
        return id_, True

    def allocate(self, key: str) -> Tuple[int, bool]:
        """→ (id, is_new). Local-refcount fast path, then the
        adopt-or-reserve/confirm loop riding full-jitter backoff across
        both CAS races and kvstore partitions."""
        with self._lock:
            held = self._local.get(key)
            if held is not None:
                self._local[key] = (held[0], held[1] + 1)
                self._account("cached")
                return held[0], False

        backoff = self._backoff_factory()
        last_err: Optional[str] = None
        while True:
            try:
                got = self._allocate_once(key)
            except _KV_DOWN as e:
                last_err = f"{type(e).__name__}: {e}"
                got = None
            if got is not None:
                self._account("new" if got[1] else "adopted")
                return got
            d = backoff.duration()
            if backoff.exhausted:
                self._account("error")
                raise FederationError(
                    f"allocation of {key!r} failed after backoff budget: "
                    f"{last_err or 'CAS contention'}"
                )
            self._account("retry")
            if d > 0.0:
                time.sleep(d)

    # -- lease renewal ---------------------------------------------------
    def heartbeat(self) -> int:
        """Lease renewal + lease-loss recovery: re-create this node's
        missing slave/master keys (resync_local_keys) and reap our own
        orphaned reserve keys (a crashed confirm's leftovers — the
        lease would reap them too; this just does it sooner). Returns
        the number of keys repaired."""
        fixed = self.resync_local_keys()
        with self._lock:
            inflight = set(self._inflight_reserves)
        for k, raw in self.backend.list_prefix(self.reserve_prefix).items():
            if (raw or b"").decode() != self.node_name:
                continue
            try:
                id_ = int(k[len(self.reserve_prefix):])
            except ValueError:
                continue
            if id_ not in inflight:
                self.backend.delete(k)
        return fixed

    def state(self) -> dict:
        """Status snapshot for GET /cluster."""
        with self._lock:
            return {
                "held": len(self._local),
                "cached": len(self._cache),
                "allocations": dict(self._counts),
            }
