"""FederationMember: one daemon's membership in the federated plane.

# policyd: hot

Composes the two kvstore planes — the reserve/confirm identity
allocator (identity_plane.py) on the SAME ``IDENTITIES_PATH`` the
pre-federation cluster code uses, and the policy-epoch exchange
(epochs.py) — and bridges them into the daemon:

- ``allocate``/``release`` are the pluggable identity source the
  ``ClusterFederation`` runtime option swaps onto
  ``daemon.allocate_identity`` (OFF restores ``registry.allocate`` —
  numbering is the only difference, compiled programs are identical);
- remote allocations observed on the watch mirror into the local
  :class:`IdentityRegistry` (insert_global) so device rows exist
  before the first flow from that node arrives — the same contract
  :class:`DistributedIdentityAllocator` keeps;
- ``pump()`` is controller-driven (the embedder's cluster-sync
  controller or tests), folding watch delivery, epoch publication, and
  periodic lease heartbeats into one deterministic tick.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..identity.distributed import key_to_labels, labels_to_key
from ..identity.model import (
    Identity,
    MAX_USER_IDENTITY,
    MIN_USER_IDENTITY,
)
from ..kvstore.backend import BackendOperations
from ..kvstore.paths import IDENTITIES_PATH
from ..labels import LabelArray
from .epochs import EpochExchange
from .identity_plane import ClusterIdentityAllocator

_KV_DOWN = (ConnectionError, TimeoutError, OSError, RuntimeError)


class FederationMember:
    """One daemon process's seat in the cluster policy plane."""

    def __init__(
        self,
        daemon,
        backend: BackendOperations,
        node_name: str,
        *,
        cluster: str = "default",
        descriptor: Optional[dict] = None,
        heartbeat_interval: float = 15.0,
        backoff_factory=None,
        identities_path: str = IDENTITIES_PATH,
    ) -> None:
        self.daemon = daemon
        self.backend = backend
        self.node_name = node_name
        self.cluster = cluster
        self.heartbeat_interval = heartbeat_interval
        # lifecycle-journal hook (policyd-journal): the daemon points
        # this at EventJournal.emit while LifecycleJournal is on; None
        # keeps heartbeat/GC at one attribute read (module is hot —
        # pump() rides the cluster-sync controller)
        self.on_journal = None
        self._lock = threading.RLock()
        # ids inserted into the registry on behalf of REMOTE
        # allocations (remote deletes release exactly one ref)
        self._remote_held: Dict[int, str] = {}
        self._closed = False
        # a nodes.registry.Node works directly as the descriptor — the
        # epoch record then carries the same addressing facts the node
        # registry announces (name/cluster/CIDRs/health port)
        if descriptor is not None and hasattr(descriptor, "to_dict"):
            descriptor = descriptor.to_dict()
        self.identities = ClusterIdentityAllocator(
            backend,
            identities_path,
            node_name=node_name,
            min_id=MIN_USER_IDENTITY,
            max_id=MAX_USER_IDENTITY,
            on_event=self._on_identity_event,
            backoff_factory=backoff_factory,
        )
        self.epochs = EpochExchange(
            backend,
            node_name,
            cluster=cluster,
            descriptor=descriptor,
            epoch_source=lambda: daemon.pipeline.policy_epoch,
        )
        self._last_heartbeat = time.monotonic()
        self.epochs.publish(force=True)
        self.pump()

    # -- identity source (daemon.allocate_identity contract) ------------
    def _on_identity_event(self, op: str, id_: int, key: Optional[str]) -> None:
        if op == "upsert":
            assert key is not None
            with self._lock:
                if id_ in self._remote_held:
                    return
                if self.daemon.registry.get(id_) is not None:
                    return  # locally held — allocate() keeps its own ref
                try:
                    self.daemon.registry.insert_global(id_, key_to_labels(key))
                except ValueError:
                    # conflicting binding from outside the kvstore path:
                    # log-and-skip semantics (allocator cache.go
                    # invalidKey) — crashing the watch pump is worse
                    return
                self._remote_held[id_] = key
        elif op == "delete":
            with self._lock:
                if id_ in self._remote_held:
                    del self._remote_held[id_]
                    self.daemon.registry.release_by_id(id_)

    def allocate(self, labels: LabelArray) -> Identity:
        """Cluster-consistent identity allocation through the
        reserve/confirm CAS; the registry row lands under the number
        the whole fleet agreed on."""
        num, _is_new = self.identities.allocate(labels_to_key(labels))
        with self._lock:
            return self.daemon.registry.insert_global(num, labels)

    def release(self, ident: Identity) -> bool:
        """Release the local use; GC reaps the number once no node's
        slave key holds it."""
        key = labels_to_key(ident.labels)
        self.identities.release(key)
        freed = self.daemon.registry.release(ident)
        if freed:
            # still live cluster-wide? re-mirror as a remote hold so
            # local policy rows keep covering it until the master-key
            # delete event arrives (DistributedIdentityAllocator's
            # release contract)
            with self._lock:
                if (
                    ident.id not in self._remote_held
                    and self.backend.get(
                        self.identities._master_key(ident.id)
                    ) is not None
                ):
                    try:
                        self.daemon.registry.insert_global(
                            ident.id, ident.labels
                        )
                        self._remote_held[ident.id] = key
                        freed = False
                    except ValueError:
                        pass
        return freed

    # -- controller tick -------------------------------------------------
    def pump(self) -> int:
        """One deterministic tick: watch delivery (identities + epochs),
        epoch publication when the local epoch moved, and the periodic
        lease heartbeat. Returns events applied."""
        n = self.identities.pump()
        self.epochs.publish()
        n += self.epochs.pump()
        now = time.monotonic()
        if now - self._last_heartbeat >= self.heartbeat_interval:
            self._last_heartbeat = now
            self.heartbeat()
        return n

    def heartbeat(self) -> int:
        """Lease renewal: repair this node's slave/master keys after a
        lease loss and re-write the epoch record (anti-entropy).
        Returns keys repaired."""
        fixed = self.identities.heartbeat()
        self.epochs.sync()
        oj = self.on_journal
        if fixed and oj is not None:
            # keys repaired means a lease EXPIRED out from under us —
            # the fleet timeline wants the loss, not the routine renew
            oj(
                kind="lease_lost",
                severity="warning",
                attrs={"repaired": int(fixed)},
            )
        return fixed

    def run_gc(self):
        reaped = self.identities.run_gc()
        oj = self.on_journal
        if reaped and oj is not None:
            oj(
                kind="identity_reap",
                attrs={"reaped": [int(i) for i in reaped],
                       "count": len(reaped)},
            )
        return reaped

    def wait_cluster_epoch(
        self, epoch: Optional[int] = None, timeout: float = 10.0, **kw
    ) -> bool:
        """Convergence barrier (see EpochExchange.wait_cluster_epoch):
        True once every publishing node enforces at least ``epoch``
        (default: this node's current policy epoch)."""
        return self.epochs.wait_cluster_epoch(epoch, timeout, **kw)

    # -- surfaces --------------------------------------------------------
    def joined(self) -> bool:
        if self._closed:
            return False
        try:
            return bool(self.backend.alive())
        except _KV_DOWN:
            return False

    def status(self) -> Dict:
        """The GET /cluster payload body."""
        view = self.epochs.view()
        return {
            "cluster": self.cluster,
            "node": self.node_name,
            "joined": self.joined(),
            "node_count": len(view),
            "nodes": [view[k] for k in sorted(view)],
            "local_epoch": self.epochs.local_epoch(),
            "cluster_epoch": self.epochs.cluster_epoch(view),
            "epoch_lag": self.epochs.epoch_lag(view),
            "identities": self.identities.state(),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.epochs.close()
        except _KV_DOWN:
            pass
        try:
            self.identities.close()
        except _KV_DOWN:
            pass
