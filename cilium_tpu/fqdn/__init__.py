"""FQDN policy: DNS-name rules resolved into generated CIDR rules
(the pkg/fqdn role — poller + TTL cache + rule translation)."""

from .cache import DNSCache
from .poller import DNSPoller, FQDNTranslator, system_resolver

__all__ = ["DNSCache", "DNSPoller", "FQDNTranslator", "system_resolver"]
