"""DNS TTL cache for ToFQDNs policy.

Reference: pkg/fqdn/cache.go — per-name IP sets with per-entry expiry;
lookups return only live addresses, and an update reports whether the
live set actually changed (the poller only re-translates rules on
change, dnspoller.go:260).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

DEFAULT_MIN_TTL = 60.0  # MinTTL floor (option.Config.ToFQDNsMinTTL)


class DNSCache:
    def __init__(self, min_ttl: float = DEFAULT_MIN_TTL) -> None:
        self.min_ttl = min_ttl
        self._lock = threading.Lock()
        # name → {ip: expiry_monotonic}
        self._entries: Dict[str, Dict[str, float]] = {}

    def update(
        self,
        name: str,
        ips: Iterable[str],
        ttl: float,
        now: Optional[float] = None,
    ) -> bool:
        """Record a lookup result. Returns True if the LIVE address set
        for ``name`` changed (new IPs appeared or stale ones expired) —
        the signal to regenerate ToCIDRSet rules."""
        now = time.monotonic() if now is None else now
        expiry = now + max(float(ttl), self.min_ttl)
        with self._lock:
            cur = self._entries.setdefault(name, {})
            before = {ip for ip, exp in cur.items() if exp > now}
            for ip in ips:
                cur[ip] = max(cur.get(ip, 0.0), expiry)
            # drop fully-expired entries while we're here
            for ip in [ip for ip, exp in cur.items() if exp <= now]:
                del cur[ip]
            after = {ip for ip, exp in cur.items() if exp > now}
            return after != before

    def lookup(self, name: str, now: Optional[float] = None) -> List[str]:
        now = time.monotonic() if now is None else now
        with self._lock:
            cur = self._entries.get(name, {})
            return sorted(ip for ip, exp in cur.items() if exp > now)

    def expire(self, now: Optional[float] = None) -> List[str]:
        """Drop expired entries; returns names whose live set changed."""
        now = time.monotonic() if now is None else now
        changed = []
        with self._lock:
            for name, cur in self._entries.items():
                stale = [ip for ip, exp in cur.items() if exp <= now]
                if stale:
                    for ip in stale:
                        del cur[ip]
                    changed.append(name)
        return changed

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)
