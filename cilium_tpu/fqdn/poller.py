"""DNS poller: ToFQDNs names → generated ToCIDRSet rules.

Reference: pkg/fqdn/dnspoller.go — MarkToFQDNRules (:160) tags rules
carrying ToFQDNs, the 5s poll loop (:78) resolves every tracked name,
and on any IP-set change the generated ToCIDRSet entries are rebuilt
and re-injected through the repository (AddGeneratedRules → here the
pure-translator swap of Repository.translate_rules, one revision
bump). Resolution itself is pluggable — production uses the system
resolver, tests inject a fake (the reference does the same with its
lookup function, dnspoller.go LookupDNSNames).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..policy.api import CIDRRule, EgressRule, Rule
from ..policy.api.rules import host_cidr as _host_cidr
from .cache import DNSCache

# resolver signature: name → (ips, ttl_seconds)
Resolver = Callable[[str], Tuple[List[str], float]]

DEFAULT_INTERVAL = 5.0  # DNSPollerInterval (dnspoller.go:43)


def system_resolver(name: str) -> Tuple[List[str], float]:
    """Default resolver over the host stack (TTL is not surfaced by
    getaddrinfo — use a fixed re-poll horizon like the reference's
    fallback)."""
    import socket

    try:
        infos = socket.getaddrinfo(name, None)
    except OSError:
        return [], 0.0
    return sorted({i[4][0] for i in infos}), 60.0


class FQDNTranslator:
    """Pure rule translator: regenerates the fqdn-generated ToCIDRSet
    of every egress rule carrying ToFQDNs from the current cache
    state. User-written CIDRs and ToServices-generated entries are
    untouched (fqdn entries are tagged generated_by="fqdn")."""

    def __init__(self, cache: DNSCache, now: Optional[float] = None) -> None:
        self.cache = cache
        self.now = time.monotonic() if now is None else now

    def translate(self, rule: Rule) -> Rule:
        if not any(eg.to_fqdns for eg in rule.egress):
            return rule
        new_egress = []
        changed = False
        for eg in rule.egress:
            if not eg.to_fqdns:
                new_egress.append(eg)
                continue
            kept = tuple(
                c for c in eg.to_cidr_set if c.generated_by != "fqdn"
            )
            gen = []
            seen = set()
            for name in eg.to_fqdns:
                for ip in self.cache.lookup(name, self.now):
                    if ip in seen:
                        continue
                    seen.add(ip)
                    gen.append(
                        CIDRRule(
                            cidr=_host_cidr(ip),
                            generated=True,
                            generated_by="fqdn",
                        )
                    )
            new_set = kept + tuple(gen)
            if new_set != eg.to_cidr_set:
                changed = True
                new_egress.append(
                    dataclasses.replace(eg, to_cidr_set=new_set)
                )
            else:
                new_egress.append(eg)
        if not changed:
            return rule
        return dataclasses.replace(rule, egress=tuple(new_egress))


class DNSPoller:
    """Tracks ToFQDNs names across the repository and re-translates on
    IP-set change. ``repo`` needs Repository's rules/translate_rules
    surface; ``on_change`` (e.g. daemon regeneration) fires after a
    revision bump."""

    def __init__(
        self,
        repo,
        resolver: Resolver = system_resolver,
        cache: Optional[DNSCache] = None,
        on_change: Optional[Callable[[int], None]] = None,
    ) -> None:
        self.repo = repo
        self.resolver = resolver
        self.cache = cache or DNSCache()
        self.on_change = on_change
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.failures = 0  # consecutive poll failures (operator signal)

    # -- name tracking (MarkToFQDNRules role) ---------------------------
    def tracked_names(self) -> List[str]:
        names = set()
        with self.repo._lock:
            rules = list(self.repo.rules)
        for r in rules:
            for eg in r.egress:
                names.update(eg.to_fqdns)
        return sorted(names)

    # -- polling --------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> int:
        """One resolution sweep. Returns the number of rules whose
        generated CIDR set changed (0 = no revision bump)."""
        now = time.monotonic() if now is None else now
        for name in self.tracked_names():
            ips, ttl = self.resolver(name)
            if ips:
                self.cache.update(name, ips, ttl, now)
        self.cache.expire(now)
        # translation runs unconditionally: it is pure and cheap, a
        # no-op poll reports 0 changed (no revision bump), and gating
        # on cache change would miss rules imported since the last
        # translate (the reference solves that with MarkToFQDNRules at
        # import time; unconditional translate covers the same gap)
        rev, changed = self.repo.translate_rules(FQDNTranslator(self.cache, now))
        if changed and self.on_change is not None:
            self.on_change(rev)
        return changed

    def start(self, interval: float = DEFAULT_INTERVAL) -> None:
        if self._thread is not None:
            return

        log = logging.getLogger("cilium_tpu.fqdn")
        # fresh Event per loop (see health/prober.py start): a restart
        # after a timed-out join must not revive the old thread
        self._stop = stop_ev = threading.Event()

        def loop():
            while not stop_ev.wait(interval):
                try:
                    self.poll_once()
                    self.failures = 0
                except Exception:
                    # poller must survive resolver hiccups — log and
                    # keep polling (dnspoller.go does the same); the
                    # failure counter gives status surfaces a signal
                    self.failures += 1
                    log.warning("fqdn poll failed (%d consecutive)",
                                self.failures, exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
