"""Node health probing (the cilium-health role: pkg/health +
cilium-health daemon — connectivity probes across the node registry)."""

from .prober import DEFAULT_HEALTH_PORT, HealthProber, NodeStatus, tcp_probe

__all__ = ["DEFAULT_HEALTH_PORT", "HealthProber", "NodeStatus", "tcp_probe"]
