"""``python -m cilium_tpu.health`` — the standalone per-node health
endpoint process (cilium-health/main.go entry point)."""

import sys

from .standalone import main

if __name__ == "__main__":
    sys.exit(main())
