"""Node connectivity prober.

Reference: pkg/health/server/prober.go — the cilium-health daemon
probes every known node (ICMP echo :229 + TCP connect to the node's
health endpoint :262) on an interval, keeps per-node status with
latency, and serves the results over its REST API; the agent launches
it at boot (daemon/main.go:927-945).

Here the probe transport is pluggable: the default TCP probe measures
a real connect() round trip; tests (and single-process clusters)
inject a fake. Results feed `cilium-tpu health` and the /health REST
route.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

DEFAULT_INTERVAL = 60.0  # prober.go ProbeInterval
DEFAULT_HEALTH_PORT = 4240  # cilium-health's node port

# probe signature: (address, port) → latency seconds, raising OSError
# on unreachable
ProbeFn = Callable[[str, int], float]


def tcp_probe(addr: str, port: int, timeout: float = 2.0) -> float:
    """Connect-based probe (prober.go TCP dial)."""
    t0 = time.monotonic()
    family = socket.AF_INET6 if ":" in addr else socket.AF_INET
    with socket.socket(family, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect((addr, port))
    return time.monotonic() - t0


@dataclasses.dataclass
class NodeStatus:
    """Per-node probe outcome (healthModels.NodeStatus)."""

    name: str
    cluster: str
    address: Optional[str]
    reachable: bool = False
    latency_s: float = 0.0
    last_probe: float = 0.0
    failures: int = 0  # consecutive
    error: str = ""

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class HealthProber:
    """Probes every node the registry knows about. ``nodes`` is any
    object with remote_nodes() → [Node] (nodes/registry.py), or None
    for a standalone single-node daemon (only self-status then)."""

    def __init__(
        self,
        nodes=None,
        probe: ProbeFn = tcp_probe,
        port: int = DEFAULT_HEALTH_PORT,
    ) -> None:
        self.nodes = nodes
        self.probe = probe
        self.port = port
        self._lock = threading.Lock()
        self._sweep_lock = threading.Lock()
        self._status: Dict[str, NodeStatus] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe_once(self) -> List[NodeStatus]:
        """One sweep over all known nodes (prober.go runProbe).

        Sweeps are serialized by ``_sweep_lock`` — the background loop
        and POST /health/probe must not interleave, or a sweep that
        blocked in a connect timeout could overwrite a newer sweep's
        result with stale state and corrupt consecutive-failure
        counts. Within a sweep, nodes are probed CONCURRENTLY (the
        reference fans out too, prober.go), bounding sweep time to
        roughly one transport timeout instead of timeouts × down
        nodes. Fresh NodeStatus objects are swapped in whole so
        report() never sees torn state."""
        with self._sweep_lock:
            nodes = list(self.nodes.remote_nodes()) if self.nodes else []

            def probe_node(n) -> NodeStatus:
                addr = n.health_ip or n.ipv4 or n.ipv6
                key = f"{n.cluster}/{n.name}"
                with self._lock:
                    prev = self._status.get(key)
                    prev_failures = prev.failures if prev else 0
                st = NodeStatus(
                    name=n.name, cluster=n.cluster, address=addr,
                    last_probe=time.time(),
                )
                if addr is None:
                    st.error = "no address"
                    st.failures = prev_failures + 1
                else:
                    # nodes may advertise their responder's port (one
                    # host running several test nodes); default 4240
                    port = getattr(n, "health_port", None) or self.port
                    try:
                        st.latency_s = self.probe(addr, port)
                        st.reachable = True
                    except OSError as e:
                        st.failures = prev_failures + 1
                        st.error = str(e) or type(e).__name__
                return st

            if not nodes:
                out: List[NodeStatus] = []
            elif len(nodes) == 1:
                out = [probe_node(nodes[0])]
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=min(32, len(nodes))
                ) as pool:
                    out = list(pool.map(probe_node, nodes))
            with self._lock:
                for st in out:
                    self._status[f"{st.cluster}/{st.name}"] = st
                # forget nodes that left the cluster
                live = {f"{n.cluster}/{n.name}" for n in nodes}
                for key in list(self._status):
                    if key not in live:
                        del self._status[key]
            return out

    def report(self) -> Dict:
        """The GET /health payload (health server Status)."""
        with self._lock:
            # statuses are replaced whole per sweep, never mutated in
            # place — snapshotting under the lock is consistent
            nodes = [st.to_dict() for st in self._status.values()]
        reachable = sum(1 for n in nodes if n["reachable"])
        return {
            "nodes": sorted(nodes, key=lambda n: (n["cluster"], n["name"])),
            "reachable": reachable,
            "total": len(nodes),
        }

    def start(self, interval: float = DEFAULT_INTERVAL) -> None:
        if self._thread is not None:
            return
        # fresh Event per loop: restart after a timed-out join must
        # not revive the old thread (it keeps watching ITS event,
        # which stays set forever, and exits at its next check)
        self._stop = stop_ev = threading.Event()

        def loop():
            # initial sweep at launch (the reference probes immediately,
            # prober.go RunLoop) — health isn't empty for the first
            # interval after boot
            while True:
                try:
                    self.probe_once()
                except Exception:
                    pass  # a registry hiccup must not kill the prober
                if stop_ev.wait(interval):
                    return

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
