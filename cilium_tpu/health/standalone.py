"""Standalone per-node health endpoint — its own PROCESS.

Reference: cilium-health (cilium-health/main.go + cmd/) and
pkg/health/server/prober.go:40,229,262 — a separate daemon per node
that

- ANSWERS other nodes' connectivity probes on the node health port
  (the TCP side of prober.go:262; ICMP is the kernel's job),
- PROBES every node it learns about from its local agent's API
  (prober.go runProbe over the agent-provided topology),
- serves its results over its OWN unix-socket REST API
  (GET /status, POST /probe — the cilium-health CLI surface),

and is launched/supervised by the agent exactly like the external
proxy (pkg/launcher). Run as::

    python -m cilium_tpu.health --agent <agent.sock> \
        --api <health.sock> [--listen-ip IP] [--port 4240]
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

from ..api.unixhttp import UnixHandler, UnixHTTPServer
from ..utils.logging import get_logger
from .prober import DEFAULT_HEALTH_PORT, HealthProber, tcp_probe

log = get_logger("health-endpoint")


class HealthResponder:
    """The probe TARGET: a TCP listener on the node health port. A
    remote prober's connect() completing IS the signal; a one-line
    banner is written so humans poking the port see who answered."""

    def __init__(self, host: str = "0.0.0.0", port: int = DEFAULT_HEALTH_PORT):
        family = socket.AF_INET6 if ":" in host else socket.AF_INET
        self._srv = socket.socket(family, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self.accepted = 0  # probes answered (telemetry)

    def start(self) -> "HealthResponder":
        threading.Thread(target=self._loop, daemon=True).start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._srv.accept()
            except OSError:
                if self._stop.is_set():
                    return
                # transient accept failure (ECONNABORTED, fd pressure):
                # the port is still advertised — keep serving. A closed
                # listener raises continuously; the stop flag (set by
                # stop(), which closes it) breaks the loop then.
                if self._srv.fileno() < 0:
                    return  # socket gone without stop(): nothing to serve
                time.sleep(0.05)
                continue
            self.accepted += 1
            try:
                conn.sendall(b"cilium-health ok\n")
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class _AgentNodeView:
    """Adapter: the agent's ``node list`` API → the ``remote_nodes()``
    shape HealthProber consumes (the reference's health server pulls
    topology from its local agent the same way)."""

    class _Node:
        def __init__(self, d: dict) -> None:
            self.name = d.get("name", "")
            self.cluster = d.get("cluster", "default")
            self.ipv4 = d.get("ipv4")
            self.ipv6 = d.get("ipv6")
            self.health_ip = d.get("health_ip") or None
            self.health_port = d.get("health_port") or None

    def __init__(self, agent_socket: str) -> None:
        self._path = agent_socket
        self._cached: List[dict] = []

    def remote_nodes(self):
        from ..api.client import APIClient, APIError

        try:
            self._cached = APIClient(self._path, timeout=5.0).node_list()
        except (OSError, APIError, ValueError):
            pass  # agent briefly down: keep probing the last topology
        return [self._Node(d) for d in self._cached]


class HealthEndpoint:
    """The in-process assembly (responder + prober + REST); main()
    wraps it as the standalone process."""

    def __init__(
        self,
        agent_socket: str,
        api_socket: str,
        listen_ip: str = "0.0.0.0",
        port: int = DEFAULT_HEALTH_PORT,
        probe_interval: float = 60.0,
    ) -> None:
        self.responder = HealthResponder(listen_ip, port)
        # Fallback probe port for peers that haven't advertised one:
        # the configured cluster convention, NEVER our own ephemeral
        # responder port (on one host that would self-connect and
        # report an unstarted peer as reachable).
        self.prober = HealthProber(
            nodes=_AgentNodeView(agent_socket),
            probe=tcp_probe,
            port=port or DEFAULT_HEALTH_PORT,
        )
        self.probe_interval = probe_interval
        self.started = time.time()
        endpoint = self

        class Handler(UnixHandler):
            def do_GET(self):
                if self.path == "/status":
                    rep = endpoint.prober.report()
                    rep["probes_answered"] = endpoint.responder.accepted
                    rep["uptime_s"] = round(time.time() - endpoint.started, 1)
                    rep["port"] = endpoint.responder.port
                    self._json(200, rep)
                elif self.path == "/healthz":
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/probe":
                    out = endpoint.prober.probe_once()
                    self._json(200, {"probed": len(out)})
                else:
                    self._json(404, {"error": "not found"})

        self._api = UnixHTTPServer(api_socket, Handler)

    def start(self) -> "HealthEndpoint":
        self.responder.start()
        self.prober.start(interval=self.probe_interval)
        threading.Thread(target=self._api.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self.prober.stop()
        self.responder.stop()
        self._api.shutdown()
        self._api.server_close()


class HealthAPIClient:
    """Client for the health endpoint's unix-socket API (the
    cilium-health CLI role)."""

    def __init__(self, api_socket: str, timeout: float = 10.0) -> None:
        from ..api.client import APIClient

        self._c = APIClient(api_socket, timeout=timeout)

    def status(self) -> dict:
        return self._c._request("GET", "/status")

    def probe(self) -> dict:
        return self._c._request("POST", "/probe")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="python -m cilium_tpu.health",
        description="standalone per-node health endpoint (cilium-health)",
    )
    ap.add_argument("--agent", required=True, help="agent API unix socket")
    ap.add_argument("--api", required=True, help="this endpoint's unix socket")
    ap.add_argument("--listen-ip", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_HEALTH_PORT)
    ap.add_argument("--interval", type=float, default=60.0)
    args = ap.parse_args(argv)
    from ..utils.procutil import die_with_parent

    die_with_parent()  # a SIGKILLed agent must not leak this sidecar
    ep = HealthEndpoint(
        args.agent, args.api, listen_ip=args.listen_ip, port=args.port,
        probe_interval=args.interval,
    ).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    print(f"READY {ep.responder.port}", flush=True)
    stop.wait()
    ep.stop()
    return 0
