"""Security identities.

Reference semantics: pkg/identity — an identity is a small integer bound
to a canonical LabelArray. Reserved identities (pkg/identity/
numericidentity.go): host=1, world=2, cluster=3, health=4, init=5.
User identities live in [256, 65535] (pkg/identity/allocator.go:77-78);
CIDR-derived identities are node-local (allocator.go cidr/).

TPU-first addition: the :class:`IdentityRegistry` also owns the *dense
row index* — identity IDs are sparse, device tensors are dense, so every
known identity gets a stable row in the packed label-bitmap matrix that
the policy compiler ships to the device.
"""

from .model import (
    Identity,
    ID_HOST,
    ID_WORLD,
    ID_CLUSTER,
    ID_HEALTH,
    ID_INIT,
    ID_INVALID,
    MIN_USER_IDENTITY,
    MAX_USER_IDENTITY,
    LOCAL_IDENTITY_BASE,
    RESERVED_IDENTITIES,
    reserved_identity_labels,
    lookup_reserved,
)
from .registry import IdentityRegistry

__all__ = [
    "Identity",
    "IdentityRegistry",
    "ID_HOST",
    "ID_WORLD",
    "ID_CLUSTER",
    "ID_HEALTH",
    "ID_INIT",
    "ID_INVALID",
    "MIN_USER_IDENTITY",
    "MAX_USER_IDENTITY",
    "LOCAL_IDENTITY_BASE",
    "RESERVED_IDENTITIES",
    "reserved_identity_labels",
    "lookup_reserved",
]
