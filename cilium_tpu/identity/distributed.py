"""Distributed identity allocation: kvstore CAS + local registry sync.

The reference's AllocateIdentity (/root/reference/pkg/identity/
allocator.go:122) allocates the {labels → small integer} binding
through the kvstore allocator so every node in the cluster numbers
identities identically; the local cache follows the kvstore watch.

Here the same contract feeds the TPU: identity numbers pick device
tensor rows, so cluster-wide agreement on numbering is what lets every
node's compiled policy tensors stay row-compatible. The flow is:

    allocate(labels)
      └ kvstore CAS (Allocator.allocate on the sorted-label key)
          └ registry.insert_global(num, labels)     # local row assign
              └ engine observer → device row patch  # (engine.py)

and remote allocations arrive as watch events through :meth:`pump`,
inserting remote identities into the registry so their rows exist
before any flow from that node shows up.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..kvstore.allocator import Allocator
from ..kvstore.backend import BackendOperations
from ..labels import LabelArray, parse_label_array
from .model import Identity, MAX_USER_IDENTITY, MIN_USER_IDENTITY
from .registry import IdentityRegistry

from ..kvstore.paths import IDENTITIES_PATH, key_to_label_strings


def labels_to_key(labels: LabelArray) -> str:
    """Canonical allocator key for a label set (the globalIdentity key
    of allocator.go:31 — sorted label serialization)."""
    return labels.sorted_key()


def key_to_labels(key: str) -> LabelArray:
    return parse_label_array(key_to_label_strings(key))


class DistributedIdentityAllocator:
    """Cluster-wide identity allocation for one node.

    Wraps a kvstore :class:`Allocator` on the identities path and keeps
    the node's :class:`IdentityRegistry` in sync both ways:

    - local ``allocate``/``release`` go through kvstore CAS, then the
      registry;
    - remote create/delete events land via :meth:`pump` (controller-
      driven), inserting/releasing the corresponding registry entries.
    """

    def __init__(
        self,
        backend: BackendOperations,
        registry: IdentityRegistry,
        node_name: str,
        *,
        base_path: str = IDENTITIES_PATH,
    ) -> None:
        self.registry = registry
        self.node_name = node_name
        self._lock = threading.RLock()
        # ids this node inserted into the registry on behalf of REMOTE
        # allocations (so remote deletes release exactly one ref)
        self._remote_held: Dict[int, str] = {}
        self.alloc = Allocator(
            backend,
            base_path,
            suffix=node_name,
            min_id=MIN_USER_IDENTITY,
            max_id=MAX_USER_IDENTITY,
            on_event=self._on_allocator_event,
        )
        self.pump()

    # ------------------------------------------------------------------
    def _on_allocator_event(self, op: str, id_: int, key: Optional[str]) -> None:
        if op == "upsert":
            assert key is not None
            with self._lock:
                if id_ in self._remote_held:
                    return  # already mirrored
                # Local allocations insert via allocate(); only mirror
                # ids we don't already hold locally.
                if self.registry.get(id_) is not None:
                    return
                try:
                    self.registry.insert_global(id_, key_to_labels(key))
                except ValueError:
                    # Conflicting binding (e.g. the labels were bound
                    # locally outside the kvstore path): skip — the
                    # reference logs-and-skips invalid remote entries
                    # (allocator cache.go invalidKey); crashing the
                    # watch pump would be strictly worse.
                    return
                self._remote_held[id_] = key
        elif op == "delete":
            with self._lock:
                if id_ in self._remote_held:
                    del self._remote_held[id_]
                    self.registry.release_by_id(id_)

    def pump(self) -> int:
        """Apply pending kvstore watch events (remote allocations /
        releases) into the registry. Returns events applied."""
        return self.alloc.pump()

    # ------------------------------------------------------------------
    def allocate(self, labels: LabelArray) -> Identity:
        """Cluster-consistent AllocateIdentity (allocator.go:122)."""
        key = labels_to_key(labels)
        num, _is_new = self.alloc.allocate(key)
        with self._lock:
            # The local use takes its OWN registry reference; a remote
            # mirror (if the watch event landed first) keeps its ref and
            # is released only by the master-key delete event — the two
            # holds are independent, so neither release can strand the
            # other.
            return self.registry.insert_global(num, labels)

    def release(self, ident: Identity) -> bool:
        """Release the local use; slave-key removal lets GC reap the
        number once no node uses it."""
        self.alloc.release(labels_to_key(ident.labels))
        freed = self.registry.release(ident)
        if freed:
            # The identity may still be live cluster-wide (other nodes'
            # slave keys keep the master key alive). Re-mirror it as a
            # remote hold so local policy rows keep covering it until
            # the master-key delete event arrives.
            key = labels_to_key(ident.labels)
            with self._lock:
                if (
                    ident.id not in self._remote_held
                    and self.alloc.backend.get(
                        self.alloc._master_key(ident.id)
                    ) is not None
                ):
                    try:
                        self.registry.insert_global(ident.id, ident.labels)
                        self._remote_held[ident.id] = key
                        freed = False
                    except ValueError:
                        pass
        return freed

    def run_gc(self):
        return self.alloc.run_gc()

    def resync(self) -> int:
        """Lease-loss recovery: re-create our slave/master keys
        (allocator.go localKeySync + recreateMasterKey)."""
        return self.alloc.resync_local_keys()

    def close(self) -> None:
        self.alloc.close()
