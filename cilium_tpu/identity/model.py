"""Identity model and reserved identities.

Reference: pkg/identity/identity.go (Identity struct),
pkg/identity/numericidentity.go (reserved numeric identities and the
``reserved:`` labels they carry).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..labels import Label, LabelArray

ID_INVALID = 0
ID_HOST = 1
ID_WORLD = 2
ID_CLUSTER = 3
ID_HEALTH = 4
ID_INIT = 5

MIN_USER_IDENTITY = 256
MAX_USER_IDENTITY = 65535

# Node-local identities (CIDR-derived). The reference scopes these
# locally too; we place them above the global space so the two can never
# collide (pkg/identity/cidr/ semantics, new numbering).
LOCAL_IDENTITY_BASE = 1 << 24

RESERVED_IDENTITIES: Dict[int, str] = {
    ID_HOST: "host",
    ID_WORLD: "world",
    ID_CLUSTER: "cluster",
    ID_HEALTH: "health",
    ID_INIT: "init",
}

_RESERVED_BY_NAME = {name: num for num, name in RESERVED_IDENTITIES.items()}


def reserved_identity_labels(num: int) -> LabelArray:
    name = RESERVED_IDENTITIES[num]
    return LabelArray([Label(source="reserved", key=name)])


def lookup_reserved(name: str) -> Optional[int]:
    return _RESERVED_BY_NAME.get(name)


@dataclasses.dataclass(frozen=True)
class Identity:
    """A numeric security identity bound to its canonical labels."""

    id: int
    labels: LabelArray

    @property
    def is_reserved(self) -> bool:
        return self.id in RESERVED_IDENTITIES

    @property
    def is_local(self) -> bool:
        return self.id >= LOCAL_IDENTITY_BASE

    def __str__(self) -> str:
        return f"Identity<{self.id}: {self.labels.sorted_key()}>"
