"""Identity registry: allocation + dense device-row management.

Host-side authority for identity↔labels (reference:
pkg/identity/allocator.go local cache + kvstore allocation; here the
kvstore-backed global allocator plugs in via
cilium_tpu.kvstore.allocator, and this registry is the local cache).

TPU-first: identities are sparse integers but device tensors are dense,
so the registry assigns every identity a stable *row* and bumps a
``version`` on any change so compiled policy tensors know to refresh.
``dense_view()`` repacks the full [rows, words] bitmap matrix on each
call (O(identities × labels) host work) — callers gate it behind the
version check, and incremental row updates are a planned optimization.
Rows are padded to ``row_bucket`` so recompiles hit shape-bucketed XLA
caches instead of a fresh trace per identity.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..labels import LabelArray, LabelVocab
from .model import (
    Identity,
    LOCAL_IDENTITY_BASE,
    MAX_USER_IDENTITY,
    MIN_USER_IDENTITY,
    RESERVED_IDENTITIES,
    reserved_identity_labels,
)


class IdentityRegistry:
    def __init__(self, vocab: Optional[LabelVocab] = None, row_bucket: int = 256):
        self.vocab = vocab or LabelVocab()
        self.row_bucket = row_bucket
        self._lock = threading.RLock()
        self._by_id: Dict[int, Identity] = {}
        self._by_labels: Dict[LabelArray, Identity] = {}
        self._refcount: Dict[int, int] = {}
        self._row_of: Dict[int, int] = {}
        self._id_of_row: List[int] = []
        self._next_user = MIN_USER_IDENTITY
        self._next_local = LOCAL_IDENTITY_BASE
        self.version = 0
        self._observers: List[Callable[[Identity, bool], None]] = []
        for num in RESERVED_IDENTITIES:
            self._insert(Identity(num, reserved_identity_labels(num)))

    # ------------------------------------------------------------------
    def _insert(self, ident: Identity) -> None:
        self._by_id[ident.id] = ident
        self._by_labels[ident.labels] = ident
        self._refcount[ident.id] = self._refcount.get(ident.id, 0) + 1
        if ident.id not in self._row_of:
            self._row_of[ident.id] = len(self._id_of_row)
            self._id_of_row.append(ident.id)
        self.version += 1
        # ordering invariant: observers must see add/remove events in
        # `version` order — delivered outside the lock, a racing
        # allocate/release pair could invert add-then-remove for the
        # same identity and corrupt row-mapping consumers. Observers
        # are contractually non-blocking and lock-free (engine appends
        # to a pending list; prefixmap diffs two sets).
        for obs in self._observers:
            obs(ident, True)  # policyd-lint: disable=LOCK003

    def observe(self, fn: Callable[[Identity, bool], None]) -> None:
        """Register a change observer fn(identity, added)."""
        self._observers.append(fn)

    def allocate(self, labels: LabelArray, *, local: bool = False) -> Identity:
        """Allocate (or ref) the identity for a canonical label set.

        Reference: AllocateIdentity (pkg/identity/allocator.go:122) —
        same labels always yield the same identity. ``local=True`` draws
        from the node-local range (CIDR identities).
        """
        with self._lock:
            existing = self._by_labels.get(labels)
            if existing is not None:
                self._refcount[existing.id] += 1
                return existing
            if local:
                num = self._next_local
                self._next_local += 1
            else:
                num = self._next_user
                if num > MAX_USER_IDENTITY:
                    raise RuntimeError("user identity space exhausted")
                self._next_user += 1
            ident = Identity(num, labels)
            self._insert(ident)
            return ident

    def insert_global(self, num: int, labels: LabelArray) -> Identity:
        """Insert (or ref) an identity under a *pre-assigned* global
        number — the path taken when the kvstore allocator (local CAS
        win or a remote node's allocation seen via watch) decides the
        number instead of this registry. Keeps the local user-range
        cursor ahead of every global number so a later local
        ``allocate`` can never collide."""
        with self._lock:
            existing = self._by_id.get(num)
            if existing is not None:
                if existing.labels != labels:
                    raise ValueError(
                        f"identity {num} already bound to different labels"
                    )
                self._refcount[num] += 1
                return existing
            # Same labels under a different number is a split-brain
            # signal; surface it to the caller, who decides (the watch
            # pumps skip the event, keeping the existing binding).
            stale = self._by_labels.get(labels)
            if stale is not None and stale.id != num:
                raise ValueError(
                    f"labels already bound to identity {stale.id}, got {num}"
                )
            ident = Identity(num, labels)
            if MIN_USER_IDENTITY <= num <= MAX_USER_IDENTITY:
                self._next_user = max(self._next_user, num + 1)
            self._insert(ident)
            return ident

    def release_by_id(self, num: int) -> bool:
        """Release one reference of identity ``num`` (remote-deletion
        path of the kvstore watch). True when freed."""
        with self._lock:
            ident = self._by_id.get(num)
            if ident is None:
                return False
            return self.release(ident)

    def release(self, ident: Identity) -> bool:
        """Unref; True when the identity was freed. Freed identities keep
        their row (tombstoned) so device tensors never reshuffle rows."""
        with self._lock:
            rc = self._refcount.get(ident.id, 0)
            if rc <= 0:
                return False
            rc -= 1
            self._refcount[ident.id] = rc
            if rc == 0 and ident.id not in RESERVED_IDENTITIES:
                self._by_id.pop(ident.id, None)
                self._by_labels.pop(ident.labels, None)
                self.version += 1
                # same ordering invariant as _insert: in-order,
                # non-blocking observer delivery under the lock
                for obs in self._observers:
                    obs(ident, False)  # policyd-lint: disable=LOCK003
                return True
            return False

    # -- lookups -------------------------------------------------------
    def get(self, num: int) -> Optional[Identity]:
        return self._by_id.get(num)

    def lookup_by_labels(self, labels: LabelArray) -> Optional[Identity]:
        return self._by_labels.get(labels)

    def __iter__(self) -> Iterator[Identity]:
        return iter(list(self._by_id.values()))

    def __len__(self) -> int:
        return len(self._by_id)

    # -- dense device view ---------------------------------------------
    def row(self, num: int) -> Optional[int]:
        return self._row_of.get(num)

    @property
    def num_rows(self) -> int:
        return len(self._id_of_row)

    def padded_rows(self) -> int:
        b = self.row_bucket
        return max(b, ((self.num_rows + b - 1) // b) * b)

    def dense_view(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(bitmaps [R, W] uint32, ids [R] int32, live [R] bool) padded to
        the row bucket. Dead/tombstoned rows have zero bitmaps and
        live=False so device kernels naturally never match them."""
        with self._lock:
            rows = self.padded_rows()
            # Intern every identity's bits BEFORE sizing the word array —
            # interning grows the vocab.
            row_bits = {}
            for r, num in enumerate(self._id_of_row):
                ident = self._by_id.get(num)
                if ident is not None:
                    row_bits[r] = self.vocab.identity_bits(ident.labels)
            words = self.vocab.num_words
            bitmaps = np.zeros((rows, words), dtype=np.uint32)
            ids = np.zeros(rows, dtype=np.int32)
            live = np.zeros(rows, dtype=bool)
            for r, num in enumerate(self._id_of_row):
                ids[r] = num
                if r in row_bits:
                    bitmaps[r] = self.vocab.pack(row_bits[r], words)
                    live[r] = True
            return bitmaps, ids, live
