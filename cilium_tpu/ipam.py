"""Host-scope IP address management.

Reference: pkg/ipam (allocator.go): a per-node allocator over the
node's pod CIDR — AllocateNext for fresh IPs, Allocate for explicit
ones (restore), Release. The network+broadcast and router addresses
are reserved like the reference does.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Dict, Optional, Set


class IPAMError(ValueError):
    """ValueError subclass so the REST layer maps it to a 400."""


class IPAM:
    def __init__(self, cidr: str, reserve_base: int = 2) -> None:
        """``reserve_base``: how many leading addresses to skip
        (network addr + router IP, pkg/ipam reserveLocalRoutes)."""
        self.net = ipaddress.ip_network(cidr, strict=False)
        self._lock = threading.Lock()
        self._used: Dict[str, str] = {}  # ip → owner
        self._next = reserve_base
        self._released: Set[int] = set()
        self.reserve_base = reserve_base

    @property
    def capacity(self) -> int:
        total = self.net.num_addresses - self.reserve_base
        if self.net.version == 4 and self.net.prefixlen < 31:
            total -= 1  # broadcast
        return max(0, total)

    def allocate_next(self, owner: str = "") -> str:
        """AllocateNext: lowest free address (released ones reused
        first, keeping churn compact)."""
        with self._lock:
            if self._released:
                off = min(self._released)
                self._released.discard(off)
                ip = str(self.net.network_address + off)
                self._used[ip] = owner
                return ip
            while self._next < self.net.num_addresses:
                off = self._next
                self._next += 1
                addr = self.net.network_address + off
                if (
                    self.net.version == 4
                    and self.net.prefixlen < 31
                    and addr == self.net.broadcast_address
                ):
                    continue
                ip = str(addr)
                if ip not in self._used:
                    self._used[ip] = owner
                    return ip
            raise IPAMError(f"pool {self.net} exhausted")

    def allocate(self, ip: str, owner: str = "") -> str:
        """Explicit allocation (endpoint restore path)."""
        addr = ipaddress.ip_address(ip)
        if addr not in self.net:
            raise IPAMError(f"{ip} outside pool {self.net}")
        key = str(addr)
        with self._lock:
            if key in self._used:
                raise IPAMError(f"{ip} already allocated")
            self._used[key] = owner
            self._released.discard(int(addr) - int(self.net.network_address))
            return key

    def release(self, ip: str) -> bool:
        key = str(ipaddress.ip_address(ip))
        with self._lock:
            if self._used.pop(key, None) is None:
                return False
            off = int(ipaddress.ip_address(key)) - int(self.net.network_address)
            if off >= self.reserve_base:
                self._released.add(off)
            return True

    def owner_of(self, ip: str) -> Optional[str]:
        with self._lock:
            return self._used.get(str(ipaddress.ip_address(ip)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._used)
