"""IP → identity cache (reference: pkg/ipcache)."""

from .ipcache import Entry, IPCache, SOURCE_AGENT, SOURCE_K8S, SOURCE_KVSTORE
from .prefilter import PreFilter

__all__ = [
    "Entry",
    "IPCache",
    "PreFilter",
    "SOURCE_AGENT",
    "SOURCE_K8S",
    "SOURCE_KVSTORE",
]
