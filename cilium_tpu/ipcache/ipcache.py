"""Authoritative userspace IP/CIDR → identity map.

Reference: pkg/ipcache/ipcache.go — `Upsert` with source-priority
overwrite rules (:183,217), `Delete` (:429), lookups by prefix and by
identity (:438-493), and listener fan-out (`IPIdentityMappingListener`,
listener.go) that keeps derived state (the datapath LPM tensors here;
the BPF ipcache map + Envoy NPHDS in the reference) in sync.

The device view: the datapath pipeline rebuilds its LPM tries
(ops/lpm.py — wide 16-bit-stride for IPv4, shared-prefix-elided
stride-8 for IPv6) from ``items()`` whenever ``version`` moves,
mapping prefixes to identity *rows*.
"""

from __future__ import annotations

import dataclasses
import ipaddress
import threading
from typing import Callable, Dict, List, Optional, Tuple


# Source priorities (ipcache.go allowOverwrite: agent-local knowledge
# beats the kvstore, which beats k8s-derived, which beats generated).
SOURCE_AGENT = "agent"
SOURCE_KVSTORE = "kvstore"
SOURCE_K8S = "k8s"
SOURCE_GENERATED = "generated"
_PRIORITY = {SOURCE_AGENT: 3, SOURCE_KVSTORE: 2, SOURCE_K8S: 1, SOURCE_GENERATED: 0}


@dataclasses.dataclass(frozen=True)
class Entry:
    identity: int
    source: str
    host_ip: Optional[str] = None  # tunnel endpoint for remote entries


# fn(cidr, old_entry_or_None, new_entry_or_None)
Listener = Callable[[str, Optional[Entry], Optional[Entry]], None]


class IPCache:
    # Bounded outward delta ring (the engine DELTA_LOG_CAP pattern):
    # consumed by the datapath pipeline's O(delta) trie patching.
    DELTA_LOG_CAP = 512

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._by_prefix: Dict[str, Entry] = {}
        self._by_identity: Dict[int, set] = {}
        self._listeners: List[Listener] = []
        self.version = 0
        # (version, cidr, old_identity|None, new_identity|None) —
        # appended under the lock by upsert/delete, oldest dropped past
        # the cap
        self._delta_log: List[Tuple[int, str, Optional[int], Optional[int]]] = []

    def _log_delta(
        self, key: str, old: Optional[int], new: Optional[int]
    ) -> None:
        self._delta_log.append((self.version, key, old, new))
        if len(self._delta_log) > self.DELTA_LOG_CAP:
            del self._delta_log[: len(self._delta_log) - self.DELTA_LOG_CAP]

    def deltas_since(self, version: int):
        """Map updates with version > ``version`` (oldest first), or
        None when the ring has been truncated past that point — the
        consumer must rebuild its derived state from ``items()``
        (engine.deltas_since semantics)."""
        with self._lock:
            if version >= self.version:
                return []
            if self._delta_log and self._delta_log[0][0] > version + 1:
                return None
            if not self._delta_log and self.version > version:
                return None
            return [e for e in self._delta_log if e[0] > version]

    # ------------------------------------------------------------------
    def _norm(self, cidr: str) -> str:
        if "/" not in cidr:
            ip = ipaddress.ip_address(cidr)
            cidr = f"{ip}/{32 if ip.version == 4 else 128}"
        return str(ipaddress.ip_network(cidr, strict=False))

    def add_listener(self, fn: Listener, replay: bool = True) -> None:
        """SetListeners (listener fan-out); replay synthesizes the
        current state like the reference's initial dump."""
        with self._lock:
            self._listeners.append(fn)
            if replay:
                for cidr, e in self._by_prefix.items():
                    fn(cidr, None, e)

    def remove_listener(self, fn: Listener) -> bool:
        """Detach a listener (cluster leave must stop announcements)."""
        with self._lock:
            try:
                self._listeners.remove(fn)
                return True
            except ValueError:
                return False

    def upsert(
        self,
        cidr: str,
        identity: int,
        source: str,
        host_ip: Optional[str] = None,
    ) -> bool:
        """Returns False when a higher-priority source owns the entry
        (ipcache.go:183 allowOverwrite)."""
        key = self._norm(cidr)
        new = Entry(identity, source, host_ip)
        # Listener fan-out happens under the lock so derived state sees
        # events in map-update order (the reference holds the ipcache
        # mutex across IPIdentityMappingListener callbacks).
        with self._lock:
            old = self._by_prefix.get(key)
            if old is not None and _PRIORITY[old.source] > _PRIORITY[source]:
                return False
            self._by_prefix[key] = new
            if old is not None:
                s = self._by_identity.get(old.identity)
                if s:
                    s.discard(key)
            self._by_identity.setdefault(identity, set()).add(key)
            self.version += 1
            self._log_delta(key, old.identity if old else None, identity)
            for fn in self._listeners:
                fn(key, old, new)
        return True

    def delete(self, cidr: str, source: str) -> bool:
        key = self._norm(cidr)
        with self._lock:
            old = self._by_prefix.get(key)
            if old is None or _PRIORITY[old.source] > _PRIORITY[source]:
                return False
            del self._by_prefix[key]
            s = self._by_identity.get(old.identity)
            if s:
                s.discard(key)
            self.version += 1
            self._log_delta(key, old.identity, None)
            for fn in self._listeners:
                fn(key, old, None)
        return True

    # -- lookups --------------------------------------------------------
    def lookup_exact(self, cidr: str) -> Optional[Entry]:
        return self._by_prefix.get(self._norm(cidr))

    def lookup_by_ip(self, ip: str) -> Optional[Entry]:
        """Host-side LPM walk (the datapath does this on device)."""
        addr = ipaddress.ip_address(ip)
        max_len = 32 if addr.version == 4 else 128
        with self._lock:
            for plen in range(max_len, -1, -1):
                net = ipaddress.ip_network(f"{ip}/{plen}", strict=False)
                e = self._by_prefix.get(str(net))
                if e is not None:
                    return e
        return None

    def prefixes_for_identity(self, identity: int) -> List[str]:
        with self._lock:
            return sorted(self._by_identity.get(identity, ()))

    def __len__(self) -> int:
        return len(self._by_prefix)

    def items(self) -> List[Tuple[str, Entry]]:
        with self._lock:
            return list(self._by_prefix.items())

