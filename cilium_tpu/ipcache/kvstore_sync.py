"""ip→identity kvstore synchronization.

Re-design of /root/reference/pkg/ipcache/kvstore.go: each node
announces its endpoints' {IP → identity, hostIP} under
``cilium/state/ip/v1/<cluster>/…`` (lease-bound), and every node's
IPIdentityWatcher merges the global view into its local IPCache with
source=kvstore — which in this framework triggers the identity-LPM
trie rebuild in the datapath pipeline (ipcache listeners → version
bump → DatapathPipeline.rebuild).
"""

from __future__ import annotations

import json
from typing import Optional

from ..kvstore.backend import (
    BackendOperations,
    EventTypeDelete,
    EventTypeListDone,
    Watcher,
)
from .ipcache import IPCache, SOURCE_KVSTORE

from ..kvstore.paths import IP_IDENTITIES_PATH


class IPIdentitySync:
    """One node's announce + watch loop on the ip→identity prefix."""

    def __init__(
        self,
        backend: BackendOperations,
        ipcache: IPCache,
        *,
        cluster: str = "default",
        base_path: str = IP_IDENTITIES_PATH,
    ) -> None:
        self.backend = backend
        self.ipcache = ipcache
        self.prefix = f"{base_path}/{cluster}/"
        self._watcher: Watcher = backend.list_and_watch(
            f"ipcache-{cluster}", self.prefix
        )
        # cidr → payload of every local announcement, for lease-loss
        # resync (the periodic kvstore sync of ipcache/kvstore.go)
        self._announced: dict = {}
        self.pump()

    # ------------------------------------------------------------------
    def _key(self, cidr: str) -> str:
        return self.prefix + cidr

    def announce(
        self, cidr: str, identity: int, host_ip: Optional[str] = None
    ) -> None:
        """Publish a local ip→identity mapping (lease-bound: dies with
        this node, the upsertToKVStore path of ipcache/kvstore.go)."""
        cidr = self.ipcache._norm(cidr)
        payload = {"ip": cidr, "identity": identity}
        if host_ip is not None:
            payload["host_ip"] = host_ip
        self.backend.update(
            self._key(cidr), json.dumps(payload, sort_keys=True).encode(), lease=True
        )
        self._announced[cidr] = payload

    def withdraw_all(self) -> int:
        """Withdraw every announcement this node made (cluster leave —
        relying on lease expiry would leave peers routing to the
        departed node for a full TTL)."""
        cidrs = list(self._announced)
        for cidr in cidrs:
            self.withdraw(cidr)
        return len(cidrs)

    def withdraw(self, cidr: str) -> None:
        cidr = self.ipcache._norm(cidr)
        self.backend.delete(self._key(cidr))
        self._announced.pop(cidr, None)

    def resync(self) -> int:
        """Re-publish every local announcement (anti-entropy after a
        lease loss wiped our lease-bound keys). Returns keys written."""
        for cidr, payload in self._announced.items():
            self.backend.update(
                self._key(cidr), json.dumps(payload, sort_keys=True).encode(),
                lease=True,
            )
        return len(self._announced)

    def pump(self) -> int:
        """Merge pending watch events into the local IPCache
        (InitIPIdentityWatcher loop). Returns events applied."""
        n = 0
        for ev in self._watcher.drain():
            n += 1
            if ev.typ == EventTypeListDone:
                continue
            cidr = ev.key[len(self.prefix):]
            if ev.typ == EventTypeDelete:
                self.ipcache.delete(cidr, SOURCE_KVSTORE)
            else:
                try:
                    payload = json.loads((ev.value or b"{}").decode())
                except ValueError:
                    continue
                self.ipcache.upsert(
                    cidr,
                    int(payload.get("identity", 0)),
                    source=SOURCE_KVSTORE,
                    host_ip=payload.get("host_ip"),
                )
        return n

    def close(self) -> None:
        self.backend.stop_watcher(self._watcher)
