"""XDP prefilter equivalent: revision-guarded CIDR deny sets.

Reference: pkg/policy/prefilter.go — four CIDR maps (v4/v6 ×
dynamic/fixed; :49) updated under a revision counter (:125,162), and
bpf/bpf_xdp.c check_v4/check_v6 (:97-156): LPM deny lookup then exact
deny lookup on the source address, earliest-possible drop.

Here both dyn (prefix) and fix (exact /32 //128) sets live in one
stride-8 trie per family (exact addresses are just max-length
prefixes); the datapath pipeline consults it before the identity
lookup, mirroring the XDP hook position.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Iterable, List, Tuple


class PreFilter:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._revision = 1
        self._dyn: set = set()  # prefix strings
        self._fix: set = set()  # exact address strings

    @property
    def revision(self) -> int:
        return self._revision

    def _split(self, cidrs: Iterable[str]) -> Tuple[List[str], List[str]]:
        dyn, fix = [], []
        for c in cidrs:
            net = ipaddress.ip_network(c, strict=False)
            full = 32 if net.version == 4 else 128
            (fix if net.prefixlen == full else dyn).append(str(net))
        return dyn, fix

    def insert(self, revision: int, cidrs: Iterable[str]) -> int:
        """Revision-guarded add (prefilter.go:125): the caller echoes the
        revision it last observed; a mismatch means a concurrent update
        won and the caller must re-read."""
        with self._lock:
            if revision != self._revision:
                raise ValueError(f"stale prefilter revision {revision} != {self._revision}")
            dyn, fix = self._split(cidrs)
            self._dyn.update(dyn)
            self._fix.update(fix)
            self._revision += 1
            return self._revision

    def delete(self, revision: int, cidrs: Iterable[str]) -> int:
        with self._lock:
            if revision != self._revision:
                raise ValueError(f"stale prefilter revision {revision} != {self._revision}")
            dyn, fix = self._split(cidrs)
            for c in dyn:
                self._dyn.discard(c)
            for c in fix:
                self._fix.discard(c)
            self._revision += 1
            return self._revision

    def dump(self) -> Tuple[int, List[str]]:
        with self._lock:
            return self._revision, sorted(self._dyn | self._fix)

