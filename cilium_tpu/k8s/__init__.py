"""Kubernetes orchestrator integration (SURVEY §1 layer 9).

Translates k8s objects — NetworkPolicy, CiliumNetworkPolicy, Service,
Endpoints, Pod — into the framework's native models. Reference:
pkg/k8s/ (network_policy.go, rule_translate.go,
apis/cilium.io/utils/utils.go) and daemon/k8s_watcher.go.
"""

from .cnp import parse_cilium_rule, parse_cnp
from .constants import policy_labels
from .network_policy import parse_network_policy
from .pods import PodOrchestrator, pod_labels
from .rule_translate import RuleTranslator, preprocess_rules
from .service_registry import (
    ServiceEndpoint,
    ServiceID,
    ServiceInfo,
    ServicePort,
    ServiceRegistry,
)
from .watcher import K8sWatcher, load_objects, objects_to_rules

__all__ = [
    "K8sWatcher",
    "PodOrchestrator",
    "RuleTranslator",
    "ServiceEndpoint",
    "ServiceID",
    "ServiceInfo",
    "ServicePort",
    "ServiceRegistry",
    "load_objects",
    "objects_to_rules",
    "parse_cilium_rule",
    "parse_cnp",
    "parse_network_policy",
    "pod_labels",
    "policy_labels",
    "preprocess_rules",
]
