"""k8s apiserver client: LIST + WATCH over the real HTTP wire protocol.

Reference: pkg/k8s/client.go + daemon/k8s_watcher.go:340 — the agent
connects to the apiserver, LISTs each resource kind, then WATCHes from
the returned resourceVersion, dispatching ADDED/MODIFIED/DELETED
events; on a dropped or expired watch (410 Gone) it re-LISTs and
reconciles (the client-go reflector/informer contract).

This client speaks that exact protocol over HTTP(S):

    GET  {base}/{prefix}?limit=...            → {"items": [...],
                                                  "metadata": {"resourceVersion": rv}}
    GET  {base}/{prefix}?watch=1&resourceVersion=rv
         → newline-delimited JSON: {"type": "ADDED|MODIFIED|DELETED",
                                     "object": {...}}

and drives a K8sWatcher: list results go through ``watcher.resync``
(healing deletes missed while disconnected), watch events through
``apply``/``delete``. Authentication is a bearer token header (the
in-cluster ServiceAccount pattern); TLS is the caller's http layer.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils.backoff import Backoff
from ..utils.logging import get_logger

log = get_logger("k8s-client")

# resource kind → collection path (all-namespaces LIST/WATCH form)
RESOURCES: Dict[str, str] = {
    "NetworkPolicy": "apis/networking.k8s.io/v1/networkpolicies",
    "CiliumNetworkPolicy": "apis/cilium.io/v2/ciliumnetworkpolicies",
    "Service": "api/v1/services",
    "Endpoints": "api/v1/endpoints",
    "Pod": "api/v1/pods",
    "Namespace": "api/v1/namespaces",
    "Ingress": "apis/extensions/v1beta1/ingresses",
    "Node": "api/v1/nodes",
}

# kind → (group-version path, plural, namespaced) for per-OBJECT writes
# (status subresources, annotation patches — collection paths above
# cannot address a single namespaced object)
_OBJECT_PATHS: Dict[str, Tuple[str, str, bool]] = {
    "CiliumNetworkPolicy": ("apis/cilium.io/v2", "ciliumnetworkpolicies", True),
    "Ingress": ("apis/extensions/v1beta1", "ingresses", True),
    "Node": ("api/v1", "nodes", False),
    "Pod": ("api/v1", "pods", True),
}

# The CNP CustomResourceDefinition the reference registers at startup
# (pkg/k8s/apis/cilium.io/v2/register.go createCustomResourceDefinitions)
CNP_CRD: Dict = {
    "apiVersion": "apiextensions.k8s.io/v1beta1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": "ciliumnetworkpolicies.cilium.io"},
    "spec": {
        "group": "cilium.io",
        "version": "v2",
        "scope": "Namespaced",
        "names": {
            "plural": "ciliumnetworkpolicies",
            "singular": "ciliumnetworkpolicy",
            "kind": "CiliumNetworkPolicy",
            "shortNames": ["cnp", "ciliumnp"],
        },
        "subresources": {"status": {}},
    },
}


class APIServerClient:
    """Minimal list/watch client over one apiserver base URL."""

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        timeout: float = 10.0,
        watch_read_timeout: float = 60.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        # a watch stream with no traffic for this long is treated as a
        # dead connection (half-open TCP after a partition would
        # otherwise block the watch thread forever); real apiservers
        # are additionally asked to end the watch server-side first
        # via timeoutSeconds, so a healthy-but-idle watch ends cleanly
        self.watch_read_timeout = watch_read_timeout

    def _open(self, path: str, query: Dict[str, str], stream: bool = False):
        url = f"{self.base_url}/{path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        # stream sockets get slack past timeoutSeconds so a healthy
        # server ends the watch before the client's deadline fires
        return urllib.request.urlopen(
            req,
            timeout=self.watch_read_timeout * 1.5 + 1.0
            if stream
            else self.timeout,
        )

    # -- writes ---------------------------------------------------------
    def _object_path(self, kind: str, namespace: str, name: str) -> str:
        gv, plural, namespaced = _OBJECT_PATHS[kind]
        if namespaced:
            return f"{gv}/namespaces/{namespace or 'default'}/{plural}/{name}"
        return f"{gv}/{plural}/{name}"

    def _request(
        self, method: str, path: str, body: Optional[Dict] = None,
        content_type: str = "application/json",
    ) -> Dict:
        url = f"{self.base_url}/{path}"
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
        return json.loads(raw.decode()) if raw else {}

    def update_status(
        self, kind: str, namespace: str, name: str, obj: Dict
    ) -> Dict:
        """PUT the object's /status subresource (the CNP per-node
        status ack and Ingress loadBalancer status writeback paths —
        daemon/k8s_watcher.go:1240 UpdateStatus)."""
        path = self._object_path(kind, namespace, name) + "/status"
        return self._request("PUT", path, obj)

    def patch_annotations(
        self, kind: str, namespace: str, name: str, annotations: Dict[str, str]
    ) -> Dict:
        """Merge-patch metadata.annotations (pkg/k8s/client.go
        AnnotateNode — CIDR/health-IP writeback)."""
        path = self._object_path(kind, namespace, name)
        return self._request(
            "PATCH", path,
            {"metadata": {"annotations": dict(annotations)}},
            content_type="application/merge-patch+json",
        )

    def ensure_cnp_crd(self) -> bool:
        """Register the CNP CRD if the apiserver doesn't have it yet
        (pkg/k8s/apis/cilium.io/v2/register.go). → True if it exists
        or was created."""
        base = "apis/apiextensions.k8s.io/v1beta1/customresourcedefinitions"
        try:
            self._request("GET", f"{base}/{CNP_CRD['metadata']['name']}")
            return True
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        try:
            self._request("POST", base, CNP_CRD)
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:  # someone else registered it concurrently
                return True
            raise

    def list(self, kind: str) -> Tuple[List[Dict], str]:
        """LIST one kind → (objects with kind injected, resourceVersion)."""
        prefix = RESOURCES[kind]
        with self._open(prefix, {}) as resp:
            data = json.loads(resp.read().decode())
        items = data.get("items") or []
        for obj in items:
            obj.setdefault("kind", kind)
        rv = str((data.get("metadata") or {}).get("resourceVersion", "0"))
        return items, rv

    def watch(self, kind: str, resource_version: str, stop: threading.Event):
        """WATCH one kind from ``resource_version`` — yields
        (event_type, object) until the stream ends, ``stop`` is set, or
        the server expires the version (raises WatchExpired → caller
        re-LISTs)."""
        prefix = RESOURCES[kind]
        try:
            resp = self._open(
                prefix,
                {
                    "watch": "1",
                    "resourceVersion": resource_version,
                    # ask the server to end the watch before our socket
                    # deadline so an idle-but-healthy stream terminates
                    # cleanly rather than tripping the read timeout
                    "timeoutSeconds": str(int(self.watch_read_timeout)),
                },
                stream=True,
            )
        except urllib.error.HTTPError as e:
            if e.code == 410:  # Gone: re-list required
                raise WatchExpired(kind) from None
            raise
        with resp:
            buf = b""
            while not stop.is_set():
                try:
                    chunk = resp.read1(65536)
                except TimeoutError:
                    # no bytes within the deadline: connection presumed
                    # half-open — end the stream; the caller reconnects
                    # from the tracked rv (no re-list needed)
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    if evt.get("type") == "ERROR":
                        status = evt.get("object") or {}
                        if status.get("code") == 410:
                            raise WatchExpired(kind)
                        raise RuntimeError(f"watch error: {status}")
                    obj = evt.get("object") or {}
                    obj.setdefault("kind", kind)
                    yield evt.get("type", ""), obj


class WatchExpired(Exception):
    """The watch resourceVersion is too old — re-LIST and reconcile."""


class Informer:
    """The reflector/informer loop: LIST → resync → WATCH → events,
    with reconnect + re-list on any failure (daemon/k8s_watcher.go:340
    wires the same handlers through client-go informers)."""

    def __init__(
        self,
        client: APIServerClient,
        watcher,  # K8sWatcher
        kinds: Optional[Iterable[str]] = None,
        relist_backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
    ) -> None:
        self.client = client
        self.watcher = watcher
        self.kinds = list(kinds or RESOURCES)
        self.relist_backoff_s = relist_backoff_s
        self.max_backoff_s = max_backoff_s
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._synced = threading.Event()
        self._relist_mu = threading.Lock()
        self._relist_gen = 0  # bumps on every completed re-list
        self._last_versions: Dict[str, str] = {}
        self.relists = 0  # observability: how many re-list cycles ran

    def _backoff(self) -> Backoff:
        return Backoff(min_s=self.relist_backoff_s, max_s=self.max_backoff_s)

    # -- one full LIST across kinds → one resync --------------------------
    def _list_all(self) -> Dict[str, str]:
        objects: List[Dict] = []
        versions: Dict[str, str] = {}
        for kind in self.kinds:
            items, rv = self.client.list(kind)
            objects.extend(items)
            versions[kind] = rv
        # ONE reconciliation over the combined snapshot: adds applied,
        # absent objects deleted (watcher.resync heals both)
        self.watcher.resync(objects)
        return versions

    def _watch_kind(self, kind: str, rv: str) -> None:
        backoff = self._backoff()
        while not self._stop.is_set():
            clean_end = False
            try:
                for etype, obj in self.client.watch(kind, rv, self._stop):
                    rv = str(
                        (obj.get("metadata") or {}).get("resourceVersion", rv)
                    )
                    try:
                        if etype in ("ADDED", "MODIFIED"):
                            self.watcher.apply(obj)
                        elif etype == "DELETED":
                            self.watcher.delete(obj)
                    except Exception as e:
                        # one malformed object must not kill the stream
                        log.warning("event apply failed", fields={
                            "kind": kind, "type": etype,
                            "err": f"{type(e).__name__}: {e}",
                        })
                clean_end = True
            except WatchExpired:
                log.info("watch expired; re-listing", fields={"kind": kind})
            except Exception as e:
                log.warning(
                    "watch failed; re-listing",
                    fields={"kind": kind, "err": f"{type(e).__name__}: {e}"},
                )
            if self._stop.is_set():
                return
            if clean_end:
                # apiservers time watches out by design: reconnect
                # from the tracked rv, no O(cluster) re-list needed
                backoff.reset()
                continue
            # failure path: ONE full re-list across all kinds (a
            # single combined resync needs no placeholder snapshots
            # and can't race partial views of other kinds). A re-list
            # that completed after THIS failure was observed — during
            # the backoff sleep or while queued on the mutex — already
            # reconciled every kind, so piggyback on its versions
            # instead of hammering the apiserver with N redundant full
            # re-lists when all watches drop at once.
            gen = self._relist_gen
            if backoff.wait(self._stop):
                return
            with self._relist_mu:
                if self._relist_gen != gen:
                    rv = self._last_versions.get(kind, rv)
                    backoff.reset()
                    continue
                try:
                    versions = self._list_all()
                    self._last_versions = versions
                    self._relist_gen += 1
                    rv = versions.get(kind, rv)
                    self.relists += 1
                    backoff.reset()
                except Exception as e:
                    log.warning(
                        "re-list failed",
                        fields={"err": f"{type(e).__name__}: {e}"},
                    )

    def start(self) -> "Informer":
        def boot():
            backoff = self._backoff()
            while not self._stop.is_set():
                try:
                    versions = self._list_all()
                    break
                except Exception as e:
                    log.warning(
                        "initial list failed; retrying",
                        fields={"err": f"{type(e).__name__}: {e}"},
                    )
                    if backoff.wait(self._stop):
                        return
            else:
                return
            self._synced.set()
            for kind in self.kinds:
                t = threading.Thread(
                    target=self._watch_kind,
                    args=(kind, versions.get(kind, "0")),
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

        t = threading.Thread(target=boot, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def wait_synced(self, timeout: float = 10.0) -> bool:
        """Block until the initial LIST landed (daemon/main.go:843-856
        waits for cache sync before regenerating restored endpoints)."""
        return self._synced.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
