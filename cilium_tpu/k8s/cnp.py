"""CiliumNetworkPolicy (cilium.io/v2) → policy Rule translation.

Reference: pkg/k8s/apis/cilium.io/v2/types.go (CiliumNetworkPolicy:
Spec + Specs), pkg/k8s/apis/cilium.io/utils/utils.go ParseToCiliumRule.

A CNP embeds native rules; translation only *scopes* them to the
namespace the object lives in:
- the endpoint selector gets ``k8s:io.kubernetes.pod.namespace=<ns>``
  injected (an explicit foreign-namespace match is illegal and is
  overridden, utils.go:201-212);
- every fromEndpoints/toEndpoints selector likewise, unless it already
  pins a namespace, matches on ``reserved:``-sourced labels, or the
  policy targets initializing pods (utils.go:60-84);
- fromRequires/toRequires get the namespace too but skip the
  reserved-prefix exemption (utils.go addK8sPrefix=false);
- provenance labels name the CNP so deletion can find the rules.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

from ..labels import parse_label_array
from ..policy.api import EndpointSelector, Rule
from ..policy.api.serialization import rule_from_dict
from .constants import (
    POD_ANY_PREFIX_LBL,
    POD_INIT_LBL,
    POD_PREFIX_LBL,
    SOURCE_RESERVED,
    extract_namespace,
    policy_labels,
)


def _namespace_valid(namespace: str, selector: EndpointSelector) -> bool:
    """An explicit namespace match is legal only when it names the
    namespace the policy lives in (utils.go namespacesAreValid)."""
    for key in (POD_PREFIX_LBL, POD_ANY_PREFIX_LBL):
        v = selector.get_match(key)
        if v is not None and v != namespace:
            return False
    return True


def _scope_selector(
    namespace: str,
    sel: EndpointSelector,
    skip_reserved: bool,
    matches_init: bool,
) -> EndpointSelector:
    """utils.go getEndpointSelector: inject the namespace match."""
    if skip_reserved and sel.has_key_prefix(f"{SOURCE_RESERVED}:"):
        return sel
    if matches_init:
        # Initializing pods carry no labels at all — adding a namespace
        # requirement would make the selector unmatchable (utils.go:74-79).
        return sel
    if sel.has_key(POD_PREFIX_LBL) or sel.has_key(POD_ANY_PREFIX_LBL):
        return sel
    return sel.with_match(POD_PREFIX_LBL, namespace)


def parse_cilium_rule(namespace: str, name: str, rule: Rule) -> Rule:
    """Namespace-scope one embedded rule (utils.go ParseToCiliumRule)."""
    subject = rule.endpoint_selector
    matches_init = subject.has_key(POD_INIT_LBL)
    if not matches_init:
        if not _namespace_valid(namespace, subject):
            # Illegal foreign-namespace match: the selector always
            # applies in the policy's own namespace (utils.go:202-211).
            subject = EndpointSelector(
                tuple(
                    (k, v)
                    for k, v in subject.match_labels
                    if k not in (POD_PREFIX_LBL, POD_ANY_PREFIX_LBL)
                ),
                subject.match_expressions,
            )
        subject = subject.with_match(POD_PREFIX_LBL, namespace)

    ingress = tuple(
        dataclasses.replace(
            ir,
            from_endpoints=tuple(
                _scope_selector(namespace, s, True, matches_init)
                for s in ir.from_endpoints
            ),
            from_requires=tuple(
                _scope_selector(namespace, s, False, matches_init)
                for s in ir.from_requires
            ),
        )
        for ir in rule.ingress
    )
    egress = tuple(
        dataclasses.replace(
            er,
            to_endpoints=tuple(
                _scope_selector(namespace, s, True, matches_init)
                for s in er.to_endpoints
            ),
            to_requires=tuple(
                _scope_selector(namespace, s, False, matches_init)
                for s in er.to_requires
            ),
        )
        for er in rule.egress
    )
    lbls = parse_label_array(
        policy_labels(namespace, name) + list(rule.labels.to_strings())
    )
    return dataclasses.replace(
        rule,
        endpoint_selector=subject,
        ingress=ingress,
        egress=egress,
        labels=lbls,
    )


def parse_cnp(obj: Dict[str, Any]) -> List[Rule]:
    """Translate one CiliumNetworkPolicy object (spec and/or specs,
    types.go:48-58). Returns the sanitized rule list."""
    meta = obj.get("metadata") or {}
    namespace = extract_namespace(meta)
    name = meta.get("name", "")
    specs: List[Dict[str, Any]] = []
    if obj.get("spec"):
        specs.append(obj["spec"])
    specs.extend(obj.get("specs") or ())
    if not specs:
        raise ValueError(f"CiliumNetworkPolicy {namespace}/{name} has no spec")
    out: List[Rule] = []
    for spec in specs:
        rule = parse_cilium_rule(namespace, name, rule_from_dict(spec))
        rule.sanitize()
        out.append(rule)
    return out
