"""Kubernetes integration constants.

Reference: pkg/k8s/apis/cilium.io/const.go and
pkg/k8s/apis/cilium.io/utils/utils.go (label keys used to scope
policies and selectors to namespaces).
"""

# Label every pod-backed endpoint carries: its namespace
# (const.go:43 PodNamespaceLabel).
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"

# Prefix under which the *namespace object's* labels are mirrored onto
# endpoints, so namespaceSelector can match them
# (const.go:40 PodNamespaceMetaLabels).
POD_NAMESPACE_META_LABELS = "io.cilium.k8s.namespace.labels"

# Derived-policy provenance labels (const.go:20,22) — attached to every
# translated rule so rules can be deleted when the k8s object goes away.
POLICY_LABEL_NAME = "io.cilium.k8s.policy.name"
POLICY_LABEL_NAMESPACE = "io.cilium.k8s.policy.namespace"
POLICY_LABEL_SERVICE_ACCOUNT = "io.cilium.k8s.policy.serviceaccount"

# Annotation carrying an override policy name (pkg/annotation Name).
ANNOTATION_NAME = "cilium.io/name"

# Label sources.
SOURCE_K8S = "k8s"
SOURCE_ANY = "any"
SOURCE_RESERVED = "reserved"

# Selector keys (utils.go:33-42).
POD_PREFIX_LBL = f"{SOURCE_K8S}:{POD_NAMESPACE_LABEL}"
POD_ANY_PREFIX_LBL = f"{SOURCE_ANY}:{POD_NAMESPACE_LABEL}"
POD_INIT_LBL = f"{SOURCE_RESERVED}:init"

DEFAULT_NAMESPACE = "default"


def extract_namespace(metadata: dict) -> str:
    """Namespace from an ObjectMeta dict, defaulting like
    pkg/k8s/utils ExtractNamespace."""
    return metadata.get("namespace") or DEFAULT_NAMESPACE


def policy_labels(namespace: str, name: str) -> list:
    """Provenance labels for a translated policy (utils.go GetPolicyLabels)."""
    return [
        f"{SOURCE_K8S}:{POLICY_LABEL_NAME}={name}",
        f"{SOURCE_K8S}:{POLICY_LABEL_NAMESPACE}={namespace}",
    ]
