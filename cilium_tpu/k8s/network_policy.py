"""k8s NetworkPolicy (networking.k8s.io/v1) → policy Rule translation.

Reference: pkg/k8s/network_policy.go ParseNetworkPolicy. Input is the
decoded object (a dict, from JSON or YAML) rather than a typed client
struct — this framework has no k8s client dependency; the watcher layer
feeds raw objects.

Semantics preserved:
- podSelector keys get the ``k8s:`` source prefix and the policy's
  namespace is injected as an extra matchLabel
  (network_policy.go:234-240);
- namespaceSelector keys are rewritten under the
  ``io.cilium.k8s.namespace.labels.`` prefix; an *empty*
  namespaceSelector becomes an Exists match on the pod-namespace label
  (selects all namespaces, network_policy.go:85-89);
- a peer podSelector is scoped to the policy's namespace
  (network_policy.go:98-101);
- empty ``from``/``to`` lists wildcard the peer
  (network_policy.go:156-165);
- the k8s default-deny idiom (empty ingress + policyTypes) becomes an
  empty IngressRule/EgressRule (network_policy.go:212-232).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..labels import parse_label_array
from ..policy.api import (
    CIDRRule,
    EgressRule,
    EndpointSelector,
    IngressRule,
    MatchExpression,
    PortProtocol,
    PortRule,
    Rule,
)
from ..policy.api.selector import EXISTS
from .constants import (
    ANNOTATION_NAME,
    POD_NAMESPACE_LABEL,
    POD_NAMESPACE_META_LABELS,
    extract_namespace,
    policy_labels,
)

POLICY_TYPE_INGRESS = "Ingress"
POLICY_TYPE_EGRESS = "Egress"


def _k8s_selector(
    label_selector: Optional[Dict[str, Any]],
    key_prefix: str = "k8s:",
    extra_labels: Optional[Dict[str, str]] = None,
) -> EndpointSelector:
    """Build an EndpointSelector from a k8s LabelSelector dict, with
    every key source-prefixed (api.NewESFromK8sLabelSelector)."""
    sel = label_selector or {}
    match: Dict[str, str] = {
        key_prefix + k: v for k, v in (sel.get("matchLabels") or {}).items()
    }
    for k, v in (extra_labels or {}).items():
        match[key_prefix + k] = v
    exprs: Tuple[MatchExpression, ...] = tuple(
        MatchExpression(
            key=key_prefix + e["key"],
            operator=e["operator"],
            values=tuple(e.get("values") or ()),
        )
        for e in sel.get("matchExpressions") or ()
    )
    return EndpointSelector.make(match, exprs)


def _parse_peer(namespace: str, peer: Dict[str, Any]) -> Optional[EndpointSelector]:
    """NetworkPolicyPeer → selector (network_policy.go:61-108);
    ipBlock handled separately by the caller."""
    ns_sel = peer.get("namespaceSelector")
    pod_sel = peer.get("podSelector")
    if ns_sel is not None:
        # Rewrite namespace-object keys under the meta-labels prefix.
        rewritten: Dict[str, Any] = {
            "matchLabels": {
                f"{POD_NAMESPACE_META_LABELS}.{k}": v
                for k, v in (ns_sel.get("matchLabels") or {}).items()
            },
            "matchExpressions": [
                dict(e, key=f"{POD_NAMESPACE_META_LABELS}.{e['key']}")
                for e in ns_sel.get("matchExpressions") or ()
            ],
        }
        if not rewritten["matchLabels"] and not rewritten["matchExpressions"]:
            # Empty namespaceSelector selects every namespace: the pod
            # namespace label must merely exist (network_policy.go:87-89).
            rewritten["matchExpressions"] = [
                {"key": POD_NAMESPACE_LABEL, "operator": EXISTS}
            ]
        combined = _k8s_selector(rewritten)
        if pod_sel is not None:
            pod_part = _k8s_selector(pod_sel)
            combined = EndpointSelector(
                tuple(sorted(set(combined.match_labels) | set(pod_part.match_labels))),
                combined.match_expressions + pod_part.match_expressions,
            )
        return combined
    if pod_sel is not None:
        # Peer pods are implicitly in the policy's own namespace.
        return _k8s_selector(pod_sel, extra_labels={POD_NAMESPACE_LABEL: namespace})
    return None


def _ip_block(block: Dict[str, Any]) -> CIDRRule:
    return CIDRRule(
        cidr=block["cidr"], except_cidrs=tuple(block.get("except") or ())
    )


def _parse_ports(ports: List[Dict[str, Any]]) -> Tuple[PortRule, ...]:
    """NetworkPolicyPort list → PortRules (network_policy.go:265-292).
    Named (string, non-numeric) ports need pod-spec knowledge this layer
    doesn't have; they are rejected at parse time rather than silently
    never matching."""
    out: List[PortRule] = []
    for port in ports:
        if port.get("protocol") is None and port.get("port") is None:
            continue
        proto = str(port.get("protocol") or "TCP").upper()
        raw = port.get("port", 0)
        try:
            num = int(raw or 0)
        except (TypeError, ValueError):
            raise ValueError(f"named port {raw!r} is not supported") from None
        out.append(PortRule(ports=(PortProtocol(port=num, protocol=proto),)))
    return tuple(out)


def parse_network_policy(obj: Dict[str, Any]) -> List[Rule]:
    """Translate one networking/v1 NetworkPolicy object. Returns the
    (sanitized) rule list to import (network_policy.go:122-251)."""
    if not obj:
        raise ValueError("cannot parse empty NetworkPolicy")
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    namespace = extract_namespace(meta)
    name = (meta.get("annotations") or {}).get(ANNOTATION_NAME) or meta.get("name", "")

    ingresses: List[IngressRule] = []
    for i_rule in spec.get("ingress") or ():
        to_ports = _parse_ports(i_rule.get("ports") or [])
        from_eps: List[EndpointSelector] = []
        from_cidr_set: List[CIDRRule] = []
        peers = i_rule.get("from") or []
        if peers:
            for peer in peers:
                sel = _parse_peer(namespace, peer)
                if sel is not None:
                    from_eps.append(sel)
                if peer.get("ipBlock"):
                    from_cidr_set.append(_ip_block(peer["ipBlock"]))
        else:
            # Empty/missing `from` matches all sources.
            from_eps.append(EndpointSelector.wildcard())
        ingresses.append(
            IngressRule(
                from_endpoints=tuple(from_eps),
                from_cidr_set=tuple(from_cidr_set),
                to_ports=to_ports,
            )
        )

    egresses: List[EgressRule] = []
    for e_rule in spec.get("egress") or ():
        to_eps: List[EndpointSelector] = []
        to_cidr_set: List[CIDRRule] = []
        peers = e_rule.get("to") or []
        if peers:
            for peer in peers:
                sel = _parse_peer(namespace, peer)
                if sel is not None:
                    to_eps.append(sel)
                if peer.get("ipBlock"):
                    to_cidr_set.append(_ip_block(peer["ipBlock"]))
        else:
            to_eps.append(EndpointSelector.wildcard())
        to_ports = _parse_ports(e_rule.get("ports") or [])
        if not to_ports and not peers:
            # Fully-empty egress rule wildcards the destination
            # (network_policy.go:196-207).
            to_eps = [EndpointSelector.wildcard()]
        egresses.append(
            EgressRule(
                to_endpoints=tuple(to_eps),
                to_cidr_set=tuple(to_cidr_set),
                to_ports=to_ports,
            )
        )

    # k8s default-deny idiom → empty (match-nothing-allowed) direction
    # rules, which flip the subject to default-deny without allowing
    # any peer (network_policy.go:212-232).
    policy_types = spec.get("policyTypes") or []
    if not ingresses and (
        POLICY_TYPE_INGRESS in policy_types or POLICY_TYPE_EGRESS not in policy_types
    ):
        ingresses = [IngressRule()]
    if not egresses and POLICY_TYPE_EGRESS in policy_types:
        egresses = [EgressRule()]

    subject = _k8s_selector(
        spec.get("podSelector") or {}, extra_labels={POD_NAMESPACE_LABEL: namespace}
    )
    rule = Rule(
        endpoint_selector=subject,
        ingress=tuple(ingresses),
        egress=tuple(egresses),
        labels=parse_label_array(policy_labels(namespace, name)),
        description=f"k8s NetworkPolicy {namespace}/{name}",
    )
    rule.sanitize()
    return [rule]
