"""Pod → endpoint translation (the CNI ADD/DEL shape).

Reference: plugins/cilium-cni/cilium-cni.go (endpoint creation from a
sandbox attach) and pkg/k8s/factory_functions.go + pkg/labels
(k8s-sourced security labels). The CNI plugin's job decomposes into:
derive the pod's security-relevant labels (own labels + namespace
label + mirrored namespace-object labels), pick addresses, and drive
Daemon.endpoint_add — which here replaces the agent's REST PUT
/endpoint/{id}.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .constants import (
    POD_NAMESPACE_LABEL,
    POD_NAMESPACE_META_LABELS,
    SOURCE_K8S,
    extract_namespace,
)


def pod_labels(
    pod: dict, namespace_labels: Optional[Dict[str, str]] = None
) -> List[str]:
    """Security labels for a pod object: every pod label under the
    ``k8s:`` source, the namespace label, and the namespace object's
    own labels mirrored under the meta prefix (so namespaceSelector
    policies can match them)."""
    meta = pod.get("metadata") or {}
    ns = extract_namespace(meta)
    out = [
        f"{SOURCE_K8S}:{k}={v}" for k, v in sorted((meta.get("labels") or {}).items())
    ]
    out.append(f"{SOURCE_K8S}:{POD_NAMESPACE_LABEL}={ns}")
    for k, v in sorted((namespace_labels or {}).items()):
        out.append(f"{SOURCE_K8S}:{POD_NAMESPACE_META_LABELS}.{k}={v}")
    sa = (pod.get("spec") or {}).get("serviceAccountName")
    if sa:
        out.append(f"{SOURCE_K8S}:io.cilium.k8s.policy.serviceaccount={sa}")
    return out


def pod_addresses(pod: dict) -> Dict[str, str]:
    """{"ipv4": ..., "ipv6": ...} from pod status."""
    status = pod.get("status") or {}
    ips = [e.get("ip") for e in status.get("podIPs") or () if e.get("ip")]
    if status.get("podIP"):
        ips.insert(0, status["podIP"])
    out: Dict[str, str] = {}
    for ip in ips:
        key = "ipv6" if ":" in ip else "ipv4"
        out.setdefault(key, ip)
    return out


class PodOrchestrator:
    """Applies pod add/delete events to a Daemon — the CNI-shaped
    endpoint lifecycle. Endpoint ids are allocated from the pod UID
    hash so re-adds are stable."""

    def __init__(self, daemon, namespace_labels: Optional[Dict[str, Dict[str, str]]] = None):
        self.daemon = daemon
        self.namespace_labels = namespace_labels or {}
        self._pod_to_ep: Dict[str, int] = {}
        self._next_id = 10000

    def pod_key(self, pod: dict) -> str:
        meta = pod.get("metadata") or {}
        return f"{extract_namespace(meta)}/{meta.get('name', '')}"

    def add_pod(self, pod: dict) -> int:
        key = self.pod_key(pod)
        if key in self._pod_to_ep:
            return self._pod_to_ep[key]
        ns = extract_namespace(pod.get("metadata") or {})
        lbls = pod_labels(pod, self.namespace_labels.get(ns))
        addrs = pod_addresses(pod)
        ep_id = self._next_id
        self._next_id += 1
        self.daemon.endpoint_add(
            ep_id,
            labels=lbls,
            ipv4=addrs.get("ipv4"),
            ipv6=addrs.get("ipv6"),
            pod_name=key,
        )
        self._pod_to_ep[key] = ep_id
        return ep_id

    def known_pods(self) -> List[Tuple[str, str]]:
        """(namespace, name) of every pod with a live endpoint — the
        resync reconciliation input."""
        return sorted(
            tuple(key.split("/", 1)) for key in self._pod_to_ep
        )

    def delete_pod(self, pod: dict) -> bool:
        ep_id = self._pod_to_ep.pop(self.pod_key(pod), None)
        if ep_id is None:
            return False
        return self.daemon.endpoint_delete(ep_id)
