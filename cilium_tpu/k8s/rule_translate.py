"""ToServices → ToCIDRSet rule translation.

Reference: pkg/k8s/rule_translate.go (RuleTranslator.Translate,
generateToCidrFromEndpoint, deleteToCidrFromEndpoint,
PreprocessRules). The reference mutates rules in place; rules here are
frozen dataclasses, so translation is pure — it returns a new Rule —
and the caller swaps it into the repository (one revision bump).

Generated entries carry ``CIDRRule.generated`` so a revert removes
exactly what translation added and nothing the user wrote.
"""

from __future__ import annotations

import dataclasses
import ipaddress
from typing import Dict, Iterable, Optional, Tuple

from ..policy.api import CIDRRule, EgressRule, Rule, ServiceSelector
from ..policy.api.rules import host_cidr as _host_cidr
from .service_registry import ServiceEndpoint, ServiceID, ServiceInfo, ServiceRegistry


def _service_matches(
    sel: ServiceSelector, sid: ServiceID, svc_labels: Dict[str, str]
) -> bool:
    """rule_translate.go serviceMatches: selector-based match over the
    service's own labels, or direct name+namespace equality. An empty
    namespace on the selector matches any namespace."""
    if sel.selector is not None:
        from ..labels import parse_label_array

        lbls = parse_label_array([f"{k}={v}" for k, v in svc_labels.items()])
        return sel.selector.matches(lbls) and sel.namespace in ("", sid.namespace)
    return sel.name == sid.name and sel.namespace in ("", sid.namespace)


def _service_owned(c: CIDRRule) -> bool:
    """Entries this translator may add/remove. generated_by == "" is
    included for backward compatibility: snapshots written before the
    ownership tag existed serialized service-generated entries as bare
    {generated: true} — treating them as service-owned lets the next
    translation clean them up instead of orphaning them forever."""
    return c.generated and c.generated_by in ("service", "")


def _populate(egress: EgressRule, endpoint: ServiceEndpoint) -> EgressRule:
    """Add one-address generated CIDRs for every backend not already
    covered (generateToCidrFromEndpoint, rule_translate.go:113-160).
    Coverage counts only user-written and service-owned entries: an
    fqdn-generated /32 that happens to equal a backend today will be
    withdrawn when DNS moves, so it must not suppress the
    service-owned entry that keeps the backend reachable."""
    existing = [
        ipaddress.ip_network(c.cidr, strict=False)
        for c in egress.to_cidr_set
        if _service_owned(c) or not c.generated
    ]
    added = list(egress.to_cidr_set)
    for ip in endpoint.backend_ips:
        addr = ipaddress.ip_address(ip)
        if any(addr in net for net in existing):
            continue
        added.append(
            CIDRRule(cidr=_host_cidr(ip), generated=True, generated_by="service")
        )
        existing.append(ipaddress.ip_network(_host_cidr(ip), strict=False))
    return dataclasses.replace(egress, to_cidr_set=tuple(added))


def _depopulate(egress: EgressRule, endpoint: ServiceEndpoint) -> EgressRule:
    """Drop generated CIDRs covering this endpoint's backends
    (deleteToCidrFromEndpoint, rule_translate.go:170-199)."""
    backends = [ipaddress.ip_address(ip) for ip in endpoint.backend_ips]
    kept = tuple(
        c
        for c in egress.to_cidr_set
        # only entries THIS translator generated are eligible for
        # removal — fqdn-generated entries belong to the DNS poller
        if not _service_owned(c)
        or not any(
            b in ipaddress.ip_network(c.cidr, strict=False) for b in backends
        )
    )
    return dataclasses.replace(egress, to_cidr_set=kept)


class RuleTranslator:
    """Populates (or reverts) ToCIDRSet entries on every egress rule
    whose ToServices matches the given service."""

    def __init__(
        self,
        service: ServiceID,
        endpoint: ServiceEndpoint,
        service_labels: Optional[Dict[str, str]] = None,
        revert: bool = False,
    ) -> None:
        self.service = service
        self.endpoint = endpoint
        self.service_labels = service_labels or {}
        self.revert = revert

    def translate(self, rule: Rule) -> Rule:
        new_egress = []
        changed = False
        for er in rule.egress:
            if any(
                _service_matches(sel, self.service, self.service_labels)
                for sel in er.to_services
            ):
                er2 = _depopulate(er, self.endpoint)
                if not self.revert:
                    er2 = _populate(er2, self.endpoint)
                changed = changed or er2 != er
                new_egress.append(er2)
            else:
                new_egress.append(er)
        if not changed:
            return rule
        return dataclasses.replace(rule, egress=tuple(new_egress))


class RegistryTranslator:
    """Idempotent whole-registry translation: for every egress rule
    with ToServices, drop all generated CIDRs and repopulate from the
    services currently known. Unlike the reference's per-event
    populate/depopulate pair (which needs the *old* endpoint object to
    revert), recomputation needs no history — service and endpoint
    deletions fall out naturally."""

    def __init__(self, registry: ServiceRegistry) -> None:
        self.registry = registry

    def translate(self, rule: Rule) -> Rule:
        new_egress = []
        changed = False
        for er in rule.egress:
            if not er.to_services:
                new_egress.append(er)
                continue
            base = dataclasses.replace(
                er,
                to_cidr_set=tuple(
                    c for c in er.to_cidr_set if not _service_owned(c)
                ),
            )
            for sid, svc, ep in self.registry.external_services():
                if any(
                    _service_matches(sel, sid, svc.labels) for sel in er.to_services
                ):
                    base = _populate(base, ep)
            changed = changed or base != er
            new_egress.append(base)
        if not changed:
            return rule
        return dataclasses.replace(rule, egress=tuple(new_egress))


def preprocess_rules(rules: Iterable[Rule], registry: ServiceRegistry) -> Tuple[Rule, ...]:
    """Translate ToServices against every known external service before
    import (rule_translate.go PreprocessRules)."""
    out = list(rules)
    for sid, svc, ep in registry.external_services():
        t = RuleTranslator(sid, ep, svc.labels)
        out = [t.translate(r) for r in out]
    return tuple(out)
