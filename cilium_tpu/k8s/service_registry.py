"""k8s Service / Endpoints registry.

Reference: pkg/loadbalancer/loadbalancer.go (K8sServiceNamespace,
K8sServiceInfo, K8sServiceEndpoint) and daemon/k8s_watcher.go service
caches. One registry instance is shared by the ToServices rule
translator (k8s/rule_translate.py) and the LB frontend programming
(lb/ service manager): services define frontends, endpoints define
backends.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class ServiceID:
    """Namespaced service name (loadbalancer.go K8sServiceNamespace)."""

    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class ServicePort:
    """One exposed port (loadbalancer.go K8sServicePort + L4Addr)."""

    name: str
    port: int
    protocol: str = "TCP"
    node_port: int = 0


@dataclasses.dataclass
class ServiceInfo:
    """Service frontend side (loadbalancer.go K8sServiceInfo)."""

    cluster_ip: str = ""
    ports: Dict[str, ServicePort] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    is_headless: bool = False

    @property
    def is_external(self) -> bool:
        """Headless/selector-less services resolve to external IPs the
        cluster does not manage (K8sServiceInfo.IsExternal: no selector)."""
        return not self.selector


@dataclasses.dataclass
class ServiceEndpoint:
    """Backend side (loadbalancer.go K8sServiceEndpoint): the union of
    ready addresses and the port name → L4 mapping."""

    backend_ips: Tuple[str, ...] = ()
    ports: Dict[str, ServicePort] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class IngressInfo:
    """Single-service Ingress backend (daemon/k8s_watcher.go:1181
    addIngressV1beta1 — the reference supports exactly this shape:
    spec.backend.{serviceName, servicePort})."""

    service_name: str
    service_port: int  # the frontend port number (IntValue of the spec)
    port_name: str = ""  # named servicePort, "" when numeric


class ServiceRegistry:
    """Thread-safe cache of Service + Endpoints objects, with observers
    so policy translation and LB programming react to churn."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.services: Dict[ServiceID, ServiceInfo] = {}
        self.endpoints: Dict[ServiceID, ServiceEndpoint] = {}
        # keyed by the INGRESS object's own (namespace, name)
        self.ingresses: Dict[ServiceID, IngressInfo] = {}
        self._observers: List = []  # callables (event, ServiceID)

    # -- mutation ------------------------------------------------------
    def upsert_service(self, sid: ServiceID, info: ServiceInfo) -> None:
        with self._lock:
            self.services[sid] = info
        self._notify("service-upsert", sid)

    def delete_service(self, sid: ServiceID) -> None:
        with self._lock:
            self.services.pop(sid, None)
        self._notify("service-delete", sid)

    def upsert_endpoints(self, sid: ServiceID, ep: ServiceEndpoint) -> None:
        with self._lock:
            self.endpoints[sid] = ep
        self._notify("endpoints-upsert", sid)

    def delete_endpoints(self, sid: ServiceID) -> None:
        with self._lock:
            self.endpoints.pop(sid, None)
        self._notify("endpoints-delete", sid)

    # -- object-shaped ingestion ---------------------------------------
    def apply_service_object(self, obj: dict) -> ServiceID:
        """Decode a v1 Service dict (k8s_watcher.go serviceAddFn)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        sid = ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
        cluster_ip = spec.get("clusterIP") or ""
        ports = {}
        for p in spec.get("ports") or ():
            name = p.get("name") or str(p.get("port", 0))
            ports[name] = ServicePort(
                name=name,
                port=int(p.get("port", 0) or 0),
                protocol=str(p.get("protocol") or "TCP").upper(),
                node_port=int(p.get("nodePort", 0) or 0),
            )
        self.upsert_service(
            sid,
            ServiceInfo(
                cluster_ip="" if cluster_ip in ("None", "") else cluster_ip,
                ports=ports,
                labels=dict(meta.get("labels") or {}),
                selector=dict(spec.get("selector") or {}),
                is_headless=cluster_ip in ("None", ""),
            ),
        )
        return sid

    def apply_endpoints_object(self, obj: dict) -> ServiceID:
        """Decode a v1 Endpoints dict (k8s_watcher.go endpointAddFn)."""
        meta = obj.get("metadata") or {}
        sid = ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
        ips: List[str] = []
        ports: Dict[str, ServicePort] = {}
        for subset in obj.get("subsets") or ():
            for addr in subset.get("addresses") or ():
                if addr.get("ip"):
                    ips.append(addr["ip"])
            for p in subset.get("ports") or ():
                name = p.get("name") or str(p.get("port", 0))
                ports[name] = ServicePort(
                    name=name,
                    port=int(p.get("port", 0) or 0),
                    protocol=str(p.get("protocol") or "TCP").upper(),
                )
        self.upsert_endpoints(
            sid, ServiceEndpoint(backend_ips=tuple(dict.fromkeys(ips)), ports=ports)
        )
        return sid

    def apply_ingress_object(self, obj: dict) -> Optional[ServiceID]:
        """Decode a v1beta1 Ingress dict. Only the single-service shape
        (spec.backend) is supported — same restriction as the reference
        (k8s_watcher.go:1188 'Single Service Ingress'). → the ingress's
        own id, or None when the shape is unsupported."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        backend = spec.get("backend")
        if not backend or not backend.get("serviceName"):
            return None
        iid = ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
        raw_port = backend.get("servicePort", 0)
        try:
            port_int = int(raw_port)
            port_name = ""
        except (TypeError, ValueError):
            port_int = 0
            port_name = str(raw_port)
        with self._lock:
            self.ingresses[iid] = IngressInfo(
                service_name=backend["serviceName"],
                service_port=port_int,
                port_name=port_name,
            )
        self._notify("ingress-upsert", iid)
        return iid

    def delete_ingress(self, iid: ServiceID) -> None:
        with self._lock:
            self.ingresses.pop(iid, None)
        self._notify("ingress-delete", iid)

    def known_ingress_ids(self) -> List[ServiceID]:
        with self._lock:
            return sorted(self.ingresses, key=lambda s: (s.namespace, s.name))

    # -- queries -------------------------------------------------------
    def get(self, sid: ServiceID) -> Tuple[Optional[ServiceInfo], Optional[ServiceEndpoint]]:
        with self._lock:
            return self.services.get(sid), self.endpoints.get(sid)

    def external_services(self) -> Iterable[Tuple[ServiceID, ServiceInfo, ServiceEndpoint]]:
        """Services eligible for ToServices CIDR translation
        (rule_translate.go PreprocessRules: external only)."""
        with self._lock:
            items = list(self.endpoints.items())
            for sid, ep in items:
                svc = self.services.get(sid)
                if svc is not None and svc.is_external:
                    yield sid, svc, ep

    # -- observers -----------------------------------------------------
    def observe(self, fn) -> None:
        self._observers.append(fn)

    def service_ids(self) -> List[ServiceID]:
        """All currently-known service ids (the resync reconciliation
        input: ids absent from a re-list snapshot are stale)."""
        with self._lock:
            return sorted(
                set(self.services) | set(self.endpoints),
                key=lambda s: (s.namespace, s.name),
            )

    def known_service_ids(self) -> List[ServiceID]:
        """Ids with a Service object (resync compares these against
        the snapshot's Service kinds)."""
        with self._lock:
            return sorted(self.services, key=lambda s: (s.namespace, s.name))

    def known_endpoints_ids(self) -> List[ServiceID]:
        """Ids with an Endpoints object (resync compares these against
        the snapshot's Endpoints kinds — Service and Endpoints are
        separate k8s objects deleted independently)."""
        with self._lock:
            return sorted(self.endpoints, key=lambda s: (s.namespace, s.name))

    def _notify(self, event: str, sid: ServiceID) -> None:
        for fn in list(self._observers):
            fn(event, sid)
