"""k8s object watcher / dispatcher.

Reference: daemon/k8s_watcher.go — the agent's single ingestion point
for NetworkPolicy, CiliumNetworkPolicy, Service, Endpoints, Pod and
Namespace events. There is no API server here; the watcher consumes
decoded objects (dicts) pushed by whatever transport the deployment
uses (file loads, tests, an external informer bridge) and applies them
to the daemon: policies into the repository (keyed by provenance
labels for deletion), services/endpoints into the ServiceRegistry
(which re-triggers ToServices translation), pods into endpoints.
"""

from __future__ import annotations

import json
import pathlib
import threading
from typing import Any, Dict, Iterable, List

from ..labels import parse_label_array
from ..policy.api.serialization import rule_from_dict, rules_to_json
from ..utils.logging import get_logger
from .cnp import parse_cnp
from .constants import extract_namespace, policy_labels
from .network_policy import parse_network_policy
from .pods import PodOrchestrator
from .rule_translate import preprocess_rules
from .service_registry import ServiceRegistry

log = get_logger("k8s-watcher")

KIND_NETWORK_POLICY = "NetworkPolicy"
KIND_CNP = "CiliumNetworkPolicy"
KIND_SERVICE = "Service"
KIND_ENDPOINTS = "Endpoints"
KIND_POD = "Pod"
KIND_NAMESPACE = "Namespace"
KIND_INGRESS = "Ingress"
KIND_NODE = "Node"

# node annotation keys the reference writes back (pkg/annotation/k8s.go)
ANNOTATION_V4_CIDR = "io.cilium.network.ipv4-pod-cidr"
ANNOTATION_V6_CIDR = "io.cilium.network.ipv6-pod-cidr"
ANNOTATION_V4_HEALTH = "io.cilium.network.ipv4-health-ip"


def load_objects(path: str) -> List[Dict[str, Any]]:
    """Decode a JSON or YAML file into a list of objects. YAML files
    may hold multiple ``---`` documents; JSON may hold a list. A bare
    rule list (no ``kind``) is returned as-is for `policy import`."""
    p = pathlib.Path(path)
    text = p.read_text()
    if p.suffix in (".yaml", ".yml"):
        import yaml

        docs = [d for d in yaml.safe_load_all(text) if d]
    else:
        data = json.loads(text)
        docs = data if isinstance(data, list) else [data]
    # A document may itself be a list (a YAML/JSON rule array).
    flat: List[Dict[str, Any]] = []
    for d in docs:
        flat.extend(d) if isinstance(d, list) else flat.append(d)
    return flat


def objects_to_rules(docs: Iterable[Dict[str, Any]]) -> list:
    """Translate a mixed list of decoded objects into policy rules.
    Bare rule dicts (no kind) pass through the native parser."""
    rules = []
    for obj in docs:
        kind = obj.get("kind", "")
        if kind == KIND_NETWORK_POLICY:
            rules.extend(parse_network_policy(obj))
        elif kind == KIND_CNP:
            rules.extend(parse_cnp(obj))
        elif kind in ("", None) or "endpointSelector" in obj:
            r = rule_from_dict(obj)
            r.sanitize()
            rules.append(r)
        # Non-policy kinds are skipped by this helper.
    return rules


class K8sWatcher:
    """Applies k8s object events to a running Daemon."""

    def __init__(self, daemon) -> None:
        self.daemon = daemon
        self.services = ServiceRegistry()
        self.pods = PodOrchestrator(daemon)
        self._namespace_labels: Dict[str, Dict[str, str]] = {}
        self.pods.namespace_labels = self._namespace_labels
        # k8s Node objects: name → {"pod_cidr", "internal_ip", ...}
        # (daemon/k8s_watcher.go node informer; feeds node routes and
        # the annotation writeback)
        self.nodes: Dict[str, Dict[str, Any]] = {}
        # Optional APIServerClient for writebacks (CNP status acks,
        # Ingress LB status, node CIDR annotations). Absent in
        # file-driven or test deployments — writebacks are skipped.
        self.status_client = None
        self.node_name = ""  # this agent's node (CNP status key)
        # (ns, name) → (spec fingerprint, revision) of applied policy
        # objects. Status-only MODIFIED events (including our OWN status
        # writebacks echoing back through the watch) must not re-import:
        # re-importing bumps the repository revision, which would change
        # the status we write, which would echo again — an infinite
        # write/regenerate loop. Spec-compare is the client-go
        # Generation-check idiom.
        self._applied_specs: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # One lock serializes apply/delete/resync: the informer runs a
        # watch thread per kind, and a resync's stale scan must not
        # interleave with another kind's live applies (an object added
        # between the scan's snapshot and its deletes would be reaped)
        self._apply_lock = threading.RLock()
        # Service churn retriggers ToServices translation of rules that
        # are already imported (k8s_watcher.go serviceModFn →
        # RuleTranslator over the repository) AND reprograms the LB
        # frontends (addK8sSVCs/syncExternalLB).
        self.services.observe(self._on_service_event)

    # -- policy --------------------------------------------------------
    def add_policy_object(self, obj: Dict[str, Any]) -> int:
        """Upsert semantics (k8s_watcher.go updates re-import under the
        same provenance labels): a MODIFIED event or a re-list after
        reconnect must replace the object's previous rules, never
        accumulate duplicates. The replace is atomic (one repository
        lock hold, one regeneration) — no window with the object's
        rules absent. CNP imports additionally write a per-node status
        ack back to the apiserver when a status client is configured
        (the CNPStatus nodes map of pkg/k8s/apis/cilium.io/v2)."""
        meta = obj.get("metadata") or {}
        key = (extract_namespace(meta), meta.get("name", ""))
        fingerprint = json.dumps(
            {"spec": obj.get("spec"), "specs": obj.get("specs"),
             "labels": meta.get("labels"),
             # bare-rule objects carry the policy at top level
             "rules": {k: v for k, v in obj.items()
                       if k not in ("metadata", "status", "kind")}},
            sort_keys=True, default=str,
        )
        prev = self._applied_specs.get(key)
        if prev is not None and prev[0] == fingerprint:
            return prev[1]  # status-only change: nothing to re-import
        lbls = policy_labels(*key)
        try:
            rules = objects_to_rules([obj])
            rules = preprocess_rules(rules, self.services)
            rev = self.daemon.policy_replace(lbls, rules_to_json(rules))[
                "revision"
            ]
        except Exception as e:
            self._applied_specs.pop(key, None)
            if obj.get("kind") == KIND_CNP:
                self._write_cnp_status(obj, ok=False, error=str(e))
            raise
        self._applied_specs[key] = (fingerprint, rev)
        if obj.get("kind") == KIND_CNP:
            self._write_cnp_status(obj, ok=True, revision=rev)
        return rev

    def _write_cnp_status(
        self, obj: Dict[str, Any], *, ok: bool, revision: int = 0,
        error: str = "",
    ) -> None:
        """Per-node CNP enforcement ack (the status.nodes[nodeName]
        entry of CiliumNetworkPolicyNodeStatus)."""
        if self.status_client is None or not self.node_name:
            return
        import time as _time

        meta = obj.get("metadata") or {}
        status = dict(obj.get("status") or {})
        nodes = dict(status.get("nodes") or {})
        entry: Dict[str, Any] = {
            "ok": ok,
            "enforcing": ok,
            "lastUpdated": _time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _time.gmtime()
            ),
        }
        if ok:
            entry["localPolicyRevision"] = revision
        else:
            entry["error"] = error
        nodes[self.node_name] = entry
        status["nodes"] = nodes
        updated = dict(obj)
        updated["status"] = status
        try:
            self.status_client.update_status(
                KIND_CNP, extract_namespace(meta), meta.get("name", ""),
                updated,
            )
        except Exception as e:
            log.warning("CNP status writeback failed", fields={
                "name": meta.get("name"), "err": f"{type(e).__name__}: {e}",
            })

    def delete_policy_object(self, obj: Dict[str, Any]) -> int:
        meta = obj.get("metadata") or {}
        key = (extract_namespace(meta), meta.get("name", ""))
        self._applied_specs.pop(key, None)
        lbls = policy_labels(*key)
        return self.daemon.policy_delete(lbls)["revision"]

    # -- services ------------------------------------------------------
    def _on_service_event(self, event: str, sid) -> None:
        from .rule_translate import RegistryTranslator

        self.daemon.policy_translate(RegistryTranslator(self.services))
        # reprogram LB frontends from the registry (the syncExternalLB
        # position: Service/Endpoints/Ingress churn all land here)
        lb = getattr(self.daemon, "services", None)
        if lb is not None and hasattr(lb, "sync_from_registry"):
            try:
                lb.sync_from_registry(self.services)
            except Exception as e:
                log.warning("LB sync failed", fields={
                    "err": f"{type(e).__name__}: {e}",
                })

    # -- ingress -------------------------------------------------------
    def _apply_ingress(self, obj: Dict[str, Any]) -> None:
        iid = self.services.apply_ingress_object(obj)
        if iid is None:
            return  # unsupported shape (no single-service backend)
        # status writeback: report the node host address as the LB
        # ingress point (k8s_watcher.go:1231-1240)
        lb = getattr(self.daemon, "services", None)
        host_ip = getattr(lb, "host_ip", "") if lb is not None else ""
        if self.status_client is not None and host_ip:
            meta = obj.get("metadata") or {}
            updated = dict(obj)
            updated["status"] = {
                "loadBalancer": {"ingress": [
                    {"ip": host_ip, "hostname": self.node_name}
                ]}
            }
            try:
                self.status_client.update_status(
                    KIND_INGRESS, meta.get("namespace") or "default",
                    meta.get("name", ""), updated,
                )
            except Exception as e:
                log.warning("ingress status writeback failed", fields={
                    "name": meta.get("name"),
                    "err": f"{type(e).__name__}: {e}",
                })

    # -- nodes ---------------------------------------------------------
    def _apply_node(self, obj: Dict[str, Any]) -> None:
        """Track k8s Node objects (podCIDR + addresses) and annotate
        OUR node with its CIDR (pkg/k8s/client.go AnnotateNode)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        name = meta.get("name", "")
        internal_ip = ""
        for addr in status.get("addresses") or ():
            if addr.get("type") == "InternalIP":
                internal_ip = addr.get("address", "")
                break
        self.nodes[name] = {
            "name": name,
            "pod_cidr": spec.get("podCIDR", ""),
            "internal_ip": internal_ip,
            "labels": dict(meta.get("labels") or {}),
        }
        if (
            self.status_client is not None
            and name == self.node_name
        ):
            cidr = str(
                getattr(getattr(self.daemon, "ipam", None), "net", "") or ""
            )
            annotations = {}
            if cidr:
                key = ANNOTATION_V6_CIDR if ":" in cidr else ANNOTATION_V4_CIDR
                annotations[key] = cidr
            existing = dict(meta.get("annotations") or {})
            if annotations and any(
                existing.get(k) != v for k, v in annotations.items()
            ):
                try:
                    self.status_client.patch_annotations(
                        KIND_NODE, "", name, annotations
                    )
                except Exception as e:
                    log.warning("node annotation failed", fields={
                        "node": name, "err": f"{type(e).__name__}: {e}",
                    })

    # -- dispatch ------------------------------------------------------
    def apply(self, obj: Dict[str, Any]) -> None:
        with self._apply_lock:
            self._apply_locked(obj)

    def _apply_locked(self, obj: Dict[str, Any]) -> None:
        kind = obj.get("kind", "")
        if kind in (KIND_NETWORK_POLICY, KIND_CNP):
            self.add_policy_object(obj)
        elif kind == KIND_SERVICE:
            self.services.apply_service_object(obj)
        elif kind == KIND_ENDPOINTS:
            self.services.apply_endpoints_object(obj)
        elif kind == KIND_POD:
            self.pods.add_pod(obj)
        elif kind == KIND_NAMESPACE:
            meta = obj.get("metadata") or {}
            self._namespace_labels[meta.get("name", "")] = dict(meta.get("labels") or {})
        elif kind == KIND_INGRESS:
            self._apply_ingress(obj)
        elif kind == KIND_NODE:
            self._apply_node(obj)
        else:
            raise ValueError(f"unsupported object kind {kind!r}")

    def resync(self, objects: Iterable[Dict[str, Any]]) -> None:
        """Full-state reconciliation after a watch reconnect: the
        informer re-lists and hands the COMPLETE current object set;
        everything present is (re-)applied (upserts are idempotent)
        and previously-known objects absent from the snapshot are
        deleted — healing adds AND deletes missed while disconnected
        (the cache-resync contract daemon/k8s_watcher.go relies on
        client-go for). Serialized against live applies; one malformed
        object is logged and skipped, never allowed to abort the whole
        reconciliation (client-go isolates handler errors the same
        way)."""
        with self._apply_lock:
            self._resync_locked(list(objects))

    def _resync_locked(self, objects: List[Dict[str, Any]]) -> None:

        def key(o: Dict[str, Any]):
            meta = o.get("metadata") or {}
            kind = o.get("kind", "")
            # cluster-scoped kinds carry no namespace: pin the key's
            # namespace slot so lookups need exactly one form
            ns = "" if kind in (KIND_NAMESPACE, KIND_NODE) else (
                meta.get("namespace") or "default"
            )
            return (kind, ns, meta.get("name", ""))

        seen = {key(o) for o in objects}
        # collect currently-known objects per kind
        stale: List[Dict[str, Any]] = []
        for r_labels in self._known_policy_labels():
            if (
                (KIND_CNP, r_labels[1], r_labels[0]) not in seen
                and (KIND_NETWORK_POLICY, r_labels[1], r_labels[0]) not in seen
            ):
                stale.append({
                    "kind": KIND_CNP,
                    "metadata": {"name": r_labels[0], "namespace": r_labels[1]},
                })
        for sid in self.services.known_service_ids():
            if (KIND_SERVICE, sid.namespace, sid.name) not in seen:
                stale.append({
                    "kind": KIND_SERVICE,
                    "metadata": {"name": sid.name, "namespace": sid.namespace},
                })
        # Endpoints are deleted independently of their Service: a
        # snapshot holding the Service but not its Endpoints means the
        # backend set was removed while disconnected
        for sid in self.services.known_endpoints_ids():
            if (KIND_ENDPOINTS, sid.namespace, sid.name) not in seen:
                stale.append({
                    "kind": KIND_ENDPOINTS,
                    "metadata": {"name": sid.name, "namespace": sid.namespace},
                })
        for pod in list(self.pods.known_pods()):
            if (KIND_POD, pod[0], pod[1]) not in seen:
                stale.append({
                    "kind": KIND_POD,
                    "metadata": {"name": pod[1], "namespace": pod[0]},
                })
        for iid in self.services.known_ingress_ids():
            if (KIND_INGRESS, iid.namespace, iid.name) not in seen:
                stale.append({
                    "kind": KIND_INGRESS,
                    "metadata": {"name": iid.name, "namespace": iid.namespace},
                })
        # nodes are cluster-scoped like namespaces: reaped only when
        # the snapshot covers the kind
        if any(o.get("kind") == KIND_NODE for o in objects):
            for node_name in list(self.nodes):
                if (KIND_NODE, "", node_name) not in seen:
                    stale.append({
                        "kind": KIND_NODE,
                        "metadata": {"name": node_name},
                    })
        # namespaces: reaped only when the snapshot covers the kind at
        # all (a snapshot from an informer not watching Namespace must
        # not wipe the label cache)
        if any(o.get("kind") == KIND_NAMESPACE for o in objects):
            for ns_name in list(self._namespace_labels):
                if (KIND_NAMESPACE, "", ns_name) not in seen:
                    stale.append({
                        "kind": KIND_NAMESPACE,
                        "metadata": {"name": ns_name},
                    })
        for obj in stale:
            try:
                self._delete_locked(obj)
            except Exception:
                log.warning("resync delete failed", fields={
                    "kind": obj.get("kind"),
                    "name": (obj.get("metadata") or {}).get("name"),
                })
        for obj in objects:
            # placeholders assert presence only — applying one would
            # wipe the real spec
            if obj.get("__placeholder__"):
                continue
            try:
                self._apply_locked(obj)
            except Exception as e:
                # one poisoned object must not block ingestion of the
                # rest (or the initial sync would never complete)
                log.warning("resync apply failed", fields={
                    "kind": obj.get("kind"),
                    "name": (obj.get("metadata") or {}).get("name"),
                    "err": f"{type(e).__name__}: {e}",
                })

    def _known_policy_labels(self) -> List[tuple]:
        """(name, namespace) pairs of k8s-sourced rules currently in
        the repository (by provenance labels)."""
        from .constants import POLICY_LABEL_NAME, POLICY_LABEL_NAMESPACE, SOURCE_K8S

        out = set()
        with self.daemon.repo._lock:
            for r in self.daemon.repo.rules:
                name = ns = None
                for l in r.labels.to_strings():
                    if l.startswith(f"{SOURCE_K8S}:{POLICY_LABEL_NAME}="):
                        name = l.split("=", 1)[1]
                    elif l.startswith(f"{SOURCE_K8S}:{POLICY_LABEL_NAMESPACE}="):
                        ns = l.split("=", 1)[1]
                if name is not None and ns is not None:
                    out.add((name, ns))
        return sorted(out)

    def delete(self, obj: Dict[str, Any]) -> None:
        with self._apply_lock:
            self._delete_locked(obj)

    def _delete_locked(self, obj: Dict[str, Any]) -> None:
        kind = obj.get("kind", "")
        if kind in (KIND_NETWORK_POLICY, KIND_CNP):
            self.delete_policy_object(obj)
        elif kind == KIND_SERVICE:
            from .service_registry import ServiceID

            meta = obj.get("metadata") or {}
            self.services.delete_service(
                ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
            )
        elif kind == KIND_ENDPOINTS:
            from .service_registry import ServiceID

            meta = obj.get("metadata") or {}
            self.services.delete_endpoints(
                ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
            )
        elif kind == KIND_POD:
            self.pods.delete_pod(obj)
        elif kind == KIND_NAMESPACE:
            meta = obj.get("metadata") or {}
            self._namespace_labels.pop(meta.get("name", ""), None)
        elif kind == KIND_INGRESS:
            from .service_registry import ServiceID

            meta = obj.get("metadata") or {}
            self.services.delete_ingress(
                ServiceID(meta.get("namespace") or "default", meta.get("name", ""))
            )
        elif kind == KIND_NODE:
            meta = obj.get("metadata") or {}
            self.nodes.pop(meta.get("name", ""), None)
        else:
            raise ValueError(f"unsupported object kind {kind!r}")
