"""Distributed-state fabric: kvstore backend, CAS allocator, shared
store, clustermesh (reference: pkg/kvstore + pkg/kvstore/allocator +
pkg/kvstore/store + pkg/clustermesh)."""

from .backend import (
    BackendOperations,
    EventTypeCreate,
    EventTypeDelete,
    EventTypeListDone,
    EventTypeModify,
    InMemoryBackend,
    InMemoryStore,
    KVEvent,
    KVLock,
    LockTimeout,
    Watcher,
)
from .allocator import Allocator, AllocatorError
from .clustermesh import ClusterMesh, RemoteCluster
from .filestore import FileBackend, FlakyBackend
from .netstore import KVStoreServer, NetBackend, backend_from_target
from .store import SharedStore

__all__ = [
    "Allocator",
    "AllocatorError",
    "BackendOperations",
    "ClusterMesh",
    "EventTypeCreate",
    "EventTypeDelete",
    "EventTypeListDone",
    "EventTypeModify",
    "FileBackend",
    "FlakyBackend",
    "InMemoryBackend",
    "InMemoryStore",
    "KVEvent",
    "KVLock",
    "KVStoreServer",
    "LockTimeout",
    "NetBackend",
    "backend_from_target",
    "RemoteCluster",
    "SharedStore",
    "Watcher",
]
