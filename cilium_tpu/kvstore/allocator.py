"""Distributed ID allocator over the kvstore (CAS master/slave keys).

Re-design of /root/reference/pkg/kvstore/allocator/allocator.go for the
TPU framework: multiple nodes requesting an ID for the same key must
converge on one number, because identity numbers index device tensor
rows — every chip in the fleet has to agree on the row basis.

Key scheme (allocator.go:80-106):

    <base>/id/<id>              = key        (master key: id → key)
    <base>/value/<key>/<node>   = id         (slave key, lease-bound)

- The master key is the allocation: as long as it exists the ID is in
  use. Created with CreateOnly (CAS) so two racing nodes cannot claim
  the same ID.
- Slave keys are per-node use counts, protected by the node's lease:
  when a node dies, its slave keys evaporate and the GC can reap master
  keys that no longer have any slave (allocator.go runGC:659).
- Lookup of key→id goes local cache → GetPrefix on the slave prefix
  (allocator.go:100-106), so a node can adopt another node's
  allocation without ever seeing a watch event.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from .backend import (
    BackendOperations,
    EventTypeCreate,
    EventTypeDelete,
    EventTypeListDone,
    EventTypeModify,
    KVEvent,
    Watcher,
)

MAX_ALLOC_ATTEMPTS = 16


class AllocatorError(Exception):
    pass


class Allocator:
    """id↔key allocation over a kvstore backend.

    ``suffix`` identifies this node in slave keys (the reference uses
    the node name / a uuid, allocator.go WithSuffix:308).
    """

    def __init__(
        self,
        backend: BackendOperations,
        base_path: str,
        *,
        suffix: str,
        min_id: int = 1,
        max_id: int = 1 << 16,
        on_event: Optional[Callable[[str, int, Optional[str]], None]] = None,
    ) -> None:
        self.backend = backend
        self.base_path = base_path.rstrip("/")
        self.id_prefix = self.base_path + "/id/"
        self.value_prefix = self.base_path + "/value/"
        self.lock_prefix = self.base_path + "/locks/"
        self.suffix = suffix
        self.min_id = min_id
        self.max_id = max_id
        self._lock = threading.RLock()
        # local refcounts: key -> (id, refcount)  (localkeys.go role)
        self._local: Dict[str, Tuple[int, int]] = {}
        # remote cache fed by the watcher: id -> key (cache.go role)
        self._cache: Dict[int, str] = {}
        self._on_event = on_event
        self._watcher: Watcher = backend.list_and_watch(
            f"allocator-{base_path}", self.id_prefix
        )
        self.pump()  # consume the initial list

    # ------------------------------------------------------------------
    def _master_key(self, id_: int) -> str:
        return f"{self.id_prefix}{id_}"

    def _slave_key(self, key: str) -> str:
        return f"{self.value_prefix}{key}/{self.suffix}"

    def _slave_prefix(self, key: str) -> str:
        return f"{self.value_prefix}{key}/"

    # -- watch-driven cache --------------------------------------------
    def pump(self) -> int:
        """Apply pending watch events to the id→key cache; returns the
        number applied. Called by the controller loop (or tests) — the
        allocator stays correct without pumping because allocation paths
        read through to the store, but the cache is what makes repeated
        lookups and remote-identity resolution cheap."""
        n = 0
        # mutate the cache under the lock (get()/cache_items() readers
        # hold it); fire callbacks only after release — an observer that
        # re-enters the allocator or takes its own lock must not do so
        # under ours. pump() runs on one controller thread, so the
        # deferred events still reach observers in watch order.
        events: List[Tuple[str, int, Optional[str]]] = []
        with self._lock:
            for ev in self._watcher.drain():
                n += 1
                if ev.typ == EventTypeListDone:
                    continue
                try:
                    id_ = int(ev.key[len(self.id_prefix):])
                except ValueError:
                    continue
                if ev.typ in (EventTypeCreate, EventTypeModify):
                    key = (ev.value or b"").decode()
                    self._cache[id_] = key
                    events.append(("upsert", id_, key))
                elif ev.typ == EventTypeDelete:
                    self._cache.pop(id_, None)
                    events.append(("delete", id_, None))
        if self._on_event:
            for typ, id_, key in events:
                self._on_event(typ, id_, key)
        return n

    # -- lookups --------------------------------------------------------
    def get_no_cache(self, key: str) -> int:
        """key → id via the first slave key found (allocator.go:600)."""
        hit = self.backend.get_prefix(self._slave_prefix(key))
        if hit is None:
            return 0
        try:
            return int(hit[1].decode())
        except ValueError:
            return 0

    def get(self, key: str) -> int:
        with self._lock:
            held = self._local.get(key)
            if held is not None:
                return held[0]
            for id_, k in self._cache.items():
                if k == key:
                    return id_
        return self.get_no_cache(key)

    def get_by_id(self, id_: int) -> Optional[str]:
        with self._lock:
            if id_ in self._cache:
                return self._cache[id_]
        raw = self.backend.get(self._master_key(id_))
        return raw.decode() if raw is not None else None

    def cache_items(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._cache)

    # -- allocation -----------------------------------------------------
    def _select_available_id(self) -> int:
        """Smallest unused id in [min, max] judged by the live master
        list (the reference uses a random idpool; smallest-first keeps
        device rows dense, which matters for tensor packing)."""
        used = set(self._cache)
        for k in self.backend.list_prefix(self.id_prefix):
            try:
                used.add(int(k[len(self.id_prefix):]))
            except ValueError:
                pass
        for cand in range(self.min_id, self.max_id + 1):
            if cand not in used:
                return cand
        return 0

    def _local_ref(self, key: str, id_: int) -> int:
        """Record one local use of (key → id) under the lock; returns
        the new refcount. Tolerates a concurrent same-node allocation
        having landed first (refcounts instead of overwriting)."""
        with self._lock:
            held = self._local.get(key)
            if held is not None:
                self._local[key] = (held[0], held[1] + 1)
                return held[1] + 1
            self._local[key] = (id_, 1)
            return 1

    def _create_slave(self, key: str, id_: int) -> bool:
        """Write our slave key *conditioned on the master key existing*
        (the reference's CreateIfExists guard, allocator.go
        createValueNodeKey:398) so adoption can't race GC into reaping
        an id we just started using. False → master is gone, retry."""
        cond = self._master_key(id_)
        slave = self._slave_key(key)
        val = str(id_).encode()
        if self.backend.create_if_exists(cond, slave, val, lease=True):
            return True
        # Slave may already exist (ours, e.g. after resync) — refresh it
        # under our lease as long as the master is still live.
        if self.backend.get(cond) is not None:
            self.backend.update(slave, val, lease=True)
            return True
        return False

    def allocate(self, key: str) -> Tuple[int, bool]:
        """→ (id, is_new). Mirrors allocator.go Allocate/lockedAllocate:
        local refcount fast path, adopt an existing allocation, else
        lock + CAS-create a fresh master key, retrying on races."""
        with self._lock:
            held = self._local.get(key)
            if held is not None:
                self._local[key] = (held[0], held[1] + 1)
                return held[0], False

        last_err: Optional[str] = None
        for _attempt in range(MAX_ALLOC_ATTEMPTS):
            self.pump()
            value = self.get_no_cache(key)
            if value == 0:
                # maybe another node allocated but wrote no slave key yet
                for id_, k in self.cache_items().items():
                    if k == key:
                        value = id_
                        break
            if value != 0:
                # adopt: serialize with GC via the per-key lock, then
                # write our slave key conditioned on the master key
                lock = self.backend.lock_path(self.lock_prefix + key)
                try:
                    if not self._create_slave(key, value):
                        last_err = f"master key {value} reaped during adopt"
                        continue
                finally:
                    lock.unlock()
                self._local_ref(key, value)
                return value, False

            id_ = self._select_available_id()
            if id_ == 0:
                raise AllocatorError("no more available IDs in configured space")
            lock = self.backend.lock_path(self.lock_prefix + key)
            try:
                if self.get_no_cache(key) != 0:
                    last_err = "lost create race (slave key appeared)"
                    continue  # retry loop adopts it
                if not self.backend.create_only(
                    self._master_key(id_), key.encode(), lease=False
                ):
                    last_err = f"master key {id_} taken"
                    continue  # another node claimed this id; retry
                self._create_slave(key, id_)
            finally:
                lock.unlock()
            with self._lock:
                self._cache[id_] = key
            self._local_ref(key, id_)
            if self._on_event:
                self._on_event("upsert", id_, key)
            return id_, True
        raise AllocatorError(f"allocation of '{key}' failed: {last_err}")

    def release(self, key: str) -> bool:
        """Drop one local reference; on the last one, delete our slave
        key (allocator.go Release:634). True when the local node no
        longer uses the key. Master-key reaping is GC's job."""
        with self._lock:
            held = self._local.get(key)
            if held is None:
                return False
            id_, rc = held
            if rc > 1:
                self._local[key] = (id_, rc - 1)
                return False
            del self._local[key]
        self.backend.delete(self._slave_key(key))
        return True

    # -- maintenance ----------------------------------------------------
    def run_gc(self) -> List[int]:
        """Reap master keys with no remaining slave keys
        (allocator.go runGC:659). Returns the ids released."""
        reaped: List[int] = []
        for mk, raw in sorted(self.backend.list_prefix(self.id_prefix).items()):
            key = raw.decode()
            if self.backend.get_prefix(self._slave_prefix(key)) is None:
                lock = self.backend.lock_path(self.lock_prefix + key)
                try:
                    # re-check under lock: a node may have re-adopted
                    if self.backend.get_prefix(self._slave_prefix(key)) is None:
                        self.backend.delete(mk)
                        try:
                            reaped.append(int(mk[len(self.id_prefix):]))
                        except ValueError:
                            pass
                finally:
                    lock.unlock()
        return reaped

    def resync_local_keys(self) -> int:
        """Re-create missing master/slave keys for every locally-held
        allocation (the localKeySyncInterval job + recreateMasterKey,
        allocator.go:58,706): after a lease loss wiped our slave keys,
        this re-establishes them so GC cannot reap identities still in
        use here. Returns the number of keys repaired."""
        fixed = 0
        with self._lock:
            held = dict(self._local)
        for key, (id_, _rc) in held.items():
            if self.backend.get(self._slave_key(key)) is None:
                self.backend.update(self._slave_key(key), str(id_).encode(), lease=True)
                fixed += 1
            if self.backend.get(self._master_key(id_)) is None:
                self.backend.create_only(self._master_key(id_), key.encode())
                fixed += 1
        return fixed

    def close(self) -> None:
        self.backend.stop_watcher(self._watcher)
