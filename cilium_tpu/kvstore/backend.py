"""kvstore backend: the distributed-state fabric of the framework.

Re-designs the reference's kvstore abstraction
(/root/reference/pkg/kvstore/backend.go:92-164 BackendOperations:
Get/GetPrefix/Set/Delete/Update/CreateOnly/CreateIfExists/ListPrefix/
LockPath/ListAndWatch + lease semantics) for the TPU framework's
control plane. Everything device-side stays derived: watch events feed
the IdentityRegistry / IPCache observers, which the PolicyEngine turns
into device row patches — the kvstore itself is pure host state.

Two pieces:

- ``BackendOperations``: the abstract client interface. Any real
  backend (etcd, consul) would implement it; the in-process
  ``InMemoryStore`` + ``InMemoryBackend`` mirror the reference's
  test/dev backend (/root/reference/pkg/kvstore/dummy.go:18) while
  keeping **real** CAS, lease, lock, and watch semantics so multi-node
  convergence is actually exercised.

- Leases: every backend client holds a lease; keys written with
  ``lease=True`` die with it (etcd lease expiry analog). Revoking a
  lease deletes its keys and emits delete events to watchers — that is
  the node-death signal the allocator GC and the shared store rely on.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

EventTypeCreate = "create"
EventTypeModify = "modify"
EventTypeDelete = "delete"
EventTypeListDone = "list-done"


@dataclasses.dataclass(frozen=True)
class KVEvent:
    """One watch event (the KeyValueEvent of pkg/kvstore/events.go)."""

    typ: str
    key: str
    value: Optional[bytes]


class Watcher:
    """Event stream for one prefix (pkg/kvstore/events.go Watcher).

    Events arrive on a thread-safe queue; consumers either block on
    :meth:`next` or drain pending events synchronously with
    :meth:`drain` (the deterministic path used by pump()-style
    consumers in tests and single-threaded controllers).
    """

    def __init__(self, name: str, prefix: str, chan_size: int = 0) -> None:
        # Unbounded queue: _emit runs under the store lock, so it must
        # never block — a slow consumer would otherwise deadlock every
        # other client of the store. (chan_size kept for API parity
        # with the reference; 0 = unbounded.)
        self.name = name
        self.prefix = prefix
        self.events: "queue.Queue[KVEvent]" = queue.Queue(maxsize=0)
        self._stopped = threading.Event()

    def _emit(self, ev: KVEvent) -> None:
        if not self._stopped.is_set():
            self.events.put(ev)

    def next(self, timeout: Optional[float] = None) -> Optional[KVEvent]:
        try:
            return self.events.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self) -> List[KVEvent]:
        out: List[KVEvent] = []
        while True:
            try:
                out.append(self.events.get_nowait())
            except queue.Empty:
                return out

    def stop(self) -> None:
        self._stopped.set()

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()


class BackendOperations:
    """Abstract kvstore client surface (backend.go:92-164)."""

    #: retry sleep for the CAS-spin lock; network backends override
    #: (each attempt is a round trip there)
    _lock_retry_s = 0.002
    name = "client"

    def status(self) -> str:
        raise NotImplementedError

    def alive(self) -> bool:
        """False once the backend can no longer reach the store (a
        network client whose connection died). Local backends are
        alive until closed."""
        return True

    def lock_path(self, path: str, timeout: float = 10.0) -> "KVLock":
        """Distributed lock by CAS-creating a lease-bound lock key,
        retried until acquired (etcd-style, pkg/kvstore/lock.go). The
        lease binding means a dead holder's lock auto-releases when
        its session dies. Always makes at least one attempt, even at
        timeout=0."""
        lock_key = path + "/.lock"
        deadline = time.monotonic() + timeout
        while True:
            if self.create_only(lock_key, self.name.encode(), lease=True):
                return KVLock(self, lock_key)
            if time.monotonic() >= deadline:
                raise LockTimeout(f"lock {path} not acquired within {timeout}s")
            time.sleep(self._lock_retry_s)

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        raise NotImplementedError

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    def update(self, key: str, value: bytes, lease: bool = False) -> None:
        raise NotImplementedError

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        raise NotImplementedError

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes, lease: bool = False
    ) -> bool:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def list_and_watch(self, name: str, prefix: str, chan_size: int = 1024) -> Watcher:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    # base64 key encoding for binary payloads (backend.go Encode/Decode)
    @staticmethod
    def encode(raw: bytes) -> str:
        return base64.urlsafe_b64encode(raw).decode("ascii")

    @staticmethod
    def decode(text: str) -> bytes:
        return base64.urlsafe_b64decode(text.encode("ascii"))


class LockTimeout(Exception):
    pass


class KVLock:
    """A held distributed lock (pkg/kvstore/lock.go). Context-manager;
    unlocking deletes the lock key. The key is lease-bound, so a dead
    owner's lock auto-releases when its lease is revoked."""

    def __init__(self, backend: "InMemoryBackend", lock_key: str) -> None:
        self._backend = backend
        self._key = lock_key

    def unlock(self) -> None:
        self._backend.delete(self._key)

    def __enter__(self) -> "KVLock":
        return self

    def __exit__(self, *exc) -> None:
        self.unlock()


@dataclasses.dataclass
class _Entry:
    value: bytes
    lease_id: Optional[int]
    create_rev: int
    mod_rev: int


class InMemoryStore:
    """The shared "etcd cluster": one instance backs many node clients.

    Provides revisioned keys, leases, and watch fan-out. All mutations
    emit events synchronously into matching watcher queues, so tests
    drive convergence deterministically (drain → apply → assert).
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: Dict[str, _Entry] = {}
        self._rev = 0
        # bumps whenever the DURABLE (non-lease) key set or its values
        # change — including deletes, which leave no surviving mod_rev
        # to witness them — so snapshot dirty-checks can't miss a
        # deletion or churn on pure lease traffic
        self._durable_rev = 0
        self._next_lease = 1
        self._leases: Dict[int, set] = {}  # lease id -> set of keys
        self._watchers: List[Tuple[str, Watcher]] = []

    # -- lease management ----------------------------------------------
    def grant_lease(self) -> int:
        with self._lock:
            lid = self._next_lease
            self._next_lease += 1
            self._leases[lid] = set()
            return lid

    def revoke_lease(self, lease_id: int) -> None:
        """Expire a lease: all keys attached to it are deleted (with
        delete events) — the etcd node-death behavior that makes slave
        keys and shared-store entries disappear when an agent dies."""
        with self._lock:
            keys = sorted(self._leases.pop(lease_id, set()))
            for k in keys:
                self._delete_locked(k)

    def lease_alive(self, lease_id: int) -> bool:
        with self._lock:
            return lease_id in self._leases

    # -- internals ------------------------------------------------------
    def _emit(self, ev: KVEvent) -> None:
        for prefix, w in list(self._watchers):
            if ev.key.startswith(prefix) and not w.stopped:
                w._emit(ev)

    def _put_locked(
        self, key: str, value: bytes, lease_id: Optional[int]
    ) -> None:
        was_durable = (
            self._data.get(key) is not None
            and self._data[key].lease_id is None
        )
        # a write racing its own lease's revocation must fail, not
        # resurrect the popped lease entry: nothing would ever revoke
        # that id again, so the key (e.g. a '/.lock') would be orphaned
        # forever (etcd likewise rejects puts on a revoked lease)
        if lease_id is not None and lease_id not in self._leases:
            raise RuntimeError(f"lease {lease_id} revoked")
        self._rev += 1
        old = self._data.get(key)
        if old is not None and old.lease_id is not None and old.lease_id != lease_id:
            self._leases.get(old.lease_id, set()).discard(key)
        if old is None:
            self._data[key] = _Entry(value, lease_id, self._rev, self._rev)
        else:
            old.value = value
            old.lease_id = lease_id
            old.mod_rev = self._rev
        if lease_id is not None:
            self._leases.setdefault(lease_id, set()).add(key)
        if lease_id is None or was_durable:
            # a durable write, or a key leaving the durable set
            # (was_durable is captured BEFORE old.lease_id is
            # overwritten above — the post-mutation value would make
            # durable->leased transitions invisible to snapshots)
            self._durable_rev = self._rev
        self._emit(
            KVEvent(EventTypeCreate if old is None else EventTypeModify, key, value)
        )

    def _delete_locked(self, key: str) -> None:
        entry = self._data.pop(key, None)
        if entry is None:
            return
        self._rev += 1
        if entry.lease_id is None:
            self._durable_rev = self._rev  # durable deletion
        else:
            self._leases.get(entry.lease_id, set()).discard(key)
        self._emit(KVEvent(EventTypeDelete, key, entry.value))

    # -- operations used by backends ------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            e = self._data.get(key)
            return e.value if e is not None else None

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        with self._lock:
            for k in sorted(self._data):
                if k.startswith(prefix):
                    return k, self._data[k].value
            return None

    def put(self, key: str, value: bytes, lease_id: Optional[int]) -> None:
        with self._lock:
            self._put_locked(key, value, lease_id)

    def create_only(self, key: str, value: bytes, lease_id: Optional[int]) -> bool:
        with self._lock:
            if key in self._data:
                return False
            self._put_locked(key, value, lease_id)
            return True

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes, lease_id: Optional[int]
    ) -> bool:
        with self._lock:
            if cond_key not in self._data:
                return False
            if key in self._data:
                return False
            self._put_locked(key, value, lease_id)
            return True

    def delete(self, key: str) -> None:
        with self._lock:
            self._delete_locked(key)

    def delete_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._data if k.startswith(prefix)]:
                self._delete_locked(k)

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        with self._lock:
            return {
                k: e.value for k, e in self._data.items() if k.startswith(prefix)
            }

    def snapshot_non_lease(self) -> Tuple[int, int, Dict[str, bytes]]:
        """(durable_rev, global_rev, {key: value}) for every key NOT
        bound to a lease — the durable subset a server snapshot
        persists (lease-bound state dies with its sessions by design).
        durable_rev witnesses every durable put AND delete, so pure
        lease churn never dirties a snapshot and a deletion always
        does; global_rev is what a restart restores so client-visible
        revisions stay monotonic."""
        with self._lock:
            return self._durable_rev, self._rev, {
                k: e.value for k, e in self._data.items()
                if e.lease_id is None
            }

    def attach_watcher(self, prefix: str, watcher: Watcher) -> None:
        with self._lock:
            self._watchers.append((prefix, watcher))

    def snapshot_and_attach(self, prefix: str, watcher: Watcher) -> None:
        """List-then-watch without a gap OR a reorder: snapshot, attach,
        AND emit under one hold of the store lock. Mutations take the
        same lock, so no event can land between the listing and the
        live stream — and none can be queued ahead of the snapshot
        (a delete racing the attach must arrive after the stale create
        it supersedes, or the consumer resurrects the key). Emitting
        under the lock is safe: Watcher queues are unbounded, _emit
        never blocks."""
        with self._lock:
            snapshot = sorted(
                (k, e.value) for k, e in self._data.items()
                if k.startswith(prefix)
            )
            for k, v in snapshot:
                watcher._emit(KVEvent(EventTypeCreate, k, v))
            watcher._emit(KVEvent(EventTypeListDone, "", None))
            self._watchers.append((prefix, watcher))

    def detach_watcher(self, watcher: Watcher) -> None:
        with self._lock:
            self._watchers = [(p, w) for p, w in self._watchers if w is not watcher]


class InMemoryBackend(BackendOperations):
    """One node's kvstore client bound to its own lease."""

    def __init__(self, store: InMemoryStore, name: str = "client") -> None:
        self.store = store
        self.name = name
        self.lease_id = store.grant_lease()
        self._watchers: List[Watcher] = []
        self._closed = False

    # ------------------------------------------------------------------
    def status(self) -> str:
        return "in-memory: %d leases live" % len(self.store._leases)

    def alive(self) -> bool:
        return not self._closed

    def _lease(self, lease: bool) -> Optional[int]:
        if not lease:
            return None
        if not self.store.lease_alive(self.lease_id):
            raise RuntimeError(f"lease of client {self.name} has expired")
        return self.lease_id

    def get(self, key: str) -> Optional[bytes]:
        return self.store.get(key)

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        return self.store.get_prefix(prefix)

    def set(self, key: str, value: bytes) -> None:
        self.store.put(key, value, None)

    def delete(self, key: str) -> None:
        self.store.delete(key)

    def delete_prefix(self, prefix: str) -> None:
        self.store.delete_prefix(prefix)

    def update(self, key: str, value: bytes, lease: bool = False) -> None:
        self.store.put(key, value, self._lease(lease))

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        return self.store.create_only(key, value, self._lease(lease))

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes, lease: bool = False
    ) -> bool:
        return self.store.create_if_exists(cond_key, key, value, self._lease(lease))

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        return self.store.list_prefix(prefix)

    def list_and_watch(self, name: str, prefix: str, chan_size: int = 1024) -> Watcher:
        """List current keys (as create events), mark list-done, then
        stream live events (backend.go ListAndWatch)."""
        w = Watcher(name, prefix, chan_size)
        self.store.snapshot_and_attach(prefix, w)
        self._watchers.append(w)
        return w

    def stop_watcher(self, w: Watcher) -> None:
        w.stop()
        self.store.detach_watcher(w)

    def close(self, revoke_lease: bool = True) -> None:
        """Close the client. ``revoke_lease=True`` models clean shutdown
        AND ungraceful death alike: lease-bound keys vanish."""
        if self._closed:
            return
        self._closed = True
        for w in self._watchers:
            self.stop_watcher(w)
        if revoke_lease:
            self.store.revoke_lease(self.lease_id)
