"""ClusterMesh: merge remote clusters' state into the local caches.

Re-design of /root/reference/pkg/clustermesh/clustermesh.go:49 +
remote_cluster.go: each remote cluster is reached through its OWN
kvstore backend; per cluster we subscribe nodes, identities, and the
ip→identity table, and merge them into the local registries. Identity
rows for remote identities land in the local IdentityRegistry, so
device policy tensors grow rows for remote workloads exactly like
local ones — the verdict kernel never knows a flow's peer lives in
another cluster.

The reference discovers clusters from a config directory (fsnotify);
here clusters are added/removed programmatically — the config-watch
loop belongs to the daemon layer.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from typing import TYPE_CHECKING

from ..identity.registry import IdentityRegistry
from ..ipcache.ipcache import IPCache, SOURCE_KVSTORE

if TYPE_CHECKING:  # runtime import is lazy — nodes.registry depends on
    from ..nodes.registry import Node  # kvstore, so a top-level import
    # here would make `import cilium_tpu.nodes` order-dependent
from ..labels import parse_label_array
from .backend import (
    BackendOperations,
    EventTypeDelete,
    EventTypeListDone,
    Watcher,
)
from .paths import (
    IDENTITIES_PATH,
    IP_IDENTITIES_PATH,
    NODES_PATH,
    key_to_label_strings,
)


def _key_to_labels(key: str):
    return parse_label_array(key_to_label_strings(key))


class RemoteCluster:
    """Subscriptions into one remote cluster's kvstore
    (remote_cluster.go): nodes + identities + ipcache + exported
    services (the global-service backend merge)."""

    def __init__(
        self,
        name: str,
        backend: BackendOperations,
        registry: IdentityRegistry,
        ipcache: IPCache,
        on_node: Optional[Callable[[str, Node, bool], None]] = None,
        services=None,  # Optional[lb.service.ServiceManager]
    ) -> None:
        self.name = name
        self.backend = backend
        self.registry = registry
        self.ipcache = ipcache
        self.services = services
        self._on_node = on_node
        self._id_prefix = f"{IDENTITIES_PATH}/id/"
        self._ip_prefix = f"{IP_IDENTITIES_PATH}/{name}/"
        self._node_prefix = f"{NODES_PATH}/"
        from ..lb.service import SERVICES_EXPORT_PATH

        self._svc_prefix = f"{SERVICES_EXPORT_PATH}/{name}/"
        self._w_ids: Watcher = backend.list_and_watch(
            f"mesh-{name}-identities", self._id_prefix
        )
        self._w_ips: Watcher = backend.list_and_watch(
            f"mesh-{name}-ip", self._ip_prefix
        )
        self._w_nodes: Watcher = backend.list_and_watch(
            f"mesh-{name}-nodes", self._node_prefix
        )
        self._w_svcs: Optional[Watcher] = (
            backend.list_and_watch(f"mesh-{name}-services", self._svc_prefix)
            if services is not None else None
        )
        self._held_ids: Dict[int, bool] = {}
        self._ip_entries: set = set()
        self._svc_frontends: set = set()
        self.nodes: Dict[str, Node] = {}
        self.pump()

    # ------------------------------------------------------------------
    def pump(self) -> int:
        """Apply pending remote events (the RemoteCache merge of
        allocator.go + ipcache kvstore watcher, scoped to this
        cluster)."""
        from ..nodes.registry import Node  # lazy: breaks import cycle

        n = 0
        for ev in self._w_ids.drain():
            n += 1
            if ev.typ == EventTypeListDone:
                continue
            try:
                id_ = int(ev.key[len(self._id_prefix):])
            except ValueError:
                continue
            if ev.typ == EventTypeDelete:
                if self._held_ids.pop(id_, None):
                    self.registry.release_by_id(id_)
            else:
                if id_ in self._held_ids or self.registry.get(id_) is not None:
                    continue
                try:
                    self.registry.insert_global(
                        id_, _key_to_labels((ev.value or b"").decode())
                    )
                    self._held_ids[id_] = True
                except ValueError:
                    # conflicting binding: local cluster wins; the
                    # reference logs and skips (cache.go invalidKey)
                    continue
        for ev in self._w_ips.drain():
            n += 1
            if ev.typ == EventTypeListDone:
                continue
            cidr = ev.key[len(self._ip_prefix):]
            if ev.typ == EventTypeDelete:
                self.ipcache.delete(cidr, SOURCE_KVSTORE)
                self._ip_entries.discard(cidr)
            else:
                try:
                    payload = json.loads((ev.value or b"{}").decode())
                except ValueError:
                    continue
                self.ipcache.upsert(
                    cidr,
                    int(payload.get("identity", 0)),
                    source=SOURCE_KVSTORE,
                    host_ip=payload.get("host_ip"),
                )
                self._ip_entries.add(cidr)
        for ev in self._w_nodes.drain():
            n += 1
            if ev.typ == EventTypeListDone:
                continue
            name = ev.key[len(self._node_prefix):]
            if ev.typ == EventTypeDelete:
                node = self.nodes.pop(name, None)
                if node is not None and self._on_node:
                    self._on_node(self.name, node, False)
            else:
                try:
                    node = Node.from_dict(json.loads((ev.value or b"{}").decode()))
                except ValueError:
                    continue
                self.nodes[name] = node
                if self._on_node:
                    self._on_node(self.name, node, True)
        if self._w_svcs is not None:
            from ..lb.service import Backend, L3n4Addr

            for ev in self._w_svcs.drain():
                n += 1
                if ev.typ == EventTypeListDone:
                    continue
                fe_str = ev.key[len(self._svc_prefix):]
                if ev.typ == EventTypeDelete:
                    fe = self._parse_frontend(fe_str)
                    if fe is not None:
                        self.services.set_remote_backends(fe, self.name, [])
                        self._svc_frontends.discard(fe)
                    continue
                try:
                    payload = json.loads((ev.value or b"{}").decode())
                    f = payload["frontend"]
                    fe = L3n4Addr(f["ip"], int(f["port"]),
                                  str(f.get("protocol", "TCP")))
                    backs = [
                        Backend(b["ip"], int(b["port"]),
                                int(b.get("weight", 1)))
                        for b in payload.get("backends", [])
                    ]
                    # set_remote_backends validates addresses — a
                    # remote cluster's malformed export must be
                    # skipped, not crash this pump loop
                    self.services.set_remote_backends(fe, self.name, backs)
                except (ValueError, KeyError, TypeError):
                    continue
                self._svc_frontends.add(fe)
        return n

    @staticmethod
    def _parse_frontend(text: str):
        from ..lb.service import L3n4Addr

        try:
            return L3n4Addr.from_string(text)
        except ValueError:
            return None

    def on_remove(self) -> None:
        """Withdraw everything this cluster contributed (clustermesh
        cluster.onRemove): release mirrored identities, drop merged
        ipcache entries, stop watchers."""
        for id_ in list(self._held_ids):
            self.registry.release_by_id(id_)
        self._held_ids.clear()
        for cidr in list(self._ip_entries):
            self.ipcache.delete(cidr, SOURCE_KVSTORE)
        self._ip_entries.clear()
        if self.services is not None:
            for fe in list(self._svc_frontends):
                self.services.set_remote_backends(fe, self.name, [])
            self._svc_frontends.clear()
        watchers = [self._w_ids, self._w_ips, self._w_nodes]
        if self._w_svcs is not None:
            watchers.append(self._w_svcs)
        for w in watchers:
            self.backend.stop_watcher(w)


class ClusterMesh:
    """The local node's cache of remote clusters
    (clustermesh.go:49)."""

    def __init__(
        self,
        registry: IdentityRegistry,
        ipcache: IPCache,
        *,
        on_node: Optional[Callable[[str, Node, bool], None]] = None,
        services=None,  # Optional[lb.service.ServiceManager]
    ) -> None:
        self.registry = registry
        self.ipcache = ipcache
        self._on_node = on_node
        self._services = services
        self._lock = threading.RLock()
        self.clusters: Dict[str, RemoteCluster] = {}

    def add_cluster(self, name: str, backend: BackendOperations) -> RemoteCluster:
        with self._lock:
            if name in self.clusters:
                return self.clusters[name]
            rc = RemoteCluster(
                name, backend, self.registry, self.ipcache, self._on_node,
                services=self._services,
            )
            self.clusters[name] = rc
            return rc

    def remove_cluster(self, name: str) -> bool:
        with self._lock:
            rc = self.clusters.pop(name, None)
        if rc is None:
            return False
        rc.on_remove()
        return True

    def pump(self) -> int:
        with self._lock:
            clusters = list(self.clusters.values())
        return sum(rc.pump() for rc in clusters)

    def num_clusters(self) -> int:
        with self._lock:
            return len(self.clusters)

    def close(self) -> None:
        with self._lock:
            names = list(self.clusters)
        for n in names:
            self.remove_cluster(n)
