"""File-backed kvstore: a cross-process BackendOperations.

The in-memory store (backend.py) covers single-process tests the way
the reference's dummy backend does (pkg/kvstore/dummy.go); this
backend is the standing-in for a real etcd: multiple PROCESSES share
one SQLite database file (WAL mode — SQLite's locking provides the
strong consistency), with revisioned keys, TTL leases kept alive by a
background thread, an append-only event log that watchers poll, and
lease-bound distributed locks. The BackendOperations surface and
event semantics match the in-memory backend, so every layer built on
it (allocator, shared store, node registry, clustermesh) runs
unchanged across processes.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Tuple

from .backend import (
    BackendOperations,
    EventTypeCreate,
    EventTypeDelete,
    EventTypeListDone,
    EventTypeModify,
    KVEvent,
    Watcher,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS kv (
    key TEXT PRIMARY KEY, value BLOB NOT NULL, lease_id INTEGER
);
CREATE TABLE IF NOT EXISTS leases (
    id INTEGER PRIMARY KEY AUTOINCREMENT, expires REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS events (
    rev INTEGER PRIMARY KEY AUTOINCREMENT,
    typ INTEGER NOT NULL, key TEXT NOT NULL, value BLOB
);
"""


class FileBackend(BackendOperations):
    def __init__(
        self,
        path: str,
        name: str = "client",
        *,
        lease_ttl: float = 15.0,
        poll_interval: float = 0.05,
    ) -> None:
        self.path = path
        self.name = name
        self.lease_ttl = lease_ttl
        self.poll_interval = poll_interval
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(
            path, timeout=10.0, check_same_thread=False
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)
        self._closed = threading.Event()
        with self._tx() as cur:
            cur.execute(
                "INSERT INTO leases (expires) VALUES (?)",
                (time.time() + lease_ttl,),
            )
            self.lease_id = cur.lastrowid
        self._watch_threads: List[threading.Thread] = []
        self._keepalive = threading.Thread(
            target=self._keepalive_loop, daemon=True
        )
        self._keepalive.start()

    # -- transactions ---------------------------------------------------
    def _tx(self):
        """IMMEDIATE transaction with the expired-lease sweep run
        first: any client observing an expired lease deletes its keys
        (with delete events) — the etcd lease-expiry behavior."""
        backend = self

        class _Tx:
            def __enter__(tx):
                backend._lock.acquire()
                try:
                    backend._conn.execute("BEGIN IMMEDIATE")
                    cur = backend._conn.cursor()
                    backend._sweep(cur)
                except BaseException:
                    # a busy-timeout here must NOT leak the RLock — a
                    # held lock with no __exit__ coming wedges every
                    # other thread's kvstore op in this process
                    try:
                        backend._conn.rollback()
                    except sqlite3.Error:
                        pass
                    backend._lock.release()
                    raise
                tx._cur = cur
                return cur

            def __exit__(tx, exc_type, *_):
                try:
                    if exc_type is None:
                        backend._conn.commit()
                    else:
                        backend._conn.rollback()
                finally:
                    backend._lock.release()

        return _Tx()

    def _read(self):
        """Read path: plain autocommit SELECTs (WAL readers never
        block on writers — a BEGIN IMMEDIATE here would serialize all
        readers across processes). Lease expiry is honored by
        filtering in the query, not by sweeping."""
        backend = self

        class _Rd:
            def __enter__(rd):
                backend._lock.acquire()
                return backend._conn.cursor()

            def __exit__(rd, *_):
                backend._lock.release()

        return _Rd()

    # WHERE fragment excluding keys whose lease has expired (sweeps
    # happen on the write path; reads must not see zombie keys)
    _LIVE = (
        "(kv.lease_id IS NULL OR EXISTS ("
        "SELECT 1 FROM leases WHERE leases.id = kv.lease_id "
        "AND leases.expires >= ?))"
    )

    def _sweep(self, cur) -> None:
        now = time.time()
        dead = [r[0] for r in cur.execute(
            "SELECT id FROM leases WHERE expires < ?", (now,)
        )]
        for lid in dead:
            for key, value in list(cur.execute(
                "SELECT key, value FROM kv WHERE lease_id = ?", (lid,)
            )):
                cur.execute("DELETE FROM kv WHERE key = ?", (key,))
                cur.execute(
                    "INSERT INTO events (typ, key, value) VALUES (?, ?, ?)",
                    (EventTypeDelete, key, value),
                )
            cur.execute("DELETE FROM leases WHERE id = ?", (lid,))

    def _keepalive_loop(self) -> None:
        while not self._closed.wait(self.lease_ttl / 3):
            try:
                with self._tx() as cur:
                    cur.execute(
                        "UPDATE leases SET expires = ? WHERE id = ?",
                        (time.time() + self.lease_ttl, self.lease_id),
                    )
            except sqlite3.Error:
                continue  # transient contention: retry next tick

    def _put(self, cur, key: str, value: bytes, lease: bool) -> None:
        row = cur.execute(
            "SELECT key FROM kv WHERE key = ?", (key,)
        ).fetchone()
        lid = self.lease_id if lease else None
        cur.execute(
            "INSERT INTO kv (key, value, lease_id) VALUES (?, ?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value, "
            "lease_id=excluded.lease_id",
            (key, value, lid),
        )
        cur.execute(
            "INSERT INTO events (typ, key, value) VALUES (?, ?, ?)",
            (EventTypeModify if row else EventTypeCreate, key, value),
        )

    # -- BackendOperations ----------------------------------------------
    def alive(self) -> bool:
        return not self._closed.is_set()

    def status(self) -> str:
        with self._read() as cur:
            n = cur.execute(
                f"SELECT COUNT(*) FROM kv WHERE {self._LIVE}",
                (time.time(),),
            ).fetchone()[0]
        return f"file:{self.path}: {n} keys"

    def get(self, key: str) -> Optional[bytes]:
        with self._read() as cur:
            row = cur.execute(
                f"SELECT value FROM kv WHERE key = ? AND {self._LIVE}",
                (key, time.time()),
            ).fetchone()
            return row[0] if row else None

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        with self._read() as cur:
            row = cur.execute(
                f"SELECT key, value FROM kv WHERE key >= ? AND key < ? "
                f"AND {self._LIVE} ORDER BY key LIMIT 1",
                (prefix, prefix + "\uffff", time.time()),
            ).fetchone()
            return (row[0], row[1]) if row else None

    def set(self, key: str, value: bytes) -> None:
        with self._tx() as cur:
            self._put(cur, key, value, lease=False)

    def update(self, key: str, value: bytes, lease: bool = False) -> None:
        with self._tx() as cur:
            self._put(cur, key, value, lease)

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        with self._tx() as cur:
            if cur.execute(
                "SELECT 1 FROM kv WHERE key = ?", (key,)
            ).fetchone():
                return False
            self._put(cur, key, value, lease)
            return True

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes, lease: bool = False
    ) -> bool:
        with self._tx() as cur:
            if not cur.execute(
                "SELECT 1 FROM kv WHERE key = ?", (cond_key,)
            ).fetchone():
                return False
            if cur.execute(
                "SELECT 1 FROM kv WHERE key = ?", (key,)
            ).fetchone():
                return False
            self._put(cur, key, value, lease)
            return True

    def delete(self, key: str) -> None:
        with self._tx() as cur:
            row = cur.execute(
                "SELECT value FROM kv WHERE key = ?", (key,)
            ).fetchone()
            if row:
                cur.execute("DELETE FROM kv WHERE key = ?", (key,))
                cur.execute(
                    "INSERT INTO events (typ, key, value) VALUES (?, ?, ?)",
                    (EventTypeDelete, key, row[0]),
                )

    def delete_prefix(self, prefix: str) -> None:
        with self._tx() as cur:
            rows = list(cur.execute(
                "SELECT key, value FROM kv WHERE key >= ? AND key < ?",
                (prefix, prefix + "\uffff"),
            ))
            for key, value in rows:
                cur.execute("DELETE FROM kv WHERE key = ?", (key,))
                cur.execute(
                    "INSERT INTO events (typ, key, value) VALUES (?, ?, ?)",
                    (EventTypeDelete, key, value),
                )

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        with self._read() as cur:
            return {
                k: v for k, v in cur.execute(
                    f"SELECT key, value FROM kv WHERE key >= ? AND key < ? "
                    f"AND {self._LIVE}",
                    (prefix, prefix + "\uffff", time.time()),
                )
            }

    # lock_path: inherited CAS-spin (backend.py); SQLite round trips
    # make tight spinning counterproductive
    _lock_retry_s = 0.02

    # -- watch ----------------------------------------------------------
    def list_and_watch(
        self, name: str, prefix: str, chan_size: int = 1024
    ) -> Watcher:
        """Initial snapshot + ListDone, then a poll thread follows the
        event log. The cursor is captured BEFORE the snapshot, so an
        event racing the snapshot is delivered (possibly twice — the
        consumers' upsert semantics absorb duplicates) rather than
        lost."""
        w = Watcher(name, prefix, chan_size)
        with self._read() as cur:
            start_rev = cur.execute(
                "SELECT COALESCE(MAX(rev), 0) FROM events"
            ).fetchone()[0]
            snapshot = list(cur.execute(
                f"SELECT key, value FROM kv WHERE key >= ? AND key < ? "
                f"AND {self._LIVE} ORDER BY key",
                (prefix, prefix + "\uffff", time.time()),
            ))
        for key, value in snapshot:
            w._emit(KVEvent(EventTypeCreate, key, value))
        w._emit(KVEvent(EventTypeListDone, prefix, None))

        def poll():
            # a dedicated connection: sqlite connections are not safe
            # for cross-thread interleaving
            conn = sqlite3.connect(self.path, timeout=10.0)
            last = start_rev
            try:
                while not self._closed.is_set() and not w.stopped:
                    try:
                        rows = list(conn.execute(
                            "SELECT rev, typ, key, value FROM events "
                            "WHERE rev > ? ORDER BY rev", (last,)
                        ))
                    except sqlite3.Error:
                        # transient contention (SQLITE_BUSY under
                        # cross-process write load) must NOT kill the
                        # poller — a dead watcher starves every layer
                        # above it silently
                        time.sleep(self.poll_interval)
                        continue
                    for rev, typ, key, value in rows:
                        last = rev
                        if key.startswith(prefix):
                            w._emit(KVEvent(typ, key, value))
                    if not rows:
                        time.sleep(self.poll_interval)
            finally:
                conn.close()

        t = threading.Thread(target=poll, daemon=True)
        t.start()
        self._watch_threads.append(t)
        return w

    def stop_watcher(self, w: Watcher) -> None:
        w.stop()

    def close(self) -> None:
        self._closed.set()
        try:
            with self._tx() as cur:
                # revoke our lease now (keys die with it via the sweep)
                cur.execute(
                    "UPDATE leases SET expires = 0 WHERE id = ?",
                    (self.lease_id,),
                )
                self._sweep(cur)
        except sqlite3.Error:
            pass
        for t in self._watch_threads:
            t.join(timeout=1.0)
        self._conn.close()


class FlakyBackend:
    """Failure-injection wrapper (the kvstore-outage chaos affordance,
    test/runtime/kvstore.go): while failing, every operation raises;
    recovery restores the inner backend untouched."""

    def __init__(self, inner: BackendOperations) -> None:
        self.inner = inner
        self.failing = False
        self.op_errors = 0

    def fail(self, on: bool = True) -> None:
        self.failing = on

    def _guard(self):
        if self.failing:
            self.op_errors += 1
            raise ConnectionError("kvstore unavailable (injected)")

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if callable(attr) and not name.startswith("_"):
            def wrapped(*a, **kw):
                self._guard()
                return attr(*a, **kw)
            return wrapped
        return attr
