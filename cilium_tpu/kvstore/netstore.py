"""Networked kvstore: the etcd-role TCP fabric for multi-host clusters.

The file/SQLite backend (filestore.py) covers multi-process on ONE
host; this module covers the reference's actual deployment shape — a
kvstore SERVER processes on any host connect to over the network
(/root/reference/pkg/kvstore/etcd.go: client sessions, leases with
keepalive, watch streams; version-gated connect) — so identity
allocation, node registry, ipcache sync, and clustermesh all run
across machines.

Two halves:

- :class:`KVStoreServer` — hosts one :class:`InMemoryStore` behind a
  TCP listener. Each connection is one client session: it gets a TTL
  lease (kept alive by client pings, revoked on disconnect or TTL
  expiry — the node-death signal), serialized request/response ops,
  and server-push watch streams (snapshot → list-done → live events,
  attached under the store lock so no event can fall in the gap).

- :class:`NetBackend` — a :class:`BackendOperations` client. One
  socket; a reader thread demuxes responses (by request id) to
  blocking callers and watch events (by watch id) into
  :class:`Watcher` queues; a keepalive thread renews the lease.

Wire protocol: 4-byte little-endian length + one JSON object
(utils/framing.py — the repo-wide socket convention). Binary
values ride base64. Requests carry ``id``; responses echo it; watch
events carry ``watch`` instead. The first frame from the server is the
hello: ``{"lease": <id>, "ttl": <seconds>, "rev": <revision>}``.

No transparent reconnect by design: a lost connection kills the lease
and with it every lease-bound key this client owned — exactly the
state the layers above must re-create through their own resync paths
(allocator re-CAS, shared-store re-sync, node re-announce), matching
the reference's session-loss semantics.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import threading
import time
from typing import Dict, Optional, Tuple

from ..utils.framing import recv_json as _recv_frame
from ..utils.framing import send_json
from ..utils.logging import get_logger
from .backend import (
    BackendOperations,
    EventTypeListDone,
    InMemoryStore,
    KVEvent,
    Watcher,
)

log = get_logger("kvstore-net")


def parse_hostport(text: str) -> Tuple[str, int]:
    """``host:port`` / ``[v6literal]:port`` → (host, port).

    An empty host (``:4240``) is allowed — callers supply their own
    default. Raises ValueError on anything else — including a bare v6
    literal like ``::1:4240``, which is ambiguous without brackets
    (RFC 3986 requires them for exactly this reason)."""
    if text.startswith("["):
        host, sep, port = text.rpartition("]:")
        if not sep or len(host) < 2 or not port.isdigit():
            raise ValueError(f"{text!r} must be [host]:port")
        host = host[1:]
    else:
        host, sep, port = text.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(f"{text!r} must be host:port")
        if ":" in host:
            raise ValueError(
                f"{text!r}: IPv6 literals need brackets — [{host}]:{port}"
            )
    if int(port) > 65535:
        raise ValueError(f"{text!r}: port must be 0-65535")
    return host, int(port)


def _send_frame(sock: socket.socket, wlock: threading.Lock, obj: dict) -> None:
    send_json(sock, obj, wlock)


def _b64(v: Optional[bytes]) -> Optional[str]:
    return None if v is None else base64.b64encode(v).decode("ascii")


def _unb64(v: Optional[str]) -> Optional[bytes]:
    return None if v is None else base64.b64decode(v)


# ---------------------------------------------------------------------------
# server


class _ClientSession:
    """One connected client: its lease, socket, and watch pumps."""

    def __init__(self, server: "KVStoreServer", sock: socket.socket, peer) -> None:
        self.server = server
        self.sock = sock
        self.peer = peer
        self.wlock = threading.Lock()
        self.lease_id = server.store.grant_lease()
        self.deadline = time.monotonic() + server.lease_ttl
        self.watches: Dict[int, Watcher] = {}
        self.closed = threading.Event()

    def close(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        for w in list(self.watches.values()):
            w.stop()
            self.server.store.detach_watcher(w)
        self.watches.clear()
        # lease revocation IS the death signal: every key this client
        # wrote with lease=True vanishes, with delete events fanning
        # out to every other session's watchers
        self.server.store.revoke_lease(self.lease_id)
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._drop(self)


class KVStoreServer:
    """TCP kvstore server — run one per cluster (or per failure
    domain), like the reference's etcd endpoint."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        lease_ttl: float = 15.0,
        state_path: Optional[str] = None,
        snapshot_interval: float = 5.0,
    ) -> None:
        self.store = InMemoryStore()
        self.lease_ttl = lease_ttl
        # durability (the etcd WAL role, snapshot-grained): non-lease
        # keys persist across server restarts via a periodically (and
        # on stop) rewritten JSON snapshot. Lease-bound keys are
        # DELIBERATELY excluded — their owners' sessions died with the
        # old server, so restoring them would resurrect state whose
        # death signal (the lease) already fired; owners re-create
        # them through their normal resync paths on reconnect.
        self.state_path = state_path
        self.snapshot_interval = snapshot_interval
        self._dirty_rev = -1
        self._snap_lock = threading.Lock()  # serializes writers
        if state_path:
            self._load_snapshot()
        family = socket.AF_INET6 if ":" in host else socket.AF_INET
        self._listener = socket.socket(family, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._sessions: Dict[int, _ClientSession] = {}
        self._slock = threading.Lock()
        self._threads: list = []

    @property
    def url(self) -> str:
        if ":" in self.host:  # v6 literal needs brackets
            return f"tcp://[{self.host}]:{self.port}"
        return f"tcp://{self.host}:{self.port}"

    def start(self) -> "KVStoreServer":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        s = threading.Thread(target=self._sweep_loop, daemon=True)
        s.start()
        self._threads += [t, s]
        if self.state_path:
            p = threading.Thread(target=self._snapshot_loop, daemon=True)
            p.start()
            self._threads.append(p)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._slock:
            sessions = list(self._sessions.values())
        for sess in sessions:
            sess.close()
        if self.state_path:
            try:
                self._write_snapshot()
            except OSError as e:
                # a failing disk must not turn shutdown into a crash
                log.warning("final kvstore snapshot failed",
                            fields={"err": str(e)})

    # -- durability -----------------------------------------------------
    def _load_snapshot(self) -> None:
        try:
            with open(self.state_path, "rb") as f:
                data = json.loads(f.read())
            kv = data["kv"] if isinstance(data, dict) else None
            if not isinstance(kv, dict):
                raise ValueError("snapshot is not a {rev, kv} object")
            decoded = {
                key: base64.b64decode(v64) for key, v64 in kv.items()
            }
        except FileNotFoundError:
            return
        except Exception as e:  # half-damaged disks produce ANY shape
            log.warning("kvstore snapshot unreadable; starting empty",
                        fields={"path": self.state_path, "err": str(e)})
            return
        for key, value in decoded.items():
            self.store.put(key, value, None)
        # keep revisions monotonic across restarts (etcd-like): the
        # hello advertises the GLOBAL rev persisted at snapshot time,
        # and a reconnecting client must not see it move backwards
        try:
            self.store._rev = max(self.store._rev, int(data.get("rev", 0)))
        except (TypeError, ValueError):
            pass
        # the restore itself is not "dirt": skip the first periodic
        # write unless something actually changes. Bare write is safe:
        # _load_snapshot runs during start(), before the accept/sweep/
        # snapshot threads exist — nothing else can hold _snap_lock yet
        self._dirty_rev = self.store._durable_rev  # policyd-lint: disable=LOCK004
        log.info("kvstore snapshot restored", fields={
            "path": self.state_path, "keys": len(decoded),
        })

    def _write_snapshot(self) -> None:
        # _snap_lock exists solely to serialize stop() against the
        # periodic snapshot loop over one tmp file; the fsync+rename
        # under it is the lock's entire purpose and no request path
        # takes it — every blocking call below is the design
        with self._snap_lock:  # stop() vs periodic loop share one tmp
            durable_rev, global_rev, data = self.store.snapshot_non_lease()
            if durable_rev == self._dirty_rev:
                return  # no durable put OR delete since the last write
            kv = {
                k: base64.b64encode(v).decode("ascii")
                for k, v in data.items()
            }
            tmp = f"{self.state_path}.tmp"
            with open(tmp, "w") as f:  # policyd-lint: disable=LOCK002
                f.write(json.dumps({"rev": global_rev, "kv": kv}))
                f.flush()
                os.fsync(f.fileno())  # rename must not outlive the data  # policyd-lint: disable=LOCK002
            os.replace(tmp, self.state_path)  # atomic: never torn  # policyd-lint: disable=LOCK002
            try:  # make the rename itself durable
                dfd = os.open(os.path.dirname(self.state_path) or ".",  # policyd-lint: disable=LOCK002
                              os.O_RDONLY)
                try:
                    os.fsync(dfd)  # policyd-lint: disable=LOCK002
                finally:
                    os.close(dfd)
            except OSError:
                pass
            self._dirty_rev = durable_rev

    def _snapshot_loop(self) -> None:
        while not self._stop.wait(self.snapshot_interval):
            try:
                self._write_snapshot()
            except OSError as e:
                log.warning("kvstore snapshot write failed",
                            fields={"err": str(e)})

    # -- internals ------------------------------------------------------
    def _drop(self, sess: _ClientSession) -> None:
        with self._slock:
            self._sessions.pop(id(sess), None)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, peer = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sess = _ClientSession(self, sock, peer)
            with self._slock:
                self._sessions[id(sess)] = sess
            threading.Thread(
                target=self._serve, args=(sess,), daemon=True
            ).start()

    def _sweep_loop(self) -> None:
        """Revoke leases whose keepalive went silent — the TTL expiry
        an etcd lease has even while the TCP connection lingers
        half-open."""
        while not self._stop.wait(min(self.lease_ttl / 3.0, 1.0)):
            now = time.monotonic()
            with self._slock:
                stale = [
                    s for s in self._sessions.values() if s.deadline < now
                ]
            for sess in stale:
                log.info("lease expired; closing session", fields={
                    "peer": str(sess.peer), "lease": sess.lease_id,
                })
                sess.close()

    def _serve(self, sess: _ClientSession) -> None:
        try:
            _send_frame(sess.sock, sess.wlock, {
                "lease": sess.lease_id,
                "ttl": self.lease_ttl,
                "rev": self.store._rev,
            })
            while not self._stop.is_set():
                req = _recv_frame(sess.sock)
                if req is None:
                    return
                try:
                    resp = self._dispatch(sess, req)
                except Exception as e:  # op error → error response
                    resp = {"err": f"{type(e).__name__}: {e}"}
                resp["id"] = req.get("id")
                _send_frame(sess.sock, sess.wlock, resp)
        except OSError:
            pass
        finally:
            sess.close()

    def _dispatch(self, sess: _ClientSession, req: dict) -> dict:
        op = req.get("op")
        st = self.store
        key = req.get("key", "")
        val = _unb64(req.get("value"))
        lease = sess.lease_id if req.get("lease") else None
        if op == "keepalive":
            sess.deadline = time.monotonic() + self.lease_ttl
            return {"ok": True}
        if op == "get":
            return {"value": _b64(st.get(key))}
        if op == "get_prefix":
            kv = st.get_prefix(key)
            if kv is None:
                return {"kv": None}
            return {"kv": [kv[0], _b64(kv[1])]}
        if op == "set":
            st.put(key, val or b"", None)
            return {"ok": True}
        if op == "update":
            st.put(key, val or b"", lease)
            return {"ok": True}
        if op == "create_only":
            return {"ok": st.create_only(key, val or b"", lease)}
        if op == "create_if_exists":
            return {"ok": st.create_if_exists(
                req["cond"], key, val or b"", lease
            )}
        if op == "delete":
            st.delete(key)
            return {"ok": True}
        if op == "delete_prefix":
            st.delete_prefix(key)
            return {"ok": True}
        if op == "list_prefix":
            return {"kvs": {
                k: _b64(v) for k, v in st.list_prefix(key).items()
            }}
        if op == "watch":
            return self._start_watch(sess, int(req["wid"]), key)
        if op == "unwatch":
            w = sess.watches.pop(int(req["wid"]), None)
            if w is not None:
                w.stop()
                st.detach_watcher(w)
            return {"ok": True}
        if op == "status":
            with self._slock:
                n = len(self._sessions)
            return {"status": f"net: {n} sessions, rev {st._rev}"}
        raise ValueError(f"unknown op {op!r}")

    def _start_watch(self, sess: _ClientSession, wid: int, prefix: str) -> dict:
        w = Watcher(f"net-{wid}", prefix)
        self.store.snapshot_and_attach(prefix, w)
        sess.watches[wid] = w
        if sess.closed.is_set():
            # raced the session teardown: close() may have swept
            # sess.watches before our insert — detach here so the
            # store never scans a dead watcher (and its unbounded
            # queue never accumulates) for the server's lifetime
            sess.watches.pop(wid, None)
            w.stop()
            self.store.detach_watcher(w)
            raise ConnectionError("session closed")

        def pump() -> None:
            while not (w.stopped or sess.closed.is_set()):
                ev = w.next(timeout=0.5)
                if ev is None:
                    continue
                try:
                    _send_frame(sess.sock, sess.wlock, {
                        "watch": wid, "typ": ev.typ,
                        "key": ev.key, "value": _b64(ev.value),
                    })
                except OSError:
                    sess.close()
                    return

        threading.Thread(target=pump, daemon=True).start()
        return {"ok": True}


# ---------------------------------------------------------------------------
# client


class NetBackend(BackendOperations):
    """kvstore client over TCP (the etcd client session analog)."""

    def __init__(
        self,
        target: str,
        name: str = "client",
        *,
        op_timeout: float = 30.0,
    ) -> None:
        if target.startswith("tcp://"):
            target = target[len("tcp://"):]
        try:
            host, port = parse_hostport(target)
            if not host:
                raise ValueError(f"{target!r}: host is required")
        except ValueError as e:
            raise ValueError(f"kvstore target: {e}") from None
        self.name = name
        self.op_timeout = op_timeout
        self._sock = socket.create_connection((host, port), timeout=10.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._pending: Dict[int, Tuple[threading.Event, list]] = {}
        self._plock = threading.Lock()
        self._next_id = 1
        self._watchers: Dict[int, Watcher] = {}
        self._closed = threading.Event()
        try:
            # the connect timeout still arms the socket here, so a peer
            # that accepts but never speaks (firewall blackhole, wrong
            # service) fails the probe instead of hanging forever
            hello = _recv_frame(self._sock)
            if hello is None or "lease" not in hello:
                raise ConnectionError(
                    "kvstore server hello missing (timeout or wrong service)"
                )
            self.lease_id = int(hello["lease"])
            self.lease_ttl = float(hello.get("ttl", 15.0))
        except Exception:
            # a peer speaking some other protocol must not leak the fd
            # (a supervisor retry loop would bleed one per attempt)
            self._sock.close()
            raise
        self._sock.settimeout(None)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._ka = threading.Thread(target=self._keepalive_loop, daemon=True)
        self._ka.start()

    # -- plumbing -------------------------------------------------------
    def _read_loop(self) -> None:
        while not self._closed.is_set():
            try:
                frame = _recv_frame(self._sock)
            except (OSError, ValueError):
                frame = None
            if frame is None:
                break
            if "watch" in frame:
                w = self._watchers.get(int(frame["watch"]))
                if w is not None:
                    w._emit(KVEvent(
                        frame["typ"], frame["key"], _unb64(frame.get("value"))
                    ))
                    if frame["typ"] == EventTypeListDone:
                        w._net_list_done.set()
                continue
            rid = frame.get("id")
            with self._plock:
                slot = self._pending.pop(rid, None)
            if slot is not None:
                slot[1].append(frame)
                slot[0].set()
        # connection died: unblock every caller, stop watchers, and
        # release the fd (a later explicit close() early-returns, so
        # this is the socket's last owner)
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for ev, out in pending:
            out.append({"err": "connection closed"})
            ev.set()
        for w in list(self._watchers.values()):
            w.stop()
            done = getattr(w, "_net_list_done", None)
            if done is not None:
                w._net_dead = True
                done.set()  # unblock a list_and_watch waiting on the snapshot

    def _keepalive_loop(self) -> None:
        interval = max(self.lease_ttl / 3.0, 0.05)
        while not self._closed.wait(interval):
            try:
                self._call({"op": "keepalive"})
            except (ConnectionError, OSError):
                return

    def _call(self, req: dict, *, nowait: bool = False) -> dict:
        if self._closed.is_set():
            raise ConnectionError("kvstore connection closed")
        ev = threading.Event()
        out: list = []
        with self._plock:
            rid = self._next_id
            self._next_id += 1
            if not nowait:
                self._pending[rid] = (ev, out)
        req["id"] = rid
        try:
            _send_frame(self._sock, self._wlock, req)
        except OSError as e:
            with self._plock:
                self._pending.pop(rid, None)
            raise ConnectionError(f"kvstore send failed: {e}") from None
        if nowait:  # fire-and-forget: the reader drops the stray reply
            return {}
        if not ev.wait(self.op_timeout):
            with self._plock:
                self._pending.pop(rid, None)
            raise TimeoutError(f"kvstore op {req.get('op')} timed out")
        resp = out[0]
        err = resp.get("err")
        if err == "connection closed":
            raise ConnectionError("kvstore connection closed")
        if err:
            raise RuntimeError(err)
        return resp

    # -- BackendOperations ---------------------------------------------
    def alive(self) -> bool:
        return not self._closed.is_set()

    def status(self) -> str:
        try:
            return self._call({"op": "status"})["status"]
        except (ConnectionError, TimeoutError) as e:
            return f"net: unreachable ({e})"

    def get(self, key: str) -> Optional[bytes]:
        return _unb64(self._call({"op": "get", "key": key}).get("value"))

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        kv = self._call({"op": "get_prefix", "key": prefix}).get("kv")
        if kv is None:
            return None
        return kv[0], _unb64(kv[1])

    def set(self, key: str, value: bytes) -> None:
        self._call({"op": "set", "key": key, "value": _b64(value)})

    def delete(self, key: str) -> None:
        self._call({"op": "delete", "key": key})

    def delete_prefix(self, prefix: str) -> None:
        self._call({"op": "delete_prefix", "key": prefix})

    def update(self, key: str, value: bytes, lease: bool = False) -> None:
        self._call({
            "op": "update", "key": key, "value": _b64(value), "lease": lease,
        })

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        return bool(self._call({
            "op": "create_only", "key": key,
            "value": _b64(value), "lease": lease,
        })["ok"])

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes, lease: bool = False
    ) -> bool:
        return bool(self._call({
            "op": "create_if_exists", "cond": cond_key, "key": key,
            "value": _b64(value), "lease": lease,
        })["ok"])

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        kvs = self._call({"op": "list_prefix", "key": prefix})["kvs"]
        return {k: _unb64(v) for k, v in kvs.items()}

    # lock_path: inherited CAS-spin (backend.py); every attempt is a
    # network round trip, so back off harder between them
    _lock_retry_s = 0.01

    def list_and_watch(self, name: str, prefix: str, chan_size: int = 1024) -> Watcher:
        w = Watcher(name, prefix, chan_size)
        with self._plock:
            wid = self._next_id
            self._next_id += 1
        # register BEFORE the request: the server streams snapshot
        # events immediately after acking and the reader thread must
        # already know where to put them
        self._watchers[wid] = w
        w._net_wid = wid  # for stop_watcher
        w._net_list_done = threading.Event()
        try:
            self._call({"op": "watch", "wid": wid, "key": prefix})
            # every other backend returns with the initial snapshot
            # already IN the watcher queue (callers do `list_and_watch`
            # then immediately pump it); hold that contract over the
            # network by blocking until the list-done frame lands
            if not w._net_list_done.wait(self.op_timeout):
                raise TimeoutError(f"watch {prefix!r}: initial list timed out")
            if getattr(w, "_net_dead", False):
                raise ConnectionError("kvstore connection closed")
        except Exception:
            self._watchers.pop(wid, None)
            w.stop()
            try:  # the server still has the watch attached; detach it so
                # its pump thread stops streaming frames nobody reads.
                # Fire-and-forget (no reply wait): this path only runs
                # when the server is already misbehaving, and a blocking
                # _call here would double the caller's failure latency
                self._call({"op": "unwatch", "wid": wid}, nowait=True)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                pass
            raise
        return w

    def stop_watcher(self, w: Watcher) -> None:
        w.stop()
        wid = getattr(w, "_net_wid", None)
        if wid is not None:
            self._watchers.pop(wid, None)
            try:
                self._call({"op": "unwatch", "wid": wid})
            except (ConnectionError, TimeoutError, RuntimeError):
                pass

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for w in list(self._watchers.values()):
            w.stop()
        self._watchers.clear()
        try:
            self._sock.close()
        except OSError:
            pass


def backend_from_target(target: str, name: str) -> BackendOperations:
    """``tcp://host:port[,tcp://host2:port2,...]`` → :class:`NetBackend`
    connected to the first reachable endpoint (the etcd client's
    endpoint-list failover); anything else is a path for the SQLite
    :class:`FileBackend` (single-host fabric)."""
    if target.startswith("tcp://"):
        endpoints = [e.strip() for e in target.split(",")]
        for ep in endpoints:  # malformed syntax fails FAST (ValueError),
            t = ep[len("tcp://"):] if ep.startswith("tcp://") else ep
            try:  # not as "unreachable"
                h, _ = parse_hostport(t)
                if not h:
                    raise ValueError(f"{t!r}: host is required")
            except ValueError as e:
                raise ValueError(f"kvstore endpoint: {e}") from None
        last: Optional[Exception] = None
        for ep in endpoints:
            try:
                return NetBackend(ep, name)
            except (OSError, ConnectionError) as e:
                last = e
        raise ConnectionError(
            f"no kvstore endpoint reachable in {target!r}: {last}"
        )
    from .filestore import FileBackend

    return FileBackend(target, name)
