"""Shared kvstore path constants + the identity key codec.

Kept dependency-free so kvstore modules and identity/ipcache sync
layers can share them without import cycles. Path layout mirrors the
reference's stable kvstore schema (pkg/kvstore/allocator, pkg/node/
store.go NodeStorePrefix, pkg/ipcache/kvstore.go IPIdentitiesPath).
"""

IDENTITIES_PATH = "cilium/state/identities/v1"
NODES_PATH = "cilium/state/nodes/v1"
IP_IDENTITIES_PATH = "cilium/state/ip/v1"
# policyd-fed: per-node descriptor + policy_epoch records (the
# federation epoch exchange; federation/epochs.py)
CLUSTER_EPOCHS_PATH = "cilium/state/epochs/v1"
# policyd-fleetobs: per-node telemetry frames, published beside the
# epoch records (observe/fleet.py TelemetryExchange)
CLUSTER_TELEMETRY_PATH = "cilium/state/telemetry/v1"
# policyd-journal: per-node lifecycle-journal tail frames
# (observe/journal.py JournalExchange)
CLUSTER_JOURNAL_PATH = "cilium/state/journal/v1"


def key_to_label_strings(key: str):
    """Allocator key (LabelArray.sorted_key: ';'-joined labels) →
    label strings."""
    return [t for t in key.split(";") if t]
