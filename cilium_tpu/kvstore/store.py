"""SharedStore: a replicated key set over the kvstore watch fabric.

Re-design of /root/reference/pkg/kvstore/store/store.go: every node
contributes lease-bound local keys under a common prefix and observes
every other node's keys via ListAndWatch. Used for the node registry
(pkg/node/store.go:60) and health state; here also the carrier for
ip→identity announcements.

Local keys are written with ``update_local_key_sync`` and re-written by
``sync_local_keys`` (the periodic anti-entropy sync of the reference's
SynchronizationInterval) so a lease loss self-heals on the next sync.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from .backend import (
    BackendOperations,
    EventTypeDelete,
    EventTypeListDone,
    Watcher,
)
from .. import faults as _faults


class SharedStore:
    """One node's view of a replicated key set.

    Keys are (name → dict) pairs; values travel as JSON. Observers fire
    on remote create/modify/delete after :meth:`pump` applies pending
    watch events (deterministic, controller-driven delivery).
    """

    def __init__(
        self,
        backend: BackendOperations,
        prefix: str,
        *,
        on_update: Optional[Callable[[str, dict], None]] = None,
        on_delete: Optional[Callable[[str, Optional[dict]], None]] = None,
    ) -> None:
        self.backend = backend
        self.prefix = prefix.rstrip("/") + "/"
        self._lock = threading.RLock()
        self._local: Dict[str, dict] = {}
        self.shared: Dict[str, dict] = {}  # full replicated view incl. local
        self._on_update = on_update
        self._on_delete = on_delete
        self._watcher: Watcher = backend.list_and_watch(
            f"store-{prefix}", self.prefix
        )
        self.synced = False
        self.pump()

    # ------------------------------------------------------------------
    def _key_path(self, name: str) -> str:
        return self.prefix + name

    def pump(self) -> int:
        """Apply pending watch events to the shared view; fires
        observers. Returns events applied."""
        if _faults.hub.active:
            try:
                _faults.hub.check(_faults.SITE_KVSTORE)
            except _faults.FaultError as e:
                if e.kind == _faults.KIND_POISONED:
                    raise
                # transient partition: events stay queued in the
                # watcher and apply on the next pump — the replicated
                # view is eventually consistent by design
                return 0
        n = 0
        for ev in self._watcher.drain():
            n += 1
            if ev.typ == EventTypeListDone:
                self.synced = True
                continue
            name = ev.key[len(self.prefix):]
            if ev.typ == EventTypeDelete:
                with self._lock:
                    old = self.shared.pop(name, None)
                if self._on_delete:
                    self._on_delete(name, old)
            else:
                try:
                    value = json.loads((ev.value or b"{}").decode())
                except ValueError:
                    continue
                with self._lock:
                    self.shared[name] = value
                if self._on_update:
                    self._on_update(name, value)
        return n

    # -- local keys -----------------------------------------------------
    def update_local_key_sync(self, name: str, value: dict) -> None:
        """Write (and remember) a local key; lease-bound so it dies with
        this node (store.go UpdateLocalKeySync)."""
        with self._lock:
            self._local[name] = value
        self.backend.update(
            self._key_path(name), json.dumps(value, sort_keys=True).encode(),
            lease=True,
        )

    def delete_local_key(self, name: str) -> None:
        with self._lock:
            self._local.pop(name, None)
        self.backend.delete(self._key_path(name))

    def sync_local_keys(self) -> int:
        """Anti-entropy: re-write every local key (periodic sync role).
        Returns the number of keys written."""
        with self._lock:
            items = list(self._local.items())
        for name, value in items:
            self.backend.update(
                self._key_path(name), json.dumps(value, sort_keys=True).encode(),
                lease=True,
            )
        return len(items)

    def local_keys(self) -> List[str]:
        with self._lock:
            return list(self._local)

    def close(self) -> None:
        self.backend.stop_watcher(self._watcher)
