"""L7 policy enforcement (reference: pkg/proxy, pkg/kafka, envoy/).

The reference redirects L7 flows to Envoy (HTTP, C++ filters enforcing
NPDS policy per request) or a built-in Kafka proxy (Go). Here the
enforcement core is TPU-shaped: HTTP method/path/host regexes compile
to one multi-pattern DFA per endpoint-port (ops/dfa.py) walked on
device over request-string batches; Kafka ACLs lower to enum/id tables.
The proxy manager keeps the redirect bookkeeping (port allocation,
redirect lifecycle, access logs) host-side.
"""

from .regex_compile import RegexError, compile_patterns, nfa_from_regex
from .http_policy import HTTPPolicy, HTTPRequest
from .kafka_policy import KafkaACL, KafkaRequest

__all__ = [
    "RegexError",
    "compile_patterns",
    "nfa_from_regex",
    "HTTPPolicy",
    "HTTPRequest",
    "KafkaACL",
    "KafkaRequest",
]
