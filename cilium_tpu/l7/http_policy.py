"""Per-endpoint-port HTTP policy: compiled DFA enforcement.

Reference: the NPDS policy Envoy enforces per request
(envoy/cilium_network_policy.h:68-202 PortNetworkPolicy.Matches chain —
remote identity must match an allowed selector AND some HTTP rule's
method/path/host/header matchers must all pass; deny → 403).

Compilation: distinct non-empty method/path/host regexes across the
rules become three multi-pattern DFAs; a rule matches when its bits are
set (or the field is a wildcard) in every field's accept mask. Header
checks are exact matches evaluated host-side (rare in practice).
Patterns that exceed the DFA state cap fall back to host `re` matching
— fail-safe, never fail-open.

With the ``L7DeviceBatch`` runtime option on, the three per-field
dispatches fuse into ONE device walk over an interned stacked table
(ops.dfa.FusedDFA via datapath.l7_pipeline) — same masks, bit for bit;
with it off, this module runs the exact pre-option path below.
"""
# policyd: hot

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import metrics
from ..datapath import l7_pipeline as l7rt
from ..ops.dfa import fuse_dfas, intern_fused_table, match_patterns
from ..policy.api import HTTPRule
from .regex_compile import (
    MultiDFA,
    RegexError,
    compile_patterns,
    compile_patterns_cached,
)


# below this many strings the device DFA dispatch costs more than a
# host table walk (the fused-path rungs are prewarmed at compile()
# time, so past this floor no request eats a first-use jit compile)
_DEVICE_BATCH_MIN = 32


class NativeL7Unsupported(ValueError):
    """This policy needs host-side evaluation (demoted regex / header
    matchers) and must not be offloaded to the native enforcer."""


@dataclasses.dataclass(frozen=True)
class HTTPRequest:
    method: str
    path: str
    host: str = ""
    headers: Tuple[Tuple[str, str], ...] = ()
    src_identity: int = 0

    def header_dict(self) -> Dict[str, str]:
        return {k.lower(): v for k, v in self.headers}


class _PatternSet:
    """Interned patterns for one field + its compiled DFA.

    Compile failure is isolated PER PATTERN: a single pathological
    regex (state-cap overflow or unsupported syntax) is demoted to
    host `re` on its own; every other pattern stays on the device DFA.
    ``dfa_pids[i]`` maps DFA accept-bit i back to the pattern id it
    represents; ``host_pids`` are the demoted patterns."""

    def __init__(self) -> None:
        self.patterns: List[str] = []
        self._ids: Dict[str, int] = {}
        self.dfa: Optional[MultiDFA] = None
        self.dfa_pids: List[int] = []
        self.host_pids: List[int] = []
        self._host_res: Dict[int, "re.Pattern"] = {}

    def intern(self, pattern: str) -> int:
        pid = self._ids.get(pattern)
        if pid is None:
            pid = len(self.patterns)
            self._ids[pattern] = pid
            self.patterns.append(pattern)
        return pid

    def compile(self) -> None:
        if not self.patterns:
            return
        # the accept mask is one uint64 bit per pattern: more than 64
        # distinct patterns on one port must fail LOUDLY at import
        # (surfaced by endpoint regeneration), never silently shift a
        # rule's bit out of the mask
        if len(self.patterns) > 64:
            raise ValueError(
                f"more than 64 distinct L7 patterns on one port "
                f"({len(self.patterns)})"
            )
        try:
            # interned: N endpoints compiling the same pattern set
            # share one host MultiDFA (and downstream, one device table)
            self.dfa = compile_patterns_cached(self.patterns)
            self.dfa_pids = list(range(len(self.patterns)))
            return
        except RegexError:
            pass
        # isolate offenders: survivors are added greedily so a pattern
        # is demoted only if the COMBINED automaton can't afford it;
        # the last successful build IS the final DFA (no recompile)
        good: List[int] = []
        dfa: Optional[MultiDFA] = None
        self.host_pids = []
        for pid in range(len(self.patterns)):
            try:
                cand = compile_patterns(
                    [self.patterns[i] for i in good] + [self.patterns[pid]]
                )
            except RegexError:
                self.host_pids.append(pid)
                continue
            good.append(pid)
            dfa = cand
        self.dfa_pids = good
        self.dfa = dfa
        # precompile host regexes NOW: a pattern our parser accepts
        # but stdlib `re` rejects must fail once at import, not per
        # request batch on the datapath
        for pid in self.host_pids:
            self._host_res[pid] = re.compile(self.patterns[pid])
        if self.host_pids:
            metrics.l7_fallback_patterns.inc(value=len(self.host_pids))

    def masks(self, values: Sequence[str], max_len: int) -> np.ndarray:
        """[B] uint64 accept masks (bit = pattern id) for a batch of
        field values.

        Values longer than ``max_len`` can't ride the fixed-width DFA
        batch, so they walk the same DFA host-side (linear time — no
        backtracking a long attacker-controlled string could exploit)
        instead of silently never matching (long request paths are
        common enough that fail-closed here would diverge from the
        reference)."""
        n = len(values)
        if not self.patterns:
            return np.zeros(n, np.uint64)
        raw: Optional[np.ndarray] = None
        if self.dfa is not None:
            encs = [v.encode() for v in values]
            if n < _DEVICE_BATCH_MIN:
                # per-request proxy checks are latency-bound: a device
                # dispatch (worst case: first-use jit compile) for a
                # handful of strings loses to a linear host table walk
                raw = np.fromiter(
                    (self.dfa.match_str(e) for e in encs), np.uint64, n
                )
            else:
                raw = match_patterns(self.dfa, encs, max_len)
                self.correct_overlong(raw, encs, max_len)
        return self.finish_masks(raw, values, n)

    def correct_overlong(self, raw: np.ndarray, encs: Sequence[bytes],
                         max_len: int) -> None:
        """Rows too long for the fixed-width device walk re-run on the
        host DFA (linear time, no backtracking) in place of the
        fail-closed 0 the kernel produced."""
        for i, enc in enumerate(encs):
            if len(enc) > max_len:
                raw[i] = np.uint64(self.dfa.match_str(enc))

    def finish_masks(self, raw: Optional[np.ndarray],
                     values: Sequence[str], n: int) -> np.ndarray:
        """DFA accept-bit masks (``raw``, slot-indexed; None = no
        device DFA) → pattern-id masks, plus the demoted-pattern host
        `re` overlay. Shared tail of the split and fused paths — the
        ON/OFF parity tests pin that both produce identical bits."""
        out = np.zeros(n, np.uint64)
        if raw is not None:
            if len(self.dfa_pids) == len(self.patterns):
                out = raw  # identity mapping (no demotions)
            else:
                for slot, pid in enumerate(self.dfa_pids):
                    out |= ((raw >> np.uint64(slot)) & np.uint64(1)) << np.uint64(pid)
        # demoted patterns: host `re` (precompiled at import), counted
        # so a production rule set silently running on Python is
        # visible in /metrics
        for pid in self.host_pids:
            cre = self._host_res[pid]
            hits = np.fromiter(
                (cre.fullmatch(v) is not None for v in values), bool, n
            )
            out |= hits.astype(np.uint64) << np.uint64(pid)
        if self.host_pids:
            metrics.l7_host_fallback_evaluations.inc(
                value=n * len(self.host_pids)
            )
        return out


@dataclasses.dataclass
class _CompiledRule:
    rule: HTTPRule
    method_pid: int  # -1 = wildcard
    path_pid: int
    host_pid: int
    allowed_identities: Optional[Set[int]]  # None = any peer


class HTTPPolicy:
    """All HTTP rules for one (endpoint, port): the NPDS
    PortNetworkPolicy equivalent. ``rules`` pairs each HTTPRule with the
    identity set it applies to (None = wildcard peer — e.g. after
    wildcardL3L4Rules widened it)."""

    def __init__(
        self,
        rules: Sequence[Tuple[HTTPRule, Optional[Set[int]]]],
        max_len: int = 256,
    ) -> None:
        self.max_len = max_len
        self._methods = _PatternSet()
        self._paths = _PatternSet()
        self._hosts = _PatternSet()
        self._rules: List[_CompiledRule] = []
        for rule, idents in rules:
            self._rules.append(
                _CompiledRule(
                    rule=rule,
                    method_pid=self._methods.intern(rule.method) if rule.method else -1,
                    path_pid=self._paths.intern(rule.path) if rule.path else -1,
                    host_pid=self._hosts.intern(rule.host) if rule.host else -1,
                    allowed_identities=set(idents) if idents is not None else None,
                )
            )
        for ps in (self._methods, self._paths, self._hosts):
            ps.compile()
        # L7DeviceBatch: fields with a device DFA fuse into one
        # interned stacked table (built lazily if the option flips on
        # after construction; prewarmed here when it's already on)
        self._fused_fields: List[Tuple[_PatternSet, int]] = []
        self._fused_table = None
        if l7rt.device_batch_enabled():
            self._ensure_fused()

    def _ensure_fused(self) -> None:
        fields = [
            (ps, cap)
            for ps, cap in (
                (self._methods, 16),
                (self._paths, self.max_len),
                (self._hosts, self.max_len),
            )
            if ps.dfa is not None
        ]
        if not fields:
            return
        key = (
            "http",
            tuple(
                tuple(ps.patterns[i] for i in ps.dfa_pids) for ps, _ in fields
            ),
        )
        self._fused_table = intern_fused_table(
            key, lambda: fuse_dfas([ps.dfa for ps, _ in fields])
        )
        self._fused_fields = fields
        pipe = l7rt.shared_pipeline()
        if pipe is not None:
            pipe.prewarm(self._fused_table, [cap for _, cap in fields])

    def _fused_masks(self, requests: Sequence[HTTPRequest]):
        """One device dispatch for every fused field of the batch →
        (m_mask, p_mask, h_mask), or None when the option raced off.
        Bit-identical to the split path: same per-field overlong host
        corrections, demotion remap and host `re` overlay."""
        pipe = l7rt.shared_pipeline()
        if pipe is None:
            return None
        if self._fused_table is None:
            self._ensure_fused()
            if self._fused_table is None:
                return None
        n = len(requests)
        by_field = {
            id(self._methods): [r.method for r in requests],
            id(self._paths): [r.path for r in requests],
            id(self._hosts): [r.host for r in requests],
        }
        encs = [
            [v.encode() for v in by_field[id(ps)]]
            for ps, _ in self._fused_fields
        ]
        pending = pipe.submit(
            self._fused_table,
            [(e, cap) for e, (_, cap) in zip(encs, self._fused_fields)],
            parser="http",
        )
        raws = pending.result()
        out = {}
        for raw, enc, (ps, cap) in zip(raws, encs, self._fused_fields):
            ps.correct_overlong(raw, enc, cap)
            out[id(ps)] = ps.finish_masks(raw, by_field[id(ps)], n)
        # fields without a device DFA (empty, or fully demoted) keep
        # their host-only evaluation
        masks = []
        for ps, cap in (
            (self._methods, 16),
            (self._paths, self.max_len),
            (self._hosts, self.max_len),
        ):
            got = out.get(id(ps))
            masks.append(got if got is not None else ps.masks(by_field[id(ps)], cap))
        return tuple(masks)

    def __len__(self) -> int:
        return len(self._rules)

    def check_batch(self, requests: Sequence[HTTPRequest]) -> np.ndarray:
        """→ [B] bool allow. Empty rule list allows everything (a filter
        with no L7 rules is a pure L4 redirect)."""
        n = len(requests)
        if not self._rules:
            return np.ones(n, bool)
        fused = None
        if l7rt.device_batch_enabled() and n >= _DEVICE_BATCH_MIN:
            fused = self._fused_masks(requests)
        if fused is not None:
            m_mask, p_mask, h_mask = fused
        else:
            m_mask = self._methods.masks([r.method for r in requests], 16)
            p_mask = self._paths.masks([r.path for r in requests], self.max_len)
            h_mask = self._hosts.masks([r.host for r in requests], self.max_len)
        out = np.zeros(n, bool)
        for i, req in enumerate(requests):
            for cr in self._rules:
                if cr.allowed_identities is not None and req.src_identity not in cr.allowed_identities:
                    continue
                if cr.method_pid >= 0 and not (int(m_mask[i]) >> cr.method_pid) & 1:
                    continue
                if cr.path_pid >= 0 and not (int(p_mask[i]) >> cr.path_pid) & 1:
                    continue
                if cr.host_pid >= 0 and not (int(h_mask[i]) >> cr.host_pid) & 1:
                    continue
                if cr.rule.headers:
                    hd = req.header_dict()
                    if not all(
                        (lambda name, want: (got := hd.get(name.strip().lower())) is not None
                         and (not want or got.strip() == want.strip()))(*h.partition(":")[::2])
                        for h in cr.rule.headers
                    ):
                        continue
                out[i] = True
                break
        return out

    def check(self, request: HTTPRequest) -> bool:
        return bool(self.check_batch([request])[0])

    def native_tables(self):
        """Export the compiled state for the native (C++) enforcer:
        → (method_dfa, path_dfa, host_dfa, rules) where each dfa is a
        MultiDFA or None and rules are (m_bit, p_bit, h_bit, idents)
        tuples — bit = the pattern's accept-bit slot in that field's
        DFA, -1 = wildcard. Raises NativeL7Unsupported when any rule
        depends on host-only evaluation (a pattern demoted from the
        DFA, or header matchers) — those policies must stay on the
        Python path, loudly."""
        def bit_of(ps: _PatternSet, pid: int) -> int:
            if pid < 0:
                return -1
            if pid in ps.host_pids:
                raise NativeL7Unsupported(
                    f"pattern {ps.patterns[pid]!r} is host-demoted"
                )
            return ps.dfa_pids.index(pid)

        rules = []
        for cr in self._rules:
            if cr.rule.headers:
                raise NativeL7Unsupported("header matchers are host-only")
            rules.append((
                bit_of(self._methods, cr.method_pid),
                bit_of(self._paths, cr.path_pid),
                bit_of(self._hosts, cr.host_pid),
                cr.allowed_identities,
            ))
        return (
            self._methods.dfa, self._paths.dfa, self._hosts.dfa, rules
        )

    @classmethod
    def from_model(cls, rules: List[Dict]) -> "HTTPPolicy":
        """Rebuild a policy from the rules_model() JSON an NPDS
        subscriber received — the external proxy's deserialization
        side (the C++ filter parses the NetworkPolicy proto the same
        way, envoy/cilium_network_policy.cc)."""
        pairs = []
        for d in rules:
            pairs.append((
                HTTPRule(
                    method=d.get("method", ""),
                    path=d.get("path", ""),
                    host=d.get("host", ""),
                    headers=tuple(d.get("headers", ())),
                ),
                set(d["remote_policies"]) if "remote_policies" in d else None,
            ))
        return cls(pairs)

    def rules_model(self) -> List[Dict]:
        """JSON-able view of the compiled rules — the NPDS
        PortNetworkPolicyRule shape (http_rules + remote_policies,
        envoy/cilium_network_policy.h) the xDS layer distributes."""
        out: List[Dict] = []
        for cr in self._rules:
            d: Dict = {}
            if cr.rule.method:
                d["method"] = cr.rule.method
            if cr.rule.path:
                d["path"] = cr.rule.path
            if cr.rule.host:
                d["host"] = cr.rule.host
            if cr.rule.headers:
                d["headers"] = list(cr.rule.headers)
            if cr.allowed_identities is not None:
                d["remote_policies"] = sorted(cr.allowed_identities)
            out.append(d)
        return out
