"""Per-endpoint-port HTTP policy: compiled DFA enforcement.

Reference: the NPDS policy Envoy enforces per request
(envoy/cilium_network_policy.h:68-202 PortNetworkPolicy.Matches chain —
remote identity must match an allowed selector AND some HTTP rule's
method/path/host/header matchers must all pass; deny → 403).

Compilation: distinct non-empty method/path/host regexes across the
rules become three multi-pattern DFAs; a rule matches when its bits are
set (or the field is a wildcard) in every field's accept mask. Header
checks are exact matches evaluated host-side (rare in practice).
Patterns that exceed the DFA state cap fall back to host `re` matching
— fail-safe, never fail-open.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..ops.dfa import match_patterns
from ..policy.api import HTTPRule
from .regex_compile import MultiDFA, RegexError, compile_patterns


@dataclasses.dataclass(frozen=True)
class HTTPRequest:
    method: str
    path: str
    host: str = ""
    headers: Tuple[Tuple[str, str], ...] = ()
    src_identity: int = 0

    def header_dict(self) -> Dict[str, str]:
        return {k.lower(): v for k, v in self.headers}


class _PatternSet:
    """Interned patterns for one field + its compiled DFA (None when any
    pattern overflowed the state cap → host fallback)."""

    def __init__(self) -> None:
        self.patterns: List[str] = []
        self._ids: Dict[str, int] = {}
        self.dfa: Optional[MultiDFA] = None
        self.fallback = False

    def intern(self, pattern: str) -> int:
        pid = self._ids.get(pattern)
        if pid is None:
            pid = len(self.patterns)
            self._ids[pattern] = pid
            self.patterns.append(pattern)
        return pid

    def compile(self) -> None:
        if not self.patterns:
            return
        try:
            self.dfa = compile_patterns(self.patterns)
        except RegexError:
            self.fallback = True

    def masks(self, values: Sequence[str], max_len: int) -> np.ndarray:
        """[B] uint64 accept masks for a batch of field values.

        Values longer than ``max_len`` can't ride the fixed-width DFA
        batch, so they walk the same DFA host-side (linear time — no
        backtracking a long attacker-controlled string could exploit)
        instead of silently never matching (long request paths are
        common enough that fail-closed here would diverge from the
        reference)."""
        if not self.patterns:
            return np.zeros(len(values), np.uint64)
        if self.dfa is not None and not self.fallback:
            encs = [v.encode() for v in values]
            out = match_patterns(self.dfa, encs, max_len)
            for i, enc in enumerate(encs):
                if len(enc) > max_len:
                    out[i] = np.uint64(self.dfa.match_str(enc))
            return out
        # DFA compile overflowed the state cap: host `re` is the only
        # engine left. re.error propagates loudly — a pattern this
        # parser accepts but `re` rejects must not silently never-match.
        return np.array(
            [
                sum(
                    1 << pid
                    for pid, p in enumerate(self.patterns)
                    if re.fullmatch(p, v)
                )
                for v in values
            ],
            np.uint64,
        )


@dataclasses.dataclass
class _CompiledRule:
    rule: HTTPRule
    method_pid: int  # -1 = wildcard
    path_pid: int
    host_pid: int
    allowed_identities: Optional[Set[int]]  # None = any peer


class HTTPPolicy:
    """All HTTP rules for one (endpoint, port): the NPDS
    PortNetworkPolicy equivalent. ``rules`` pairs each HTTPRule with the
    identity set it applies to (None = wildcard peer — e.g. after
    wildcardL3L4Rules widened it)."""

    def __init__(
        self,
        rules: Sequence[Tuple[HTTPRule, Optional[Set[int]]]],
        max_len: int = 256,
    ) -> None:
        self.max_len = max_len
        self._methods = _PatternSet()
        self._paths = _PatternSet()
        self._hosts = _PatternSet()
        self._rules: List[_CompiledRule] = []
        for rule, idents in rules:
            self._rules.append(
                _CompiledRule(
                    rule=rule,
                    method_pid=self._methods.intern(rule.method) if rule.method else -1,
                    path_pid=self._paths.intern(rule.path) if rule.path else -1,
                    host_pid=self._hosts.intern(rule.host) if rule.host else -1,
                    allowed_identities=set(idents) if idents is not None else None,
                )
            )
        for ps in (self._methods, self._paths, self._hosts):
            ps.compile()

    def __len__(self) -> int:
        return len(self._rules)

    def check_batch(self, requests: Sequence[HTTPRequest]) -> np.ndarray:
        """→ [B] bool allow. Empty rule list allows everything (a filter
        with no L7 rules is a pure L4 redirect)."""
        n = len(requests)
        if not self._rules:
            return np.ones(n, bool)
        m_mask = self._methods.masks([r.method for r in requests], 16)
        p_mask = self._paths.masks([r.path for r in requests], self.max_len)
        h_mask = self._hosts.masks([r.host for r in requests], self.max_len)
        out = np.zeros(n, bool)
        for i, req in enumerate(requests):
            for cr in self._rules:
                if cr.allowed_identities is not None and req.src_identity not in cr.allowed_identities:
                    continue
                if cr.method_pid >= 0 and not (int(m_mask[i]) >> cr.method_pid) & 1:
                    continue
                if cr.path_pid >= 0 and not (int(p_mask[i]) >> cr.path_pid) & 1:
                    continue
                if cr.host_pid >= 0 and not (int(h_mask[i]) >> cr.host_pid) & 1:
                    continue
                if cr.rule.headers:
                    hd = req.header_dict()
                    if not all(
                        (lambda name, want: (got := hd.get(name.strip().lower())) is not None
                         and (not want or got.strip() == want.strip()))(*h.partition(":")[::2])
                        for h in cr.rule.headers
                    ):
                        continue
                out[i] = True
                break
        return out

    def check(self, request: HTTPRequest) -> bool:
        return bool(self.check_batch([request])[0])
