"""Kafka ACL enforcement as broadcast-compare tables.

Reference: pkg/kafka/policy.go:144,200 — a request (api_key,
api_version, client_id, topics) matches a rule when every set field
matches, with Role produce/consume expanding to api-key sets
(pkg/policy/api/kafka.go). Deny → synthesized error response
(pkg/kafka/request.go:158).

Tensorization: api-key sets become a 32-bit mask per rule; topics and
client-ids are interned to ids; a batch check is [B, R] broadcast
compares — fully device-friendly, no string work per request after
interning.

With ``L7DeviceBatch`` on, the topic/client-id string→id resolution
rides the same fused DFA path as HTTP (each interned literal becomes
one pattern; the accept bit IS the id), sharing interned device tables
across endpoints with the same ACL. Off, the dict-lookup path below
runs unchanged.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..datapath import l7_pipeline as l7rt
from ..ops.dfa import fuse_dfas, intern_fused_table
from ..policy.api import KafkaRule
from .http_policy import _DEVICE_BATCH_MIN
from .regex_compile import RegexError, compile_patterns_cached


def _mask_ids(mask: np.ndarray) -> np.ndarray:
    """[B] uint64 one-hot accept masks → [B] int32 literal ids (-2 =
    no match, the dict-lookup miss sentinel). Distinct literals are
    disjoint, so at most one bit is set; frexp's exponent recovers the
    bit index exactly (powers of two are exact in float64)."""
    ids = np.full(mask.shape, -2, np.int32)
    nz = mask != 0
    if nz.any():
        _, e = np.frexp(mask[nz].astype(np.float64))
        ids[nz] = (e - 1).astype(np.int32)
    return ids


@dataclasses.dataclass(frozen=True)
class KafkaRequest:
    api_key: int
    api_version: int = 0
    client_id: str = ""
    topic: str = ""
    src_identity: int = 0


class KafkaACL:
    """All Kafka rules for one (endpoint, port)."""

    def __init__(self, rules: Sequence[Tuple[KafkaRule, Optional[Set[int]]]]) -> None:
        self._rules = list(rules)
        self._topic_ids: Dict[str, int] = {}
        r = len(rules)
        self.key_mask = np.zeros(r, np.uint32)  # bit k = api_key k allowed
        self.key_wild = np.zeros(r, bool)  # rule has no api-key restriction
        self.version = np.full(r, -1, np.int32)  # -1 = wildcard
        self.topic_id = np.full(r, -1, np.int32)
        self.client_id: List[str] = []
        for i, (rule, _idents) in enumerate(rules):
            keys = rule.allowed_api_keys()
            self.key_wild[i] = not keys
            self.key_mask[i] = (
                np.uint32(0xFFFFFFFF)
                if not keys
                else np.uint32(sum(1 << k for k in keys))
            )
            if rule.api_version:
                self.version[i] = int(rule.api_version)
            if rule.topic:
                self.topic_id[i] = self._intern_topic(rule.topic)
            self.client_id.append(rule.client_id)
        # Per-batch-invariant lookup state, hoisted out of check_batch:
        # rebuilding the client-id intern map and the scoped identity
        # arrays per call made every batch pay O(R) dict/array builds —
        # the kafka_acl_rps drag once batches got small and frequent.
        self._cli_ids: Dict[str, int] = (
            {c: k for k, c in enumerate(sorted(set(self.client_id)))}
            if any(self.client_id)
            else {}
        )
        self._rule_cli_id: Optional[np.ndarray] = (
            np.array(
                [self._cli_ids[c] if c else -1 for c in self.client_id],
                np.int32,
            )
            if self._cli_ids
            else None
        )
        self._scoped: List[Tuple[int, np.ndarray]] = [
            (j, np.fromiter(idents, np.int64, len(idents)))
            for j, (_r, idents) in enumerate(self._rules)
            if idents is not None
        ]
        # L7DeviceBatch literal classification (built lazily on first
        # gated batch so the OFF path never touches the device)
        self._fused_ready = False
        self._fused_table = None
        self._fused_fields: List[Tuple[str, int]] = []
        if l7rt.device_batch_enabled():
            self._ensure_fused()

    def _ensure_fused(self) -> None:
        if self._fused_ready:
            return
        self._fused_ready = True
        fields: List[Tuple[str, List[str]]] = []
        # literal ids are accept-bit positions, so id order must equal
        # pattern order; one uint64 mask caps each map at 64 literals
        if self._topic_ids and len(self._topic_ids) <= 64:
            fields.append(
                ("topic", sorted(self._topic_ids, key=self._topic_ids.get))
            )
        if self._cli_ids and len(self._cli_ids) <= 64:
            fields.append(
                ("client_id", sorted(self._cli_ids, key=self._cli_ids.get))
            )
        if not fields:
            return
        try:
            dfas = [
                compile_patterns_cached([re.escape(v) for v in vals])
                for _, vals in fields
            ]
        except RegexError:
            return  # state cap — the dict path serves this ACL
        key = ("kafka", tuple((name, tuple(vals)) for name, vals in fields))
        self._fused_table = intern_fused_table(key, lambda: fuse_dfas(dfas))
        # a request string longer than every interned literal can't
        # match one, so the field cap is the longest literal: overlong
        # rows fail closed to -2, which is exactly the dict miss
        self._fused_fields = [
            (name, max(len(v.encode()) for v in vals)) for name, vals in fields
        ]
        pipe = l7rt.shared_pipeline()
        if pipe is not None:
            pipe.prewarm(self._fused_table, [c for _, c in self._fused_fields])

    def _device_ids(
        self, requests: Sequence[KafkaRequest]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Resolve topic/client-id strings to interned ids on device →
        {"topic": [B] int32, "client_id": ...} (keys only for fused
        fields), or None when the device path doesn't apply."""
        self._ensure_fused()
        if self._fused_table is None:
            return None
        pipe = l7rt.shared_pipeline()
        if pipe is None:
            return None
        by_name = {
            "topic": lambda r: r.topic,
            "client_id": lambda r: r.client_id,
        }
        encs = [
            [by_name[name](r).encode() for r in requests]
            for name, _ in self._fused_fields
        ]
        pending = pipe.submit(
            self._fused_table,
            [(e, cap) for e, (_, cap) in zip(encs, self._fused_fields)],
            parser="kafka",
        )
        raws = pending.result()
        return {
            name: _mask_ids(raw)
            for raw, (name, _) in zip(raws, self._fused_fields)
        }

    def _intern_topic(self, topic: str) -> int:
        tid = self._topic_ids.get(topic)
        if tid is None:
            tid = len(self._topic_ids)
            self._topic_ids[topic] = tid
        return tid

    def __len__(self) -> int:
        return len(self._rules)

    def check_batch(self, requests: Sequence[KafkaRequest]) -> np.ndarray:
        """→ [B] bool allow (empty rule list allows everything)."""
        n = len(requests)
        if not self._rules:
            return np.ones(n, bool)
        api_key = np.array([r.api_key for r in requests], np.int32)
        version = np.array([r.api_version for r in requests], np.int32)
        dev = (
            self._device_ids(requests)
            if l7rt.device_batch_enabled() and n >= _DEVICE_BATCH_MIN
            else None
        )
        if dev is not None and "topic" in dev:
            topic = dev["topic"]
        else:
            topic = np.array(
                [self._topic_ids.get(r.topic, -2) for r in requests], np.int32
            )
        # [B, R] broadcast compares (the device-friendly form; numpy here
        # because L7 batch sizes are modest — the same expressions jit
        # directly when wired into the proxy fast path).
        # Real api keys exceed 31 (DescribeConfigs=32, SaslAuthenticate=36);
        # the 32-bit mask only constrains rules with an explicit key set —
        # wildcard rules match every key.
        in_mask = (self.key_mask[None, :] >> api_key[:, None].clip(0, 31)) & 1 == 1
        in_range = (api_key[:, None] >= 0) & (api_key[:, None] < 32)
        key_ok = self.key_wild[None, :] | (in_mask & in_range)
        ver_ok = (self.version[None, :] < 0) | (self.version[None, :] == version[:, None])
        top_ok = (self.topic_id[None, :] < 0) | (self.topic_id[None, :] == topic[:, None])
        ok = key_ok & ver_ok & top_ok
        # client-id: interned compare, vectorized over the batch
        # (an O(B·R) Python loop here dominated the batch rate ~20×);
        # the intern map and rule-side id array are __init__ caches
        if self._rule_cli_id is not None:
            if dev is not None and "client_id" in dev:
                req_cli_id = dev["client_id"]
            else:
                req_cli_id = np.array(
                    [self._cli_ids.get(r.client_id, -2) for r in requests],
                    np.int32,
                )
            ok &= (self._rule_cli_id[None, :] < 0) | (
                self._rule_cli_id[None, :] == req_cli_id[:, None]
            )
        # identity scoping: per scoped rule, one vectorized membership
        if self._scoped:
            src = np.array([r.src_identity for r in requests], np.int64)
            for j, idents_arr in self._scoped:
                cand = ok[:, j]
                if cand.any():
                    ok[cand, j] = np.isin(src[cand], idents_arr)
        return ok.any(axis=1)

    @classmethod
    def from_model(cls, rules: List[Dict]) -> "KafkaACL":
        """Rebuild an ACL from the rules_model() JSON an NPDS
        subscriber received (the external proxy's deserialization
        side)."""
        pairs = []
        for d in rules:
            pairs.append((
                KafkaRule(
                    role=d.get("role", ""),
                    api_key=d.get("api_key", ""),
                    api_version=d.get("api_version", ""),
                    client_id=d.get("client_id", ""),
                    topic=d.get("topic", ""),
                ),
                set(d["remote_policies"]) if "remote_policies" in d else None,
            ))
        return cls(pairs)

    def rules_model(self) -> List[Dict]:
        """JSON-able view of the rules + their identity scopes (the
        NPDS kafka_rules shape, mirroring HTTPPolicy.rules_model)."""
        out: List[Dict] = []
        for rule, idents in self._rules:
            d: Dict = {}
            for key, val in (
                ("role", rule.role), ("api_key", rule.api_key),
                ("api_version", rule.api_version),
                ("client_id", rule.client_id), ("topic", rule.topic),
            ):
                if val:
                    d[key] = val
            if idents is not None:
                d["remote_policies"] = sorted(idents)
            out.append(d)
        return out

    def check(self, request: KafkaRequest) -> bool:
        return bool(self.check_batch([request])[0])
