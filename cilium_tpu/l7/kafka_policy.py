"""Kafka ACL enforcement as broadcast-compare tables.

Reference: pkg/kafka/policy.go:144,200 — a request (api_key,
api_version, client_id, topics) matches a rule when every set field
matches, with Role produce/consume expanding to api-key sets
(pkg/policy/api/kafka.go). Deny → synthesized error response
(pkg/kafka/request.go:158).

Tensorization: api-key sets become a 32-bit mask per rule; topics and
client-ids are interned to ids; a batch check is [B, R] broadcast
compares — fully device-friendly, no string work per request after
interning.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..policy.api import KafkaRule


@dataclasses.dataclass(frozen=True)
class KafkaRequest:
    api_key: int
    api_version: int = 0
    client_id: str = ""
    topic: str = ""
    src_identity: int = 0


class KafkaACL:
    """All Kafka rules for one (endpoint, port)."""

    def __init__(self, rules: Sequence[Tuple[KafkaRule, Optional[Set[int]]]]) -> None:
        self._rules = list(rules)
        self._topic_ids: Dict[str, int] = {}
        r = len(rules)
        self.key_mask = np.zeros(r, np.uint32)  # bit k = api_key k allowed
        self.key_wild = np.zeros(r, bool)  # rule has no api-key restriction
        self.version = np.full(r, -1, np.int32)  # -1 = wildcard
        self.topic_id = np.full(r, -1, np.int32)
        self.client_id: List[str] = []
        for i, (rule, _idents) in enumerate(rules):
            keys = rule.allowed_api_keys()
            self.key_wild[i] = not keys
            self.key_mask[i] = (
                np.uint32(0xFFFFFFFF)
                if not keys
                else np.uint32(sum(1 << k for k in keys))
            )
            if rule.api_version:
                self.version[i] = int(rule.api_version)
            if rule.topic:
                self.topic_id[i] = self._intern_topic(rule.topic)
            self.client_id.append(rule.client_id)
        # Per-batch-invariant lookup state, hoisted out of check_batch:
        # rebuilding the client-id intern map and the scoped identity
        # arrays per call made every batch pay O(R) dict/array builds —
        # the kafka_acl_rps drag once batches got small and frequent.
        self._cli_ids: Dict[str, int] = (
            {c: k for k, c in enumerate(sorted(set(self.client_id)))}
            if any(self.client_id)
            else {}
        )
        self._rule_cli_id: Optional[np.ndarray] = (
            np.array(
                [self._cli_ids[c] if c else -1 for c in self.client_id],
                np.int32,
            )
            if self._cli_ids
            else None
        )
        self._scoped: List[Tuple[int, np.ndarray]] = [
            (j, np.fromiter(idents, np.int64, len(idents)))
            for j, (_r, idents) in enumerate(self._rules)
            if idents is not None
        ]

    def _intern_topic(self, topic: str) -> int:
        tid = self._topic_ids.get(topic)
        if tid is None:
            tid = len(self._topic_ids)
            self._topic_ids[topic] = tid
        return tid

    def __len__(self) -> int:
        return len(self._rules)

    def check_batch(self, requests: Sequence[KafkaRequest]) -> np.ndarray:
        """→ [B] bool allow (empty rule list allows everything)."""
        n = len(requests)
        if not self._rules:
            return np.ones(n, bool)
        api_key = np.array([r.api_key for r in requests], np.int32)
        version = np.array([r.api_version for r in requests], np.int32)
        topic = np.array(
            [self._topic_ids.get(r.topic, -2) for r in requests], np.int32
        )
        # [B, R] broadcast compares (the device-friendly form; numpy here
        # because L7 batch sizes are modest — the same expressions jit
        # directly when wired into the proxy fast path).
        # Real api keys exceed 31 (DescribeConfigs=32, SaslAuthenticate=36);
        # the 32-bit mask only constrains rules with an explicit key set —
        # wildcard rules match every key.
        in_mask = (self.key_mask[None, :] >> api_key[:, None].clip(0, 31)) & 1 == 1
        in_range = (api_key[:, None] >= 0) & (api_key[:, None] < 32)
        key_ok = self.key_wild[None, :] | (in_mask & in_range)
        ver_ok = (self.version[None, :] < 0) | (self.version[None, :] == version[:, None])
        top_ok = (self.topic_id[None, :] < 0) | (self.topic_id[None, :] == topic[:, None])
        ok = key_ok & ver_ok & top_ok
        # client-id: interned compare, vectorized over the batch
        # (an O(B·R) Python loop here dominated the batch rate ~20×);
        # the intern map and rule-side id array are __init__ caches
        if self._rule_cli_id is not None:
            req_cli_id = np.array(
                [self._cli_ids.get(r.client_id, -2) for r in requests],
                np.int32,
            )
            ok &= (self._rule_cli_id[None, :] < 0) | (
                self._rule_cli_id[None, :] == req_cli_id[:, None]
            )
        # identity scoping: per scoped rule, one vectorized membership
        if self._scoped:
            src = np.array([r.src_identity for r in requests], np.int64)
            for j, idents_arr in self._scoped:
                cand = ok[:, j]
                if cand.any():
                    ok[cand, j] = np.isin(src[cand], idents_arr)
        return ok.any(axis=1)

    @classmethod
    def from_model(cls, rules: List[Dict]) -> "KafkaACL":
        """Rebuild an ACL from the rules_model() JSON an NPDS
        subscriber received (the external proxy's deserialization
        side)."""
        pairs = []
        for d in rules:
            pairs.append((
                KafkaRule(
                    role=d.get("role", ""),
                    api_key=d.get("api_key", ""),
                    api_version=d.get("api_version", ""),
                    client_id=d.get("client_id", ""),
                    topic=d.get("topic", ""),
                ),
                set(d["remote_policies"]) if "remote_policies" in d else None,
            ))
        return cls(pairs)

    def rules_model(self) -> List[Dict]:
        """JSON-able view of the rules + their identity scopes (the
        NPDS kafka_rules shape, mirroring HTTPPolicy.rules_model)."""
        out: List[Dict] = []
        for rule, idents in self._rules:
            d: Dict = {}
            for key, val in (
                ("role", rule.role), ("api_key", rule.api_key),
                ("api_version", rule.api_version),
                ("client_id", rule.client_id), ("topic", rule.topic),
            ):
                if val:
                    d[key] = val
            if idents is not None:
                d["remote_policies"] = sorted(idents)
            out.append(d)
        return out

    def check(self, request: KafkaRequest) -> bool:
        return bool(self.check_batch([request])[0])
