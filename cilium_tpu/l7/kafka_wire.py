"""Kafka wire protocol: request parsing + reject-response synthesis.

Reference: pkg/kafka/request.go — ReadRequest (:30) decodes the
request header (api_key, api_version, correlation_id, client_id) and
extracts topics per api key (GetTopics :186); CreateResponse (:158)
synthesizes a correctly-framed error response that preserves the
correlation id so the client sees a protocol-legal authorization
failure instead of a dead connection; correlation_cache.go matches
in-flight requests to responses when the proxy renumbers correlation
ids.

Scope mirrors the reference's 0.11-era coverage: Produce, Fetch,
ListOffsets, Metadata, OffsetCommit, OffsetFetch get full topic
extraction + typed reject bodies; other api keys parse the header and
reject with a header-only frame.
"""

from __future__ import annotations

import dataclasses
import struct
import threading
from typing import Dict, List, Optional, Tuple

# api keys (kafka protocol)
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9

ERR_TOPIC_AUTHORIZATION_FAILED = 29


class KafkaParseError(ValueError):
    pass


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise KafkaParseError("truncated request")
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8", "replace")

    def skip(self, n: int) -> None:
        self._take(n)


@dataclasses.dataclass(frozen=True)
class ParsedRequest:
    """Decoded request header + the topic/partition view the ACL and
    the reject builder need. ``raw`` is the full frame (size prefix
    included) for pass-through forwarding."""

    api_key: int
    api_version: int
    correlation_id: int
    client_id: str
    topics: Tuple[str, ...]
    partitions: Dict[str, Tuple[int, ...]]
    raw: bytes
    # False only for Produce with acks=0: the client expects NO
    # response frame (pkg/kafka/request.go tracks the same bit so the
    # proxy neither waits on the broker nor synthesizes a reject)
    expect_response: bool = True


def _parse_topic_partitions(r: _Reader, with_partition_body) -> Dict[str, Tuple[int, ...]]:
    """array of [topic string, array of partition entries]."""
    out: Dict[str, Tuple[int, ...]] = {}
    n = r.i32()
    if n < 0:
        return out
    if n > 1_000_000:
        raise KafkaParseError("implausible topic count")
    for _ in range(n):
        topic = r.string() or ""
        parts = []
        pn = r.i32()
        if pn < 0:
            pn = 0
        if pn > 1_000_000:
            raise KafkaParseError("implausible partition count")
        for _ in range(pn):
            parts.append(r.i32())
            with_partition_body(r)
        out[topic] = tuple(parts)
    return out


def parse_request(data: bytes) -> ParsedRequest:
    """Decode one length-prefixed request frame (ReadRequest,
    request.go:30)."""
    if len(data) < 4:
        raise KafkaParseError("short frame")
    (size,) = struct.unpack(">i", data[:4])
    if size < 8 or 4 + size > len(data):
        raise KafkaParseError(f"bad frame size {size}")
    r = _Reader(data[4:4 + size])
    api_key = r.i16()
    api_version = r.i16()
    correlation_id = r.i32()
    client_id = r.string() or ""
    topics: Dict[str, Tuple[int, ...]] = {}
    try:
        expect_response = True
        if api_key == API_PRODUCE:
            if api_version >= 3:
                r.string()  # transactional_id
            acks = r.i16()
            expect_response = acks != 0
            r.i32()  # timeout
            # partition body: message set size + bytes
            topics = _parse_topic_partitions(
                r, lambda rr: rr.skip(max(0, rr.i32()))
            )
        elif api_key == API_FETCH:
            r.i32()  # replica_id
            r.i32()  # max_wait
            r.i32()  # min_bytes
            if api_version >= 3:
                r.i32()  # max_bytes
            if api_version >= 4:
                r.i8()  # isolation_level
            # partition body: fetch_offset i64 (+v5 log_start i64) + max_bytes i32
            def fetch_part(rr):
                rr.i64()
                if api_version >= 5:
                    rr.i64()
                rr.i32()

            topics = _parse_topic_partitions(r, fetch_part)
        elif api_key == API_LIST_OFFSETS:
            r.i32()  # replica_id
            if api_version >= 2:
                r.i8()  # isolation_level
            def lo_part(rr):
                rr.i64()  # timestamp
                if api_version == 0:
                    rr.i32()  # max_num_offsets
            topics = _parse_topic_partitions(r, lo_part)
        elif api_key == API_METADATA:
            n = r.i32()
            if n > 1_000_000:
                raise KafkaParseError("implausible topic count")
            for _ in range(max(0, n)):
                topics[r.string() or ""] = ()
        elif api_key == API_OFFSET_COMMIT:
            r.string()  # group id
            if api_version >= 1:
                r.i32()  # generation
                r.string()  # member id
            if api_version >= 2:
                r.i64()  # retention
            def oc_part(rr):
                rr.i64()  # offset
                if api_version == 1:
                    rr.i64()  # timestamp
                rr.string()  # metadata
            topics = _parse_topic_partitions(r, oc_part)
        elif api_key == API_OFFSET_FETCH:
            r.string()  # group id
            topics = _parse_topic_partitions(r, lambda rr: None)
    except KafkaParseError:
        raise
    return ParsedRequest(
        api_key=api_key,
        api_version=api_version,
        correlation_id=correlation_id,
        client_id=client_id,
        topics=tuple(topics),
        partitions=topics,
        raw=bytes(data[:4 + size]),
        expect_response=expect_response,
    )


# ---------------------------------------------------------------------
# reject synthesis (CreateResponse, request.go:158)

def _w_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _frame(correlation_id: int, body: bytes) -> bytes:
    payload = struct.pack(">i", correlation_id) + body
    return struct.pack(">i", len(payload)) + payload


def reject_response(
    req: ParsedRequest, error_code: int = ERR_TOPIC_AUTHORIZATION_FAILED
) -> bytes:
    """Protocol-legal error response preserving the correlation id —
    the client's library surfaces 'authorization failed' instead of
    hanging on a silently-dropped request."""
    k, v = req.api_key, req.api_version
    parts = lambda t: req.partitions.get(t) or (0,)
    body = b""
    if k == API_PRODUCE:
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += _w_str(t) + struct.pack(">i", len(parts(t)))
            for p in parts(t):
                body += struct.pack(">ihq", p, error_code, -1)
                if v >= 2:
                    body += struct.pack(">q", -1)  # log_append_time
        if v >= 1:
            body += struct.pack(">i", 0)  # throttle_time
    elif k == API_FETCH:
        if v >= 1:
            body += struct.pack(">i", 0)  # throttle_time
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += _w_str(t) + struct.pack(">i", len(parts(t)))
            for p in parts(t):
                body += struct.pack(">ihq", p, error_code, -1)  # high watermark
                if v >= 4:
                    body += struct.pack(">q", -1)  # last_stable_offset
                    if v >= 5:
                        body += struct.pack(">q", -1)  # log_start_offset
                    # aborted_transactions is a NULLABLE array: null
                    # encodes as count -1 (not an empty array)
                    body += struct.pack(">i", -1)
                body += struct.pack(">i", 0)  # message set size
    elif k == API_METADATA:
        if v >= 3:
            body += struct.pack(">i", 0)  # throttle_time
        body += struct.pack(">i", 0)  # brokers: empty
        if v >= 2:
            body += _w_str("")  # cluster id (nullable → empty)
        if v >= 1:
            body += struct.pack(">i", -1)  # controller id
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += struct.pack(">h", error_code) + _w_str(t)
            if v >= 1:
                body += struct.pack(">b", 0)  # is_internal
            body += struct.pack(">i", 0)  # partitions: empty
    elif k == API_LIST_OFFSETS:
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += _w_str(t) + struct.pack(">i", len(parts(t)))
            for p in parts(t):
                if v == 0:
                    body += struct.pack(">ihi", p, error_code, 0)  # offsets []
                else:
                    body += struct.pack(">ihqq", p, error_code, -1, -1)
    elif k == API_OFFSET_COMMIT:
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += _w_str(t) + struct.pack(">i", len(parts(t)))
            for p in parts(t):
                body += struct.pack(">ih", p, error_code)
    elif k == API_OFFSET_FETCH:
        if v >= 3:
            body += struct.pack(">i", 0)  # throttle_time
        body += struct.pack(">i", len(req.topics))
        for t in req.topics:
            body += _w_str(t) + struct.pack(">i", len(parts(t)))
            for p in parts(t):
                body += struct.pack(">iq", p, -1) + _w_str("") + struct.pack(
                    ">h", error_code
                )
        if v >= 2:
            # v2+ carries a top-level error code after the topic array
            body += struct.pack(">h", error_code)
    # other api keys: header-only frame (still unblocks the client)
    return _frame(req.correlation_id, body)


# ---------------------------------------------------------------------
class CorrelationCache:
    """Proxy-side correlation-id renumbering (correlation_cache.go):
    requests forwarded upstream get a fresh id (distinct streams can
    reuse client ids); responses are matched back and rewritten to the
    client's original id."""

    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._next = 1
        self._inflight: Dict[int, int] = {}  # proxy cid → client cid
        self.capacity = capacity

    def forward(self, req: ParsedRequest) -> bytes:
        """Rewrite the request frame with a proxy correlation id;
        remembers the mapping. Raises if too many in flight."""
        with self._lock:
            if len(self._inflight) >= self.capacity:
                raise KafkaParseError("correlation cache full")
            cid = self._next
            self._next = (self._next + 1) & 0x7FFFFFFF or 1
            self._inflight[cid] = req.correlation_id
        # correlation id sits at bytes 8..12 of the frame
        return req.raw[:8] + struct.pack(">i", cid) + req.raw[12:]

    def correlate(self, response: bytes) -> Optional[bytes]:
        """Match a response frame to its request; returns the frame
        rewritten to the client's correlation id, or None for an
        unknown id (response dropped, request.go behavior)."""
        if len(response) < 8:
            return None
        (cid,) = struct.unpack(">i", response[4:8])
        with self._lock:
            orig = self._inflight.pop(cid, None)
        if orig is None:
            return None
        return response[:4] + struct.pack(">i", orig) + response[8:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._inflight)
