"""POSIX-egrep-subset regex → multi-pattern DFA transition tables.

Reference semantics: pkg/policy/api/http.go:23-28 — HTTP rule fields
(Path, Method, Host) are anchored POSIX regexes compiled with Go's
regexp. The supported subset here covers what HTTP policies use:
literals, '.', character classes [a-z0-9_] with negation and escapes,
alternation '|', grouping '()', quantifiers * + ? and {m}/{m,}/{m,n}
(n bounded), and escaped metacharacters. Patterns are fully anchored
(Go wraps with ^(?:...)$ — server.go:316 getHTTPRule uses anchored
matchers).

Pipeline: parse → Thompson NFA → subset-construction DFA over the
byte alphabet, with *all patterns combined into one DFA* whose accept
sets are per-state pattern bitmasks — one table walk classifies a
string against every pattern at once (the vmapped-NFA-tables idea from
BASELINE.json). State count is capped; overflow raises RegexError and
the caller falls back to host-side matching.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

MAX_REPEAT = 32
MAX_DFA_STATES = 4096
ALPHABET = 256


class RegexError(ValueError):
    pass


# -- parser (recursive descent) -> NFA fragments ---------------------------
# NFA: states are ints; transitions: List[Dict[int, Set[int]]] byte→states;
# epsilon: List[Set[int]].


class _NFA:
    def __init__(self) -> None:
        self.trans: List[Dict[int, Set[int]]] = []
        self.eps: List[Set[int]] = []

    def new_state(self) -> int:
        self.trans.append({})
        self.eps.append(set())
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].add(b)

    def add_byte(self, a: int, byte: int, b: int) -> None:
        self.trans[a].setdefault(byte, set()).add(b)


_META = set("().[]*+?{}|\\^$")

_ESCAPE_CLASSES = {
    "d": set(range(ord("0"), ord("9") + 1)),
    "w": set(range(ord("a"), ord("z") + 1))
    | set(range(ord("A"), ord("Z") + 1))
    | set(range(ord("0"), ord("9") + 1))
    | {ord("_")},
    "s": {0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C},
}


def _class_for_escape(c: str) -> Optional[Set[int]]:
    """\\d/\\w/\\s → byte set, uppercase → complement, else None."""
    if c in _ESCAPE_CLASSES:
        return _ESCAPE_CLASSES[c]
    if c.isupper() and c.lower() in _ESCAPE_CLASSES:
        return set(range(ALPHABET)) - _ESCAPE_CLASSES[c.lower()]
    return None


class _Parser:
    """Grammar: alt := concat ('|' concat)* ; concat := repeat* ;
    repeat := atom ('*'|'+'|'?'|'{m,n}')* ; atom := literal | '.' |
    class | '(' alt ')'."""

    def __init__(self, pattern: str, nfa: _NFA) -> None:
        self.p = pattern
        self.i = 0
        self.nfa = nfa

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self) -> Tuple[int, int]:
        start, end = self.alt()
        if self.i != len(self.p):
            raise RegexError(f"unexpected {self.p[self.i]!r} at {self.i}")
        return start, end

    def alt(self) -> Tuple[int, int]:
        frags = [self.concat()]
        while self.peek() == "|":
            self.take()
            frags.append(self.concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.new_state(), self.nfa.new_state()
        for fs, fe in frags:
            self.nfa.add_eps(s, fs)
            self.nfa.add_eps(fe, e)
        return s, e

    def concat(self) -> Tuple[int, int]:
        frags: List[Tuple[int, int]] = []
        while self.peek() is not None and self.peek() not in "|)":
            frags.append(self.repeat())
        if not frags:
            s = self.nfa.new_state()
            return s, s
        for (a_s, a_e), (b_s, b_e) in zip(frags, frags[1:]):
            self.nfa.add_eps(a_e, b_s)
        return frags[0][0], frags[-1][1]

    def repeat(self) -> Tuple[int, int]:
        frag = self.atom()
        while self.peek() in ("*", "+", "?", "{"):
            op = self.peek()
            if op == "{":
                save = self.i
                reps = self._parse_brace()
                if reps is None:
                    self.i = save
                    break
                lo, hi = reps
                frag = self._repeat_range(frag, lo, hi)
            else:
                self.take()
                if op == "*":
                    frag = self._star(frag)
                elif op == "+":
                    s2 = self._star(self._clone(frag))
                    self.nfa.add_eps(frag[1], s2[0])
                    frag = (frag[0], s2[1])
                else:  # '?'
                    s, e = self.nfa.new_state(), self.nfa.new_state()
                    self.nfa.add_eps(s, frag[0])
                    self.nfa.add_eps(frag[1], e)
                    self.nfa.add_eps(s, e)
                    frag = (s, e)
        return frag

    def _parse_brace(self) -> Optional[Tuple[int, int]]:
        # '{m}' '{m,}' '{m,n}' — returns None when not a valid brace
        # (POSIX treats a stray '{' as a literal).
        assert self.take() == "{"
        num = ""
        while self.peek() is not None and self.peek().isdigit():
            num += self.take()
        if not num:
            return None
        lo = int(num)
        hi: Optional[int] = lo
        if self.peek() == ",":
            self.take()
            num2 = ""
            while self.peek() is not None and self.peek().isdigit():
                num2 += self.take()
            hi = int(num2) if num2 else None  # {m,} = unbounded
        if self.peek() != "}":
            return None
        self.take()
        bound = hi if hi is not None else lo
        if (hi is not None and hi < lo) or bound > MAX_REPEAT:
            raise RegexError(f"repeat bound too large (max {MAX_REPEAT})")
        return lo, hi

    # -- fragment combinators ------------------------------------------
    def _star(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        s, e = self.nfa.new_state(), self.nfa.new_state()
        self.nfa.add_eps(s, frag[0])
        self.nfa.add_eps(frag[1], e)
        self.nfa.add_eps(s, e)
        self.nfa.add_eps(frag[1], frag[0])
        return s, e

    def _clone(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        """Deep-copy the subgraph reachable from frag start (bounded by
        construction: quantified atoms are parsed before cloning)."""
        mapping: Dict[int, int] = {}
        stack = [frag[0]]
        reach = set()
        while stack:
            s = stack.pop()
            if s in reach:
                continue
            reach.add(s)
            for tgts in self.nfa.trans[s].values():
                stack.extend(tgts)
            stack.extend(self.nfa.eps[s])
        for s in reach:
            mapping[s] = self.nfa.new_state()
        for s in reach:
            for byte, tgts in self.nfa.trans[s].items():
                for t in tgts:
                    if t in mapping:
                        self.nfa.add_byte(mapping[s], byte, mapping[t])
            for t in self.nfa.eps[s]:
                if t in mapping:
                    self.nfa.add_eps(mapping[s], mapping[t])
        return mapping[frag[0]], mapping[frag[1]]

    def _repeat_range(
        self, frag: Tuple[int, int], lo: int, hi: Optional[int]
    ) -> Tuple[int, int]:
        """{lo,hi} expansion; hi None = unbounded ({m,} → m copies with
        a trailing star)."""
        s = self.nfa.new_state()
        e = self.nfa.new_state()
        n_copies = hi if hi is not None else max(lo, 1)
        if n_copies == 0:  # {0} / {0,0} matches only the empty string
            self.nfa.add_eps(s, e)
            return s, e
        parts = [frag] + [self._clone(frag) for _ in range(n_copies - 1)]
        self.nfa.add_eps(s, parts[0][0])
        for (a_s, a_e), (b_s, b_e) in zip(parts, parts[1:]):
            self.nfa.add_eps(a_e, b_s)
        self.nfa.add_eps(parts[-1][1], e)
        if hi is None:
            # unbounded tail: loop the last copy
            self.nfa.add_eps(parts[-1][1], parts[-1][0])
        # optional tail: copies beyond `lo` may exit early
        if lo == 0:
            self.nfa.add_eps(s, e)
        for idx in range(max(lo, 1), n_copies):
            self.nfa.add_eps(parts[idx - 1][1], e)
        return s, e

    # -- atoms ----------------------------------------------------------
    def atom(self) -> Tuple[int, int]:
        c = self.peek()
        if c is None or c in "*+?|)":
            raise RegexError(f"unexpected {c!r} at {self.i}")
        if c == "(":
            self.take()
            frag = self.alt()
            if self.peek() != ")":
                raise RegexError("unbalanced parenthesis")
            self.take()
            return frag
        if c == "[":
            return self._char_class()
        if c == ".":
            self.take()
            return self._byte_set(set(range(ALPHABET)) - {0x0A})
        if c == "\\":
            self.take()
            if self.peek() is None:
                raise RegexError("trailing backslash")
            return self._escape(self.take())
        if c in ("^", "$"):
            # Anchors are implicit (full match); explicit ones at the
            # edges are accepted as no-ops for Go-pattern compatibility.
            self.take()
            s = self.nfa.new_state()
            return s, s
        self.take()
        return self._byte_set({ord(c)})

    def _escape(self, c: str) -> Tuple[int, int]:
        cls = _class_for_escape(c)
        if cls is not None:
            return self._byte_set(cls)
        return self._byte_set({ord(c)})

    def _char_class(self) -> Tuple[int, int]:
        assert self.take() == "["
        negate = False
        if self.peek() == "^":
            negate = True
            self.take()
        chars: Set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexError("unbalanced character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            self.take()
            if c == "\\":
                nxt = self.take()
                cls = _class_for_escape(nxt)
                if cls is not None:
                    chars |= cls
                    continue
                cv = ord(nxt)
            else:
                cv = ord(c)
            if self.peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                self.take()
                hi_c = self.take()
                if hi_c == "\\":
                    hi_c = self.take()
                for b in range(cv, ord(hi_c) + 1):
                    chars.add(b)
            else:
                chars.add(cv)
        if negate:
            chars = set(range(ALPHABET)) - chars
        return self._byte_set(chars)

    def _byte_set(self, bytes_: Set[int]) -> Tuple[int, int]:
        s, e = self.nfa.new_state(), self.nfa.new_state()
        for b in bytes_:
            self.nfa.add_byte(s, b, e)
        return s, e


def nfa_from_regex(pattern: str, nfa: Optional[_NFA] = None) -> Tuple[_NFA, int, int]:
    nfa = nfa or _NFA()
    start, end = _Parser(pattern, nfa).parse()
    return nfa, start, end


# -- subset construction ----------------------------------------------------


@dataclasses.dataclass
class MultiDFA:
    """Combined DFA: ``trans [Q, 256] int32`` (state 0 = dead sink),
    ``accept [Q] uint64`` pattern bitmask (bit i = pattern i accepts),
    ``start`` state id."""

    trans: np.ndarray
    accept: np.ndarray
    start: int
    n_patterns: int

    def match_str(self, s: bytes) -> int:
        """Host-side walk → accept bitmask (for tests/fallback)."""
        q = self.start
        for b in s:
            q = int(self.trans[q, b])
            if q == 0:
                return 0
        return int(self.accept[q])


def compile_patterns(patterns: Sequence[str], max_states: int = MAX_DFA_STATES) -> MultiDFA:
    """Compile ≤64 anchored patterns into one multi-accept DFA."""
    if len(patterns) > 64:
        raise RegexError("at most 64 patterns per DFA (accept bitmask is u64)")
    nfa = _NFA()
    starts: List[int] = []
    ends: Dict[int, int] = {}  # nfa end state → pattern idx
    for idx, p in enumerate(patterns):
        _, s, e = nfa_from_regex(p, nfa)
        starts.append(s)
        ends[e] = idx

    def eclose(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = eclose(frozenset(starts))
    # DFA state 0 = dead sink; real states from 1.
    ids: Dict[FrozenSet[int], int] = {start_set: 1}
    table: List[List[int]] = [[0] * ALPHABET, [0] * ALPHABET]
    accepts: List[int] = [0, _accept_mask(start_set, ends)]
    work = [start_set]
    while work:
        cur = work.pop()
        cur_id = ids[cur]
        by_byte: Dict[int, Set[int]] = {}
        for s in cur:
            for byte, tgts in nfa.trans[s].items():
                by_byte.setdefault(byte, set()).update(tgts)
        for byte, tgts in by_byte.items():
            nxt = eclose(frozenset(tgts))
            nid = ids.get(nxt)
            if nid is None:
                nid = len(table)
                if nid > max_states:
                    raise RegexError(f"DFA state cap exceeded ({max_states})")
                ids[nxt] = nid
                table.append([0] * ALPHABET)
                accepts.append(_accept_mask(nxt, ends))
                work.append(nxt)
            table[cur_id][byte] = nid
    return MultiDFA(
        trans=np.asarray(table, np.int32),
        accept=np.asarray(accepts, np.uint64),
        start=1,
        n_patterns=len(patterns),
    )


def _accept_mask(states: FrozenSet[int], ends: Dict[int, int]) -> int:
    mask = 0
    for s in states:
        idx = ends.get(s)
        if idx is not None:
            mask |= 1 << idx
    return mask


# -- compile interning ------------------------------------------------------
# Subset construction is the expensive half of policy compile; N
# endpoints with the same rule set produce the same pattern tuples, so
# the host MultiDFA is interned by (patterns, max_states) — the same
# content-addressed discipline ops.dfa uses for the device tables.
# Successes only: a RegexError must re-raise per call site (demotion
# probing in http_policy depends on it).
_COMPILE_CACHE_CAP = 256
_compile_lock = threading.Lock()
_compile_cache: "OrderedDict[Tuple, MultiDFA]" = OrderedDict()


def compile_patterns_cached(
    patterns: Sequence[str], max_states: int = MAX_DFA_STATES
) -> MultiDFA:
    """``compile_patterns`` with an interned result. Callers must
    treat the returned MultiDFA as immutable — it is shared."""
    key = (tuple(patterns), max_states)
    with _compile_lock:
        hit = _compile_cache.get(key)
        if hit is not None:
            _compile_cache.move_to_end(key)
            return hit
    built = compile_patterns(patterns, max_states)
    with _compile_lock:
        raced = _compile_cache.get(key)
        if raced is not None:
            _compile_cache.move_to_end(key)
            return raced
        _compile_cache[key] = built
        while len(_compile_cache) > _COMPILE_CACHE_CAP:
            _compile_cache.popitem(last=False)
    return built
