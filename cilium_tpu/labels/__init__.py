"""Security labels.

Reference semantics: cilium pkg/labels (labels.go, array.go, cidr.go,
filter.go). Labels are (source, key, value) triples; a sorted
:class:`LabelArray` is the canonical key for a security identity.

TPU relevance: every label is interned into a global :class:`LabelVocab`
bit position so that identities and selectors become fixed-width packed
bitmaps (uint32 words) — the unit of the device-side matching kernels in
:mod:`cilium_tpu.ops.bitmap`.
"""

from .label import Label, LabelArray, parse_label, parse_label_array
from .cidr import cidr_labels, ip_string_to_label
from .vocab import LabelVocab
from .filter import LabelFilter

SRC_K8S = "k8s"
SRC_CONTAINER = "container"
SRC_RESERVED = "reserved"
SRC_CIDR = "cidr"
SRC_UNSPEC = "unspec"
SRC_ANY = "any"

__all__ = [
    "Label",
    "LabelArray",
    "LabelVocab",
    "LabelFilter",
    "parse_label",
    "parse_label_array",
    "cidr_labels",
    "ip_string_to_label",
    "SRC_K8S",
    "SRC_CONTAINER",
    "SRC_RESERVED",
    "SRC_CIDR",
    "SRC_UNSPEC",
    "SRC_ANY",
]
