"""CIDR → label expansion.

Reference semantics: pkg/labels/cidr.go — an IP/prefix gets one ``cidr:``
label *per covering prefix length* (0..n), so a selector written against
``cidr:10.0.0.0/8`` matches the identity allocated for ``10.1.2.3/32``.
IPv6 colons are replaced with dashes in the label key (labels may not
contain ':').

The full expansion is what lets CIDR policy participate in the same
bitmap-matching kernels as every other label; the LPM *datapath* lookup
is handled separately by the bit-trie tensors in cilium_tpu.ops.lpm.
"""

from __future__ import annotations

import ipaddress
from typing import List, Union

from .label import Label

_Network = Union[ipaddress.IPv4Network, ipaddress.IPv6Network]


def _format_net(net: _Network) -> str:
    return f"{net.network_address}/{net.prefixlen}".replace(":", "-")


def ip_string_to_label(cidr: str) -> Label:
    """The exact-prefix ``cidr:`` label for one CIDR string."""
    net = ipaddress.ip_network(cidr, strict=False)
    return Label(source="cidr", key=_format_net(net))


def cidr_labels(cidr: str) -> List[Label]:
    """All covering-prefix labels for ``cidr``, widest first.

    ``10.1.2.0/24`` → [cidr:0.0.0.0/0, cidr:10.0.0.0/8 … cidr:10.1.2.0/24]
    (every prefix length, not just octet boundaries, matching the
    reference's maskedIPToLabelString loop).
    """
    net = ipaddress.ip_network(cidr, strict=False)
    labels = []
    for plen in range(net.prefixlen + 1):
        super_net = net.supernet(new_prefix=plen) if plen < net.prefixlen else net
        labels.append(Label(source="cidr", key=_format_net(super_net)))
    return labels
