"""User label filter.

Reference semantics: pkg/labels/filter.go — an ordered allow/deny prefix
list deciding which workload labels are security-relevant (only those
feed identity allocation). Default: k8s/container/reserved labels are
included; ``io.kubernetes``-style infra labels are excluded.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from .label import Label

_DEFAULT_DENIED_PREFIXES = (
    "io.kubernetes",
    "kubernetes.io",
    "pod-template-generation",
    "pod-template-hash",
    "controller-revision-hash",
    "annotation.",
    "etcd_node",
)


class LabelFilter:
    """Ordered include/exclude prefix filter over label keys.

    Each entry is (include: bool, source or "", key-prefix). First match
    wins; unmatched labels are included unless any explicit inclusive
    filter exists (mirroring the reference's behaviour where a user
    allowlist flips the default).
    """

    def __init__(self, entries: Iterable[Tuple[bool, str, str]] = ()):
        self._entries: List[Tuple[bool, str, str]] = list(entries)
        for prefix in _DEFAULT_DENIED_PREFIXES:
            self._entries.append((False, "", prefix))
        self._has_includes = any(inc for inc, _, _ in self._entries)

    @classmethod
    def parse(cls, specs: Iterable[str]) -> "LabelFilter":
        """Parse CLI-style specs: ``[+|-]source:prefix`` (pkg/labels
        ParseLabelPrefixCfg). ``+`` or bare = include, ``-`` = exclude."""
        entries = []
        for spec in specs:
            include = True
            if spec.startswith("!") or spec.startswith("-"):
                include, spec = False, spec[1:]
            elif spec.startswith("+"):
                spec = spec[1:]
            source, _, prefix = spec.rpartition(":")
            entries.append((include, source, prefix))
        return cls(entries)

    def allows(self, label: Label) -> bool:
        for include, source, prefix in self._entries:
            if source and source != label.source:
                continue
            if label.key.startswith(prefix):
                return include
        if label.is_reserved:
            return True
        return not self._has_includes

    def filter(self, labels: Iterable[Label]) -> Tuple[List[Label], List[Label]]:
        """Split labels into (security-relevant, ignored)."""
        kept, dropped = [], []
        for l in labels:
            (kept if self.allows(l) else dropped).append(l)
        return kept, dropped
