"""Label and LabelArray.

Reference semantics: pkg/labels/labels.go (Label struct, NewLabel,
ParseLabel source-prefix handling) and pkg/labels/array.go (sorted
canonical form used as the identity allocation key).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Tuple

_DEFAULT_SOURCE = "unspec"
_ANY_SOURCE = "any"


@dataclasses.dataclass(frozen=True, order=True)
class Label:
    """A single security-relevant label.

    Ordering/equality are over (source, key, value) which makes sorted
    tuples of labels canonical identity keys.
    """

    source: str
    key: str
    value: str = ""

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"

    @property
    def is_reserved(self) -> bool:
        return self.source == "reserved"

    @property
    def is_cidr(self) -> bool:
        return self.source == "cidr"

    def matches(self, other: "Label") -> bool:
        """Selector-style match: ``self`` (the selector label) matches
        ``other`` when key and value agree and the source agrees or the
        selector's source is ``any`` (pkg/labels/labels.go Label.Matches).
        """
        if self.key != other.key or self.value != other.value:
            return False
        return self.source == _ANY_SOURCE or self.source == other.source


def parse_label(text: str) -> Label:
    """Parse ``source:key=value`` (source and value optional).

    ``app=web`` → unspec source. ``k8s:app=web`` → k8s source. A leading
    ``any:`` keeps the wildcard source. Mirrors pkg/labels ParseLabel.
    """
    text = text.strip()
    source = _DEFAULT_SOURCE
    rest = text
    if ":" in text:
        maybe_source, after = text.split(":", 1)
        # Only treat the prefix as a source when it looks like one (no '='
        # before the colon), matching the reference parser.
        if "=" not in maybe_source:
            source, rest = (maybe_source or _DEFAULT_SOURCE), after
    if "=" in rest:
        key, value = rest.split("=", 1)
    else:
        key, value = rest, ""
    return Label(source=source, key=key, value=value)


def parse_label_array(texts: Iterable[str]) -> "LabelArray":
    return LabelArray(parse_label(t) for t in texts)


class LabelArray:
    """An immutable, sorted, de-duplicated set of labels.

    The sorted tuple is the canonical form: two LabelArrays with the same
    labels in any order are equal and hash equal — this is the identity
    allocation key (pkg/identity/allocator.go globalIdentity keyed by
    sorted label list).
    """

    __slots__ = ("_labels",)

    def __init__(self, labels: Iterable[Label] = ()):
        self._labels: Tuple[Label, ...] = tuple(sorted(set(labels)))

    def __iter__(self) -> Iterator[Label]:
        return iter(self._labels)

    def __len__(self) -> int:
        return len(self._labels)

    def __contains__(self, label: Label) -> bool:
        return label in self._labels

    def __eq__(self, other: object) -> bool:
        return isinstance(other, LabelArray) and self._labels == other._labels

    def __hash__(self) -> int:
        return hash(self._labels)

    def __repr__(self) -> str:
        return f"LabelArray([{', '.join(str(l) for l in self._labels)}])"

    def sorted_key(self) -> str:
        """Canonical string key for kvstore identity allocation."""
        return ";".join(str(l) for l in self._labels)

    def union(self, other: "LabelArray") -> "LabelArray":
        return LabelArray((*self._labels, *other._labels))

    def has(self, selector_label: Label) -> bool:
        """True when any member matches ``selector_label`` under
        wildcard-source rules."""
        return any(selector_label.matches(l) for l in self._labels)

    def to_strings(self) -> Tuple[str, ...]:
        return tuple(str(l) for l in self._labels)
