"""Label vocabulary: label ↔ bit position interning.

This is new TPU-first design (no reference equivalent): to evaluate
selector↔identity matches as bitwise AND/subset tests on device, every
distinct label observed in identities or selectors is interned to a bit
position. An identity's labels become a packed uint32 bitmap; a selector
becomes (require_bits, forbid_bits) so that

    matches(id) == (id_bits & require == require) and (id_bits & forbid == 0)

covers matchLabels, Exists, NotIn and DoesNotExist (k8s LabelSelector
semantics wrapped by the reference's pkg/policy/api/selector.go).

Bit layout per identity label (source, key, value):
  - kv bit for (source, key, value)
  - kv bit for (any, key, value)       — wildcard-source selectors
  - exists bit for (source, key)
  - exists bit for (any, key)          — Exists / DoesNotExist selectors

Selector labels consume exactly one bit each (their own kv or exists
bit), so subset-testing is exact.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Tuple

import numpy as np

from .label import Label, LabelArray

_ANY = "any"

# (kind, source, key, value); kind ∈ {"kv", "exists"}
_BitKey = Tuple[str, str, str, str]


class LabelVocab:
    """Grow-only label→bit interner.

    ``version`` increments whenever a new bit is allocated; consumers
    (the policy compiler) use it to know when identity bitmaps must be
    re-packed. Thread-safe: the daemon's watchers intern concurrently.
    """

    def __init__(self) -> None:
        self._bits: Dict[_BitKey, int] = {}
        self._lock = threading.Lock()
        self.version = 0

    def __len__(self) -> int:
        return len(self._bits)

    @property
    def num_words(self) -> int:
        """uint32 words needed for a full bitmap (≥1, padded)."""
        return max(1, (len(self._bits) + 31) // 32)

    def _intern(self, key: _BitKey) -> int:
        bit = self._bits.get(key)
        if bit is None:
            with self._lock:
                bit = self._bits.get(key)
                if bit is None:
                    bit = len(self._bits)
                    self._bits[key] = bit
                    self.version += 1
        return bit

    # -- selector side ----------------------------------------------------
    def kv_bit(self, label: Label) -> int:
        return self._intern(("kv", label.source, label.key, label.value))

    def exists_bit(self, source: str, key: str) -> int:
        return self._intern(("exists", source, key, ""))

    # -- identity side ----------------------------------------------------
    def identity_bits(self, labels: LabelArray) -> List[int]:
        """All bits set for an identity carrying ``labels``."""
        bits = []
        for l in labels:
            bits.append(self._intern(("kv", l.source, l.key, l.value)))
            bits.append(self._intern(("exists", l.source, l.key, "")))
            if l.source != _ANY:
                bits.append(self._intern(("kv", _ANY, l.key, l.value)))
                bits.append(self._intern(("exists", _ANY, l.key, "")))
        return bits

    # -- packing ----------------------------------------------------------
    def pack(self, bits: Iterable[int], num_words: int | None = None) -> np.ndarray:
        """Pack bit positions into a uint32 word vector."""
        nw = num_words if num_words is not None else self.num_words
        out = np.zeros(nw, dtype=np.uint32)
        for b in bits:
            out[b // 32] |= np.uint32(1) << np.uint32(b % 32)
        return out

    def pack_identity(self, labels: LabelArray, num_words: int | None = None) -> np.ndarray:
        return self.pack(self.identity_bits(labels), num_words)
