"""Load balancing: service tables, weighted backend selection, revNAT.

The TPU-native stand-in for pkg/loadbalancer + pkg/maps/lbmap +
bpf/lib/lb.h — VIP→backend translation runs as a device tensor stage
ahead of the egress policy check.
"""

from .device import LBTables, MAX_SEQ, flow_hash32, lb_translate
from .service import Backend, L3n4Addr, LBService, ServiceManager, build_selection_seq

__all__ = [
    "Backend",
    "L3n4Addr",
    "LBService",
    "LBTables",
    "MAX_SEQ",
    "ServiceManager",
    "build_selection_seq",
    "flow_hash32",
    "lb_translate",
]
