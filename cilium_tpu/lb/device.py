"""Device LB tables + the jitted VIP→backend translate step.

Reference: bpf/lib/lb.h:36-83 (``cilium_lb4_services`` /
``cilium_lb4_backends-in-service`` slave slots / ``cilium_lb4_rr_seq``)
and their Go programming side (pkg/maps/lbmap/lbmap.go:274,351).
The kernel does three hash-map probes per packet: frontend lookup,
slave-slot lookup, revNAT record.

TPU-first redesign: the frontend "hash map" becomes a dense [B, F]
compare — the reference caps frontends at 256 (bpf/lib/lb.h:36), so F
is tiny and the compare vectorizes perfectly. Slave selection is one
gather into a per-service **selection sequence**: the weighted-RR
sequence of lbmap.go:351 and plain hash-mod selection collapse into
the same tensor (equal weights ⇒ the sequence is just the backend
list). Backend translation is one row gather. Everything is
branch-free, static-shaped, and fuses into the surrounding verdict
dispatch under jit.
"""

from __future__ import annotations

from typing import Optional

import chex
import jax
import jax.numpy as jnp
import numpy as np

MAX_SEQ = 64  # selection-sequence width (weighted-RR resolution)


@chex.dataclass(frozen=True)
class LBTables:
    """Device state for one address family (L = 4 or 16 address bytes).

    Empty frontend slots carry fe_port = -1 (never matches a real
    dport ≥ 0); fe_proto 0 means ANY (L4Addr with protocol NONE).
    """

    fe_bytes: jnp.ndarray  # [F, L] int32 VIP address bytes
    fe_port: jnp.ndarray  # [F] int32
    fe_proto: jnp.ndarray  # [F] int32 (0 = ANY)
    fe_seq: jnp.ndarray  # [F, MAX_SEQ] int32 backend row per slot
    fe_seq_len: jnp.ndarray  # [F] int32 live slots (0 = no backends)
    fe_revnat: jnp.ndarray  # [F] int32 revNAT id
    be_bytes: jnp.ndarray  # [NB, L] int32 backend address bytes
    be_port: jnp.ndarray  # [NB] int32


@jax.jit
def lb_translate(
    t: LBTables,
    peer_bytes: jnp.ndarray,  # [B, L] int32 destination address bytes
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
    fhash: jnp.ndarray,  # [B] int32 flow hash (slave selector)
):
    """→ (new_bytes [B, L], new_port [B], revnat [B], translated [B]
    bool, no_backend [B] bool).

    ``no_backend`` marks flows that matched a frontend with zero
    backends — the kernel drops these (lb4_local: slave lookup
    failure → DROP_NO_SERVICE).
    """
    m = (t.fe_bytes[None, :, :] == peer_bytes[:, None, :]).all(-1)
    m &= dport[:, None] == t.fe_port[None, :]
    m &= (t.fe_proto[None, :] == 0) | (proto[:, None] == t.fe_proto[None, :])
    hit = m.any(axis=1)
    fe = jnp.argmax(m, axis=1)
    slen = t.fe_seq_len[fe]
    idx = jnp.remainder(fhash, jnp.maximum(slen, 1)).astype(jnp.int32)
    be = t.fe_seq[fe, idx]
    ok = hit & (slen > 0)
    no_backend = hit & (slen == 0)
    new_bytes = jnp.where(ok[:, None], t.be_bytes[be], peer_bytes)
    new_port = jnp.where(ok, t.be_port[be], dport)
    revnat = jnp.where(hit, t.fe_revnat[fe], 0)
    return new_bytes, new_port, revnat, ok, no_backend


def flow_hash32(
    peer_bytes: np.ndarray,  # [B, L] address bytes of the pre-NAT dst
    sports: Optional[np.ndarray],
    dports: np.ndarray,
    protos: np.ndarray,
    ep_ids: np.ndarray,  # [B] STABLE endpoint ids (not list indices)
) -> np.ndarray:
    """[B] int32 ≥ 0 deterministic per-flow hash (the skb flow-hash
    role). Determinism matters beyond affinity: the conntrack key of a
    load-balanced flow embeds the *translated* backend tuple, so the
    same packet must keep selecting the same backend for the
    established-flow bypass to hit. The endpoint contribution must be
    the endpoint's stable ID — a positional index would re-select
    backends for every established flow whenever an unrelated endpoint
    joins or leaves the list."""
    b = peer_bytes.shape[0]
    x = np.zeros(b, np.uint32)
    with np.errstate(over="ignore"):
        for col in range(peer_bytes.shape[1]):
            x = (x * np.uint32(0x01000193)) ^ peer_bytes[:, col].astype(np.uint32)
        if sports is not None:
            x ^= np.asarray(sports, np.uint32) << np.uint32(16)
        x ^= np.asarray(dports, np.uint32)
        x ^= np.asarray(protos, np.uint32) << np.uint32(8)
        x ^= np.asarray(ep_ids, np.uint32) << np.uint32(24)
        # final avalanche (murmur3 fmix32)
        x ^= x >> np.uint32(16)
        x *= np.uint32(0x85EBCA6B)
        x ^= x >> np.uint32(13)
        x *= np.uint32(0xC2B2AE35)
        x ^= x >> np.uint32(16)
    return (x & np.uint32(0x7FFFFFFF)).astype(np.int32)
