"""Host service manager: frontends, weighted backends, revNAT records.

Reference: pkg/loadbalancer (L3n4Addr/LBSVC types), pkg/maps/lbmap
(service + backend + RR-sequence programming, lbmap.go:274,351), and
pkg/service (kvstore-backed global service ID allocation,
service.go). The manager owns the authoritative service table and
emits immutable device snapshots (lb/device.py LBTables) for the
pipeline's egress pre-policy stage — the lb4_lookup_service /
lb4_local position of bpf/bpf_lxc.c:444-455.
"""

from __future__ import annotations

import dataclasses
import ipaddress
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import u8proto
from .device import LBTables, MAX_SEQ

SERVICES_ID_PATH = "cilium/state/services/v1/id"
SERVICES_VALUE_PATH = "cilium/state/services/v1/value"
SERVICES_EXPORT_PATH = "cilium/state/services/v1/exports"


@dataclasses.dataclass(frozen=True, order=True)
class L3n4Addr:
    """Frontend / backend address (pkg/loadbalancer L3n4Addr)."""

    ip: str
    port: int
    protocol: str = "TCP"  # TCP | UDP | ANY

    def __post_init__(self) -> None:
        # normalize ONCE at construction: frontends round-trip through
        # string keys (clustermesh export paths, CLI args) and a
        # case-mismatched protocol would make delete miss its upsert
        object.__setattr__(self, "protocol", self.protocol.upper())

    @property
    def family(self) -> int:
        return 6 if ipaddress.ip_address(self.ip).version == 6 else 4

    @property
    def proto_num(self) -> int:
        return 0 if self.protocol.upper() in ("ANY", "NONE") else u8proto.from_name(
            self.protocol
        )

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}/{self.protocol}"

    @classmethod
    def from_string(cls, text: str) -> "L3n4Addr":
        """Inverse of __str__ ('ip:port[/proto]', brackets around v6
        literals tolerated) — the ONE place the frontend wire format
        is parsed (CLI args, clustermesh export keys)."""
        proto = "TCP"
        if "/" in text:
            text, proto = text.rsplit("/", 1)
        ip, _, port = text.rpartition(":")
        return cls(ip.strip("[]"), int(port), proto.upper())


@dataclasses.dataclass(frozen=True)
class Backend:
    """One backend with an RR weight (lbmap.go LBBackEnd)."""

    ip: str
    port: int
    weight: int = 1


@dataclasses.dataclass
class LBService:
    """A programmed service (pkg/loadbalancer LBSVC)."""

    id: int  # global service / revNAT id
    frontend: L3n4Addr
    backends: Tuple[Backend, ...]


def _addr_bytes(ip: str, length: int) -> List[int]:
    return list(ipaddress.ip_address(ip).packed.rjust(length, b"\x00"))[-length:]


def build_selection_seq(backends: Sequence[Backend]) -> List[int]:
    """Backend indices repeated by weight — the weighted-RR sequence of
    lbmap.go:351 (generateWrrSeq). Capped at MAX_SEQ slots: when
    weights overflow the cap they are rescaled with every backend
    guaranteed ≥ 1 slot; when the backend COUNT itself exceeds MAX_SEQ
    only the first MAX_SEQ backends receive slots (deterministic
    truncation — the reference's slave-slot maps have the same kind of
    hard capacity, bpf/lib/lb.h LB_MAX)."""
    if not backends:
        return []
    # weight 0 means "no traffic" in BOTH paths; all-zero degrades to
    # equal shares (the reference treats weightless services as plain
    # round-robin)
    live = [(i, max(0, b.weight)) for i, b in enumerate(backends)]
    if all(w == 0 for _, w in live):
        live = [(i, 1) for i, _ in live]
    else:
        live = [(i, w) for i, w in live if w > 0]
    live = live[:MAX_SEQ]
    idxs = [i for i, _ in live]
    weights = [w for _, w in live]
    total = sum(weights)
    if total <= MAX_SEQ:
        reps = weights
    else:
        # every positive-weight backend gets 1 slot; remaining slots
        # go by largest weight remainder so shares stay proportional
        n = len(live)
        spare = MAX_SEQ - n
        shares = [w * spare / total for w in weights]
        reps = [1 + int(s) for s in shares]
        spare -= sum(int(s) for s in shares)
        order = sorted(range(n), key=lambda i: shares[i] - int(shares[i]),
                       reverse=True)
        for i in order[:spare]:
            reps[i] += 1
    seq: List[int] = []
    # interleave round-robin style so short prefixes are still mixed
    counts = list(reps)
    while any(c > 0 for c in counts):
        for k, c in enumerate(counts):
            if c > 0:
                seq.append(idxs[k])
                counts[k] -= 1
    return seq[:MAX_SEQ]


class ServiceManager:
    """Thread-safe service table with device snapshot builds.

    Service IDs double as revNAT ids (the reference allocates one
    ID per frontend, pkg/service/service.go). With a kvstore backend
    the allocation is a cluster-global CAS (create_only on the
    frontend's value key); standalone it is a local counter.
    """

    def __init__(self, kvstore=None, host_ip: str = "") -> None:
        self._lock = threading.RLock()
        self._services: Dict[L3n4Addr, LBService] = {}
        self._next_id = 1
        self._kv = kvstore
        self.version = 0
        # node host address — the Ingress frontend IP (the reference
        # uses Config.HostV4Addr, k8s_watcher.go:1209)
        self.host_ip = host_ip
        self._synced_frontends: set = set()  # frontends owned by k8s sync
        # (frontend, remote_cluster) → backends merged in via
        # clustermesh (the global-service merge; remote_cluster.go)
        self._remote: Dict[Tuple[L3n4Addr, str], Tuple[Backend, ...]] = {}

    # -- id allocation --------------------------------------------------
    def _allocate_id(self, frontend: L3n4Addr) -> int:
        if self._kv is None:
            sid = self._next_id
            self._next_id += 1
            return sid
        key = f"{SERVICES_VALUE_PATH}/{frontend}"
        existing = self._kv.get(key)
        if existing is not None:
            return int(existing.decode())
        while True:
            candidate = self._next_id
            self._next_id += 1
            if self._kv.create_only(
                f"{SERVICES_ID_PATH}/{candidate}", str(frontend).encode()
            ):
                self._kv.set(key, str(candidate).encode())
                return candidate

    # -- mutation -------------------------------------------------------
    @staticmethod
    def _validate(frontend: L3n4Addr, backends: Sequence[Backend]) -> None:
        """Reject malformed addresses BEFORE mutating the table: a bad
        entry would otherwise poison every later build_device() (and,
        via the daemon's state snapshot, survive restarts)."""
        ipaddress.ip_address(frontend.ip)  # raises ValueError if bad
        frontend.proto_num  # raises on unknown protocol names
        if not 0 < frontend.port < 65536:
            raise ValueError(f"frontend port out of range: {frontend.port}")
        for b in backends:
            ipaddress.ip_address(b.ip)
            if not 0 < b.port < 65536:
                raise ValueError(f"backend port out of range: {b.port}")

    def upsert(
        self, frontend: L3n4Addr, backends: Sequence[Backend]
    ) -> LBService:
        self._validate(frontend, backends)
        with self._lock:
            existing = self._services.get(frontend)
            sid = existing.id if existing else self._allocate_id(frontend)
            svc = LBService(id=sid, frontend=frontend, backends=tuple(backends))
            self._services[frontend] = svc
            self.version += 1
            return svc

    def restore(
        self, frontend: L3n4Addr, backends: Sequence[Backend], sid: int
    ) -> LBService:
        """Re-install a service keeping its persisted id (daemon
        restart must not renumber services: revNAT ids are API-visible
        and recorded in snapshots)."""
        self._validate(frontend, backends)
        with self._lock:
            svc = LBService(id=sid, frontend=frontend, backends=tuple(backends))
            self._services[frontend] = svc
            self._next_id = max(self._next_id, sid + 1)
            self.version += 1
            return svc

    def delete(self, frontend: L3n4Addr) -> bool:
        with self._lock:
            if self._services.pop(frontend, None) is None:
                return False
            self.version += 1
            return True

    # -- queries --------------------------------------------------------
    def get(self, frontend: L3n4Addr) -> Optional[LBService]:
        with self._lock:
            return self._services.get(frontend)

    def list(self) -> List[LBService]:
        with self._lock:
            return sorted(self._services.values(), key=lambda s: s.id)

    # -- clustermesh merge (global services) ----------------------------
    def set_remote_backends(
        self, frontend: L3n4Addr, cluster: str, backends: Sequence[Backend]
    ) -> None:
        """Merge (or clear, with an empty list) one remote cluster's
        backends for a frontend. Only frontends that exist LOCALLY are
        served — the local cluster decides which services are global
        (remote_cluster.go mergeExternalServiceUpdate)."""
        with self._lock:
            key = (frontend, cluster)
            if backends:
                self._validate(frontend, backends)
                self._remote[key] = tuple(backends)
            elif key not in self._remote:
                return
            else:
                del self._remote[key]
            self.version += 1

    def effective_backends(self, frontend: L3n4Addr) -> List[Backend]:
        """Own backends + every remote cluster's merged backends."""
        with self._lock:
            svc = self._services.get(frontend)
            out = list(svc.backends) if svc else []
            for (fe, _cluster), backs in sorted(
                self._remote.items(), key=lambda kv: kv[0][1]
            ):
                if fe == frontend:
                    out.extend(backs)
            return out

    def rev_nat(self, revnat_id: int) -> Optional[L3n4Addr]:
        """revNAT id → original frontend (the cilium_lb4_reverse_nat
        role): rewrites reply source back to the VIP."""
        with self._lock:
            for svc in self._services.values():
                if svc.id == revnat_id:
                    return svc.frontend
        return None

    # -- k8s bridge -----------------------------------------------------
    def sync_from_registry(self, registry) -> int:
        """Full resync from a k8s ServiceRegistry: every ClusterIP
        service port becomes a frontend; backends come from the
        Endpoints object's matching port name (daemon/k8s_watcher.go
        addK8sSVCs). Ingress objects add a frontend on the node's host
        address pointing at the named service's backends
        (k8s_watcher.go:1181 addIngressV1beta1 — requires ``host_ip``
        to be set). Frontends previously created by sync but gone from
        the registry are deleted. Returns the live frontend count."""
        desired: Dict[L3n4Addr, List[Backend]] = {}
        with registry._lock:
            services = dict(registry.services)
            endpoints = dict(registry.endpoints)
            ingresses = dict(getattr(registry, "ingresses", {}))
        for sid, info in services.items():
            if not info.cluster_ip or info.is_headless:
                continue
            ep = endpoints.get(sid)
            for pname, sp in info.ports.items():
                fe = L3n4Addr(info.cluster_ip, sp.port, sp.protocol)
                backs: List[Backend] = []
                if ep is not None:
                    tgt = ep.ports.get(pname) or ep.ports.get(str(sp.port))
                    if tgt is not None:
                        backs = [Backend(ip, tgt.port) for ip in ep.backend_ips]
                desired[fe] = backs
        if self.host_ip:
            for iid, ing in ingresses.items():
                svc_id = type(iid)(iid.namespace, ing.service_name)
                ep = endpoints.get(svc_id)
                backs = []
                fe_port = ing.service_port
                if ep is not None:
                    tgt = (
                        ep.ports.get(ing.port_name)
                        or ep.ports.get(str(ing.service_port))
                    )
                    if tgt is None and len(ep.ports) == 1:
                        tgt = next(iter(ep.ports.values()))
                    if tgt is not None:
                        backs = [Backend(ip, tgt.port) for ip in ep.backend_ips]
                        if not fe_port:  # named servicePort: number from
                            fe_port = tgt.port  # the endpoints mapping
                if fe_port:
                    desired[L3n4Addr(self.host_ip, fe_port, "TCP")] = backs
        with self._lock:
            for fe in self._synced_frontends - set(desired):
                self.delete(fe)
            synced = set()
            for fe, backs in desired.items():
                try:
                    cur = self._services.get(fe)
                    if cur is None or cur.backends != tuple(backs):
                        self.upsert(fe, backs)
                    synced.add(fe)
                except ValueError:
                    # malformed registry data (bad IP/port) — skip the
                    # one service rather than abort the sync
                    continue
            self._synced_frontends = synced
        return len(synced)

    # -- clustermesh export ---------------------------------------------
    def export_to_store(self, backend, cluster: str) -> int:
        """Publish this cluster's services (frontend + OWN backends,
        never merged remote ones — re-export loops would amplify) for
        clustermesh consumers. Lease-bound: a dead agent's export
        disappears with its lease. Idempotent full sync; returns the
        exported service count."""
        import json as _json

        prefix = f"{SERVICES_EXPORT_PATH}/{cluster}/"
        with self._lock:
            services = list(self._services.values())
        desired = {}
        for svc in services:
            desired[prefix + str(svc.frontend)] = _json.dumps({
                "frontend": {
                    "ip": svc.frontend.ip,
                    "port": svc.frontend.port,
                    "protocol": svc.frontend.protocol,
                },
                "backends": [
                    {"ip": b.ip, "port": b.port, "weight": b.weight}
                    for b in svc.backends
                ],
            }, sort_keys=True).encode()
        existing = backend.list_prefix(prefix)
        for key in existing:
            if key not in desired:
                backend.delete(key)
        for key, value in desired.items():
            if existing.get(key) != value:
                backend.update(key, value, lease=True)
        return len(desired)

    # -- device snapshot ------------------------------------------------
    def build_device(self) -> Dict[int, Optional[LBTables]]:
        """→ {4: LBTables|None, 6: LBTables|None} (None = no frontends
        of that family; the pipeline skips the stage entirely)."""
        import jax.numpy as jnp

        with self._lock:
            services = sorted(self._services.values(), key=lambda s: s.id)
        out: Dict[int, Optional[LBTables]] = {4: None, 6: None}
        for family, length in ((4, 4), (6, 16)):
            fam = [s for s in services if s.frontend.family == family]
            if not fam:
                continue
            nf = max(1, len(fam))
            fe_bytes = np.zeros((nf, length), np.int32)
            fe_port = np.full(nf, -1, np.int32)
            fe_proto = np.zeros(nf, np.int32)
            fe_seq = np.zeros((nf, MAX_SEQ), np.int32)
            fe_seq_len = np.zeros(nf, np.int32)
            fe_revnat = np.zeros(nf, np.int32)
            be_rows: List[Tuple[List[int], int]] = []
            for i, svc in enumerate(fam):
                fe_bytes[i] = _addr_bytes(svc.frontend.ip, length)
                fe_port[i] = svc.frontend.port
                fe_proto[i] = svc.frontend.proto_num
                fe_revnat[i] = svc.id
                base = len(be_rows)
                live = [
                    b for b in self.effective_backends(svc.frontend)
                    if ipaddress.ip_address(b.ip).version == (6 if family == 6 else 4)
                ]
                for b in live:
                    be_rows.append((_addr_bytes(b.ip, length), b.port))
                seq = build_selection_seq(live)
                fe_seq_len[i] = len(seq)
                for j, rel in enumerate(seq):
                    fe_seq[i, j] = base + rel
            nb = max(1, len(be_rows))
            be_bytes = np.zeros((nb, length), np.int32)
            be_port = np.zeros(nb, np.int32)
            for r, (byts, port) in enumerate(be_rows):
                be_bytes[r] = byts
                be_port[r] = port
            out[family] = LBTables(
                fe_bytes=jnp.asarray(fe_bytes),
                fe_port=jnp.asarray(fe_port),
                fe_proto=jnp.asarray(fe_proto),
                fe_seq=jnp.asarray(fe_seq),
                fe_seq_len=jnp.asarray(fe_seq_len),
                fe_revnat=jnp.asarray(fe_revnat),
                be_bytes=jnp.asarray(be_bytes),
                be_port=jnp.asarray(be_port),
            )
        return out
