"""Realized datapath state maps (reference: pkg/maps/*)."""

from .policymap import PolicyMap
from .ctmap import ConntrackEntry, ConntrackMap

__all__ = ["PolicyMap", "ConntrackEntry", "ConntrackMap"]
