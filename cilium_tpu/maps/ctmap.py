"""Conntrack table with TTL-based garbage collection.

Reference: pkg/maps/ctmap (ctmap.go:345 GC, :242 dump/filter) over the
kernel tables of bpf/lib/conntrack.h. Here: the host-side flow cache
the datapath front-end consults so established flows skip the full
policy path (the role CT_ESTABLISHED plays in bpf_lxc.c:477), with the
same lifetime/accounting semantics.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Iterator, Optional, Tuple

# (src_ip, dst_ip, sport, dport, proto, direction)
FlowTuple = Tuple[int, int, int, int, int, int]

# Single source of truth for CT lifetimes; datapath/conntrack.py (the
# vectorized batch table) imports these.
DEFAULT_LIFETIME_TCP = 21600.0  # CT_CONNECTION_LIFETIME_TCP (6h)
DEFAULT_LIFETIME_OTHER = 60.0


@dataclasses.dataclass
class ConntrackEntry:
    expires: float
    verdict: int = 0
    redirect: bool = False
    packets: int = 0
    bytes: int = 0
    flags_seen: int = 0


class ConntrackMap:
    def __init__(self, max_entries: int = 1 << 18) -> None:
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: Dict[FlowTuple, ConntrackEntry] = {}

    def lookup(self, key: FlowTuple) -> Optional[ConntrackEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.expires < time.monotonic():
                return None
            return e

    def create(self, key: FlowTuple, verdict: int, redirect: bool, lifetime: Optional[float] = None) -> ConntrackEntry:
        if lifetime is None:
            lifetime = DEFAULT_LIFETIME_TCP if key[4] == 6 else DEFAULT_LIFETIME_OTHER
        e = ConntrackEntry(expires=time.monotonic() + lifetime, verdict=verdict, redirect=redirect)
        with self._lock:
            if len(self._entries) >= self.max_entries:
                self._gc_locked(time.monotonic())
                # Still full (nothing expired): evict soonest-expiring
                # entries so the cap holds (the kernel map fails the
                # insert; eviction keeps hot flows cached instead).
                if len(self._entries) >= self.max_entries:
                    evict = max(1, self.max_entries // 64)
                    for k in sorted(self._entries, key=lambda k: self._entries[k].expires)[:evict]:
                        del self._entries[k]
            self._entries[key] = e
        return e

    def refresh(self, key: FlowTuple, packets: int = 1, bytes_: int = 0) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.packets += packets
                e.bytes += bytes_
                lifetime = DEFAULT_LIFETIME_TCP if key[4] == 6 else DEFAULT_LIFETIME_OTHER
                e.expires = time.monotonic() + lifetime

    def _gc_locked(self, now: float) -> int:
        stale = [k for k, e in self._entries.items() if e.expires < now]
        for k in stale:
            del self._entries[k]
        return len(stale)

    def gc(self) -> int:
        """Reap expired entries; returns count (ctmap.go GC:345)."""
        with self._lock:
            return self._gc_locked(time.monotonic())

    def flush(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def __len__(self) -> int:
        return len(self._entries)

    def items(self) -> Iterator[Tuple[FlowTuple, ConntrackEntry]]:
        with self._lock:
            return iter(list(self._entries.items()))
