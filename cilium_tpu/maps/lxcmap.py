"""Local-endpoint map: IP → endpoint delivery info.

Reference: pkg/maps/lxcmap (cilium_lxc: EndpointKey IP →
EndpointInfo{ifindex, lxc_id, mac, node_mac}, lxcmap.go) and the boot
sync of daemon/daemon.go:953 syncLXCMap. The datapath consults it to
decide local delivery vs encap (bpf/lib/eps.h lookup_ip4_endpoint).
Here it is the host-authoritative table the pipeline's local-delivery
stage and the CNI plumbing read; synced from the endpoint manager.
"""

from __future__ import annotations

import dataclasses
import ipaddress
import threading
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class EndpointInfo:
    """lxcmap.go EndpointInfo."""

    endpoint_id: int
    ifindex: int = 0
    mac: str = ""
    node_mac: str = ""


class LXCMap:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_ip: Dict[str, EndpointInfo] = {}

    @staticmethod
    def _norm(ip: str) -> str:
        return str(ipaddress.ip_address(ip))

    def upsert(self, ip: str, info: EndpointInfo) -> None:
        with self._lock:
            self._by_ip[self._norm(ip)] = info

    def delete(self, ip: str) -> bool:
        with self._lock:
            return self._by_ip.pop(self._norm(ip), None) is not None

    def lookup(self, ip: str) -> Optional[EndpointInfo]:
        with self._lock:
            return self._by_ip.get(self._norm(ip))

    def items(self) -> List[Tuple[str, EndpointInfo]]:
        with self._lock:
            return sorted(self._by_ip.items())

    def sync_endpoints(self, endpoints) -> int:
        """Full resync from endpoint objects (syncLXCMap,
        daemon/daemon.go:953): every endpoint IP maps to its info;
        stale entries are removed. Returns the live entry count."""
        desired: Dict[str, EndpointInfo] = {}
        for ep in endpoints:
            info = EndpointInfo(endpoint_id=ep.id)
            for ip in (ep.ipv4, ep.ipv6):
                if ip:
                    desired[self._norm(ip)] = info
        with self._lock:
            self._by_ip = desired
        return len(desired)

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_ip)
