"""Per-endpoint realized policymap (reference: pkg/maps/policymap).

The reference's `cilium_policy_%d` BPF hash holds
`PolicyKey{Identity, DestPort, Nexthdr, TrafficDirection}` →
`PolicyEntry{ProxyPort, Packets, Bytes}` (policymap.go:64,73) and is
the unit the endpoint's desired/realized diff writes into
(pkg/endpoint/endpoint.go:2572 syncPolicyMap). Here it is host state:
the authoritative realized map mirrored by the device lookup tables,
with per-entry counters fed back from batch processing.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from ..ops.materialize import PolicyKey


@dataclasses.dataclass
class PolicyEntry:
    proxy_port: int = 0
    packets: int = 0
    bytes: int = 0


class PolicyMap:
    def __init__(self, name: str = "") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._entries: Dict[PolicyKey, PolicyEntry] = {}

    def allow(self, key: PolicyKey, proxy_port: int = 0) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = PolicyEntry(proxy_port=proxy_port)
            else:
                e.proxy_port = proxy_port

    def delete(self, key: PolicyKey) -> bool:
        with self._lock:
            return self._entries.pop(key, None) is not None

    def exists(self, key: PolicyKey) -> bool:
        return key in self._entries

    def lookup(self, key: PolicyKey) -> Optional[PolicyEntry]:
        return self._entries.get(key)

    def dump(self) -> List[Tuple[PolicyKey, PolicyEntry]]:
        with self._lock:
            return list(self._entries.items())

    def flush(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)

    def account(self, key: PolicyKey, packets: int, bytes_: int) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.packets += packets
                e.bytes += bytes_
