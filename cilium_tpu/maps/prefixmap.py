"""Shared longest-prefix map machinery.

TunnelMap and RouteTable are both "remote prefix → something" tables
programmed from node-registry events; this base keeps the prefix
normalization, the LPM lookup (parsed networks cached at insert — no
re-parsing per lookup), and the per-node programmed-set diffing in
ONE place so the two cannot drift.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Dict, List, Optional, Set, Tuple


def norm_prefix(prefix: str) -> str:
    return str(ipaddress.ip_network(prefix, strict=False))


class PrefixMap:
    """prefix (CIDR) → value with longest-prefix lookup."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # prefix → (parsed network, value)
        self._entries: Dict[str, Tuple[object, object]] = {}

    def upsert_value(self, prefix: str, value) -> None:
        net = ipaddress.ip_network(prefix, strict=False)
        with self._lock:
            self._entries[str(net)] = (net, value)

    def delete(self, prefix: str) -> bool:
        with self._lock:
            return self._entries.pop(norm_prefix(prefix), None) is not None

    def lookup_value(self, ip: str):
        addr = ipaddress.ip_address(ip)
        best, best_len = None, -1
        with self._lock:
            for net, value in self._entries.values():
                if net.version == addr.version and addr in net:
                    if net.prefixlen > best_len:
                        best, best_len = value, net.prefixlen
        return best

    def value_items(self) -> List[Tuple[str, object]]:
        with self._lock:
            return sorted(
                (prefix, value)
                for prefix, (_net, value) in self._entries.items()
            )

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def observe_node_cidrs(registry, on_change) -> None:
    """Subscribe ``on_change(node, host_ip, new_cidrs, stale_cidrs)``
    to a NodeRegistry with the shared semantics both maps need:

    - the LOCAL node is skipped (its prefixes deliver locally),
    - a node with alloc CIDRs but NO address yet (partial
      registration) programs nothing — a half-registered peer must
      not install entries claiming reachability,
    - a changed CIDR set reports the removed prefixes as stale.
    """
    local_key = registry.local.key_name
    programmed: Dict[str, Set[str]] = {}

    def on_node(node, live: bool) -> None:
        if node.key_name == local_key:
            return
        host = node.ipv4 or node.ipv6
        new = (
            {
                norm_prefix(c)
                for c in (node.ipv4_alloc_cidr, node.ipv6_alloc_cidr)
                if c
            }
            if live and host else set()
        )
        old = programmed.get(node.key_name, set())
        on_change(node, host, new, old - new)
        if new:
            programmed[node.key_name] = new
        else:
            programmed.pop(node.key_name, None)

    registry.observe(on_node, replay=True)
