"""Proxy map: redirected 5-tuple → original destination + source
identity.

Reference: pkg/maps/proxymap (cilium_proxy4/6) written by the datapath
on redirect verdicts and read by the C++ bpf_metadata listener filter
(envoy/cilium_bpf_metadata.cc) to recover where a proxied connection
was originally headed and who sent it. Here the pipeline records
redirected flows and the L7 layer queries by the flow tuple.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

DEFAULT_LIFETIME = 120.0  # proxymap entries are short-lived handoffs


@dataclasses.dataclass(frozen=True)
class ProxyValue:
    """proxymap.go Proxy4Value: original destination + source identity."""

    orig_dst_ip: str
    orig_dst_port: int
    src_identity: int


Key = Tuple[str, int, str, int, int]  # (sip, sport, dip, dport, proto)


class ProxyMap:
    def __init__(self, lifetime: float = DEFAULT_LIFETIME) -> None:
        self.lifetime = lifetime
        self._lock = threading.Lock()
        self._entries: Dict[Key, Tuple[ProxyValue, float]] = {}

    def record(
        self,
        sip: str, sport: int, dip: str, dport: int, proto: int,
        value: ProxyValue,
    ) -> None:
        with self._lock:
            self._entries[(sip, sport, dip, dport, proto)] = (
                value, time.monotonic() + self.lifetime,
            )

    def lookup(
        self, sip: str, sport: int, dip: str, dport: int, proto: int
    ) -> Optional[ProxyValue]:
        """The bpf_metadata getsockopt(SO_ORIGINAL_DST) analog."""
        now = time.monotonic()
        with self._lock:
            hit = self._entries.get((sip, sport, dip, dport, proto))
            if hit is None or hit[1] <= now:
                return None
            return hit[0]

    def items(self) -> list:
        """Readable live entries (cilium bpf proxy list)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "src": f"{k[0]}:{k[1]}", "dst": f"{k[2]}:{k[3]}",
                    "proto": k[4],
                    "orig_dst": f"{v.orig_dst_ip}:{v.orig_dst_port}",
                    "src_identity": v.src_identity,
                }
                for k, (v, exp) in self._entries.items() if exp > now
            ]

    def gc(self) -> int:
        now = time.monotonic()
        with self._lock:
            stale = [k for k, (_, exp) in self._entries.items() if exp <= now]
            for k in stale:
                del self._entries[k]
            return len(stale)

    def __len__(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for _, exp in self._entries.values() if exp > now)
