"""Node route table.

Reference: pkg/datapath/route (route.go) + the per-remote-node route
installation of pkg/node/manager.go — each remote node's allocation
CIDR gets a route via the tunnel device (encap) or the node's address
(direct routing). Here the "kernel table" is a host map the datapath
simulator and debuginfo read; fed by the same node-registry observer
machinery as the tunnel map (maps/prefixmap.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from .prefixmap import PrefixMap, observe_node_cidrs


@dataclasses.dataclass(frozen=True)
class Route:
    prefix: str
    nexthop: Optional[str]  # None = on-link / via tunnel device
    device: str
    mtu: int = 0


class RouteTable(PrefixMap):
    def upsert(self, route: Route) -> None:
        self.upsert_value(route.prefix, route)

    def lookup(self, ip: str) -> Optional[Route]:
        """Longest-prefix route for a destination."""
        return self.lookup_value(ip)

    def items(self) -> List[Route]:
        return [route for _prefix, route in self.value_items()]

    def observe_nodes(self, registry, *, tunnel_device: str = "cilium_vxlan",
                      route_mtu: int = 0) -> None:
        """Remote nodes' alloc CIDRs → routes (node/manager.go
        nodeUpdated route install); shared node-event semantics in
        prefixmap.observe_node_cidrs."""

        def on_change(node, host, new, stale) -> None:
            for prefix in stale:
                self.delete(prefix)
            for prefix in new:
                self.upsert(Route(
                    prefix=prefix,
                    nexthop=host,
                    device=tunnel_device,
                    mtu=route_mtu,
                ))

        observe_node_cidrs(registry, on_change)
