"""Tunnel map: remote prefix → tunnel endpoint (node host IP).

Reference: pkg/maps/tunnel (cilium_tunnel_map) + the per-remote-node
programming of pkg/node/manager.go:94-195 (each node's allocation
CIDRs map to its host IP for encap). Fed by a node-registry observer;
the datapath's encap stage consults it for non-local destinations.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Dict, List, Optional, Tuple


class TunnelMap:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_prefix: Dict[str, str] = {}  # CIDR → tunnel endpoint IP

    @staticmethod
    def _norm(prefix: str) -> str:
        return str(ipaddress.ip_network(prefix, strict=False))

    def upsert(self, prefix: str, endpoint_ip: str) -> None:
        with self._lock:
            self._by_prefix[self._norm(prefix)] = endpoint_ip

    def delete(self, prefix: str) -> bool:
        with self._lock:
            return self._by_prefix.pop(self._norm(prefix), None) is not None

    def lookup(self, ip: str) -> Optional[str]:
        """Longest-prefix match → tunnel endpoint for a destination."""
        addr = ipaddress.ip_address(ip)
        with self._lock:
            best, best_len = None, -1
            for prefix, ep in self._by_prefix.items():
                net = ipaddress.ip_network(prefix)
                if net.version == addr.version and addr in net:
                    if net.prefixlen > best_len:
                        best, best_len = ep, net.prefixlen
            return best

    def items(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._by_prefix.items())

    def observe_nodes(self, registry) -> None:
        """Wire to a NodeRegistry: REMOTE nodes' alloc CIDRs → their
        node IP (node/manager.go nodeUpdated/nodeDeleted). The local
        node is skipped — local pod prefixes must deliver locally,
        never encapsulate back to ourselves. Tracks what each node
        programmed so a node UPDATE that changes its CIDR also removes
        the old prefix (stale entries would longest-prefix-match
        traffic for prefixes later reassigned elsewhere)."""
        local_key = registry.local.key_name
        programmed: Dict[str, set] = {}

        def on_node(node, live: bool) -> None:
            if node.key_name == local_key:
                return
            host = node.ipv4 or node.ipv6
            new = {
                self._norm(c)
                for c in (node.ipv4_alloc_cidr, node.ipv6_alloc_cidr)
                if c
            } if live and host else set()
            old = programmed.get(node.key_name, set())
            for cidr in old - new:
                self.delete(cidr)
            for cidr in new:
                self.upsert(cidr, host)
            if new:
                programmed[node.key_name] = new
            else:
                programmed.pop(node.key_name, None)

        registry.observe(on_node, replay=True)

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_prefix)
