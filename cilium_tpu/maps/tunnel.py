"""Tunnel map: remote prefix → tunnel endpoint (node host IP).

Reference: pkg/maps/tunnel (cilium_tunnel_map) + the per-remote-node
programming of pkg/node/manager.go:94-195 (each node's allocation
CIDRs map to its host IP for encap). Fed by a node-registry observer;
the datapath's encap stage consults it for non-local destinations.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .prefixmap import PrefixMap, observe_node_cidrs


class TunnelMap(PrefixMap):
    def upsert(self, prefix: str, endpoint_ip: str) -> None:
        self.upsert_value(prefix, endpoint_ip)

    def lookup(self, ip: str) -> Optional[str]:
        """Longest-prefix match → tunnel endpoint for a destination."""
        return self.lookup_value(ip)

    def items(self) -> List[Tuple[str, str]]:
        return self.value_items()

    def observe_nodes(self, registry) -> None:
        """Wire to a NodeRegistry: REMOTE nodes' alloc CIDRs → their
        node IP (node/manager.go nodeUpdated/nodeDeleted). Shared
        semantics (local-node skip, partial-registration guard, stale
        CIDR removal) live in prefixmap.observe_node_cidrs."""

        def on_change(node, host, new, stale) -> None:
            for prefix in stale:
                self.delete(prefix)
            for prefix in new:
                self.upsert(prefix, host)

        observe_node_cidrs(registry, on_change)
