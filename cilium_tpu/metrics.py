"""Metrics registry with Prometheus text exposition.

Reference: pkg/metrics/metrics.go:37,87-180 — a process-wide registry
of counters/gauges/histograms covering endpoint regeneration, policy
revision/import counts, datapath errors, and event counts, served over
HTTP and bridged into the REST API. No external client library — the
text exposition format is trivial to emit.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Counter:
    # Exposition TYPE word. Subclasses override this instead of
    # duplicating expose(): the HELP/TYPE header emission lives in
    # exactly one place, so the two can never drift apart.
    _TYPE = "counter"

    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[_LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        k = _labels_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def series(self) -> Dict[_LabelKey, float]:
        """Point-in-time snapshot of every label series (for /profile
        readers that want values, not exposition text)."""
        with self._lock:
            return dict(self._values)

    def expose(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self._TYPE}",
        ]
        # snapshot under the lock: a concurrent inc() on a fresh label
        # set would otherwise mutate the dict mid-iteration
        with self._lock:
            items = sorted(self._values.items())
        for k, v in items:
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge(Counter):
    _TYPE = "gauge"

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = value


class _HistSeries:
    """One (label-set) series of a histogram: per-bucket counts + sum/n."""

    __slots__ = ("counts", "sum", "n")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * (n_buckets + 1)
        self.sum = 0.0
        self.n = 0


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)

    def __init__(self, name: str, help_: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        # label-set → series; the unlabeled series exists from the
        # start so an unobserved histogram still exposes its zeros
        self._series: Dict[_LabelKey, _HistSeries] = {
            (): _HistSeries(len(self.buckets))
        }
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        k = _labels_key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = _HistSeries(len(self.buckets))
            s.sum += value
            s.n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s.counts[i] += 1
                    return
            s.counts[-1] += 1

    def get_count(self, labels: Optional[Dict[str, str]] = None) -> int:
        s = self._series.get(_labels_key(labels))
        return 0 if s is None else s.n

    def series_labels(self) -> List[Dict[str, str]]:
        """Label sets with at least one series (incl. the unlabeled
        {}) — lets /traces and /profile walk per-phase quantiles
        without reaching into the series dict."""
        with self._lock:
            keys = list(self._series.keys())
        return [dict(k) for k in keys]

    def quantile(
        self, q: float, labels: Optional[Dict[str, str]] = None
    ) -> Optional[float]:
        """Estimate the q-quantile (0 < q <= 1) of one label series by
        linear interpolation within the landing bucket — the standard
        Prometheus histogram_quantile() estimate. Returns None for an
        unobserved series. Values past the last finite bucket clamp to
        that bucket bound (+Inf has no upper edge to interpolate to)."""
        if not 0.0 < q <= 1.0:
            raise ValueError("quantile must be in (0, 1]")
        with self._lock:
            s = self._series.get(_labels_key(labels))
            if s is None or s.n == 0:
                return None
            counts = list(s.counts)
            n = s.n
        rank = q * n
        cum = 0
        for i, b in enumerate(self.buckets):
            prev_cum = cum
            cum += counts[i]
            if cum >= rank:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                if counts[i] == 0:
                    return b
                return lo + (b - lo) * (rank - prev_cum) / counts[i]
        return self.buckets[-1]

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            series = sorted(self._series.items())
        for key, s in series:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += s.counts[i]
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key + (('le', str(b)),))} {cum}"
                )
            cum += s.counts[-1]
            out.append(
                f"{self.name}_bucket{_fmt_labels(key + (('le', '+Inf'),))} {cum}"
            )
            out.append(f"{self.name}_sum{_fmt_labels(key)} {s.sum}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {s.n}")
        return out


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets))

    def _get(self, name, ctor):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = ctor()
                self._metrics[name] = m
            return m

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics.values():
                lines.extend(m.expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"


# Process-wide registry + the metric families of pkg/metrics/metrics.go.
registry = Registry()

endpoint_regeneration_count = registry.counter(
    "cilium_tpu_endpoint_regenerations_total", "Count of endpoint regenerations"
)
endpoint_regeneration_time = registry.histogram(
    "cilium_tpu_endpoint_regeneration_seconds", "Endpoint regeneration latency"
)
policy_count = registry.gauge("cilium_tpu_policy_count", "Rules in the repository")
policy_revision = registry.gauge("cilium_tpu_policy_max_revision", "Policy revision")
policy_import_errors = registry.counter(
    "cilium_tpu_policy_import_errors_total", "Failed policy imports"
)
verdict_batches = registry.counter(
    "cilium_tpu_datapath_batches_total", "Flow batches processed"
)
verdicts_total = registry.counter(
    "cilium_tpu_datapath_verdicts_total",
    "Flow verdicts by outcome (batches dispatched under VerdictSharding "
    "report per-device series via an extra device label instead of the "
    "plain outcome series — sum across labels for the total)",
)
identity_count = registry.gauge("cilium_tpu_identity_count", "Allocated identities")
l7_fallback_patterns = registry.counter(
    "cilium_tpu_l7_fallback_patterns_total",
    "L7 regex patterns demoted from the device DFA to host re",
)
l7_host_fallback_evaluations = registry.counter(
    "cilium_tpu_l7_host_fallback_evaluations_total",
    "Request-field evaluations that ran on host re instead of the DFA",
)
compile_time = registry.histogram(
    "cilium_tpu_policy_compile_seconds", "Policy tensor compile latency"
)

# -- policyd-trace (observe/) families -----------------------------------
# Verdict-path phases run µs–ms, far below DEFAULT_BUCKETS' 1ms floor;
# the top buckets still catch first-compile outliers.
PHASE_BUCKETS = (
    20e-6, 50e-6, 100e-6, 250e-6, 500e-6, 1e-3, 2.5e-3, 5e-3, 10e-3,
    25e-3, 50e-3, 100e-3, 250e-3, 1.0,
)
pipeline_phase_seconds = registry.histogram(
    "cilium_tpu_pipeline_phase_seconds",
    "Verdict-path phase latency (label: phase — a stable name set, "
    "see cilium_tpu/observe/README.md)",
    buckets=PHASE_BUCKETS,
)
batch_total_seconds = registry.histogram(
    "cilium_tpu_pipeline_batch_seconds",
    "End-to-end wall time of one traced verdict batch",
    buckets=PHASE_BUCKETS,
)
engine_refresh_seconds = registry.histogram(
    "cilium_tpu_engine_refresh_seconds",
    "Policy engine refresh latency (label kind: full|incremental|delta — "
    "delta is the pipeline's O(delta) materialization patch)",
    buckets=PHASE_BUCKETS,
)
engine_refreshes_total = registry.counter(
    "cilium_tpu_engine_refreshes_total",
    "Policy engine refreshes by kind (full recompile vs incremental patch)",
)

# -- policyd-delta (O(delta) refresh) families -----------------------------
engine_delta_rows_total = registry.counter(
    "cilium_tpu_engine_delta_rows_total",
    "Identity rows updated through the coalesced delta path (one per "
    "(row, identity, live) event scattered to the device tables)",
)
engine_delta_cols_total = registry.counter(
    "cilium_tpu_engine_delta_cols_total",
    "Identity rows carried by selector column-patch events (policyd-"
    "sparse): a new-selector append touching k identities logs one "
    "\"cols\" delta and scatters O(k·window) words instead of the full "
    "[N, S/32] sel_match matrix",
)
lpm_trie_patches_total = registry.counter(
    "cilium_tpu_lpm_trie_patches_total",
    "ipcache prefix upserts/deletes applied to the device LPM tries as "
    "O(delta) node patches (policyd-sparse; label family: 4|6) instead "
    "of whole-trie rebuilds",
)
engine_epoch_swaps_total = registry.counter(
    "cilium_tpu_engine_epoch_swaps_total",
    "Shadow-built device-table generations atomically swapped in at a "
    "batch boundary (full rebuilds that did NOT stop the verdict world)",
)
jit_shape_buckets_total = registry.counter(
    "cilium_tpu_jit_shape_buckets_total",
    "Shape-bucket cache outcomes (result=miss ≈ an XLA recompile)",
)
device_transfers_total = registry.counter(
    "cilium_tpu_device_transfers_total",
    "Host↔device array transfers on traced dispatches (label: direction; "
    "under VerdictSharding each logical transfer counts once per mesh "
    "device — the slices/gathers actually issued)",
)
pipeline_inflight_depth = registry.gauge(
    "cilium_tpu_pipeline_inflight_depth",
    "Verdict batches enqueued on device but not yet pulled to host "
    "(bounded by VerdictPipelineDepth)",
)

# -- policyd-autotune (adaptive dispatch) families -------------------------
dispatch_pad_lanes_total = registry.counter(
    "cilium_tpu_dispatch_pad_lanes_total",
    "Device lanes dispatched as shape-bucket padding, not live flows "
    "(label: family — divide by live+pad for the pad-waste fraction; "
    "counted on every dispatch path, bucketed or not)",
)
pipeline_depth_current = registry.gauge(
    "cilium_tpu_pipeline_depth_current",
    "Effective verdict pipeline depth right now (moves between 1 and "
    "VerdictPipelineMaxDepth while DispatchAutoTune is on; otherwise "
    "the static configured depth)",
)
autotune_adjustments_total = registry.counter(
    "cilium_tpu_autotune_adjustments_total",
    "Depth steps taken by the dispatch auto-tuner "
    "(label direction: up|down)",
)

# -- policyd-failsafe (fault injection + degradation ladder) families ------
pipeline_faults_total = registry.counter(
    "cilium_tpu_pipeline_faults_total",
    "Classified verdict-path faults (labels: site = the stable "
    "cilium_tpu/faults.py site set, kind = transient|poisoned; counts "
    "injected faults at injection time and real classified errors at "
    "handling time)",
)
degradations_total = registry.counter(
    "cilium_tpu_pipeline_degradations_total",
    "Degradation-ladder transitions (labels from/to: "
    "sharded|single-device|host; re-promotions count too — a recovery "
    "probe is a transition back up)",
)
pipeline_mode = registry.gauge(
    "cilium_tpu_pipeline_mode",
    "Current verdict-path ladder level: 0 = full device complement "
    "(sharded when VerdictSharding is on), 1 = single-device (mesh "
    "re-formed excluding faulted devices), 2 = host/numpy fallback",
)

# -- policyd-mesh (placement + identity sharding) families -----------------
mesh_axis_size = registry.gauge(
    "cilium_tpu_mesh_axis_size",
    "Resolved verdict-mesh axis extents (label axis: flows|ident; 0 = "
    "axis absent — no mesh or no 2D split). flows × ident = devices in "
    "the active placement plan",
)
sharded_table_bytes = registry.gauge(
    "cilium_tpu_sharded_table_bytes",
    "PER-DEVICE bytes of the identity-indexed device tables under the "
    "active placement (label family: policymap|rule_tab; a 2D "
    "flows×ident plan divides the replicated footprint by the ident "
    "axis size, within last-shard padding)",
)

# -- policyd-l7batch (fused L7 classification) families --------------------
l7_batch_seconds = registry.histogram(
    "cilium_tpu_l7_batch_seconds",
    "End-to-end wall time of one L7 classification batch through the "
    "overlapped submit() pipeline (prep → device walk → mask pull)",
    buckets=PHASE_BUCKETS,
)
l7_dfa_tables_interned = registry.gauge(
    "cilium_tpu_l7_dfa_tables_interned",
    "Fused DFA device tables currently interned (shared across every "
    "endpoint whose policy compiles to the same pattern-set key)",
)
l7_dfa_intern_total = registry.counter(
    "cilium_tpu_l7_dfa_intern_total",
    "Fused-table intern outcomes (result=hit: an endpoint reused an "
    "existing device table; miss: a new table was built and "
    "transferred; evict: LRU displacement past the cap)",
)
l7_pad_lanes_total = registry.counter(
    "cilium_tpu_l7_pad_lanes_total",
    "L7 ladder padding (kind=lane: rows dispatched to fill a lane "
    "rung; kind=len_bytes: padded byte-steps under the length rung — "
    "divide by the live counterpart for the pad-waste fraction)",
)
l7_batches_total = registry.counter(
    "cilium_tpu_l7_batches_total",
    "L7 request batches classified through the fused device path "
    "(label parser: http|kafka)",
)

# -- policyd-flows (verdict attribution) families -------------------------
rule_hits_total = registry.counter(
    "cilium_tpu_rule_hits_total",
    "Verdicts attributed to a repository rule (labels: origin = the "
    "rule's label set or rule-<index>, direction = ingress|egress; "
    "only incremented while FlowAttribution is on — the [R] hit tensor "
    "is segment-summed on device and pulled at batch completion)",
)
drop_reasons_total = registry.counter(
    "cilium_tpu_drop_reasons_total",
    "Dropped flows by attribution reason (label: reason — the stable "
    "policyd-flows taxonomy in monitor/events.py; generic codes when "
    "FlowAttribution is off)",
)

# -- policyd-overload (admission control + watchdog) families --------------
admission_shed_total = registry.counter(
    "cilium_tpu_admission_shed_total",
    "Flows resolved by the admission gate instead of the full verdict "
    "path (label reason: prefilter = coarse drop-table match, code 144; "
    "deadline = deferred past the batch deadline and resolved via the "
    "fail-closed 155 / FailOpen semantics)",
)
queue_wait_seconds = registry.histogram(
    "cilium_tpu_queue_wait_seconds",
    "Wall time a submitted batch spent gated at admission before "
    "entering the verdict pipeline (only recorded while "
    "AdmissionControl is on; ungated batches observe ~0)",
    buckets=PHASE_BUCKETS,
)
admission_queue_depth = registry.gauge(
    "cilium_tpu_admission_queue_depth",
    "In-flight verdict batches as seen by the admission controller at "
    "its last gate decision (vs its AIMD limit, see GET /healthz)",
)
watchdog_stalls_total = registry.counter(
    "cilium_tpu_watchdog_stalls_total",
    "Stuck operations detected by the dispatch watchdog (label site: "
    "the faults.py site the stalled operation registered under — "
    "dispatch for in-flight batches, attach/compile for registered "
    "external waits, stall for injected sweeps)",
)

# -- policyd-prof (device profiler + memory/transfer ledger) families ------
profile_samples_total = registry.counter(
    "cilium_tpu_profile_samples_total",
    "Dispatches sampled by the device profiler (label site: dispatch|l7; "
    "every profile_sample_every-th batch while DeviceProfiling is on)",
)
profile_phase_seconds = registry.histogram(
    "cilium_tpu_profile_phase_seconds",
    "Sampled dispatch RTT decomposition from the profiler's "
    "block_until_ready sandwiches (label phase: h2d|device_compute|d2h; "
    "only sampled batches observe — scale rates by profile_sample_every)",
    buckets=PHASE_BUCKETS,
)
device_table_bytes = registry.gauge(
    "cilium_tpu_device_table_bytes",
    "PER-DEVICE resident bytes of each policy table family (labels: "
    "family = policymap|rule_tab|sel_match|lpm_trie|dfa, placement = "
    "replicated|ident-sharded; the memory-ledger counterpart of "
    "cilium_tpu_sharded_table_bytes, covering every family)",
)
device_transfer_bytes_total = registry.counter(
    "cilium_tpu_device_transfer_bytes_total",
    "Host↔device bytes moved on traced dispatches (label: direction — "
    "the byte-ledger sibling of the count-only "
    "cilium_tpu_device_transfers_total; logical bytes, not multiplied "
    "by mesh device count, since shard slices sum to the full array)",
)

# -- policyd-fed (cluster federation) families -----------------------------
cluster_nodes = registry.gauge(
    "cilium_tpu_cluster_nodes",
    "Nodes currently publishing in the federated policy plane (the "
    "epoch-exchange view; records are lease-bound, so a dead node "
    "ages out with its kvstore lease)",
)
cluster_identity_allocations_total = registry.counter(
    "cilium_tpu_cluster_identity_allocations_total",
    "Cluster identity-allocator outcomes (label result: new = won the "
    "reserve/confirm CAS, adopted = joined a peer's allocation, "
    "cached = local refcount hit, retry = CAS race or kvstore "
    "partition re-attempt, error = backoff budget exhausted or id "
    "space full)",
)
cluster_epoch_lag = registry.gauge(
    "cilium_tpu_cluster_epoch_lag",
    "Local policy_epoch minus the cluster convergence floor (the min "
    "over every published node); 0 means this node's last full "
    "rebuild is enforced fleet-wide as far as the exchange can prove",
)

# -- policyd-survive (restart/drain continuity) families -------------------
ct_restored_entries_total = registry.counter(
    "cilium_tpu_ct_restored_entries_total",
    "Conntrack entries processed by restore paths (label result: kept = "
    "re-placed live into the table, expired = TTL ran out while the "
    "process was down or the entry lost its probe neighborhood, "
    "flushed = dropped whole because the CT snapshot's policy basis "
    "did not match the restored compiled snapshot)",
)
restart_downtime_seconds = registry.gauge(
    "cilium_tpu_restart_downtime_seconds",
    "Wall time from the start of restore_state() to the first verdict "
    "batch completed after a restart (set once per process; the bench "
    "--chaos restart round reports the same quantity cross-process as "
    "restart_downtime_ms)",
)
drain_seconds = registry.histogram(
    "cilium_tpu_drain_seconds",
    "Wall time of one bounded graceful drain (SIGTERM/shutdown): shed "
    "new admissions, FIFO-complete in-flight verdict + L7 batches "
    "under the deadline, persist CT + compiled + state.json",
)
state_snapshot_bytes = registry.gauge(
    "cilium_tpu_state_snapshot_bytes",
    "Bytes of the last state-dir snapshot written (label kind: "
    "compiled|ct|state_json)",
)

# -- policyd-fleetobs (fleet telemetry plane) families ---------------------
timeseries_snapshots_total = registry.counter(
    "cilium_tpu_timeseries_snapshots_total",
    "Sampler ticks appended to the fleet time-series ring (one row "
    "per FleetTelemetry cadence tick; rate ~= 1/telemetry_sample_s "
    "while the option is on)",
)
slo_burn_ratio = registry.gauge(
    "cilium_tpu_slo_burn_ratio",
    "Observed/target burn ratio per declared SLO objective and "
    "reduction window (labels: objective = the observe/fleet.py "
    "DEFAULT_OBJECTIVES names, window = 10s|1m|5m; >= 1.0 means the "
    "objective is out of budget over that window)",
)
telemetry_frames_total = registry.counter(
    "cilium_tpu_telemetry_frames_total",
    "Fleet telemetry frame outcomes (label result: published = frame "
    "written to the exchange, publish_error = kvstore down at publish "
    "time, rejected = peer frame failed version/stamp validation, "
    "stale = peer frame aged past the staleness horizon at read time)",
)
fleet_nodes_reporting = registry.gauge(
    "cilium_tpu_fleet_nodes_reporting",
    "Nodes with a live (non-stale, version-compatible) telemetry "
    "frame in the last fleet aggregation — the scoreboard's liveness "
    "denominator; drops within seconds of a node dying, ahead of its "
    "kvstore lease expiry",
)

# -- policyd-journal (lifecycle event journal) families --------------------
journal_events_total = registry.counter(
    "cilium_tpu_journal_events_total",
    "Lifecycle events recorded by the EventJournal (labels: kind = "
    "contracts.JOURNAL_KINDS row, severity = info|warning|error); "
    "counts every emit, including events later evicted from the ring",
)
journal_dropped_total = registry.counter(
    "cilium_tpu_journal_dropped_total",
    "Lifecycle events evicted from the bounded journal ring to make "
    "room for newer ones (journal_ring_capacity overflow); the GET "
    "/events tail is complete iff this stayed 0 since boot",
)
journal_frames_total = registry.counter(
    "cilium_tpu_journal_frames_total",
    "Journal tail frame outcomes on the federation exchange (label "
    "result: published | publish_error | rejected | stale — same "
    "vocabulary as telemetry_frames_total)",
)
