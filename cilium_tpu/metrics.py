"""Metrics registry with Prometheus text exposition.

Reference: pkg/metrics/metrics.go:37,87-180 — a process-wide registry
of counters/gauges/histograms covering endpoint regeneration, policy
revision/import counts, datapath errors, and event counts, served over
HTTP and bridged into the REST API. No external client library — the
text exposition format is trivial to emit.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[_LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        k = _labels_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = value

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)

    def __init__(self, name: str, help_: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        cum += self._counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {self._sum}")
        out.append(f"{self.name}_count {self._n}")
        return out


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets))

    def _get(self, name, ctor):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = ctor()
                self._metrics[name] = m
            return m

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics.values():
                lines.extend(m.expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"


# Process-wide registry + the metric families of pkg/metrics/metrics.go.
registry = Registry()

endpoint_regeneration_count = registry.counter(
    "cilium_tpu_endpoint_regenerations_total", "Count of endpoint regenerations"
)
endpoint_regeneration_time = registry.histogram(
    "cilium_tpu_endpoint_regeneration_seconds", "Endpoint regeneration latency"
)
policy_count = registry.gauge("cilium_tpu_policy_count", "Rules in the repository")
policy_revision = registry.gauge("cilium_tpu_policy_max_revision", "Policy revision")
policy_import_errors = registry.counter(
    "cilium_tpu_policy_import_errors_total", "Failed policy imports"
)
verdict_batches = registry.counter(
    "cilium_tpu_datapath_batches_total", "Flow batches processed"
)
verdicts_total = registry.counter(
    "cilium_tpu_datapath_verdicts_total", "Flow verdicts by outcome"
)
identity_count = registry.gauge("cilium_tpu_identity_count", "Allocated identities")
l7_fallback_patterns = registry.counter(
    "cilium_tpu_l7_fallback_patterns_total",
    "L7 regex patterns demoted from the device DFA to host re",
)
l7_host_fallback_evaluations = registry.counter(
    "cilium_tpu_l7_host_fallback_evaluations_total",
    "Request-field evaluations that ran on host re instead of the DFA",
)
compile_time = registry.histogram(
    "cilium_tpu_policy_compile_seconds", "Policy tensor compile latency"
)
