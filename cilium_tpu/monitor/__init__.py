"""Observability event stream: drop/trace/agent/L7 notifications,
lossy multicast hub, and the monitor socket protocol (the roles of
monitor/ + pkg/monitor in the reference)."""

from .dissect import Dissection, dissect, render_waterfall
from .events import (
    EVENT_AGENT,
    EVENT_CAPTURE,
    EVENT_DROP,
    EVENT_L7,
    EVENT_POLICY_VERDICT,
    EVENT_TRACE,
    EVENT_TRACE_SUMMARY,
    REASON_NO_SERVICE,
    REASON_POLICY,
    REASON_PREFILTER,
    AgentNotify,
    DebugCapture,
    DropNotify,
    L7Notify,
    PolicyVerdictNotify,
    TraceNotify,
    TraceSummary,
    decode,
    encode,
    reason_name,
)
from .hub import MonitorHub, Subscription
from .server import MonitorServer, monitor_stream

__all__ = [
    "AgentNotify",
    "DebugCapture",
    "Dissection",
    "dissect",
    "DropNotify",
    "EVENT_AGENT",
    "EVENT_DROP",
    "EVENT_L7",
    "EVENT_POLICY_VERDICT",
    "EVENT_TRACE",
    "EVENT_TRACE_SUMMARY",
    "L7Notify",
    "PolicyVerdictNotify",
    "TraceSummary",
    "render_waterfall",
    "MonitorHub",
    "MonitorServer",
    "REASON_NO_SERVICE",
    "REASON_POLICY",
    "REASON_PREFILTER",
    "Subscription",
    "TraceNotify",
    "decode",
    "encode",
    "monitor_stream",
    "reason_name",
]
