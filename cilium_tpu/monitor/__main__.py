"""``python -m cilium_tpu.monitor`` — the standalone node monitor
process (cilium-node-monitor entry point, monitor/monitor.go)."""

import sys

from .standalone import main

if __name__ == "__main__":
    sys.exit(main())
