"""Packet dissection for monitor output.

Reference: pkg/monitor/dissect.go — the monitor decodes the raw packet
bytes a DebugCapture/TraceNotify payload carries (gopacket layers:
Ethernet, ARP, IPv4, IPv6, TCP, UDP, ICMPv4, ICMPv6) into one summary
line per packet. Same layers here, hand-decoded (no scapy in the
image), producing reference-shaped summaries like::

    IP 10.1.0.5:3380 -> 10.1.0.7:80 tcp SYN
    IPv6 fd00::1 -> fd00::2 icmpv6 EchoRequest
    ARP request 10.0.0.1 tell 10.0.0.2
"""

from __future__ import annotations

import dataclasses
import ipaddress
import struct
from typing import Optional

ETH_P_IPV4 = 0x0800
ETH_P_ARP = 0x0806
ETH_P_IPV6 = 0x86DD
ETH_P_8021Q = 0x8100

_TCP_FLAG_NAMES = (
    (0x01, "FIN"), (0x02, "SYN"), (0x04, "RST"), (0x08, "PSH"),
    (0x10, "ACK"), (0x20, "URG"), (0x40, "ECE"), (0x80, "CWR"),
)

_ICMP4_TYPES = {0: "EchoReply", 3: "DestinationUnreachable", 5: "Redirect",
                8: "EchoRequest", 11: "TimeExceeded"}
_ICMP6_TYPES = {1: "DestinationUnreachable", 3: "TimeExceeded",
                128: "EchoRequest", 129: "EchoReply",
                135: "NeighborSolicitation", 136: "NeighborAdvertisement"}

# IPv6 extension headers skipped while hunting the upper-layer proto.
# ESP (50) is NOT here: past it everything is encrypted, so the walk
# stops and reports proto 50. AH (51) has its own 4*(len+2) sizing and
# is handled separately in _ipv6.
_V6_EXT = {0, 43, 44, 60}


@dataclasses.dataclass
class Dissection:
    """Decoded layers of one packet (None = layer absent/truncated)."""

    src_mac: str = ""
    dst_mac: str = ""
    ethertype: int = 0
    vlan: Optional[int] = None
    src_ip: str = ""
    dst_ip: str = ""
    proto: int = 0  # upper-layer protocol number (6/17/1/58/...)
    ttl: int = 0
    sport: Optional[int] = None
    dport: Optional[int] = None
    tcp_flags: str = ""
    icmp_type: str = ""
    arp_op: str = ""
    truncated: bool = False

    def summary(self) -> str:
        if self.arp_op:
            return f"ARP {self.arp_op} {self.dst_ip} tell {self.src_ip}"
        if not self.src_ip:
            return (
                f"Ethernet {self.src_mac} -> {self.dst_mac} "
                f"ethertype 0x{self.ethertype:04x}"
            )
        fam = "IP" if self.ethertype == ETH_P_IPV4 else "IPv6"
        if self.proto == 6 and self.sport is not None:
            return (
                f"{fam} {self.src_ip}:{self.sport} -> {self.dst_ip}:"
                f"{self.dport} tcp {self.tcp_flags or '-'}"
            )
        if self.proto == 17 and self.sport is not None:
            return (
                f"{fam} {self.src_ip}:{self.sport} -> {self.dst_ip}:"
                f"{self.dport} udp"
            )
        if self.proto in (1, 58):
            name = "icmp" if self.proto == 1 else "icmpv6"
            return (
                f"{fam} {self.src_ip} -> {self.dst_ip} {name} "
                f"{self.icmp_type or '?'}"
            )
        tail = " (truncated)" if self.truncated else ""
        return f"{fam} {self.src_ip} -> {self.dst_ip} proto {self.proto}{tail}"


def _mac(b: bytes) -> str:
    return ":".join(f"{x:02x}" for x in b)


def l2_offsets(data: bytes):
    """Shared L2 framing rules: Ethernet frame → (ethertype, l3_offset,
    vlan_id_or_None), or None when the frame is cut before the payload
    ethertype is knowable. ONE definition of the ethertype/802.1Q/
    truncation handling — both the human-facing dissector below and the
    hot-path tuple extractor (datapath/wire.py) build on it, so a
    framing fix lands in exactly one place."""
    if len(data) < 14:
        return None
    (etype,) = struct.unpack_from(">H", data, 12)  # _from: no slice
    off = 14  # allocations on the wire front-end's per-packet path
    vlan = None
    if etype == ETH_P_8021Q:
        if len(data) < 18:
            return None  # cut inside the VLAN tag
        (tci, etype) = struct.unpack_from(">HH", data, 14)
        vlan = tci & 0x0FFF
        off = 18
    return etype, off, vlan


def dissect(data: bytes) -> Dissection:
    """Decode one Ethernet frame, best-effort: truncated packets keep
    whatever layers fit (the monitor must never crash on a capture)."""
    d = Dissection()
    if len(data) < 14:
        d.truncated = True
        return d
    d.dst_mac = _mac(data[0:6])
    d.src_mac = _mac(data[6:12])
    l2 = l2_offsets(data)
    if l2 is None:
        # cut inside the VLAN tag: the payload ethertype is gone
        (d.ethertype,) = struct.unpack(">H", data[12:14])
        d.truncated = True
        return d
    etype, off, vlan = l2
    if vlan is not None:
        d.vlan = vlan
    d.ethertype = etype
    if etype == ETH_P_ARP:
        return _arp(d, data[off:])
    if etype == ETH_P_IPV4:
        return _ipv4(d, data[off:])
    if etype == ETH_P_IPV6:
        return _ipv6(d, data[off:])
    return d


def _arp(d: Dissection, p: bytes) -> Dissection:
    if len(p) < 28:
        d.truncated = True
        return d
    (op,) = struct.unpack(">H", p[6:8])
    d.arp_op = {1: "request", 2: "reply"}.get(op, f"op-{op}")
    d.src_ip = str(ipaddress.IPv4Address(p[14:18]))  # sender
    d.dst_ip = str(ipaddress.IPv4Address(p[24:28]))  # target
    return d


def _ipv4(d: Dissection, p: bytes) -> Dissection:
    if len(p) < 20:
        d.truncated = True
        return d
    ihl = (p[0] & 0x0F) * 4
    d.ttl = p[8]
    d.proto = p[9]
    d.src_ip = str(ipaddress.IPv4Address(p[12:16]))
    d.dst_ip = str(ipaddress.IPv4Address(p[16:20]))
    if len(p) < ihl:
        d.truncated = True
        return d
    return _l4(d, p[ihl:])


def _ipv6(d: Dissection, p: bytes) -> Dissection:
    if len(p) < 40:
        d.truncated = True
        return d
    nxt = p[6]
    d.ttl = p[7]  # hop limit
    d.src_ip = str(ipaddress.IPv6Address(p[8:24]))
    d.dst_ip = str(ipaddress.IPv6Address(p[24:40]))
    off = 40
    # walk common extension headers (fixed 8*(len+1) sizing; AH uses
    # 4*(len+2) per RFC 4302)
    while nxt in _V6_EXT or nxt == 51:
        if len(p) < off + 8:
            d.truncated = True
            d.proto = nxt
            return d
        is_ah = nxt == 51
        nxt, hlen = p[off], p[off + 1]
        off += (hlen + 2) * 4 if is_ah else (hlen + 1) * 8
    d.proto = nxt
    return _l4(d, p[off:])


def _l4(d: Dissection, p: bytes) -> Dissection:
    if d.proto == 6:
        if len(p) < 14:
            d.truncated = True
            return d
        d.sport, d.dport = struct.unpack(">HH", p[0:4])
        flags = p[13]
        d.tcp_flags = ", ".join(n for bit, n in _TCP_FLAG_NAMES if flags & bit)
    elif d.proto == 17:
        if len(p) < 8:
            d.truncated = True
            return d
        d.sport, d.dport = struct.unpack(">HH", p[0:4])
    elif d.proto == 1:
        if len(p) < 2:
            d.truncated = True
            return d
        d.icmp_type = _ICMP4_TYPES.get(p[0], f"type-{p[0]}")
    elif d.proto == 58:
        if len(p) < 2:
            d.truncated = True
            return d
        d.icmp_type = _ICMP6_TYPES.get(p[0], f"type-{p[0]}")
    return d


# ---------------------------------------------------------------------
# policyd-trace waterfall rendering (the trace-summary analogue of a
# packet dissection: turn one TraceSummary's phase list into a human
# view). Lives here so the CLI and monitor share one renderer.

def _fmt_ns(ns: int) -> str:
    if ns >= 1_000_000:
        return f"{ns / 1e6:.2f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.1f}µs"
    return f"{ns}ns"


def render_waterfall(
    kind: str,
    batch: int,
    total_ns: int,
    phases,
    width: int = 40,
) -> str:
    """Render one trace as a phase waterfall::

        v4-ingress batch=1024 total=1.20ms
          rebuild      |#                   |   12.0µs   1.0%
          dispatch     |   ########        |  480.0µs  40.0%

    ``phases`` is the trace's ordered (name, rel_start_ns, dur_ns)
    list; bars are positioned by start offset so overlap/ordering is
    visible at a glance. Phase names are a stable API (observe/
    README.md) — bench rounds diff these waterfalls across commits.
    """
    total = max(1, int(total_ns))
    name_w = max((len(p[0]) for p in phases), default=4)
    lines = [f"{kind} batch={batch} total={_fmt_ns(int(total_ns))}"]
    for name, rel, dur in phases:
        start = min(width, int(rel * width / total))
        span = max(1, int(dur * width / total))
        span = min(span, width - start) or 1
        bar = " " * start + "#" * span
        pct = 100.0 * dur / total
        lines.append(
            f"  {name:<{name_w}} |{bar:<{width}}| "
            f"{_fmt_ns(int(dur)):>9} {pct:5.1f}%"
        )
    return "\n".join(lines)
