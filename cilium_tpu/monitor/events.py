"""Datapath event model + binary codecs.

Reference: pkg/monitor/datapath_drop.go:28 (DropNotify), pkg/monitor/
datapath_trace.go:28 (TraceNotify), pkg/monitor/agent.go (agent
notifications), and the notify event types of bpf/lib/common.h:209.
The kernel emits fixed-layout C structs into the perf ring; here the
pipeline emits typed events whose wire form is a fixed-layout struct
too (monitor/server.py frames them onto the monitor socket), so
external consumers get the same "binary payload protocol" boundary
the reference's monitor daemon speaks (monitor/monitor.go:184,301).
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional, Tuple

# event types (common.h:209 CILIUM_NOTIFY_*)
EVENT_DROP = 1
EVENT_TRACE = 2
EVENT_AGENT = 3
EVENT_L7 = 4
EVENT_CAPTURE = 5  # DebugCapture (datapath_debug.go:368)
EVENT_TRACE_SUMMARY = 6  # policyd-trace per-batch phase breakdown
EVENT_POLICY_VERDICT = 7  # PolicyVerdictNotify (datapath_policy.go:21)

# drop reasons (bpf/lib/common.h DROP_* / pkg/monitor/api errors)
REASON_POLICY = 133  # DROP_POLICY (generic / attribution off)
REASON_PREFILTER = 144  # prefilter deny (XDP)
REASON_NO_SERVICE = 146  # lb4_local: frontend without backends
REASON_CT_MAP_FULL = 135
REASON_UNKNOWN = 0
# policyd-flows attribution taxonomy (FlowAttribution=true): DROP_POLICY
# refined by WHICH term decided the flow. Codes picked from the unused
# 150s of the u8 reason space (the codec carries reasons in the u8
# "sub" field). STABLE API — ROADMAP lists them; renumbering breaks
# stored flow logs and monitor consumers.
REASON_POLICY_DENY = 151  # an explicit deny rule matched
REASON_POLICY_NO_L3 = 152  # no L3 allow covered the peer
REASON_POLICY_NO_L4 = 153  # L4 coverage existed, peer not allowed
REASON_PROXY_REDIRECT = 154  # allowed, but diverted to the L7 proxy
# policyd-failsafe: the pipeline could not verdict the batch (device
# fault exhausted its retries) and FailOpen is off — fail-closed deny
REASON_PIPELINE_DEGRADED = 155

_REASON_NAMES = {
    REASON_POLICY: "Policy denied",
    REASON_PREFILTER: "Prefilter denied",
    REASON_NO_SERVICE: "No service backend",
    REASON_CT_MAP_FULL: "CT map insertion failed",
    REASON_UNKNOWN: "Unknown",
    REASON_POLICY_DENY: "Policy denied (deny rule)",
    REASON_POLICY_NO_L3: "Policy denied (no L3 allow)",
    REASON_POLICY_NO_L4: "Policy denied (no L4 allow)",
    REASON_PROXY_REDIRECT: "Proxy redirect (L7)",
    REASON_PIPELINE_DEGRADED: "Pipeline degraded (fail-closed)",
}

# trace observation points (pkg/monitor/datapath_trace.go TraceTo*)
TRACE_TO_ENDPOINT = 1
TRACE_FROM_ENDPOINT = 2
TRACE_TO_PROXY = 3

_TRACE_NAMES = {
    TRACE_TO_ENDPOINT: "to-endpoint",
    TRACE_FROM_ENDPOINT: "from-endpoint",
    TRACE_TO_PROXY: "to-proxy",
}


def reason_name(code: int) -> str:
    return _REASON_NAMES.get(code, f"reason-{code}")


@dataclasses.dataclass(frozen=True)
class DropNotify:
    """One dropped flow (DropNotify, datapath_drop.go:28)."""

    reason: int
    endpoint: int  # local endpoint id
    src_identity: int  # peer identity row's identity (0 if unknown)
    family: int  # 4 | 6
    peer_addr: bytes  # 4 or 16 address bytes (the REMOTE address)
    dport: int
    proto: int
    ingress: bool
    # reason-144 disambiguation: WHICH of the two prefilter producers
    # dropped the flow — "admission" (host admission gate) or
    # "prefilter" (device shed kernel). Empty for every other reason
    # (those have a single producer each). Bounded by construction:
    # contracts.METRIC_BOUNDED_LABEL_KEYS lists "producer".
    producer: str = ""
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_DROP

    def summary(self) -> str:
        d = "ingress" if self.ingress else "egress"
        import ipaddress

        ip = ipaddress.ip_address(self.peer_addr)
        via = f" via {self.producer}" if self.producer else ""
        return (
            f"xx drop ({reason_name(self.reason)}){via} {d} "
            f"ep {self.endpoint} peer {ip} identity {self.src_identity} "
            f"dport {self.dport} proto {self.proto}"
        )


@dataclasses.dataclass(frozen=True)
class TraceNotify:
    """One forwarded flow (TraceNotify, datapath_trace.go:28)."""

    obs_point: int
    endpoint: int
    src_identity: int
    family: int
    peer_addr: bytes
    dport: int
    proto: int
    ingress: bool
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_TRACE

    def summary(self) -> str:
        import ipaddress

        ip = ipaddress.ip_address(self.peer_addr)
        return (
            f"-> {_TRACE_NAMES.get(self.obs_point, self.obs_point)} "
            f"ep {self.endpoint} peer {ip} identity {self.src_identity} "
            f"dport {self.dport} proto {self.proto}"
        )


@dataclasses.dataclass(frozen=True)
class PolicyVerdictNotify:
    """One policy verdict (PolicyVerdictNotify, pkg/monitor/
    datapath_policy.go:21), emitted per sampled flow while the
    PolicyVerdictNotification option is on — unlike DropNotify/
    TraceNotify it reports ALLOWED flows too, with the wire reason
    that decided them."""

    action: int  # 0 = denied, 1 = allowed, 2 = redirected (L7)
    reason: int  # REASON_* wire code (REASON_UNKNOWN for plain allow)
    endpoint: int
    src_identity: int
    family: int
    peer_addr: bytes
    dport: int
    proto: int
    ingress: bool
    # matched rule position from the attribution kernel's origin
    # output; -1 while FlowAttribution is off (no recompile either way)
    rule_index: int = -1
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_POLICY_VERDICT

    def summary(self) -> str:
        import ipaddress

        verdict = {0: "denied", 1: "allowed", 2: "redirected"}.get(
            self.action, f"action-{self.action}"
        )
        ip = ipaddress.ip_address(self.peer_addr)
        rule = f" rule {self.rule_index}" if self.rule_index >= 0 else ""
        return (
            f"policy-verdict {verdict} ({reason_name(self.reason)})"
            f"{rule} ep {self.endpoint} peer {ip} "
            f"identity {self.src_identity} dport {self.dport} "
            f"proto {self.proto}"
        )


@dataclasses.dataclass(frozen=True)
class AgentNotify:
    """Control-plane event (pkg/monitor/agent.go AgentNotify):
    policy imports, endpoint lifecycle, regenerations."""

    kind: str  # "policy-updated" | "endpoint-created" | ...
    message: str
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_AGENT

    def summary(self) -> str:
        return f">> agent {self.kind}: {self.message}"


@dataclasses.dataclass(frozen=True)
class L7Notify:
    """L7 access-log record surfaced on the monitor stream
    (pkg/proxy/logger → monitor agent events)."""

    verdict: str
    detail: str
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_L7

    def summary(self) -> str:
        return f"L7 {self.verdict}: {self.detail}"


# ---------------------------------------------------------------------
# Binary wire codec — fixed little-endian layouts, one per event type.
# Flow events: type u8, sub u8 (reason/obs), flags u8 (bit0 ingress,
# bit1 family==6), proto u8, endpoint u32, identity u32, dport u16,
# pad u16, timestamp f64, addr 16s (v4 left-aligned, zero-padded).
@dataclasses.dataclass(frozen=True)
class DebugCapture:
    """A raw packet capture from the datapath (DebugCapture,
    pkg/monitor/datapath_debug.go:368): the monitor dissects the
    payload into a per-layer summary (dissect.py — the gopacket role
    of pkg/monitor/dissect.go)."""

    endpoint: int
    data: bytes  # raw Ethernet frame (possibly truncated by the capture)
    orig_len: int = 0  # pre-truncation length (0 = len(data))
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_CAPTURE

    def summary(self) -> str:
        from .dissect import dissect

        n = self.orig_len or len(self.data)
        return (
            f"** capture ep {self.endpoint} ({n} bytes): "
            f"{dissect(self.data).summary()}"
        )


@dataclasses.dataclass(frozen=True)
class TraceSummary:
    """One completed verdict-batch trace (policyd-trace): total wall
    time plus the (name, start-offset-ns, duration-ns) phase list. The
    tracer publishes these only while a monitor listener is attached
    (hub.active), same cost contract as flow events."""

    kind: str  # e.g. "v4-ingress"
    batch: int  # flow count of the batch
    total_ns: int
    phases: Tuple[Tuple[str, int, int], ...]
    timestamp: float = dataclasses.field(default_factory=time.time)

    @property
    def type(self) -> int:
        return EVENT_TRACE_SUMMARY

    def summary(self) -> str:
        top = sorted(self.phases, key=lambda p: -p[2])[:3]
        parts = ", ".join(f"{n} {d / 1e6:.2f}ms" for n, _r, d in top)
        return (
            f"## trace {self.kind} batch={self.batch} "
            f"total={self.total_ns / 1e6:.2f}ms [{parts}]"
        )


_FLOW_FMT = "<BBBBIIHHd16s"
_FLOW_LEN = struct.calcsize(_FLOW_FMT)
# DropNotify producer rides the flow layout's previously-zero pad u16
# (same frame length, old decoders read it as pad): the wire stays
# layout-stable while reason-144 frames carry WHICH producer shed.
_PRODUCER_CODES = {"": 0, "admission": 1, "prefilter": 2}
_PRODUCER_NAMES = {v: k for k, v in _PRODUCER_CODES.items()}
# verdict events: the flow layout (sub = reason) with action u8 and
# rule index i16 appended
_VERDICT_FMT = "<BBBBIIHHd16sBh"
_VERDICT_LEN = struct.calcsize(_VERDICT_FMT)


def encode(ev) -> bytes:
    t = ev.type
    if t in (EVENT_DROP, EVENT_TRACE):
        sub = ev.reason if t == EVENT_DROP else ev.obs_point
        flags = (1 if ev.ingress else 0) | (2 if ev.family == 6 else 0)
        pad = (
            _PRODUCER_CODES.get(ev.producer, 0) if t == EVENT_DROP else 0
        )
        return struct.pack(
            _FLOW_FMT, t, sub, flags, ev.proto, ev.endpoint,
            ev.src_identity, ev.dport, pad, ev.timestamp,
            bytes(ev.peer_addr).ljust(16, b"\x00"),
        )
    if t == EVENT_POLICY_VERDICT:
        flags = (1 if ev.ingress else 0) | (2 if ev.family == 6 else 0)
        return struct.pack(
            _VERDICT_FMT, t, ev.reason, flags, ev.proto, ev.endpoint,
            ev.src_identity, ev.dport, 0, ev.timestamp,
            bytes(ev.peer_addr).ljust(16, b"\x00"),
            ev.action, ev.rule_index,
        )
    if t == EVENT_AGENT:
        kind = ev.kind.encode()
        msg = ev.message.encode()
        return struct.pack("<BHH", t, len(kind), len(msg)) + kind + msg + struct.pack("<d", ev.timestamp)
    if t == EVENT_L7:
        v = ev.verdict.encode()
        d = ev.detail.encode()
        return struct.pack("<BHH", t, len(v), len(d)) + v + d + struct.pack("<d", ev.timestamp)
    if t == EVENT_CAPTURE:
        # the wire length field is u16: oversized aggregates (GRO/
        # jumbo) ship their head + the true length — never a codec
        # crash inside the publish path
        data = ev.data[:65535]
        return (
            struct.pack("<BIIHd", t, ev.endpoint,
                        ev.orig_len or len(ev.data), len(data),
                        ev.timestamp)
            + data
        )
    if t == EVENT_TRACE_SUMMARY:
        kind = ev.kind.encode()[:255]
        out = [struct.pack(
            "<BBHIQd", t, len(kind), len(ev.phases), ev.batch,
            ev.total_ns, ev.timestamp,
        ), kind]
        for name, rel, dur in ev.phases:
            nb = name.encode()[:255]
            out.append(struct.pack("<B", len(nb)))
            out.append(nb)
            out.append(struct.pack("<QQ", rel, dur))
        return b"".join(out)
    raise ValueError(f"unknown event type {t}")


def decode(buf: bytes):
    t = buf[0]
    if t in (EVENT_DROP, EVENT_TRACE):
        (t, sub, flags, proto, ep, ident, dport, _pad, ts, addr) = struct.unpack(
            _FLOW_FMT, buf[:_FLOW_LEN]
        )
        family = 6 if flags & 2 else 4
        peer = addr[:16] if family == 6 else addr[:4]
        cls = DropNotify if t == EVENT_DROP else TraceNotify
        kw = dict(
            endpoint=ep, src_identity=ident, family=family, peer_addr=peer,
            dport=dport, proto=proto, ingress=bool(flags & 1), timestamp=ts,
        )
        if t == EVENT_DROP:
            return DropNotify(
                reason=sub, producer=_PRODUCER_NAMES.get(_pad, ""), **kw
            )
        return TraceNotify(obs_point=sub, **kw)
    if t == EVENT_POLICY_VERDICT:
        (
            t, reason, flags, proto, ep, ident, dport, _pad, ts, addr,
            action, rule_index,
        ) = struct.unpack(_VERDICT_FMT, buf[:_VERDICT_LEN])
        family = 6 if flags & 2 else 4
        return PolicyVerdictNotify(
            action=action, reason=reason, endpoint=ep,
            src_identity=ident, family=family,
            peer_addr=addr[:16] if family == 6 else addr[:4],
            dport=dport, proto=proto, ingress=bool(flags & 1),
            rule_index=rule_index, timestamp=ts,
        )
    if t in (EVENT_AGENT, EVENT_L7):
        _, la, lb = struct.unpack("<BHH", buf[:5])
        a = buf[5:5 + la].decode()
        b = buf[5 + la:5 + la + lb].decode()
        (ts,) = struct.unpack("<d", buf[5 + la + lb:5 + la + lb + 8])
        if t == EVENT_AGENT:
            return AgentNotify(kind=a, message=b, timestamp=ts)
        return L7Notify(verdict=a, detail=b, timestamp=ts)
    if t == EVENT_CAPTURE:
        hdr = struct.calcsize("<BIIHd")
        _, ep, orig, dlen, ts = struct.unpack("<BIIHd", buf[:hdr])
        return DebugCapture(
            endpoint=ep, data=buf[hdr:hdr + dlen], orig_len=orig,
            timestamp=ts,
        )
    if t == EVENT_TRACE_SUMMARY:
        hdr = struct.calcsize("<BBHIQd")
        _, klen, n_phases, batch, total_ns, ts = struct.unpack(
            "<BBHIQd", buf[:hdr]
        )
        off = hdr
        kind = buf[off:off + klen].decode()
        off += klen
        phases = []
        for _ in range(n_phases):
            nlen = buf[off]
            off += 1
            name = buf[off:off + nlen].decode()
            off += nlen
            rel, dur = struct.unpack("<QQ", buf[off:off + 16])
            off += 16
            phases.append((name, rel, dur))
        return TraceSummary(
            kind=kind, batch=batch, total_ns=total_ns,
            phases=tuple(phases), timestamp=ts,
        )
    raise ValueError(f"unknown event type {t}")
