"""Monitor hub: lossy per-subscriber event fan-out.

Reference: monitor/monitor.go:184,301 — the node monitor reads the
BPF perf ring and multicasts payloads to however many listeners are
attached; a slow listener loses events (the perf ring overwrites),
never blocks the datapath. Same contract here: publish() is
non-blocking, each subscriber has a bounded queue, overflow increments
a per-subscriber lost counter (the reference reports lost samples the
same way).

The datapath checks ``hub.active`` (O(1)) before building any event
objects, so an unmonitored pipeline pays one attribute read per batch.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, List, Optional


class Subscription:
    def __init__(self, hub: "MonitorHub", capacity: int) -> None:
        self._hub = hub
        self._q: Deque = deque(maxlen=capacity)
        self._cond = threading.Condition()
        self.lost = 0
        self.closed = False
        self._passive = False

    # a PASSIVE subscription still receives events but does not count
    # toward hub.active — the standalone-monitor feeder sits here
    # permanently and flips passive by downstream demand, so an
    # unwatched datapath keeps skipping event construction. The flip
    # routes through the hub so ``active`` stays an O(1) counter read.
    @property
    def passive(self) -> bool:
        return self._passive

    @passive.setter
    def passive(self, value: bool) -> None:
        self._hub._set_passive(self, value)

    def _push(self, ev) -> None:
        with self._cond:
            if len(self._q) == self._q.maxlen:
                self.lost += 1  # oldest event falls off (lossy ring)
            self._q.append(ev)
            self._cond.notify()

    def next(self, timeout: Optional[float] = None):
        """Pop the next event (None on timeout/close)."""
        with self._cond:
            if not self._q:
                self._cond.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def drain(self) -> List:
        with self._cond:
            out = list(self._q)
            self._q.clear()
            return out

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
        self._hub._remove(self)


class MonitorHub:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: List[Subscription] = []
        self._active_count = 0  # non-passive subscriptions
        self.published = 0

    @property
    def active(self) -> bool:
        return self._active_count > 0  # O(1): read on the batch hot path

    def subscribe(self, capacity: int = 8192) -> Subscription:
        sub = Subscription(self, capacity)
        with self._lock:
            self._subs.append(sub)
            self._active_count += 1
        return sub

    def _set_passive(self, sub: Subscription, value: bool) -> None:
        with self._lock:
            if sub._passive == value or sub not in self._subs:
                sub._passive = value
                return
            sub._passive = value
            self._active_count += -1 if value else 1

    def _remove(self, sub: Subscription) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                return
            if not sub._passive:
                self._active_count -= 1

    def publish(self, ev) -> None:
        with self._lock:
            subs = list(self._subs)
            self.published += 1
        for s in subs:
            s._push(ev)

    def publish_many(self, events) -> None:
        with self._lock:
            subs = list(self._subs)
        n = 0
        for ev in events:
            n += 1
            for s in subs:
                s._push(ev)
        with self._lock:
            self.published += n
