"""Monitor socket: stream events to external listeners.

Reference: the standalone cilium-node-monitor serves the perf-ring
event stream to `cilium monitor` clients over a unix socket with a
length-framed binary payload protocol (monitor/monitor.go:184,
listener1_2.go). Same boundary here: each connected client gets its
own lossy Subscription off the hub; frames are ``u32 length`` +
events.py binary codec.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Iterator, Optional

from .events import decode, encode
from .hub import MonitorHub


class MonitorServer:
    def __init__(self, hub: MonitorHub, socket_path: str) -> None:
        self.hub = hub
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None
        self._threads = []
        self._stop = threading.Event()
        self.clients = 0
        # serializes count updates AND their callbacks: two concurrent
        # attach/detach threads must deliver count frames in the order
        # the counts were computed, or the feeder's demand gate sticks
        self._clients_lock = threading.Lock()
        # fn(count) on every client attach/detach — the standalone
        # monitor relays this to the agent so an unwatched datapath
        # skips event construction
        self.on_clients = None

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # daemon client threads are fire-and-forget (they exit on
            # disconnect or stop) — retaining them would leak one
            # Thread object per reconnecting monitor client
            threading.Thread(
                target=self._serve_client, args=(conn,), daemon=True
            ).start()

    def _notify_clients(self, delta: int) -> None:
        with self._clients_lock:
            self.clients += delta
            cb = self.on_clients
            if cb is not None:
                try:
                    cb(self.clients)
                except Exception:
                    pass

    def _serve_client(self, conn: socket.socket) -> None:
        sub = self.hub.subscribe()
        self._notify_clients(+1)
        try:
            while not self._stop.is_set():
                ev = sub.next(timeout=0.2)
                if ev is None:
                    # idle: probe for disconnect — with no events to
                    # send, a closed client would otherwise never be
                    # noticed (the thread and its attach count leak;
                    # clients send nothing, so any bytes are discarded)
                    try:
                        if conn.recv(64, socket.MSG_DONTWAIT) == b"":
                            return
                    except BlockingIOError:
                        pass
                    continue
                # the standalone monitor's feed publishes wire-encoded
                # payloads straight through (no decode/re-encode)
                payload = (
                    ev if isinstance(ev, (bytes, bytearray)) else encode(ev)
                )
                conn.sendall(struct.pack("<I", len(payload)) + payload)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            sub.close()
            self._notify_clients(-1)
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass


def monitor_stream(socket_path: str, timeout: Optional[float] = 1.0) -> Iterator:
    """Client side (`cilium monitor`): connect and yield decoded
    events until the socket closes, or until ``timeout`` idle seconds
    pass (timeout=None blocks forever)."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect(socket_path)
    buf = b""
    try:
        while True:
            try:
                chunk = s.recv(65536)
            except socket.timeout:
                return
            if not chunk:
                return
            buf += chunk
            while len(buf) >= 4:
                (n,) = struct.unpack("<I", buf[:4])
                if len(buf) < 4 + n:
                    break
                yield decode(buf[4:4 + n])
                buf = buf[4 + n:]
    finally:
        s.close()
