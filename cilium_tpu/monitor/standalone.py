"""Standalone node monitor — its own PROCESS.

Reference: cilium-node-monitor (monitor/monitor.go:184) runs apart
from the agent so event streaming survives agent stalls and restarts:
the monitor owns the client socket; the agent is just the event
SOURCE. Same split here:

- the monitor process listens on the ``cilium monitor`` client socket
  (monitor/server.py protocol, unchanged — clients can't tell the
  difference from the in-process server),
- the agent connects to the monitor's FEED socket (the perf-ring
  analog) and streams encoded events through
  :class:`MonitorFeeder`; a dropped feed (agent crash/restart) leaves
  every client stream attached — events simply resume when the agent
  reconnects,
- the agent launches/supervises it like the proxy and health sidecars
  (pkg/launcher).

Run as ``python -m cilium_tpu.monitor --listen <sock> --feed <sock>``.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import List, Optional

from ..utils.logging import get_logger
from .events import encode
from .hub import MonitorHub
from .server import MonitorServer

log = get_logger("monitor-standalone")


class StandaloneMonitor:
    """The monitor process assembly: client server + feed ingestion."""

    def __init__(self, listen_path: str, feed_path: str) -> None:
        self.hub = MonitorHub()
        self.server = MonitorServer(self.hub, listen_path)
        # client-count feedback: every attach/detach is pushed to the
        # connected agents so their datapaths only build events while
        # someone is actually watching (hub.active gate round trip)
        self.server.on_clients = self._broadcast_clients
        self.feed_path = feed_path
        self._stop = threading.Event()
        self.feeds_accepted = 0
        self._feed_conns: List[socket.socket] = []
        self._feed_lock = threading.Lock()
        if os.path.exists(feed_path):
            os.unlink(feed_path)
        self._feed_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._feed_sock.bind(feed_path)
        self._feed_sock.listen(4)
        self._feed_sock.settimeout(0.2)

    def _broadcast_clients(self, count: int) -> None:
        """Push a demand frame to every feed. Runs under the server's
        client lock (ordering guarantee), so sends must never block: a
        stalled agent — the exact failure this process isolates — must
        not wedge client attach/detach handling. An unwritable or
        partially-written feed is closed; the feeder reconnects and
        receives the then-current count."""
        frame = struct.pack("<I", count)
        with self._feed_lock:
            conns = list(self._feed_conns)
        for c in conns:
            try:
                n = c.send(frame, socket.MSG_DONTWAIT)
            except (BlockingIOError, OSError):
                n = -1
            if n != len(frame):
                # full buffer (dead agent) or torn frame (desync):
                # drop the feed; its pump thread reaps it on read
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def start(self) -> "StandaloneMonitor":
        self.server.start()
        threading.Thread(target=self._feed_accept, daemon=True).start()
        return self

    def _feed_accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._feed_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.feeds_accepted += 1
            # register + send the initial demand under the SERVER's
            # client lock: a concurrent attach/detach broadcast must
            # order strictly after this frame, or the feeder could end
            # up trusting a stale count forever
            with self.server._clients_lock:
                with self._feed_lock:
                    self._feed_conns.append(conn)
                try:
                    # one 4-byte frame to a just-accepted local socket;
                    # the lock hold is the ordering invariant documented
                    # above, not an accidental I/O convoy
                    conn.sendall(struct.pack("<I", self.server.clients))  # policyd-lint: disable=LOCK002
                except OSError:
                    pass
            threading.Thread(
                target=self._pump_feed, args=(conn,), daemon=True
            ).start()

    def _pump_feed(self, conn: socket.socket) -> None:
        """One agent feed connection: frames in → hub fan-out. The
        frames are already wire-encoded; publish the RAW payloads so
        the per-client path doesn't pay a decode/re-encode round trip
        (monitor/server.py passes bytes through encode())."""
        from ..utils.framing import recv_exact

        try:
            while not self._stop.is_set():
                hdr = recv_exact(conn, 4)
                if hdr is None:
                    return
                (n,) = struct.unpack("<I", hdr)
                if n > (1 << 20):
                    return  # corrupt frame: drop the feed, keep clients
                payload = recv_exact(conn, n)
                if payload is None:
                    return
                self.hub.publish(payload)
        except OSError:
            pass
        finally:
            with self._feed_lock:
                try:
                    self._feed_conns.remove(conn)
                except ValueError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._feed_sock.close()
        except OSError:
            pass
        self.server.stop()


class MonitorFeeder:
    """Agent side: forwards the in-process hub's events to the external
    monitor's feed socket. Lossy by design (the hub subscription is a
    bounded ring) and self-healing: a dead monitor is retried with
    backoff while the agent keeps running untouched."""

    def __init__(
        self, hub: MonitorHub, feed_path: str,
        retry_s: float = 0.5, max_retry_s: float = 10.0,
    ) -> None:
        self.hub = hub
        self.feed_path = feed_path
        self.retry_s = retry_s
        self.max_retry_s = max_retry_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.reconnects = 0
        self._demand_gen = 0  # bumps per feed connection
        # makes the gen-check + passivity flip atomic: a stale demand
        # thread must not overwrite the new connection's state between
        # its check and its set
        self._demand_lock = threading.Lock()

    def start(self) -> "MonitorFeeder":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        backoff = self.retry_s
        sub = self.hub.subscribe()
        # passive until the monitor reports a watching client: the
        # agent's datapath keeps its "nobody's listening" fast path
        # (hub.active False) even though this subscription is permanent
        sub.passive = True
        try:
            while not self._stop.is_set():
                conn = None
                try:
                    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    conn.connect(self.feed_path)
                except OSError:
                    if conn is not None:  # socket() itself may raise
                        conn.close()
                    if self._stop.wait(backoff):
                        return
                    backoff = min(backoff * 2, self.max_retry_s)
                    continue
                backoff = self.retry_s
                self.reconnects += 1
                # generation token: a STALE demand thread from the
                # previous connection must never flip passivity after
                # this connection took over
                with self._demand_lock:
                    self._demand_gen += 1
                    gen = self._demand_gen
                threading.Thread(
                    target=self._read_demand,
                    args=(conn, sub, gen), daemon=True,
                ).start()
                try:
                    while not self._stop.is_set():
                        ev = sub.next(timeout=0.2)
                        if ev is None:
                            continue
                        payload = encode(ev)
                        conn.sendall(
                            struct.pack("<I", len(payload)) + payload
                        )
                    # graceful stop: flush what is still queued — only
                    # a CRASH may lose events, never a clean shutdown
                    for ev in sub.drain():
                        payload = encode(ev)
                        conn.sendall(
                            struct.pack("<I", len(payload)) + payload
                        )
                except OSError:
                    pass  # monitor died/restarted: reconnect loop
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass
        finally:
            sub.close()

    def _read_demand(self, conn: socket.socket, sub, gen: int) -> None:
        """Consume the monitor's client-count frames on this feed
        connection, flipping the subscription's passivity with demand.
        A dead connection leaves the sub passive (no clients known) —
        unless a NEWER connection's demand thread already took over
        (``gen`` mismatch: this thread must not touch the sub)."""
        from ..utils.framing import recv_exact

        try:
            while not self._stop.is_set():
                frame = recv_exact(conn, 4)
                if frame is None:
                    return
                (count,) = struct.unpack("<I", frame)
                with self._demand_lock:  # atomic gen-check + flip
                    if gen == self._demand_gen:
                        sub.passive = count == 0
        except OSError:
            pass
        finally:
            with self._demand_lock:
                if gen == self._demand_gen:
                    sub.passive = True

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="python -m cilium_tpu.monitor",
        description="standalone node monitor (cilium-node-monitor)",
    )
    ap.add_argument("--listen", required=True,
                    help="client socket (`cilium monitor` connects here)")
    ap.add_argument("--feed", required=True,
                    help="agent feed socket (event source)")
    args = ap.parse_args(argv)
    from ..utils.procutil import die_with_parent

    die_with_parent()
    mon = StandaloneMonitor(args.listen, args.feed).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    print("READY", flush=True)
    stop.wait()
    mon.stop()
    return 0
