"""MTU derivation.

Reference: pkg/mtu (mtu.go): the device MTU plus the derived route and
tunnel MTUs — tunnel overhead subtracts the encap header so encapsulated
paths don't fragment.
"""

from __future__ import annotations

import dataclasses

ETH_MTU_DEFAULT = 1500
TUNNEL_OVERHEAD_VXLAN = 50  # outer IPv4 + UDP + VXLAN
TUNNEL_OVERHEAD_GENEVE = 50
MIN_MTU = 576  # RFC 791 floor


@dataclasses.dataclass(frozen=True)
class MTUConfig:
    """mtu.go Configuration."""

    device_mtu: int = ETH_MTU_DEFAULT
    tunnel: str = "vxlan"  # vxlan | geneve | disabled

    def __post_init__(self) -> None:
        if self.tunnel not in ("vxlan", "geneve", "disabled"):
            raise ValueError(f"unknown tunnel mode {self.tunnel!r}")
        if self.device_mtu < MIN_MTU:
            raise ValueError(f"device MTU {self.device_mtu} below {MIN_MTU}")
        # the tunnel payload must itself clear the floor — clamping
        # route_mtu UP would advertise more than the encap can carry
        # and reintroduce the fragmentation this module exists to avoid
        if self.tunnel != "disabled" and self.route_mtu < MIN_MTU:
            raise ValueError(
                f"device MTU {self.device_mtu} leaves tunnel payload "
                f"{self.route_mtu} below {MIN_MTU}"
            )

    @property
    def route_mtu(self) -> int:
        """MTU for routes toward remote pods (GetRouteMTU): the tunnel
        payload size when encapsulating, the device MTU otherwise."""
        if self.tunnel == "disabled":
            return self.device_mtu
        overhead = (
            TUNNEL_OVERHEAD_GENEVE if self.tunnel == "geneve"
            else TUNNEL_OVERHEAD_VXLAN
        )
        return self.device_mtu - overhead

    @property
    def device(self) -> int:
        """MTU for local devices (GetDeviceMTU)."""
        return self.device_mtu
