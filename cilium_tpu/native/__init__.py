"""Native (C++) enforcement front-end — the eBPF-datapath role of the
reference rebuilt as a userspace batch evaluator consuming the
TPU-compiled policy state (SURVEY native census item 1)."""

from .build import available as native_available
from .fastpath import NativeFastpath

__all__ = ["NativeFastpath", "native_available"]
