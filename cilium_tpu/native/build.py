"""Build + load the native front-end shared library.

The reference ships its datapath as C compiled on the node by the
agent (clang via pkg/datapath/loader); same stance here — g++ is part
of the node toolchain, the .so is built once per source hash and
cached, and loading is a plain dlopen via ctypes (no pybind11 in the
image; SURVEY environment notes)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "fastpath.cpp")
# per-user 0700 cache dir: the path is predictable, so a shared dir
# would let another local user pre-plant a .so at the known hash path
# and get code into our process at dlopen
_CACHE_DIR = os.environ.get(
    "CILIUM_TPU_NATIVE_CACHE",
    os.path.join(
        tempfile.gettempdir(), f"cilium_tpu_native_{os.getuid()}"
    ),
)

_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_CACHE_DIR, f"fastpath_{digest}.so")


def _check_owned(path: str) -> bool:
    try:
        st = os.stat(path)
    except OSError:
        return False
    return st.st_uid == os.getuid()


def build() -> str:
    """Compile (cached by source hash) → .so path. A cached .so is
    trusted only if we own it — never dlopen another user's file."""
    so = _so_path()
    if os.path.exists(so) and _check_owned(so):
        return so
    os.makedirs(_CACHE_DIR, mode=0o700, exist_ok=True)
    if not _check_owned(_CACHE_DIR):
        raise RuntimeError(f"native cache dir {_CACHE_DIR} not owned by us")
    tmp = so + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", tmp, _SRC,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed: {proc.stderr[-2000:]}")
    os.replace(tmp, so)  # atomic: concurrent builders race safely
    return so


def load() -> ctypes.CDLL:
    """Build if needed and dlopen; signature setup happens here once."""
    global _lib, _build_error
    if _lib is not None:
        return _lib
    if _build_error is not None:
        raise RuntimeError(_build_error)
    try:
        lib = ctypes.CDLL(build())
    except (RuntimeError, OSError) as e:
        _build_error = str(e)
        raise RuntimeError(_build_error) from None
    c = ctypes
    u8p, i8p = c.POINTER(c.c_uint8), c.POINTER(c.c_int8)
    i32p, u32p = c.POINTER(c.c_int32), c.POINTER(c.c_uint32)
    i64p, u64p = c.POINTER(c.c_int64), c.POINTER(c.c_uint64)
    lib.nf_create.restype = c.c_void_p
    lib.nf_create.argtypes = [c.c_uint32, c.c_int]
    lib.nf_destroy.argtypes = [c.c_void_p]
    lib.nf_set_world.argtypes = [c.c_void_p, c.c_uint64]
    lib.nf_load_policy.restype = c.c_int64
    lib.nf_load_policy.argtypes = [
        c.c_void_p, c.c_int64, u64p, u32p, u32p, u32p, u32p, u8p,
    ]
    lib.nf_load_trie.argtypes = [
        c.c_void_p, c.c_int, i32p, i32p, c.c_int32, c.c_int,
    ]
    lib.nf_ct_flush.argtypes = [c.c_void_p]
    lib.nf_set_endpoint_ids.argtypes = [c.c_void_p, c.c_int64, u32p]
    lib.nf_load_lb.argtypes = [
        c.c_void_p, c.c_int, c.c_int32, c.c_int, u8p, i32p, i32p, i32p,
        i32p, i32p, c.c_int32, u8p, i32p,
    ]
    lib.nf_l7_set_http.argtypes = [
        c.c_void_p, c.c_uint32, c.c_uint32, c.c_uint8,
        i32p, u64p, c.c_int32, c.c_int32,  # method DFA
        i32p, u64p, c.c_int32, c.c_int32,  # path DFA
        i32p, u64p, c.c_int32, c.c_int32,  # host DFA
        c.c_int32, i32p, i32p, i32p, u8p, i64p, u64p,
    ]
    lib.nf_l7_set_kafka.argtypes = [
        c.c_void_p, c.c_uint32, c.c_uint32, c.c_uint8,
        c.c_int32, u32p, u8p, i32p, i32p, i32p, u8p, i64p, u64p,
        c.c_int32, u8p, i64p, c.c_int32, u8p, i64p,
    ]
    lib.nf_l7_http_batch.argtypes = [
        c.c_void_p, c.c_uint32, c.c_uint32, c.c_uint8, c.c_int64,
        u8p, c.c_int32, i32p, u8p, c.c_int32, i32p, u8p, c.c_int32, i32p,
        u64p, u8p,
    ]
    lib.nf_l7_kafka_batch.argtypes = [
        c.c_void_p, c.c_uint32, c.c_uint32, c.c_uint8, c.c_int64,
        i32p, i32p, u8p, c.c_int32, i32p, u8p, c.c_int32, i32p,
        u64p, u8p,
    ]
    lib.nf_eval_batch.argtypes = [
        c.c_void_p, c.c_int64, u8p, c.c_int, i32p, i32p, i32p, i32p,
        c.c_uint8, i8p, u8p,
    ]
    lib.nf_counters.argtypes = [c.c_void_p, i64p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except RuntimeError:
        return False
