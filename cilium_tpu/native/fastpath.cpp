// Native batch enforcement front-end.
//
// The role of the reference's in-kernel eBPF datapath (SURVEY native
// census item 1): consume the control plane's compiled state — the
// TPU-materialized policymap rows, the ipcache/prefilter stride-8
// tries — and enforce verdicts for flow batches at memory speed with
// no interpreter in the loop. Mirrors the per-packet path of
// bpf/bpf_lxc.c + bpf/lib/policy.h:
//
//   conntrack probe (one hash)            conntrack.h ct_lookup
//   prefilter deny LPM (ingress only)     bpf_xdp.c check_filters
//   identity LPM, world on miss           bpf_netdev.c secctx
//   policymap: exact -> L3 -> L4          policy.h __policy_can_access
//   CT create on allow (not on redirect)  ct_create4
//
// Exposed as a C ABI consumed through ctypes (no pybind11 in the
// image). All tables are copied in at load time; eval runs without
// allocation or locks (one loader thread / N eval threads is the
// supported pattern, same as pinned BPF maps: writers swap, readers
// race-free on the snapshot they started with).

#include <cstdint>
#include <cstring>
#include <ctime>
#include <vector>

namespace {

constexpr int kProbes = 16;
constexpr uint64_t kEmpty = ~0ull;

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30; x *= 0xbf58476d1ce4e5b9ull;
  x ^= x >> 27; x *= 0x94d049bb133111ebull;
  x ^= x >> 31;
  return x;
}

inline double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// open-addressing (ka, kb) -> uint8 value table
struct HashTable {
  std::vector<uint64_t> ka, kb;
  std::vector<uint8_t> val;
  uint64_t mask = 0;

  void init(size_t entries) {
    size_t cap = 64;
    while (cap < entries * 4) cap <<= 1;  // load factor <= 0.25
    ka.assign(cap, kEmpty);
    kb.assign(cap, 0);
    val.assign(cap, 0);
    mask = cap - 1;
  }

  bool insert(uint64_t a, uint64_t b, uint8_t v) {
    uint64_t h = mix64(a ^ mix64(b));
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty || (ka[s] == a && kb[s] == b)) {
        ka[s] = a; kb[s] = b; val[s] = v;
        return true;
      }
    }
    return false;
  }

  inline int find(uint64_t a, uint64_t b) const {
    uint64_t h = mix64(a ^ mix64(b));
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty) return -1;
      if (ka[s] == a && kb[s] == b) return int(val[s]);
    }
    return -1;
  }
};

// stride-8 trie (same layout as ops/lpm.py): child[M][256], info[M][256]
struct Trie {
  std::vector<int32_t> child, info;
  int levels = 0;
  bool loaded = false;

  // walk -> deepest non-zero info (value+1), 0 = miss
  inline int32_t lookup(const uint8_t* addr) const {
    int32_t node = 0, best = 0;
    for (int l = 0; l < levels; ++l) {
      size_t idx = size_t(node) * 256 + addr[l];
      int32_t v = info[idx];
      if (v) best = v;
      node = child[idx];
      if (!node) break;
    }
    return best;
  }
};

// conntrack: (ka, kb, kc) keys with expiry; same tuple packing as
// datapath/conntrack.py so behavior is comparable
struct Conntrack {
  std::vector<uint64_t> ka, kb, kc;
  std::vector<double> expires;
  uint64_t mask = 0;
  double tcp_life = 21600.0, other_life = 60.0;

  void init(int bits) {
    size_t cap = 1ull << bits;
    ka.assign(cap, kEmpty);
    kb.assign(cap, 0);
    kc.assign(cap, 0);
    expires.assign(cap, 0.0);
    mask = cap - 1;
  }

  inline uint64_t hash(uint64_t a, uint64_t b, uint64_t c) const {
    return mix64(a ^ mix64(b ^ mix64(c)));
  }

  inline bool probe(uint64_t a, uint64_t b, uint64_t c, double now) {
    uint64_t h = hash(a, b, c);
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty) return false;
      if (ka[s] == a && kb[s] == b && kc[s] == c && expires[s] > now) {
        expires[s] = now + (((c >> 1) & 0xff) == 6 ? tcp_life : other_life);
        return true;
      }
    }
    return false;
  }

  // forward tuple, then the flipped reply tuple (swapped sport/dport,
  // inverted direction bit) — the same pair FlowConntrack.lookup_batch
  // probes via flip_kc, mirroring the kernel's forward/reverse tuple
  // pair (bpf/lib/conntrack.h ct_lookup)
  inline bool probe_pair(uint64_t a, uint64_t b, uint64_t c, double now) {
    if (probe(a, b, c, now)) return true;
    uint64_t ep = c >> 41;
    uint64_t sport = (c >> 25) & 0xFFFF;
    uint64_t dport = (c >> 9) & 0xFFFF;
    uint64_t proto = (c >> 1) & 0xFF;
    uint64_t dir = c & 1;
    uint64_t flipped = (ep << 41) | (dport << 25) | (sport << 9) |
                       (proto << 1) | (dir ^ 1);
    return probe(a, b, flipped, now);
  }

  inline void insert(uint64_t a, uint64_t b, uint64_t c, double now) {
    uint64_t h = hash(a, b, c);
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty || expires[s] <= now ||
          (ka[s] == a && kb[s] == b && kc[s] == c)) {
        ka[s] = a; kb[s] = b; kc[s] = c;
        expires[s] = now + (((c >> 1) & 0xff) == 6 ? tcp_life : other_life);
        return;
      }
    }
    // full neighborhood: drop (flow re-verdicts next packet)
  }

  void flush() {
    std::fill(ka.begin(), ka.end(), kEmpty);
  }
};

// LB service tables (IPv4): mirrors lb/device.py LBTables — dense
// frontend compare + per-service selection sequence + backend rows
struct LBTables {
  std::vector<uint32_t> fe_addr;   // [F] VIP (host order)
  std::vector<int32_t> fe_port;    // [F] (-1 = empty slot)
  std::vector<int32_t> fe_proto;   // [F] (0 = ANY)
  std::vector<int32_t> fe_seq;     // [F * seq_width]
  std::vector<int32_t> fe_seq_len; // [F]
  std::vector<int32_t> fe_revnat;  // [F]
  std::vector<uint32_t> be_addr;   // [NB]
  std::vector<int32_t> be_port;    // [NB]
  int seq_width = 0;
  bool loaded = false;
};

struct Fastpath {
  HashTable policy;     // ka = identity, kb = ep<<32|dport<<16|proto<<8|dir
  Trie ip4, ip6;        // value = identity (not row: standalone table)
  Trie deny4, deny6;    // prefilter
  Conntrack ct;
  LBTables lb;
  bool ct_enabled = false;
  uint64_t world_identity = 2;
  uint32_t ep_count = 0;
  std::vector<int64_t> counters;  // [ep][3] fwd/drop_policy/drop_prefilter
  std::vector<uint32_t> ep_ids;   // [ep] stable endpoint ids (hash input)
};

// verdict codes — match datapath/pipeline.py
constexpr int8_t FORWARD = 1;
constexpr int8_t DROP_POLICY = 2;
constexpr int8_t DROP_PREFILTER = 3;
constexpr int8_t DROP_NO_SERVICE = 4;

// per-flow hash — MUST match lb/device.py flow_hash32 exactly (the
// translated CT key depends on deterministic backend selection, and
// native/device parity requires identical picks)
inline int32_t flow_hash32(const uint8_t* addr, int stride, int32_t sport,
                           int32_t dport, int32_t proto, uint32_t ep_id,
                           bool has_sport) {
  uint32_t x = 0;
  for (int i = 0; i < stride; ++i) x = (x * 0x01000193u) ^ addr[i];
  if (has_sport) x ^= uint32_t(sport) << 16;
  x ^= uint32_t(dport);
  x ^= uint32_t(proto) << 8;
  x ^= ep_id << 24;
  x ^= x >> 16; x *= 0x85EBCA6Bu;
  x ^= x >> 13; x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return int32_t(x & 0x7FFFFFFFu);
}

inline uint64_t policy_kb(uint32_t ep, uint32_t dport, uint32_t proto,
                          uint32_t dir) {
  return (uint64_t(ep) << 32) | (uint64_t(dport) << 16) |
         (uint64_t(proto) << 8) | dir;
}

}  // namespace

extern "C" {

void* nf_create(uint32_t ep_count, int ct_bits) {
  auto* fp = new Fastpath();
  fp->ep_count = ep_count;
  fp->counters.assign(size_t(ep_count ? ep_count : 1) * 3, 0);
  if (ct_bits > 0) {
    fp->ct.init(ct_bits);
    fp->ct_enabled = true;
  }
  return fp;
}

void nf_destroy(void* h) { delete static_cast<Fastpath*>(h); }

void nf_set_world(void* h, uint64_t identity) {
  static_cast<Fastpath*>(h)->world_identity = identity;
}

// entries: parallel arrays — identity u64, ep u32, dport u32, proto
// u32, dir u32, redirect u8. value stored = 1 (allow) | 2 (redirect)
int64_t nf_load_policy(void* h, int64_t n, const uint64_t* identity,
                       const uint32_t* ep, const uint32_t* dport,
                       const uint32_t* proto, const uint32_t* dir,
                       const uint8_t* redirect) {
  auto* fp = static_cast<Fastpath*>(h);
  fp->policy.init(size_t(n));
  int64_t loaded = 0;
  for (int64_t i = 0; i < n; ++i) {
    loaded += fp->policy.insert(
        identity[i], policy_kb(ep[i], dport[i], proto[i], dir[i]),
        redirect[i] ? 2 : 1);
  }
  return loaded;
}

// which: 0 = ipcache v4, 1 = ipcache v6, 2 = deny v4, 3 = deny v6
void nf_load_trie(void* h, int which, const int32_t* child,
                  const int32_t* info, int32_t n_nodes, int levels) {
  auto* fp = static_cast<Fastpath*>(h);
  Trie* t = which == 0 ? &fp->ip4 : which == 1 ? &fp->ip6
            : which == 2 ? &fp->deny4 : &fp->deny6;
  t->child.assign(child, child + size_t(n_nodes) * 256);
  t->info.assign(info, info + size_t(n_nodes) * 256);
  t->levels = levels;
  t->loaded = true;
}

void nf_ct_flush(void* h) { static_cast<Fastpath*>(h)->ct.flush(); }

void nf_set_endpoint_ids(void* h, int64_t n, const uint32_t* ids) {
  auto* fp = static_cast<Fastpath*>(h);
  fp->ep_ids.assign(ids, ids + n);
}

// IPv4 LB tables; any (re)load flushes CT in the WRAPPER (caller).
void nf_load_lb(void* h, int32_t n_fe, int seq_width,
                const uint32_t* fe_addr, const int32_t* fe_port,
                const int32_t* fe_proto, const int32_t* fe_seq,
                const int32_t* fe_seq_len, const int32_t* fe_revnat,
                int32_t n_be, const uint32_t* be_addr,
                const int32_t* be_port) {
  auto* fp = static_cast<Fastpath*>(h);
  LBTables& t = fp->lb;
  t.fe_addr.assign(fe_addr, fe_addr + n_fe);
  t.fe_port.assign(fe_port, fe_port + n_fe);
  t.fe_proto.assign(fe_proto, fe_proto + n_fe);
  t.fe_seq.assign(fe_seq, fe_seq + size_t(n_fe) * seq_width);
  t.fe_seq_len.assign(fe_seq_len, fe_seq_len + n_fe);
  t.fe_revnat.assign(fe_revnat, fe_revnat + n_fe);
  t.be_addr.assign(be_addr, be_addr + n_be);
  t.be_port.assign(be_port, be_port + n_be);
  t.seq_width = seq_width;
  t.loaded = n_fe > 0;
}

// addr: n * stride bytes (stride 4 = v4, 16 = v6), big-endian address
// bytes (the trie's walk order). sports may be null (disables CT).
void nf_eval_batch(void* h, int64_t n, const uint8_t* addr, int stride,
                   const int32_t* ep_idx, const int32_t* dport,
                   const int32_t* proto, const int32_t* sport,
                   uint8_t ingress, int8_t* verdict_out,
                   uint8_t* redirect_out) {
  auto* fp = static_cast<Fastpath*>(h);
  const bool v6 = stride == 16;
  const Trie& ip = v6 ? fp->ip6 : fp->ip4;
  const Trie& deny = v6 ? fp->deny6 : fp->deny4;
  const bool use_ct = fp->ct_enabled && sport != nullptr;
  const double now = use_ct ? now_s() : 0.0;
  const uint32_t dir = ingress ? 0u : 1u;

  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* a = addr + size_t(i) * stride;
    int32_t dport_i = dport[i];

    // ── LB stage (egress, IPv4): VIP→backend translate BEFORE CT
    // and policy, exactly like DatapathPipeline._process. The flow
    // hash uses the PRE-NAT address + stable endpoint id so the pick
    // matches the device path bit for bit.
    uint8_t abuf[4];
    bool no_service = false;
    if (!ingress && !v6 && fp->lb.loaded) {
      uint32_t dst = (uint32_t(a[0]) << 24) | (uint32_t(a[1]) << 16) |
                     (uint32_t(a[2]) << 8) | a[3];
      const LBTables& t = fp->lb;
      for (size_t f = 0; f < t.fe_addr.size(); ++f) {
        if (t.fe_addr[f] != dst || t.fe_port[f] != dport_i) continue;
        if (t.fe_proto[f] != 0 && t.fe_proto[f] != proto[i]) continue;
        if (t.fe_seq_len[f] <= 0) {
          no_service = true;
          break;
        }
        // mirror pipeline.py's np.clip fallback exactly: with a
        // non-empty id table, out-of-range indices CLAMP (not raw)
        uint32_t ep_id;
        if (fp->ep_ids.empty()) {
          ep_id = uint32_t(ep_idx[i]);
        } else {
          int64_t ci = ep_idx[i];
          if (ci < 0) ci = 0;
          if (ci >= int64_t(fp->ep_ids.size()))
            ci = int64_t(fp->ep_ids.size()) - 1;
          ep_id = fp->ep_ids[ci];
        }
        int32_t hsh = flow_hash32(
            a, 4, sport ? sport[i] : 0, dport_i, proto[i], ep_id,
            sport != nullptr);
        int32_t be = t.fe_seq[f * t.seq_width + (hsh % t.fe_seq_len[f])];
        uint32_t ba = t.be_addr[be];
        abuf[0] = (ba >> 24) & 0xFF;
        abuf[1] = (ba >> 16) & 0xFF;
        abuf[2] = (ba >> 8) & 0xFF;
        abuf[3] = ba & 0xFF;
        a = abuf;
        dport_i = t.be_port[be];
        break;
      }
      if (no_service) {
        verdict_out[i] = DROP_NO_SERVICE;
        redirect_out[i] = 0;
        if (uint32_t(ep_idx[i]) < fp->ep_count)
          fp->counters[size_t(ep_idx[i]) * 3 + 2]++;  // dropped_other
        continue;
      }
    }

    uint64_t ct_a = 0, ct_b = 0, ct_c = 0;
    if (use_ct) {
      // pack_keys layout (datapath/conntrack.py)
      if (v6) {
        for (int k = 0; k < 8; ++k) ct_a = (ct_a << 8) | a[k];
        for (int k = 8; k < 16; ++k) ct_b = (ct_b << 8) | a[k];
      } else {
        ct_b = (uint64_t(a[0]) << 24) | (uint64_t(a[1]) << 16) |
               (uint64_t(a[2]) << 8) | a[3];
      }
      ct_c = (uint64_t(ep_idx[i]) << 41) | (uint64_t(sport[i]) << 25) |
             (uint64_t(dport_i) << 9) | (uint64_t(proto[i]) << 1) | dir;
      if (fp->ct.probe_pair(ct_a, ct_b, ct_c, now)) {
        verdict_out[i] = FORWARD;
        redirect_out[i] = 0;
        if (uint32_t(ep_idx[i]) < fp->ep_count)
          fp->counters[size_t(ep_idx[i]) * 3]++;
        continue;
      }
    }
    int8_t v;
    uint8_t red = 0;
    if (ingress && deny.loaded && deny.lookup(a) > 0) {
      v = DROP_PREFILTER;
    } else {
      int32_t hit = ip.loaded ? ip.lookup(a) : 0;
      uint64_t ident = hit > 0 ? uint64_t(hit - 1) : fp->world_identity;
      // __policy_can_access probe order (bpf/lib/policy.h:46):
      // exact {id,dport,proto} -> L3-only {id} -> L4-only {dport,proto}
      int val = fp->policy.find(
          ident, policy_kb(uint32_t(ep_idx[i]), uint32_t(dport_i),
                           uint32_t(proto[i]), dir));
      if (val < 0)
        val = fp->policy.find(ident,
                              policy_kb(uint32_t(ep_idx[i]), 0, 0, dir));
      if (val < 0)
        val = fp->policy.find(
            0, policy_kb(uint32_t(ep_idx[i]), uint32_t(dport_i),
                         uint32_t(proto[i]), dir));
      if (val > 0) {
        v = FORWARD;
        red = (val == 2);
        if (use_ct && !red) fp->ct.insert(ct_a, ct_b, ct_c, now);
      } else {
        v = DROP_POLICY;
      }
    }
    verdict_out[i] = v;
    redirect_out[i] = red;
    if (uint32_t(ep_idx[i]) < fp->ep_count) {
      int cls = v == FORWARD ? 0 : v == DROP_POLICY ? 1 : 2;
      fp->counters[size_t(ep_idx[i]) * 3 + cls]++;
    }
  }
}

void nf_counters(void* h, int64_t* out) {
  auto* fp = static_cast<Fastpath*>(h);
  std::memcpy(out, fp->counters.data(),
              fp->counters.size() * sizeof(int64_t));
}

}  // extern "C"
