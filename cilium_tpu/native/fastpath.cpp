// Native batch enforcement front-end.
//
// The role of the reference's in-kernel eBPF datapath plus its C++
// Envoy filters (SURVEY native census items 1 and 3): consume the
// control plane's compiled state — the TPU-materialized policymap
// rows, the ipcache/prefilter stride-8 tries, the LB selection
// sequences, and the L7 DFA/ACL tables — and enforce verdicts for
// flow batches at memory speed with no interpreter in the loop.
// Mirrors the per-packet path of bpf/bpf_lxc.c + bpf/lib/policy.h:
//
//   conntrack probe (fwd + reply tuple)   conntrack.h ct_lookup
//   LB VIP->backend translate (egress)    lb.h lb4_local / lb6_local
//   prefilter deny LPM (ingress only)     bpf_xdp.c check_filters
//   identity LPM, world on miss           bpf_netdev.c secctx
//   policymap: exact -> L3 -> L4          policy.h __policy_can_access
//   CT create on allow (not on redirect)  ct_create4
//
// and the per-request path of envoy/cilium_l7policy.cc (HTTP DFA rule
// match) + pkg/kafka/policy.go (Kafka ACL).
//
// Exposed as a C ABI consumed through ctypes (no pybind11 in the
// image).
//
// CONCURRENCY MODEL — one loader / N eval threads, for real:
//   - All lookup tables (policy, tries, LB, L7) live in an immutable
//     `Tables` snapshot held by shared_ptr. Loaders build a modified
//     copy under the load mutex and swap the pointer; evals pin the
//     snapshot they started with (read-only, race-free), exactly the
//     pinned-BPF-map replace semantics.
//   - Per-endpoint counters are relaxed atomics.
//   - Conntrack is shared and mutable: slots use an acquire/release
//     publish protocol on the key word (claim with a busy sentinel,
//     write the payload, publish the key) with a re-validation read,
//     so concurrent eval threads insert/refresh without locks.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <mutex>
#include <vector>

namespace {

constexpr int kProbes = 16;
constexpr uint64_t kEmpty = ~0ull;
constexpr uint64_t kBusy = ~1ull;

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30; x *= 0xbf58476d1ce4e5b9ull;
  x ^= x >> 27; x *= 0x94d049bb133111ebull;
  x ^= x >> 31;
  return x;
}

inline uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

// open-addressing (ka, kb) -> uint8 value table (immutable post-build)
struct HashTable {
  std::vector<uint64_t> ka, kb;
  std::vector<uint8_t> val;
  uint64_t mask = 0;

  void init(size_t entries) {
    size_t cap = 64;
    while (cap < entries * 4) cap <<= 1;  // load factor <= 0.25
    ka.assign(cap, kEmpty);
    kb.assign(cap, 0);
    val.assign(cap, 0);
    mask = cap - 1;
  }

  bool insert(uint64_t a, uint64_t b, uint8_t v) {
    uint64_t h = mix64(a ^ mix64(b));
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty || (ka[s] == a && kb[s] == b)) {
        ka[s] = a; kb[s] = b; val[s] = v;
        return true;
      }
    }
    return false;
  }

  inline int find(uint64_t a, uint64_t b) const {
    uint64_t h = mix64(a ^ mix64(b));
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      if (ka[s] == kEmpty) return -1;
      if (ka[s] == a && kb[s] == b) return int(val[s]);
    }
    return -1;
  }
};

// stride-8 trie (same layout as ops/lpm.py): child[M][256], info[M][256]
struct Trie {
  std::vector<int32_t> child, info;
  int levels = 0;
  bool loaded = false;

  inline int32_t lookup(const uint8_t* addr) const {
    int32_t node = 0, best = 0;
    for (int l = 0; l < levels; ++l) {
      size_t idx = size_t(node) * 256 + addr[l];
      int32_t v = info[idx];
      if (v) best = v;
      node = child[idx];
      if (!node) break;
    }
    return best;
  }
};

// ── conntrack ────────────────────────────────────────────────────────
// (ka, kb, kc) keys with expiry; same tuple packing as
// datapath/conntrack.py. Shared-mutable: ka is the published atomic
// key word; kb/kc/expires are valid only while ka holds the key
// (seqlock-lite: readers re-validate ka after reading the payload).
struct Conntrack {
  std::unique_ptr<std::atomic<uint64_t>[]> ka;
  std::vector<uint64_t> kb, kc;
  std::unique_ptr<std::atomic<uint64_t>[]> expires;  // monotonic ns
  // bumped at the START of every flush: an insert that claimed its
  // slot before the flush must not survive it (the entry's verdict
  // basis predates the reload that triggered the flush)
  std::atomic<uint64_t> flush_epoch{0};
  uint64_t mask = 0;
  uint64_t tcp_life_ns = 21600ull * 1000000000ull;
  uint64_t other_life_ns = 60ull * 1000000000ull;
  bool enabled = false;

  void init(int bits) {
    size_t cap = 1ull << bits;
    ka = std::make_unique<std::atomic<uint64_t>[]>(cap);
    expires = std::make_unique<std::atomic<uint64_t>[]>(cap);
    for (size_t i = 0; i < cap; ++i) {
      ka[i].store(kEmpty, std::memory_order_relaxed);
      expires[i].store(0, std::memory_order_relaxed);
    }
    kb.assign(cap, 0);
    kc.assign(cap, 0);
    mask = cap - 1;
    enabled = true;
  }

  inline uint64_t hash(uint64_t a, uint64_t b, uint64_t c) const {
    return mix64(a ^ mix64(b ^ mix64(c)));
  }

  inline uint64_t life_ns(uint64_t c) const {
    return ((c >> 1) & 0xff) == 6 ? tcp_life_ns : other_life_ns;
  }

  inline bool probe(uint64_t a, uint64_t b, uint64_t c, uint64_t now) {
    uint64_t h = hash(a, b, c);
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      uint64_t cur = ka[s].load(std::memory_order_acquire);
      if (cur == kEmpty) return false;
      if (cur != a) continue;
      if (kb[s] != b || kc[s] != c) continue;
      if (expires[s].load(std::memory_order_relaxed) <= now) continue;
      // payload read under a possibly-concurrent rewrite: re-validate
      if (ka[s].load(std::memory_order_acquire) != a) continue;
      expires[s].store(now + life_ns(c), std::memory_order_relaxed);
      return true;
    }
    return false;
  }

  // forward tuple, then the flipped reply tuple (swapped sport/dport,
  // inverted direction bit) — the same pair FlowConntrack.lookup_batch
  // probes via flip_kc, mirroring the kernel's forward/reverse tuple
  // pair (bpf/lib/conntrack.h ct_lookup)
  inline bool probe_pair(uint64_t a, uint64_t b, uint64_t c, uint64_t now) {
    if (probe(a, b, c, now)) return true;
    uint64_t ep = c >> 41;
    uint64_t sport = (c >> 25) & 0xFFFF;
    uint64_t dport = (c >> 9) & 0xFFFF;
    uint64_t proto = (c >> 1) & 0xFF;
    uint64_t dir = c & 1;
    uint64_t flipped = (ep << 41) | (dport << 25) | (sport << 9) |
                       (proto << 1) | (dir ^ 1);
    return probe(a, b, flipped, now);
  }

  inline void insert(uint64_t a, uint64_t b, uint64_t c, uint64_t now) {
    uint64_t epoch0 = flush_epoch.load(std::memory_order_acquire);
    uint64_t h = hash(a, b, c);
    for (int p = 0; p < kProbes; ++p) {
      uint64_t s = (h + p) & mask;
      uint64_t cur = ka[s].load(std::memory_order_acquire);
      if (cur == kBusy) continue;  // another writer owns the slot
      bool reusable = cur == kEmpty ||
                      expires[s].load(std::memory_order_relaxed) <= now ||
                      (cur == a && kb[s] == b && kc[s] == c);
      if (!reusable) continue;
      if (!ka[s].compare_exchange_strong(cur, kBusy,
                                         std::memory_order_acq_rel))
        continue;  // lost the claim race; try the next slot
      kb[s] = b;
      kc[s] = c;
      expires[s].store(now + life_ns(c), std::memory_order_relaxed);
      // publish via CAS: a concurrent flush stores kEmpty over our
      // kBusy claim — failing here means "flushed, drop the entry"
      uint64_t busy = kBusy;
      if (!ka[s].compare_exchange_strong(busy, a,
                                         std::memory_order_acq_rel))
        return;
      // the flush may also have swept this slot BEFORE we claimed it:
      // an entry whose verdict basis predates the flush must not
      // survive, so self-retract on an epoch move
      if (flush_epoch.load(std::memory_order_acquire) != epoch0) {
        uint64_t expect = a;
        ka[s].compare_exchange_strong(expect, kEmpty,
                                      std::memory_order_acq_rel);
      }
      return;
    }
    // full neighborhood: drop (flow re-verdicts next packet)
  }

  void flush() {
    if (!enabled) return;
    flush_epoch.fetch_add(1, std::memory_order_acq_rel);
    for (size_t i = 0; i <= mask; ++i)
      ka[i].store(kEmpty, std::memory_order_release);
  }
};

// ── LB tables ────────────────────────────────────────────────────────
// byte-addressed so IPv4 (stride 4) and IPv6 (stride 16) share the
// code path; mirrors lb/device.py LBTables / bpf/lib/lb.h:36-83
struct LBT {
  int stride = 4;
  std::vector<uint8_t> fe_addr;    // [F * stride] VIP address bytes
  std::vector<int32_t> fe_port;    // [F]
  std::vector<int32_t> fe_proto;   // [F] (0 = ANY)
  std::vector<int32_t> fe_seq;     // [F * seq_width]
  std::vector<int32_t> fe_seq_len; // [F]
  std::vector<int32_t> fe_revnat;  // [F]
  std::vector<uint8_t> be_addr;    // [NB * stride]
  std::vector<int32_t> be_port;    // [NB]
  int seq_width = 0;
  size_t n_fe = 0;
  bool loaded = false;
};

// ── L7 ───────────────────────────────────────────────────────────────
// One multi-pattern DFA (l7/regex_compile.py MultiDFA): trans[Q][256],
// accept[Q] u64 pattern mask. Q == 0 means the field is unused.
struct DFA {
  std::vector<int32_t> trans;
  std::vector<uint64_t> accept;
  int32_t start = 0;
  int32_t q = 0;

  inline uint64_t run(const uint8_t* s, int32_t len) const {
    if (len < 0) return 0;  // overlong: fail closed (strings_to_batch)
    int32_t state = start;
    for (int32_t i = 0; i < len; ++i) {
      state = trans[size_t(state) * 256 + s[i]];
      if (!state) return 0;  // dead state
    }
    return accept[state];
  }
};

// HTTP policy for one (endpoint, port, direction): the
// envoy/cilium_network_policy.h:68-202 rule chain with the regex
// matchers compiled to DFAs host-side.
struct HTTPPolicyN {
  DFA method, path, host;
  std::vector<int32_t> m_bit, p_bit, h_bit;  // [R] accept-bit or -1
  std::vector<uint8_t> scoped;               // [R] identity-scoped?
  std::vector<int64_t> ident_off;            // [R+1]
  std::vector<uint64_t> idents;              // sorted per rule
  size_t n_rules = 0;

  inline bool ident_ok(size_t r, uint64_t id) const {
    if (!scoped[r]) return true;
    const uint64_t* lo = idents.data() + ident_off[r];
    const uint64_t* hi = idents.data() + ident_off[r + 1];
    while (lo < hi) {  // binary search
      const uint64_t* mid = lo + (hi - lo) / 2;
      if (*mid == id) return true;
      if (*mid < id) lo = mid + 1; else hi = mid;
    }
    return false;
  }

  inline bool check(uint64_t m_mask, uint64_t p_mask, uint64_t h_mask,
                    uint64_t src_identity) const {
    if (n_rules == 0) return true;  // no L7 rules: pure L4 redirect
    for (size_t r = 0; r < n_rules; ++r) {
      if (!ident_ok(r, src_identity)) continue;
      if (m_bit[r] >= 0 && !((m_mask >> m_bit[r]) & 1)) continue;
      if (p_bit[r] >= 0 && !((p_mask >> p_bit[r]) & 1)) continue;
      if (h_bit[r] >= 0 && !((h_mask >> h_bit[r]) & 1)) continue;
      return true;
    }
    return false;
  }
};

// Kafka ACL for one (endpoint, port, direction): pkg/kafka/policy.go
// MatchesRule as dense vectors + interned topic/client strings.
struct KafkaACLN {
  std::vector<uint32_t> key_mask;  // [R]
  std::vector<uint8_t> key_wild;   // [R]
  std::vector<int32_t> version;    // [R] (-1 wildcard)
  std::vector<int32_t> topic_id;   // [R] (-1 wildcard)
  std::vector<int32_t> client_id;  // [R] (-1 wildcard)
  std::vector<uint8_t> scoped;     // [R]
  std::vector<int64_t> ident_off;  // [R+1]
  std::vector<uint64_t> idents;
  std::vector<std::string> topics;   // interned topic strings
  std::vector<std::string> clients;  // interned client ids
  size_t n_rules = 0;

  inline int32_t intern_of(const std::vector<std::string>& tbl,
                           const uint8_t* s, int32_t len) const {
    for (size_t i = 0; i < tbl.size(); ++i)
      if (int32_t(tbl[i].size()) == len &&
          std::memcmp(tbl[i].data(), s, size_t(len)) == 0)
        return int32_t(i);
    return -2;  // unknown string: matches only wildcard rules
  }

  inline bool ident_ok(size_t r, uint64_t id) const {
    if (!scoped[r]) return true;
    const uint64_t* lo = idents.data() + ident_off[r];
    const uint64_t* hi = idents.data() + ident_off[r + 1];
    while (lo < hi) {
      const uint64_t* mid = lo + (hi - lo) / 2;
      if (*mid == id) return true;
      if (*mid < id) lo = mid + 1; else hi = mid;
    }
    return false;
  }

  inline bool check(int32_t api_key, int32_t api_version, int32_t tid,
                    int32_t cid, uint64_t src_identity) const {
    if (n_rules == 0) return true;
    for (size_t r = 0; r < n_rules; ++r) {
      if (!key_wild[r]) {
        if (api_key < 0 || api_key >= 32) continue;
        if (!((key_mask[r] >> api_key) & 1)) continue;
      }
      if (version[r] >= 0 && version[r] != api_version) continue;
      if (topic_id[r] >= 0 && topic_id[r] != tid) continue;
      if (client_id[r] >= 0 && client_id[r] != cid) continue;
      if (!ident_ok(r, src_identity)) continue;
      return true;
    }
    return false;
  }
};

inline uint64_t l7_key(uint32_t ep, uint32_t port, uint32_t dir) {
  return (uint64_t(ep) << 32) | (uint64_t(port) << 8) | dir;
}

// ── the immutable snapshot ───────────────────────────────────────────
struct Tables {
  HashTable policy;  // ka = identity, kb = ep<<32|dport<<16|proto<<8|dir
  Trie ip4, ip6;     // value = identity (standalone table)
  Trie deny4, deny6; // prefilter
  LBT lb4, lb6;
  uint64_t world_identity = 2;
  std::vector<uint32_t> ep_ids;  // stable endpoint ids (LB hash input)
  std::map<uint64_t, HTTPPolicyN> http;   // (ep,port,dir) -> policy
  std::map<uint64_t, KafkaACLN> kafka;
};

struct Fastpath {
  std::shared_ptr<const Tables> tables;
  std::mutex load_mu;   // serializes loaders (copy-mutate-swap)
  Conntrack ct;
  uint32_t ep_count = 0;
  // [ep][3] fwd / drop_policy / drop_other — relaxed atomics
  std::unique_ptr<std::atomic<int64_t>[]> counters;

  std::shared_ptr<const Tables> snap() const {
    return std::atomic_load_explicit(&tables, std::memory_order_acquire);
  }
  void swap(std::shared_ptr<const Tables> t) {
    std::atomic_store_explicit(&tables, std::move(t),
                               std::memory_order_release);
  }
  // copy-on-write: clone the current snapshot for mutation
  std::shared_ptr<Tables> clone() const {
    return std::make_shared<Tables>(*snap());
  }
};

// verdict codes — match datapath/pipeline.py
constexpr int8_t FORWARD = 1;
constexpr int8_t DROP_POLICY = 2;
constexpr int8_t DROP_PREFILTER = 3;
constexpr int8_t DROP_NO_SERVICE = 4;

// per-flow hash — MUST match lb/device.py flow_hash32 exactly (the
// translated CT key depends on deterministic backend selection, and
// native/device parity requires identical picks)
inline int32_t flow_hash32(const uint8_t* addr, int stride, int32_t sport,
                           int32_t dport, int32_t proto, uint32_t ep_id,
                           bool has_sport) {
  uint32_t x = 0;
  for (int i = 0; i < stride; ++i) x = (x * 0x01000193u) ^ addr[i];
  if (has_sport) x ^= uint32_t(sport) << 16;
  x ^= uint32_t(dport);
  x ^= uint32_t(proto) << 8;
  x ^= ep_id << 24;
  x ^= x >> 16; x *= 0x85EBCA6Bu;
  x ^= x >> 13; x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return int32_t(x & 0x7FFFFFFFu);
}

inline uint64_t policy_kb(uint32_t ep, uint32_t dport, uint32_t proto,
                          uint32_t dir) {
  return (uint64_t(ep) << 32) | (uint64_t(dport) << 16) |
         (uint64_t(proto) << 8) | dir;
}

void load_dfa(DFA& d, const int32_t* trans, const uint64_t* accept,
              int32_t q, int32_t start) {
  d.q = q;
  d.start = start;
  if (q > 0) {
    d.trans.assign(trans, trans + size_t(q) * 256);
    d.accept.assign(accept, accept + q);
  } else {
    d.trans.clear();
    d.accept.clear();
  }
}

}  // namespace

extern "C" {

void* nf_create(uint32_t ep_count, int ct_bits) {
  auto* fp = new Fastpath();
  fp->tables = std::make_shared<Tables>();
  fp->ep_count = ep_count;
  size_t n = size_t(ep_count ? ep_count : 1) * 3;
  fp->counters = std::make_unique<std::atomic<int64_t>[]>(n);
  for (size_t i = 0; i < n; ++i)
    fp->counters[i].store(0, std::memory_order_relaxed);
  if (ct_bits > 0) fp->ct.init(ct_bits);
  return fp;
}

void nf_destroy(void* h) { delete static_cast<Fastpath*>(h); }

void nf_set_world(void* h, uint64_t identity) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  t->world_identity = identity;
  fp->swap(std::move(t));
}

// entries: parallel arrays — identity u64, ep u32, dport u32, proto
// u32, dir u32, redirect u8. value stored = 1 (allow) | 2 (redirect)
int64_t nf_load_policy(void* h, int64_t n, const uint64_t* identity,
                       const uint32_t* ep, const uint32_t* dport,
                       const uint32_t* proto, const uint32_t* dir,
                       const uint8_t* redirect) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  t->policy.init(size_t(n));
  int64_t loaded = 0;
  for (int64_t i = 0; i < n; ++i) {
    loaded += t->policy.insert(
        identity[i], policy_kb(ep[i], dport[i], proto[i], dir[i]),
        redirect[i] ? 2 : 1);
  }
  fp->swap(std::move(t));
  return loaded;
}

// which: 0 = ipcache v4, 1 = ipcache v6, 2 = deny v4, 3 = deny v6
void nf_load_trie(void* h, int which, const int32_t* child,
                  const int32_t* info, int32_t n_nodes, int levels) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  Trie* tr = which == 0 ? &t->ip4 : which == 1 ? &t->ip6
             : which == 2 ? &t->deny4 : &t->deny6;
  tr->child.assign(child, child + size_t(n_nodes) * 256);
  tr->info.assign(info, info + size_t(n_nodes) * 256);
  tr->levels = levels;
  tr->loaded = true;
  fp->swap(std::move(t));
}

void nf_ct_flush(void* h) { static_cast<Fastpath*>(h)->ct.flush(); }

void nf_set_endpoint_ids(void* h, int64_t n, const uint32_t* ids) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  t->ep_ids.assign(ids, ids + n);
  fp->swap(std::move(t));
}

// LB tables for one family (stride 4 = IPv4, 16 = IPv6); fe_addr /
// be_addr are n*stride big-endian address bytes. Any (re)load flushes
// CT in the WRAPPER (caller).
void nf_load_lb(void* h, int stride, int32_t n_fe, int seq_width,
                const uint8_t* fe_addr, const int32_t* fe_port,
                const int32_t* fe_proto, const int32_t* fe_seq,
                const int32_t* fe_seq_len, const int32_t* fe_revnat,
                int32_t n_be, const uint8_t* be_addr,
                const int32_t* be_port) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto tt = fp->clone();
  LBT& t = stride == 16 ? tt->lb6 : tt->lb4;
  t.stride = stride;
  t.fe_addr.assign(fe_addr, fe_addr + size_t(n_fe) * stride);
  t.fe_port.assign(fe_port, fe_port + n_fe);
  t.fe_proto.assign(fe_proto, fe_proto + n_fe);
  t.fe_seq.assign(fe_seq, fe_seq + size_t(n_fe) * seq_width);
  t.fe_seq_len.assign(fe_seq_len, fe_seq_len + n_fe);
  t.fe_revnat.assign(fe_revnat, fe_revnat + n_fe);
  t.be_addr.assign(be_addr, be_addr + size_t(n_be) * stride);
  t.be_port.assign(be_port, be_port + n_be);
  t.seq_width = seq_width;
  t.n_fe = size_t(n_fe);
  t.loaded = n_fe > 0;
  fp->swap(std::move(tt));
}

// ── L7 loading ───────────────────────────────────────────────────────

// HTTP policy for one (ep, port, dir). DFAs: trans [q][256] + accept
// [q] u64 + start; q = 0 marks an unused field. Rules: per-rule accept
// BIT index per field (-1 = wildcard), identity scoping as sorted
// flattened u64 lists.
void nf_l7_set_http(void* h, uint32_t ep, uint32_t port, uint8_t ingress,
                    const int32_t* m_trans, const uint64_t* m_accept,
                    int32_t m_q, int32_t m_start,
                    const int32_t* p_trans, const uint64_t* p_accept,
                    int32_t p_q, int32_t p_start,
                    const int32_t* h_trans, const uint64_t* h_accept,
                    int32_t h_q, int32_t h_start,
                    int32_t n_rules, const int32_t* m_bit,
                    const int32_t* p_bit, const int32_t* h_bit,
                    const uint8_t* scoped, const int64_t* ident_off,
                    const uint64_t* idents) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  HTTPPolicyN pol;
  load_dfa(pol.method, m_trans, m_accept, m_q, m_start);
  load_dfa(pol.path, p_trans, p_accept, p_q, p_start);
  load_dfa(pol.host, h_trans, h_accept, h_q, h_start);
  pol.n_rules = size_t(n_rules);
  pol.m_bit.assign(m_bit, m_bit + n_rules);
  pol.p_bit.assign(p_bit, p_bit + n_rules);
  pol.h_bit.assign(h_bit, h_bit + n_rules);
  pol.scoped.assign(scoped, scoped + n_rules);
  pol.ident_off.assign(ident_off, ident_off + n_rules + 1);
  pol.idents.assign(idents, idents + ident_off[n_rules]);
  t->http[l7_key(ep, port, ingress ? 0u : 1u)] = std::move(pol);
  fp->swap(std::move(t));
}

// Kafka ACL for one (ep, port, dir): rule vectors + interned topic /
// client string tables (concatenated bytes + offsets).
void nf_l7_set_kafka(void* h, uint32_t ep, uint32_t port, uint8_t ingress,
                     int32_t n_rules, const uint32_t* key_mask,
                     const uint8_t* key_wild, const int32_t* version,
                     const int32_t* topic_id, const int32_t* client_id,
                     const uint8_t* scoped, const int64_t* ident_off,
                     const uint64_t* idents,
                     int32_t n_topics, const uint8_t* topic_bytes,
                     const int64_t* topic_off,
                     int32_t n_clients, const uint8_t* client_bytes,
                     const int64_t* client_off) {
  auto* fp = static_cast<Fastpath*>(h);
  std::lock_guard<std::mutex> g(fp->load_mu);
  auto t = fp->clone();
  KafkaACLN acl;
  acl.n_rules = size_t(n_rules);
  acl.key_mask.assign(key_mask, key_mask + n_rules);
  acl.key_wild.assign(key_wild, key_wild + n_rules);
  acl.version.assign(version, version + n_rules);
  acl.topic_id.assign(topic_id, topic_id + n_rules);
  acl.client_id.assign(client_id, client_id + n_rules);
  acl.scoped.assign(scoped, scoped + n_rules);
  acl.ident_off.assign(ident_off, ident_off + n_rules + 1);
  acl.idents.assign(idents, idents + ident_off[n_rules]);
  for (int32_t i = 0; i < n_topics; ++i)
    acl.topics.emplace_back(
        reinterpret_cast<const char*>(topic_bytes) + topic_off[i],
        size_t(topic_off[i + 1] - topic_off[i]));
  for (int32_t i = 0; i < n_clients; ++i)
    acl.clients.emplace_back(
        reinterpret_cast<const char*>(client_bytes) + client_off[i],
        size_t(client_off[i + 1] - client_off[i]));
  t->kafka[l7_key(ep, port, ingress ? 0u : 1u)] = std::move(acl);
  fp->swap(std::move(t));
}

// ── L7 evaluation ────────────────────────────────────────────────────

// strings: [n, max_len] padded bytes + [n] lengths (-1 = overlong →
// fail closed, matching ops/dfa.strings_to_batch)
void nf_l7_http_batch(void* h, uint32_t ep, uint32_t port, uint8_t ingress,
                      int64_t n,
                      const uint8_t* methods, int32_t m_len,
                      const int32_t* m_lens,
                      const uint8_t* paths, int32_t p_len,
                      const int32_t* p_lens,
                      const uint8_t* hosts, int32_t h_len,
                      const int32_t* h_lens,
                      const uint64_t* src_identity, uint8_t* allow_out) {
  auto* fp = static_cast<Fastpath*>(h);
  auto t = fp->snap();
  auto it = t->http.find(l7_key(ep, port, ingress ? 0u : 1u));
  if (it == t->http.end()) {
    std::memset(allow_out, 1, size_t(n));  // no policy: pure L4 redirect
    return;
  }
  const HTTPPolicyN& pol = it->second;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t mm = pol.method.q
        ? pol.method.run(methods + size_t(i) * m_len, m_lens[i]) : 0;
    uint64_t pm = pol.path.q
        ? pol.path.run(paths + size_t(i) * p_len, p_lens[i]) : 0;
    uint64_t hm = pol.host.q
        ? pol.host.run(hosts + size_t(i) * h_len, h_lens[i]) : 0;
    allow_out[i] = pol.check(mm, pm, hm, src_identity[i]) ? 1 : 0;
  }
}

void nf_l7_kafka_batch(void* h, uint32_t ep, uint32_t port, uint8_t ingress,
                       int64_t n, const int32_t* api_key,
                       const int32_t* api_version,
                       const uint8_t* topics, int32_t t_len,
                       const int32_t* topic_lens,
                       const uint8_t* clients, int32_t c_len,
                       const int32_t* client_lens,
                       const uint64_t* src_identity, uint8_t* allow_out) {
  auto* fp = static_cast<Fastpath*>(h);
  auto t = fp->snap();
  auto it = t->kafka.find(l7_key(ep, port, ingress ? 0u : 1u));
  if (it == t->kafka.end()) {
    std::memset(allow_out, 1, size_t(n));
    return;
  }
  const KafkaACLN& acl = it->second;
  for (int64_t i = 0; i < n; ++i) {
    int32_t tid = acl.intern_of(
        acl.topics, topics + size_t(i) * t_len, topic_lens[i]);
    int32_t cid = acl.intern_of(
        acl.clients, clients + size_t(i) * c_len, client_lens[i]);
    allow_out[i] = acl.check(api_key[i], api_version[i], tid, cid,
                             src_identity[i]) ? 1 : 0;
  }
}

// ── L3/L4 evaluation ─────────────────────────────────────────────────

// addr: n * stride bytes (stride 4 = v4, 16 = v6), big-endian address
// bytes (the trie's walk order). sports may be null (disables CT).
// Thread-safe: any number of concurrent callers (snapshot reads,
// atomic counters, lock-free CT).
void nf_eval_batch(void* h, int64_t n, const uint8_t* addr, int stride,
                   const int32_t* ep_idx, const int32_t* dport,
                   const int32_t* proto, const int32_t* sport,
                   uint8_t ingress, int8_t* verdict_out,
                   uint8_t* redirect_out) {
  auto* fp = static_cast<Fastpath*>(h);
  auto t = fp->snap();
  const bool v6 = stride == 16;
  const Trie& ip = v6 ? t->ip6 : t->ip4;
  const Trie& deny = v6 ? t->deny6 : t->deny4;
  const LBT& lb = v6 ? t->lb6 : t->lb4;
  const bool use_ct = fp->ct.enabled && sport != nullptr;
  const uint64_t now = use_ct ? now_ns() : 0;
  const uint32_t dir = ingress ? 0u : 1u;

  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* a = addr + size_t(i) * stride;
    int32_t dport_i = dport[i];

    // ── LB stage (egress): VIP→backend translate BEFORE CT and
    // policy, exactly like DatapathPipeline._process. The flow hash
    // uses the PRE-NAT address + stable endpoint id so the pick
    // matches the device path bit for bit.
    uint8_t abuf[16];
    bool no_service = false;
    if (!ingress && lb.loaded) {
      for (size_t f = 0; f < lb.n_fe; ++f) {
        if (std::memcmp(lb.fe_addr.data() + f * stride, a, stride) != 0)
          continue;
        if (lb.fe_port[f] != dport_i) continue;
        if (lb.fe_proto[f] != 0 && lb.fe_proto[f] != proto[i]) continue;
        if (lb.fe_seq_len[f] <= 0) {
          no_service = true;
          break;
        }
        // mirror pipeline.py's np.clip fallback exactly: with a
        // non-empty id table, out-of-range indices CLAMP (not raw)
        uint32_t ep_id;
        if (t->ep_ids.empty()) {
          ep_id = uint32_t(ep_idx[i]);
        } else {
          int64_t ci = ep_idx[i];
          if (ci < 0) ci = 0;
          if (ci >= int64_t(t->ep_ids.size()))
            ci = int64_t(t->ep_ids.size()) - 1;
          ep_id = t->ep_ids[ci];
        }
        int32_t hsh = flow_hash32(
            a, stride, sport ? sport[i] : 0, dport_i, proto[i], ep_id,
            sport != nullptr);
        int32_t be = lb.fe_seq[f * lb.seq_width + (hsh % lb.fe_seq_len[f])];
        std::memcpy(abuf, lb.be_addr.data() + size_t(be) * stride, stride);
        a = abuf;
        dport_i = lb.be_port[be];
        break;
      }
      if (no_service) {
        verdict_out[i] = DROP_NO_SERVICE;
        redirect_out[i] = 0;
        if (uint32_t(ep_idx[i]) < fp->ep_count)
          fp->counters[size_t(ep_idx[i]) * 3 + 2].fetch_add(
              1, std::memory_order_relaxed);
        continue;
      }
    }

    uint64_t ct_a = 0, ct_b = 0, ct_c = 0;
    if (use_ct) {
      // pack_keys layout (datapath/conntrack.py)
      if (v6) {
        for (int k = 0; k < 8; ++k) ct_a = (ct_a << 8) | a[k];
        for (int k = 8; k < 16; ++k) ct_b = (ct_b << 8) | a[k];
      } else {
        ct_b = (uint64_t(a[0]) << 24) | (uint64_t(a[1]) << 16) |
               (uint64_t(a[2]) << 8) | a[3];
      }
      ct_c = (uint64_t(ep_idx[i]) << 41) | (uint64_t(sport[i]) << 25) |
             (uint64_t(dport_i) << 9) | (uint64_t(proto[i]) << 1) | dir;
      if (fp->ct.probe_pair(ct_a, ct_b, ct_c, now)) {
        verdict_out[i] = FORWARD;
        redirect_out[i] = 0;
        if (uint32_t(ep_idx[i]) < fp->ep_count)
          fp->counters[size_t(ep_idx[i]) * 3].fetch_add(
              1, std::memory_order_relaxed);
        continue;
      }
    }
    int8_t v;
    uint8_t red = 0;
    if (ingress && deny.loaded && deny.lookup(a) > 0) {
      v = DROP_PREFILTER;
    } else {
      int32_t hit = ip.loaded ? ip.lookup(a) : 0;
      uint64_t ident = hit > 0 ? uint64_t(hit - 1) : t->world_identity;
      // __policy_can_access probe order (bpf/lib/policy.h:46):
      // exact {id,dport,proto} -> L3-only {id} -> L4-only {dport,proto}
      int val = t->policy.find(
          ident, policy_kb(uint32_t(ep_idx[i]), uint32_t(dport_i),
                           uint32_t(proto[i]), dir));
      if (val < 0)
        val = t->policy.find(ident,
                             policy_kb(uint32_t(ep_idx[i]), 0, 0, dir));
      if (val < 0)
        val = t->policy.find(
            0, policy_kb(uint32_t(ep_idx[i]), uint32_t(dport_i),
                         uint32_t(proto[i]), dir));
      if (val > 0) {
        v = FORWARD;
        red = (val == 2);
        if (use_ct && !red) fp->ct.insert(ct_a, ct_b, ct_c, now);
      } else {
        v = DROP_POLICY;
      }
    }
    verdict_out[i] = v;
    redirect_out[i] = red;
    if (uint32_t(ep_idx[i]) < fp->ep_count) {
      int cls = v == FORWARD ? 0 : v == DROP_POLICY ? 1 : 2;
      fp->counters[size_t(ep_idx[i]) * 3 + cls].fetch_add(
          1, std::memory_order_relaxed);
    }
  }
}

void nf_counters(void* h, int64_t* out) {
  auto* fp = static_cast<Fastpath*>(h);
  size_t n = size_t(fp->ep_count ? fp->ep_count : 1) * 3;
  for (size_t i = 0; i < n; ++i)
    out[i] = fp->counters[i].load(std::memory_order_relaxed);
}

}  // extern "C"
