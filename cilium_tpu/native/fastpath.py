"""ctypes wrapper: the native enforcement front-end.

Consumes the SAME compiled state the device pipeline materializes —
per-endpoint policymap snapshots (ops/materialize.py) and the
ipcache/prefilter prefixes — and answers flow batches entirely in
native code: conntrack probe, deny LPM, identity LPM, 3-step
policymap lookup, per-endpoint counters. This is the SURVEY native
census item 1: the eBPF datapath role, re-hosted as a userspace C++
library fed by TPU-computed policy tensors. The device pipeline stays
the batch/cold path and the source of truth; this front-end is the
per-node enforcement loop a non-Python dataplane embeds.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from ..ops.lpm import TrieBuilder, ipv4_to_bytes
from ..ops.materialize import TRAFFIC_INGRESS
from . import build as _build

FORWARD = 1
DROP_POLICY = 2
DROP_PREFILTER = 3

_WHICH_IP4, _WHICH_IP6, _WHICH_DENY4, _WHICH_DENY6 = 0, 1, 2, 3


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


class NativeFastpath:
    """One loaded enforcement state (policy + tries + CT)."""

    def __init__(self, ep_count: int, ct_bits: int = 18) -> None:
        self._lib = _build.load()
        self._h = self._lib.nf_create(ep_count, ct_bits)
        self.ep_count = ep_count

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.nf_destroy(h)
            self._h = None

    # -- loading --------------------------------------------------------
    def set_world_identity(self, identity: int) -> None:
        self._lib.nf_set_world(self._h, identity)

    def load_policy_snapshots(self, snapshots: Sequence) -> int:
        """Load per-endpoint EndpointPolicySnapshot dicts (the
        realized policymap the TPU materialization produced); snapshot
        order defines the endpoint index, matching the pipeline.
        Raises if the C++ table dropped any entry (a dropped allow
        would silently misenforce). Any load flushes conntrack — the
        established-flow bypass is only sound while the verdict basis
        that admitted the flow still holds (same invariant as
        DatapathPipeline.rebuild)."""
        idents, eps, dports, protos, dirs, reds = [], [], [], [], [], []
        for ep_idx, snap in enumerate(snapshots):
            for key, red in snap.entries.items():
                idents.append(key.identity)
                eps.append(ep_idx)
                dports.append(key.dport)
                protos.append(key.nexthdr)
                dirs.append(key.direction)
                reds.append(1 if red else 0)
        n = len(idents)
        identity = np.asarray(idents, np.uint64)
        ep = np.asarray(eps, np.uint32)
        dport = np.asarray(dports, np.uint32)
        proto = np.asarray(protos, np.uint32)
        dir_ = np.asarray(dirs, np.uint32)
        red = np.asarray(reds, np.uint8)
        loaded = int(self._lib.nf_load_policy(
            self._h, n,
            _ptr(identity, ctypes.c_uint64), _ptr(ep, ctypes.c_uint32),
            _ptr(dport, ctypes.c_uint32), _ptr(proto, ctypes.c_uint32),
            _ptr(dir_, ctypes.c_uint32), _ptr(red, ctypes.c_uint8),
        ))
        if loaded != n:
            raise RuntimeError(
                f"native policy table dropped {n - loaded} of {n} entries "
                "(hash neighborhood overflow)"
            )
        self.ct_flush()
        return loaded

    def _load_trie(self, which: int, prefixes, levels: int) -> None:
        """prefixes: iterable of (cidr_string, value)."""
        import ipaddress

        tb = TrieBuilder(levels)
        for cidr, value in prefixes:
            net = ipaddress.ip_network(cidr, strict=False)
            tb.insert(net.network_address.packed, net.prefixlen, int(value))
        child, info = tb.arrays()
        child = np.ascontiguousarray(child, np.int32)
        info = np.ascontiguousarray(info, np.int32)
        self._lib.nf_load_trie(
            self._h, which, _ptr(child, ctypes.c_int32),
            _ptr(info, ctypes.c_int32), child.shape[0], levels,
        )

    def load_ipcache(self, ipcache) -> None:
        """IP→IDENTITY tries from the authoritative ipcache (values are
        identities, not device rows — this table is standalone).
        Empty lists STILL load (an empty trie): a reload that removed
        the last entry must not leave the previous trie enforcing
        stale mappings. Flushes conntrack (verdict basis moved)."""
        v4 = [(c, e.identity) for c, e in ipcache.items() if ":" not in c]
        v6 = [(c, e.identity) for c, e in ipcache.items() if ":" in c]
        self._load_trie(_WHICH_IP4, v4, 4)
        self._load_trie(_WHICH_IP6, v6, 16)
        self.ct_flush()

    def load_prefilter(self, prefilter) -> None:
        _, cidrs = prefilter.dump()
        v4 = [(c, 1) for c in cidrs if ":" not in c]
        v6 = [(c, 1) for c in cidrs if ":" in c]
        self._load_trie(_WHICH_DENY4, v4, 4)
        self._load_trie(_WHICH_DENY6, v6, 16)
        self.ct_flush()

    def ct_flush(self) -> None:
        self._lib.nf_ct_flush(self._h)

    def set_endpoint_ids(self, ids: Sequence[int]) -> None:
        """Stable endpoint ids per datapath index — the LB flow hash
        input (a positional index would re-pick backends on unrelated
        endpoint churn, same invariant as the device path)."""
        arr = np.ascontiguousarray(ids, np.uint32)
        self._lib.nf_set_endpoint_ids(
            self._h, arr.shape[0], _ptr(arr, ctypes.c_uint32)
        )

    def load_lb(self, manager) -> None:
        """Load BOTH address families' service tables from a
        lb.ServiceManager — built through the SAME build_device() used
        by the device path so frontend order, selection sequences, and
        backend rows are bit-identical (deterministic hash ⇒ identical
        picks, bpf/lib/lb.h lb4/lb6 dual-stack). Flushes conntrack
        (translated CT keys change with the tables)."""
        tables = manager.build_device()
        for family, stride in ((4, 4), (6, 16)):
            t = tables.get(family)
            if t is None:
                self._load_lb_family(stride, None)
            else:
                self._load_lb_family(stride, t)
        self.ct_flush()

    def _load_lb_family(self, stride: int, t) -> None:
        if t is None:
            z8 = np.zeros(1, np.uint8)
            z32 = np.zeros(1, np.int32)
            self._lib.nf_load_lb(
                self._h, stride, 0, 1,
                _ptr(z8, ctypes.c_uint8), _ptr(z32, ctypes.c_int32),
                _ptr(z32, ctypes.c_int32), _ptr(z32, ctypes.c_int32),
                _ptr(z32, ctypes.c_int32), _ptr(z32, ctypes.c_int32),
                0, _ptr(z8, ctypes.c_uint8), _ptr(z32, ctypes.c_int32),
            )
            return
        fe_addr = np.ascontiguousarray(np.asarray(t.fe_bytes), np.uint8)
        be_addr = np.ascontiguousarray(np.asarray(t.be_bytes), np.uint8)
        fe_port = np.ascontiguousarray(t.fe_port, np.int32)
        fe_proto = np.ascontiguousarray(t.fe_proto, np.int32)
        fe_seq = np.ascontiguousarray(t.fe_seq, np.int32)
        fe_seq_len = np.ascontiguousarray(t.fe_seq_len, np.int32)
        fe_revnat = np.ascontiguousarray(t.fe_revnat, np.int32)
        be_port = np.ascontiguousarray(t.be_port, np.int32)
        self._lib.nf_load_lb(
            self._h, stride, fe_addr.shape[0], fe_seq.shape[1],
            _ptr(fe_addr, ctypes.c_uint8), _ptr(fe_port, ctypes.c_int32),
            _ptr(fe_proto, ctypes.c_int32), _ptr(fe_seq, ctypes.c_int32),
            _ptr(fe_seq_len, ctypes.c_int32),
            _ptr(fe_revnat, ctypes.c_int32),
            be_addr.shape[0], _ptr(be_addr, ctypes.c_uint8),
            _ptr(be_port, ctypes.c_int32),
        )

    # -- L7 -------------------------------------------------------------
    def load_l7_http(
        self, endpoint_id: int, port: int, http_policy, *,
        ingress: bool = True,
    ) -> None:
        """Load one (endpoint, port, direction)'s HTTP policy into the
        native enforcer (the envoy/cilium_l7policy.cc role): the SAME
        MultiDFA tables HTTPPolicy compiled, plus per-rule accept-bit
        indices and identity scopes. Raises when any rule relies on
        host-only matching (regex demoted from the DFA, or header
        matchers) — refusing loudly beats silently diverging."""
        m, p, hst, rules = http_policy.native_tables()
        n = len(rules)
        m_bit = np.ascontiguousarray([r[0] for r in rules], np.int32)
        p_bit = np.ascontiguousarray([r[1] for r in rules], np.int32)
        h_bit = np.ascontiguousarray([r[2] for r in rules], np.int32)
        scoped = np.ascontiguousarray(
            [1 if r[3] is not None else 0 for r in rules], np.uint8
        )
        off = [0]
        idents: list = []
        for r in rules:
            if r[3] is not None:
                idents.extend(sorted(r[3]))
            off.append(len(idents))
        ident_off = np.ascontiguousarray(off, np.int64)
        ident_arr = np.ascontiguousarray(idents or [0], np.uint64)

        def dfa_args(d):
            if d is None:
                z = np.zeros(256, np.int32)
                za = np.zeros(1, np.uint64)
                return (_ptr(z, ctypes.c_int32), _ptr(za, ctypes.c_uint64),
                        0, 0, (z, za))
            trans = np.ascontiguousarray(d.trans, np.int32)
            accept = np.ascontiguousarray(d.accept, np.uint64)
            return (_ptr(trans, ctypes.c_int32),
                    _ptr(accept, ctypes.c_uint64),
                    trans.shape[0], int(d.start), (trans, accept))

        mt, ma, mq, ms, mk = dfa_args(m)
        pt, pa, pq, ps, pk = dfa_args(p)
        ht, ha, hq, hs, hk = dfa_args(hst)
        self._lib.nf_l7_set_http(
            self._h, endpoint_id, port, 1 if ingress else 0,
            mt, ma, mq, ms, pt, pa, pq, ps, ht, ha, hq, hs,
            n, _ptr(m_bit, ctypes.c_int32), _ptr(p_bit, ctypes.c_int32),
            _ptr(h_bit, ctypes.c_int32), _ptr(scoped, ctypes.c_uint8),
            _ptr(ident_off, ctypes.c_int64),
            _ptr(ident_arr, ctypes.c_uint64),
        )

    def load_l7_kafka(
        self, endpoint_id: int, port: int, kafka_acl, *,
        ingress: bool = True,
    ) -> None:
        """Load one (endpoint, port, direction)'s Kafka ACL vectors +
        interned topic/client tables (pkg/kafka/policy.go MatchesRule,
        natively)."""
        n = len(kafka_acl)
        key_mask = np.ascontiguousarray(kafka_acl.key_mask, np.uint32)
        key_wild = np.ascontiguousarray(kafka_acl.key_wild, np.uint8)
        version = np.ascontiguousarray(kafka_acl.version, np.int32)
        topic_id = np.ascontiguousarray(kafka_acl.topic_id, np.int32)
        clients = kafka_acl.client_id  # list of strings per rule
        cli_tbl = sorted({c for c in clients if c})
        cli_ids = {c: i for i, c in enumerate(cli_tbl)}
        client_id = np.ascontiguousarray(
            [cli_ids.get(c, -1) if c else -1 for c in clients], np.int32
        )
        scoped = np.ascontiguousarray(
            [1 if idents is not None else 0 for _r, idents in kafka_acl._rules],
            np.uint8,
        )
        off = [0]
        idents_flat: list = []
        for _r, idents in kafka_acl._rules:
            if idents is not None:
                idents_flat.extend(sorted(idents))
            off.append(len(idents_flat))
        ident_off = np.ascontiguousarray(off, np.int64)
        ident_arr = np.ascontiguousarray(idents_flat or [0], np.uint64)

        def strtab(strs):
            offs = [0]
            blob = b""
            for s in strs:
                blob += s.encode()
                offs.append(len(blob))
            b = np.frombuffer(blob or b"\0", np.uint8).copy()
            return b, np.ascontiguousarray(offs, np.int64)

        topics = [t for t, _ in sorted(
            kafka_acl._topic_ids.items(), key=lambda kv: kv[1]
        )]
        t_bytes, t_off = strtab(topics)
        c_bytes, c_off = strtab(cli_tbl)
        self._lib.nf_l7_set_kafka(
            self._h, endpoint_id, port, 1 if ingress else 0,
            n, _ptr(key_mask, ctypes.c_uint32),
            _ptr(key_wild, ctypes.c_uint8), _ptr(version, ctypes.c_int32),
            _ptr(topic_id, ctypes.c_int32), _ptr(client_id, ctypes.c_int32),
            _ptr(scoped, ctypes.c_uint8), _ptr(ident_off, ctypes.c_int64),
            _ptr(ident_arr, ctypes.c_uint64),
            len(topics), _ptr(t_bytes, ctypes.c_uint8),
            _ptr(t_off, ctypes.c_int64),
            len(cli_tbl), _ptr(c_bytes, ctypes.c_uint8),
            _ptr(c_off, ctypes.c_int64),
        )

    def check_http_batch(
        self, endpoint_id: int, port: int, requests, *,
        ingress: bool = True, max_len: int = 256,
    ) -> np.ndarray:
        """Native per-request HTTP enforcement → [B] bool allow (the
        same contract as HTTPPolicy.check_batch). Field widths adapt to
        the batch's longest value so overlong strings still match —
        HTTPPolicy deliberately host-walks overlong values rather than
        failing closed, and the native path must agree. Values past
        64KiB raise (bounded allocation; route those to the Python
        path)."""
        from ..l7.http_policy import NativeL7Unsupported
        from ..ops.dfa import strings_to_batch

        n = len(requests)
        enc_m = [r.method.encode() for r in requests]
        enc_p = [r.path.encode() for r in requests]
        enc_h = [r.host.encode() for r in requests]

        def width(encs, floor):
            longest = max(map(len, encs), default=0)
            if longest > 65536:
                raise NativeL7Unsupported(
                    f"request field of {longest} bytes exceeds the "
                    "native 64KiB cap"
                )
            return max(floor, longest)

        m_w = width(enc_m, 16)
        p_w = width(enc_p, max_len)
        h_w = width(enc_h, max_len)
        mb, ml = strings_to_batch(enc_m, m_w)
        pb, pl = strings_to_batch(enc_p, p_w)
        hb, hl = strings_to_batch(enc_h, h_w)
        src = np.ascontiguousarray(
            [r.src_identity for r in requests], np.uint64
        )
        allow = np.empty(n, np.uint8)
        self._lib.nf_l7_http_batch(
            self._h, endpoint_id, port, 1 if ingress else 0, n,
            _ptr(np.ascontiguousarray(mb, np.uint8), ctypes.c_uint8), m_w,
            _ptr(np.ascontiguousarray(ml, np.int32), ctypes.c_int32),
            _ptr(np.ascontiguousarray(pb, np.uint8), ctypes.c_uint8),
            p_w,
            _ptr(np.ascontiguousarray(pl, np.int32), ctypes.c_int32),
            _ptr(np.ascontiguousarray(hb, np.uint8), ctypes.c_uint8),
            h_w,
            _ptr(np.ascontiguousarray(hl, np.int32), ctypes.c_int32),
            _ptr(src, ctypes.c_uint64), _ptr(allow, ctypes.c_uint8),
        )
        return allow.astype(bool)

    def check_kafka_batch(
        self, endpoint_id: int, port: int, requests, *,
        ingress: bool = True,
    ) -> np.ndarray:
        """Native Kafka ACL enforcement → [B] bool allow (the same
        contract as KafkaACL.check_batch)."""
        from ..ops.dfa import strings_to_batch

        n = len(requests)
        tb, tl = strings_to_batch([r.topic.encode() for r in requests], 255)
        cb, cl = strings_to_batch(
            [r.client_id.encode() for r in requests], 255
        )
        api_key = np.ascontiguousarray([r.api_key for r in requests], np.int32)
        api_ver = np.ascontiguousarray(
            [r.api_version for r in requests], np.int32
        )
        src = np.ascontiguousarray(
            [r.src_identity for r in requests], np.uint64
        )
        allow = np.empty(n, np.uint8)
        self._lib.nf_l7_kafka_batch(
            self._h, endpoint_id, port, 1 if ingress else 0, n,
            _ptr(api_key, ctypes.c_int32), _ptr(api_ver, ctypes.c_int32),
            _ptr(np.ascontiguousarray(tb, np.uint8), ctypes.c_uint8), 255,
            _ptr(np.ascontiguousarray(tl, np.int32), ctypes.c_int32),
            _ptr(np.ascontiguousarray(cb, np.uint8), ctypes.c_uint8), 255,
            _ptr(np.ascontiguousarray(cl, np.int32), ctypes.c_int32),
            _ptr(src, ctypes.c_uint64), _ptr(allow, ctypes.c_uint8),
        )
        return allow.astype(bool)

    # -- evaluation -----------------------------------------------------
    def process(
        self,
        src_ips: np.ndarray,  # [B] uint32 IPv4 peer addresses
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
    ):
        """Same contract as DatapathPipeline.process → (verdict int8,
        redirect bool)."""
        peer = np.ascontiguousarray(
            ipv4_to_bytes(np.asarray(src_ips)), np.uint8
        )
        return self._eval(peer, 4, ep_idx, dports, protos, sports, ingress)

    def process_v6(
        self, peer_bytes: np.ndarray, ep_idx, dports, protos,
        *, ingress: bool = True, sports=None,
    ):
        peer = np.ascontiguousarray(peer_bytes, np.uint8)
        return self._eval(peer, 16, ep_idx, dports, protos, sports, ingress)

    def _eval(self, peer, stride, ep_idx, dports, protos, sports, ingress):
        n = peer.shape[0]
        ep_idx = np.ascontiguousarray(ep_idx, np.int32)
        dports = np.ascontiguousarray(dports, np.int32)
        protos = np.ascontiguousarray(protos, np.int32)
        verdict = np.empty(n, np.int8)
        redirect = np.empty(n, np.uint8)
        sp = (
            None if sports is None
            else np.ascontiguousarray(sports, np.int32)
        )
        self._lib.nf_eval_batch(
            self._h, n, _ptr(peer, ctypes.c_uint8), stride,
            _ptr(ep_idx, ctypes.c_int32), _ptr(dports, ctypes.c_int32),
            _ptr(protos, ctypes.c_int32),
            None if sp is None else _ptr(sp, ctypes.c_int32),
            1 if ingress else 0,
            _ptr(verdict, ctypes.c_int8), _ptr(redirect, ctypes.c_uint8),
        )
        return verdict, redirect.astype(bool)

    @property
    def counters(self) -> np.ndarray:
        out = np.zeros(max(1, self.ep_count) * 3, np.int64)
        self._lib.nf_counters(self._h, _ptr(out, ctypes.c_int64))
        return out.reshape(-1, 3)

    # -- convenience ----------------------------------------------------
    @classmethod
    def from_pipeline(
        cls, pipeline, *, ingress: bool = True, ct_bits: int = 18
    ) -> "NativeFastpath":
        """Snapshot a DatapathPipeline's realized state into a native
        front-end (both directions are loaded; `ingress` only selects
        which snapshot list defines endpoint order — they share it)."""
        from ..identity.model import ID_WORLD
        from ..ops.materialize import TRAFFIC_EGRESS

        pipeline.rebuild()
        ing = pipeline._mat[TRAFFIC_INGRESS].snapshots
        eg = pipeline._mat[TRAFFIC_EGRESS].snapshots
        from ..ops.materialize import EndpointPolicySnapshot

        nf = cls(ep_count=len(ing), ct_bits=ct_bits)
        nf.set_world_identity(ID_WORLD)
        # both directions share endpoint indices; merge entry dicts
        merged = [
            EndpointPolicySnapshot(
                entries={**a.entries, **b.entries}, slots=a.slots
            )
            for a, b in zip(ing, eg)
        ]
        nf.load_policy_snapshots(merged)
        nf.load_ipcache(pipeline.ipcache)
        nf.load_prefilter(pipeline.prefilter)
        nf.set_endpoint_ids(pipeline._endpoint_ids)
        if pipeline.lb is not None:
            nf.load_lb(pipeline.lb)
        return nf
