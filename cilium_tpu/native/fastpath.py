"""ctypes wrapper: the native enforcement front-end.

Consumes the SAME compiled state the device pipeline materializes —
per-endpoint policymap snapshots (ops/materialize.py) and the
ipcache/prefilter prefixes — and answers flow batches entirely in
native code: conntrack probe, deny LPM, identity LPM, 3-step
policymap lookup, per-endpoint counters. This is the SURVEY native
census item 1: the eBPF datapath role, re-hosted as a userspace C++
library fed by TPU-computed policy tensors. The device pipeline stays
the batch/cold path and the source of truth; this front-end is the
per-node enforcement loop a non-Python dataplane embeds.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from ..ops.lpm import TrieBuilder, ipv4_to_bytes
from ..ops.materialize import TRAFFIC_INGRESS
from . import build as _build

FORWARD = 1
DROP_POLICY = 2
DROP_PREFILTER = 3

_WHICH_IP4, _WHICH_IP6, _WHICH_DENY4, _WHICH_DENY6 = 0, 1, 2, 3


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


class NativeFastpath:
    """One loaded enforcement state (policy + tries + CT)."""

    def __init__(self, ep_count: int, ct_bits: int = 18) -> None:
        self._lib = _build.load()
        self._h = self._lib.nf_create(ep_count, ct_bits)
        self.ep_count = ep_count

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.nf_destroy(h)
            self._h = None

    # -- loading --------------------------------------------------------
    def set_world_identity(self, identity: int) -> None:
        self._lib.nf_set_world(self._h, identity)

    def load_policy_snapshots(self, snapshots: Sequence) -> int:
        """Load per-endpoint EndpointPolicySnapshot dicts (the
        realized policymap the TPU materialization produced); snapshot
        order defines the endpoint index, matching the pipeline.
        Raises if the C++ table dropped any entry (a dropped allow
        would silently misenforce). Any load flushes conntrack — the
        established-flow bypass is only sound while the verdict basis
        that admitted the flow still holds (same invariant as
        DatapathPipeline.rebuild)."""
        idents, eps, dports, protos, dirs, reds = [], [], [], [], [], []
        for ep_idx, snap in enumerate(snapshots):
            for key, red in snap.entries.items():
                idents.append(key.identity)
                eps.append(ep_idx)
                dports.append(key.dport)
                protos.append(key.nexthdr)
                dirs.append(key.direction)
                reds.append(1 if red else 0)
        n = len(idents)
        identity = np.asarray(idents, np.uint64)
        ep = np.asarray(eps, np.uint32)
        dport = np.asarray(dports, np.uint32)
        proto = np.asarray(protos, np.uint32)
        dir_ = np.asarray(dirs, np.uint32)
        red = np.asarray(reds, np.uint8)
        loaded = int(self._lib.nf_load_policy(
            self._h, n,
            _ptr(identity, ctypes.c_uint64), _ptr(ep, ctypes.c_uint32),
            _ptr(dport, ctypes.c_uint32), _ptr(proto, ctypes.c_uint32),
            _ptr(dir_, ctypes.c_uint32), _ptr(red, ctypes.c_uint8),
        ))
        if loaded != n:
            raise RuntimeError(
                f"native policy table dropped {n - loaded} of {n} entries "
                "(hash neighborhood overflow)"
            )
        self.ct_flush()
        return loaded

    def _load_trie(self, which: int, prefixes, levels: int) -> None:
        """prefixes: iterable of (cidr_string, value)."""
        import ipaddress

        tb = TrieBuilder(levels)
        for cidr, value in prefixes:
            net = ipaddress.ip_network(cidr, strict=False)
            tb.insert(net.network_address.packed, net.prefixlen, int(value))
        child, info = tb.arrays()
        child = np.ascontiguousarray(child, np.int32)
        info = np.ascontiguousarray(info, np.int32)
        self._lib.nf_load_trie(
            self._h, which, _ptr(child, ctypes.c_int32),
            _ptr(info, ctypes.c_int32), child.shape[0], levels,
        )

    def load_ipcache(self, ipcache) -> None:
        """IP→IDENTITY tries from the authoritative ipcache (values are
        identities, not device rows — this table is standalone).
        Empty lists STILL load (an empty trie): a reload that removed
        the last entry must not leave the previous trie enforcing
        stale mappings. Flushes conntrack (verdict basis moved)."""
        v4 = [(c, e.identity) for c, e in ipcache.items() if ":" not in c]
        v6 = [(c, e.identity) for c, e in ipcache.items() if ":" in c]
        self._load_trie(_WHICH_IP4, v4, 4)
        self._load_trie(_WHICH_IP6, v6, 16)
        self.ct_flush()

    def load_prefilter(self, prefilter) -> None:
        _, cidrs = prefilter.dump()
        v4 = [(c, 1) for c in cidrs if ":" not in c]
        v6 = [(c, 1) for c in cidrs if ":" in c]
        self._load_trie(_WHICH_DENY4, v4, 4)
        self._load_trie(_WHICH_DENY6, v6, 16)
        self.ct_flush()

    def ct_flush(self) -> None:
        self._lib.nf_ct_flush(self._h)

    def set_endpoint_ids(self, ids: Sequence[int]) -> None:
        """Stable endpoint ids per datapath index — the LB flow hash
        input (a positional index would re-pick backends on unrelated
        endpoint churn, same invariant as the device path)."""
        arr = np.ascontiguousarray(ids, np.uint32)
        self._lib.nf_set_endpoint_ids(
            self._h, arr.shape[0], _ptr(arr, ctypes.c_uint32)
        )

    def load_lb(self, manager) -> None:
        """Load the IPv4 service tables from a lb.ServiceManager —
        built through the SAME build_device() used by the device path
        so frontend order, selection sequences, and backend rows are
        bit-identical (deterministic hash ⇒ identical picks). Flushes
        conntrack (translated CT keys change with the tables).
        IPv6 service tables are NOT supported natively — refusing
        loudly beats silently diverging from the device path."""
        tables = manager.build_device()
        if tables.get(6) is not None:
            raise RuntimeError(
                "native front-end does not support IPv6 service tables"
            )
        t = tables.get(4)
        if t is None:
            self._lib.nf_load_lb(
                self._h, 0, 1,
                _ptr(np.zeros(1, np.uint32), ctypes.c_uint32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
                0,
                _ptr(np.zeros(1, np.uint32), ctypes.c_uint32),
                _ptr(np.zeros(1, np.int32), ctypes.c_int32),
            )
            self.ct_flush()
            return
        fe_bytes = np.asarray(t.fe_bytes, np.uint32)
        fe_addr = np.ascontiguousarray(
            (fe_bytes[:, 0] << 24) | (fe_bytes[:, 1] << 16)
            | (fe_bytes[:, 2] << 8) | fe_bytes[:, 3], np.uint32
        )
        be_bytes = np.asarray(t.be_bytes, np.uint32)
        be_addr = np.ascontiguousarray(
            (be_bytes[:, 0] << 24) | (be_bytes[:, 1] << 16)
            | (be_bytes[:, 2] << 8) | be_bytes[:, 3], np.uint32
        )
        fe_port = np.ascontiguousarray(t.fe_port, np.int32)
        fe_proto = np.ascontiguousarray(t.fe_proto, np.int32)
        fe_seq = np.ascontiguousarray(t.fe_seq, np.int32)
        fe_seq_len = np.ascontiguousarray(t.fe_seq_len, np.int32)
        fe_revnat = np.ascontiguousarray(t.fe_revnat, np.int32)
        be_port = np.ascontiguousarray(t.be_port, np.int32)
        self._lib.nf_load_lb(
            self._h, fe_addr.shape[0], fe_seq.shape[1],
            _ptr(fe_addr, ctypes.c_uint32), _ptr(fe_port, ctypes.c_int32),
            _ptr(fe_proto, ctypes.c_int32), _ptr(fe_seq, ctypes.c_int32),
            _ptr(fe_seq_len, ctypes.c_int32),
            _ptr(fe_revnat, ctypes.c_int32),
            be_addr.shape[0], _ptr(be_addr, ctypes.c_uint32),
            _ptr(be_port, ctypes.c_int32),
        )
        self.ct_flush()

    # -- evaluation -----------------------------------------------------
    def process(
        self,
        src_ips: np.ndarray,  # [B] uint32 IPv4 peer addresses
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
    ):
        """Same contract as DatapathPipeline.process → (verdict int8,
        redirect bool)."""
        peer = np.ascontiguousarray(
            ipv4_to_bytes(np.asarray(src_ips)), np.uint8
        )
        return self._eval(peer, 4, ep_idx, dports, protos, sports, ingress)

    def process_v6(
        self, peer_bytes: np.ndarray, ep_idx, dports, protos,
        *, ingress: bool = True, sports=None,
    ):
        peer = np.ascontiguousarray(peer_bytes, np.uint8)
        return self._eval(peer, 16, ep_idx, dports, protos, sports, ingress)

    def _eval(self, peer, stride, ep_idx, dports, protos, sports, ingress):
        n = peer.shape[0]
        ep_idx = np.ascontiguousarray(ep_idx, np.int32)
        dports = np.ascontiguousarray(dports, np.int32)
        protos = np.ascontiguousarray(protos, np.int32)
        verdict = np.empty(n, np.int8)
        redirect = np.empty(n, np.uint8)
        sp = (
            None if sports is None
            else np.ascontiguousarray(sports, np.int32)
        )
        self._lib.nf_eval_batch(
            self._h, n, _ptr(peer, ctypes.c_uint8), stride,
            _ptr(ep_idx, ctypes.c_int32), _ptr(dports, ctypes.c_int32),
            _ptr(protos, ctypes.c_int32),
            None if sp is None else _ptr(sp, ctypes.c_int32),
            1 if ingress else 0,
            _ptr(verdict, ctypes.c_int8), _ptr(redirect, ctypes.c_uint8),
        )
        return verdict, redirect.astype(bool)

    @property
    def counters(self) -> np.ndarray:
        out = np.zeros(max(1, self.ep_count) * 3, np.int64)
        self._lib.nf_counters(self._h, _ptr(out, ctypes.c_int64))
        return out.reshape(-1, 3)

    # -- convenience ----------------------------------------------------
    @classmethod
    def from_pipeline(
        cls, pipeline, *, ingress: bool = True, ct_bits: int = 18
    ) -> "NativeFastpath":
        """Snapshot a DatapathPipeline's realized state into a native
        front-end (both directions are loaded; `ingress` only selects
        which snapshot list defines endpoint order — they share it)."""
        from ..identity.model import ID_WORLD
        from ..ops.materialize import TRAFFIC_EGRESS

        pipeline.rebuild()
        ing = pipeline._mat[TRAFFIC_INGRESS].snapshots
        eg = pipeline._mat[TRAFFIC_EGRESS].snapshots
        from ..ops.materialize import EndpointPolicySnapshot

        nf = cls(ep_count=len(ing), ct_bits=ct_bits)
        nf.set_world_identity(ID_WORLD)
        # both directions share endpoint indices; merge entry dicts
        merged = [
            EndpointPolicySnapshot(
                entries={**a.entries, **b.entries}, slots=a.slots
            )
            for a, b in zip(ing, eg)
        ]
        nf.load_policy_snapshots(merged)
        nf.load_ipcache(pipeline.ipcache)
        nf.load_prefilter(pipeline.prefilter)
        nf.set_endpoint_ids(pipeline._endpoint_ids)
        if pipeline.lb is not None:
            nf.load_lb(pipeline.lb)
        return nf
