from .registry import Node, NodeRegistry, NODES_PATH

__all__ = ["Node", "NodeRegistry", "NODES_PATH"]
