"""Cluster node registry over the shared store.

Re-design of /root/reference/pkg/node (store.go:60 registerNode,
manager.go:62 cluster node manager): the local node registers itself —
name, cluster, addresses, per-family allocation CIDRs — as a
lease-bound shared-store key, and observes every other node. Observers
get add/update/delete callbacks; the datapath consumer uses them to
maintain tunnel-endpoint state (the tunnel-map role) so remote-node
prefixes resolve to a host IP.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional

from ..kvstore.backend import BackendOperations
from ..kvstore.store import SharedStore

from ..kvstore.paths import NODES_PATH


@dataclasses.dataclass(frozen=True)
class Node:
    """node.Node (pkg/node/node.go): addressing facts other nodes need."""

    name: str
    cluster: str = "default"
    ipv4: Optional[str] = None
    ipv6: Optional[str] = None
    health_ip: Optional[str] = None
    # port of the node's cilium-health responder; None = the default
    # 4240 (single-host test clusters need per-node ports)
    health_port: Optional[int] = None
    ipv4_alloc_cidr: Optional[str] = None
    ipv6_alloc_cidr: Optional[str] = None

    @property
    def key_name(self) -> str:
        # store.go GetKeyName: cluster/name — STABLE API in the reference
        return f"{self.cluster}/{self.name}"

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(**{f.name: d.get(f.name) for f in dataclasses.fields(cls)
                      if f.name in d})


# fn(node, present)  — present=False on delete
NodeObserver = Callable[[Node, bool], None]


class NodeRegistry:
    """One node's membership + view of the cluster."""

    def __init__(
        self,
        backend: BackendOperations,
        local: Node,
        *,
        base_path: str = NODES_PATH,
    ) -> None:
        self.local = local
        self._lock = threading.RLock()
        self._observers: List[NodeObserver] = []
        self.nodes: Dict[str, Node] = {}
        self.store = SharedStore(
            backend,
            base_path,
            on_update=self._on_update,
            on_delete=self._on_delete,
        )
        self.store.update_local_key_sync(local.key_name, local.to_dict())
        self.pump()

    # ------------------------------------------------------------------
    def _on_update(self, name: str, value: dict) -> None:
        node = Node.from_dict(value)
        with self._lock:
            self.nodes[name] = node
            obs = list(self._observers)
        for fn in obs:
            fn(node, True)

    def _on_delete(self, name: str, old: Optional[dict]) -> None:
        with self._lock:
            node = self.nodes.pop(name, None)
            obs = list(self._observers)
        if node is None and old is not None:
            node = Node.from_dict(old)
        if node is not None:
            for fn in obs:
                fn(node, False)

    def observe(self, fn: NodeObserver, replay: bool = True) -> None:
        with self._lock:
            self._observers.append(fn)
            current = list(self.nodes.values()) if replay else []
        for node in current:
            fn(node, True)

    def pump(self) -> int:
        return self.store.pump()

    def remote_nodes(self) -> List[Node]:
        with self._lock:
            return [n for n in self.nodes.values() if n.name != self.local.name]

    def get(self, cluster: str, name: str) -> Optional[Node]:
        with self._lock:
            return self.nodes.get(f"{cluster}/{name}")

    def announce_local(self, node: Node) -> None:
        """Replace this node's cluster announcement (store.go
        registerNode re-announce — e.g. once the health sidecar's port
        is known)."""
        self.local = node
        self.store.update_local_key_sync(node.key_name, node.to_dict())

    def unregister(self) -> None:
        self.store.delete_local_key(self.local.key_name)

    def resync(self) -> int:
        return self.store.sync_local_keys()

    def close(self) -> None:
        self.store.close()
