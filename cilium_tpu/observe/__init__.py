"""policyd-trace: verdict-path observability (see README.md here).

Span tracer + phase-timing telemetry for the datapath. Import-light by
design (stdlib only) — the CLI and the analysis tooling import this
without pulling JAX.
"""

from .flows import FlowRecord, FlowRing, SAMPLE_CAP
from .profiler import DeviceProfiler
from .tracer import BatchTrace, NOOP_BATCH, Tracer

__all__ = [
    "BatchTrace",
    "DeviceProfiler",
    "FlowRecord",
    "FlowRing",
    "NOOP_BATCH",
    "SAMPLE_CAP",
    "Tracer",
]
