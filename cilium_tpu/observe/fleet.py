"""policyd-fleetobs: SLO burn rates + the fleet telemetry exchange.

Three layers, bottom-up:

- :class:`SLOEvaluator` — multi-window burn rates over declared
  objectives (verdict latency p99, drop-mix ratio, epoch lag, restart
  downtime), read from a :class:`~.timeseries.TimeSeriesRing`. Burn
  ratio is observed/target per window; the state machine is the
  classic multi-window alert: *burning* only when both the shortest
  AND the longest window exceed budget (a sustained burn that is still
  happening), *warn* when any single window does, *ok* otherwise.
  Ratios surface as the ``cilium_tpu_slo_burn_ratio{objective,window}``
  gauge family.

- :class:`FleetSampler` — the cadence thread the ``FleetTelemetry``
  runtime option starts: every ``interval_s`` it snapshots the
  process-wide metric families into the ring (counter totals through
  reset-safe :class:`~.timeseries.CounterDelta`), re-evaluates the
  SLOs, and (when a :class:`TelemetryExchange` is attached) publishes
  one frame. This module is ONLY imported when the option turns on —
  the daemon's OFF path never touches it (the tripwire test pins
  that).

- :class:`TelemetryExchange` + :func:`aggregate` — each daemon
  publishes a compact versioned frame (counter-derived rates +
  quantiles + SLO states + policy_epoch + pipeline_mode, stamped with
  node id and a monotonic frame seq) through a federation
  :class:`~..kvstore.store.SharedStore` under
  ``CLUSTER_TELEMETRY_PATH`` — beside its epoch-exchange node
  descriptor. ``aggregate`` folds every live (non-stale,
  version-compatible) frame into one scoreboard: fleet vps, per-node
  health grid, epoch skew, worst burn. A killed node's frames age out
  by wall-clock ``ts`` long before its kvstore lease dies, so the
  scoreboard heals in seconds, not lease-TTLs.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from .. import metrics as _metrics
from ..kvstore.paths import CLUSTER_TELEMETRY_PATH
from ..kvstore.store import SharedStore
from .timeseries import WINDOWS, CounterDelta, TimeSeriesRing

log = logging.getLogger(__name__)

_KV_DOWN = (ConnectionError, TimeoutError, OSError, RuntimeError)

# -- SLO evaluation ---------------------------------------------------------

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_BURNING = "burning"
_STATE_RANK = {STATE_OK: 0, STATE_WARN: 1, STATE_BURNING: 2}


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declared objective: ``field`` of the sampler ring, reduced
    per window with ``reduce``, burning budget at ``target`` (same
    unit as the field). Burn ratio = reduced value / target."""

    name: str
    field: str
    target: float
    reduce: str = "mean"


# The declared objective set (ISSUE: verdict latency p99, drop-mix
# ratio, epoch lag, restart downtime). Targets are deliberately
# generous defaults — operators tune per deployment via the
# FleetSampler ctor; the STATES are the contract, not the numbers.
DEFAULT_OBJECTIVES: Tuple[SLObjective, ...] = (
    SLObjective("verdict_latency_p99", "verdict_p99_ms", 50.0, "max"),
    SLObjective("drop_mix_ratio", "drop_ratio", 0.5, "mean"),
    SLObjective("epoch_lag", "epoch_lag", 2.0, "max"),
    SLObjective("restart_downtime", "restart_downtime_s", 5.0, "max"),
)


class SLOEvaluator:
    """Multi-window burn-rate evaluation over one sampler ring."""

    def __init__(
        self,
        ring: TimeSeriesRing,
        objectives: Tuple[SLObjective, ...] = DEFAULT_OBJECTIVES,
        windows: Tuple[Tuple[str, float], ...] = WINDOWS,
    ) -> None:
        for obj in objectives:
            if obj.target <= 0:
                raise ValueError(f"objective {obj.name!r}: target must be > 0")
        self.ring = ring
        self.objectives = tuple(objectives)
        self.windows = tuple(windows)

    def evaluate(self, now: Optional[float] = None) -> Dict:
        """Evaluate every objective over every window; refresh the
        ``slo_burn_ratio`` gauge family; return the full result:
        ``{"objectives": {...}, "worst": {...}, "burning": bool}``."""
        short, long_ = self.windows[0][0], self.windows[-1][0]
        per: Dict[str, Dict] = {}
        for obj in self.objectives:
            ratios: Dict[str, float] = {}
            for label, secs in self.windows:
                v = self.ring.reduce(obj.field, obj.reduce, secs, now)
                r = 0.0 if v is None else max(0.0, float(v) / obj.target)
                ratios[label] = round(r, 6)
                _metrics.slo_burn_ratio.set(
                    ratios[label], {"objective": obj.name, "window": label}
                )
            if ratios[short] >= 1.0 and ratios[long_] >= 1.0:
                state = STATE_BURNING
            elif any(r >= 1.0 for r in ratios.values()):
                state = STATE_WARN
            else:
                state = STATE_OK
            per[obj.name] = {
                "state": state,
                "windows": ratios,
                "worst_ratio": max(ratios.values()),
            }
        worst_name = max(
            per,
            key=lambda n: (_STATE_RANK[per[n]["state"]], per[n]["worst_ratio"]),
        )
        worst = {
            "objective": worst_name,
            "state": per[worst_name]["state"],
            "ratio": per[worst_name]["worst_ratio"],
        }
        return {
            "objectives": per,
            "worst": worst,
            "burning": worst["state"] == STATE_BURNING,
        }


# -- telemetry frame codec --------------------------------------------------

FRAME_VERSION = 1


def encode_frame(
    node: str,
    seq: int,
    body: Mapping,
    *,
    cluster: str = "default",
    ts: Optional[float] = None,
) -> Dict:
    """One wire frame: version + identity stamp + the sampler body."""
    frame: Dict = dict(body)
    frame.update(
        {
            "v": FRAME_VERSION,
            "node": node,
            "cluster": cluster,
            "seq": int(seq),
            # wall clock on purpose: staleness must compare across
            # processes, which monotonic clocks never do
            "ts": time.time() if ts is None else float(ts),
        }
    )
    return frame


def decode_frame(rec) -> Optional[Dict]:
    """Validate one stored record back into a frame; None for version
    mismatches and malformed stamps (the aggregator counts these as
    ``telemetry_frames_total{result="rejected"}``)."""
    if not isinstance(rec, dict) or rec.get("v") != FRAME_VERSION:
        return None
    node = rec.get("node")
    if not isinstance(node, str) or not node:
        return None
    try:
        int(rec["seq"])
        float(rec["ts"])
    except (KeyError, TypeError, ValueError):
        return None
    return dict(rec)


# -- the exchange -----------------------------------------------------------


class TelemetryExchange:
    """One node's frame publication + its view of every peer's frames,
    over a SharedStore under ``CLUSTER_TELEMETRY_PATH`` (the sibling
    of the epoch exchange's node-descriptor records)."""

    def __init__(
        self,
        backend,
        node_name: str,
        *,
        cluster: str = "default",
        base_path: str = CLUSTER_TELEMETRY_PATH,
        stale_s: float = 15.0,
    ) -> None:
        self.node_name = node_name
        self.cluster = cluster
        self.stale_s = float(stale_s)
        self.key_name = f"{cluster}/{node_name}"
        self._seq = 0
        self.store = SharedStore(backend, base_path)

    def publish(self, body: Mapping, *, ts: Optional[float] = None) -> bool:
        """Publish one frame (lease-bound; dies with the node). False
        when the kvstore is down — the sampler keeps ticking locally
        and the next successful publish carries a later seq."""
        self._seq += 1
        frame = encode_frame(
            self.node_name, self._seq, body, cluster=self.cluster, ts=ts
        )
        try:
            self.store.update_local_key_sync(self.key_name, frame)
        except _KV_DOWN:
            _metrics.telemetry_frames_total.inc({"result": "publish_error"})
            return False
        _metrics.telemetry_frames_total.inc({"result": "published"})
        return True

    def pump(self) -> int:
        """Apply pending peer frame events; returns events applied."""
        return self.store.pump()

    def frames(
        self, *, now: Optional[float] = None, stale_s: Optional[float] = None
    ) -> Dict[str, Dict]:
        """node → live decoded frame. Rejects version mismatches and
        ages out frames older than ``stale_s`` — a kill -9'd node
        disappears here within seconds, while its lease-bound record
        lingers until the kvstore lease expires."""
        ref = time.time() if now is None else float(now)
        horizon = self.stale_s if stale_s is None else float(stale_s)
        out: Dict[str, Dict] = {}
        for rec in dict(self.store.shared).values():
            f = decode_frame(rec)
            if f is None:
                _metrics.telemetry_frames_total.inc({"result": "rejected"})
                continue
            if f.get("cluster") != self.cluster:
                continue
            if ref - f["ts"] > horizon:
                _metrics.telemetry_frames_total.inc({"result": "stale"})
                continue
            out[f["node"]] = f
        return out

    def sync(self) -> int:
        """Anti-entropy re-write of our frame (heartbeat path)."""
        return self.store.sync_local_keys()

    def close(self) -> None:
        try:
            self.store.delete_local_key(self.key_name)
        except _KV_DOWN:
            pass  # backend gone; the lease reaps our record
        self.store.close()


# -- fleet aggregation ------------------------------------------------------


def aggregate(frames: Mapping[str, Dict], *, now: Optional[float] = None) -> Dict:
    """Fold live frames into the fleet scoreboard (the GET /fleet body
    and the bench --fleetobs substrate). Refreshes the
    ``fleet_nodes_reporting`` gauge as a side effect."""
    ref = time.time() if now is None else float(now)
    rows: List[Dict] = []
    worst = {"objective": None, "state": STATE_OK, "ratio": 0.0, "node": None}
    fleet_vps = 0.0
    epochs: List[int] = []
    lag_max = 0.0
    for name in sorted(frames):
        f = frames[name]
        slo = f.get("slo") or {}
        w = slo.get("worst") or {}
        state = w.get("state", STATE_OK)
        ratio = float(w.get("ratio", 0.0))
        if (_STATE_RANK.get(state, 0), ratio) > (
            _STATE_RANK.get(worst["state"], 0),
            worst["ratio"],
        ):
            worst = {
                "objective": w.get("objective"),
                "state": state,
                "ratio": ratio,
                "node": name,
            }
        vps = float(f.get("vps", 0.0))
        fleet_vps += vps
        if "policy_epoch" in f:
            epochs.append(int(f["policy_epoch"]))
        lag_max = max(lag_max, float(f.get("epoch_lag", 0.0)))
        rows.append(
            {
                "node": name,
                "seq": int(f["seq"]),
                "age_s": round(max(0.0, ref - f["ts"]), 3),
                "vps": round(vps, 3),
                "drop_ratio": float(f.get("drop_ratio", 0.0)),
                "verdict_p99_ms": f.get("verdict_p99_ms"),
                "pipeline_mode": f.get("pipeline_mode"),
                "policy_epoch": f.get("policy_epoch"),
                "epoch_lag": f.get("epoch_lag"),
                "slo_state": state,
                "worst_objective": w.get("objective"),
            }
        )
    _metrics.fleet_nodes_reporting.set(float(len(rows)))
    return {
        "nodes_reporting": len(rows),
        "fleet_vps": round(fleet_vps, 3),
        "epoch_skew": (max(epochs) - min(epochs)) if epochs else 0,
        "epoch_lag_max": lag_max,
        "worst_burn": worst,
        "nodes": rows,
    }


# -- the sampler ------------------------------------------------------------

# The ring's field vocabulary: one column per sampled signal. Derived
# rates are computed at sample time (counter deltas / tick dt) so the
# ring holds directly-reducible values.
SAMPLE_FIELDS: Tuple[str, ...] = (
    "vps",
    "drop_ratio",
    "shed_ratio",
    "verdict_p50_ms",
    "verdict_p99_ms",
    "pipeline_mode",
    "epoch_lag",
    "transfer_bps",
    "restart_downtime_s",
)


def _series_sum(counter, pred: Optional[Callable[[Dict], bool]] = None) -> float:
    total = 0.0
    for key, v in counter.series().items():
        if pred is None or pred(dict(key)):
            total += v
    return total


class FleetSampler:
    """The ``FleetTelemetry`` cadence thread: snapshot → ring → SLO →
    (optionally) publish one frame. ``sample_once`` is the whole tick
    and is directly callable for deterministic tests."""

    def __init__(
        self,
        *,
        interval_s: float = 1.0,
        capacity: int = 600,
        objectives: Tuple[SLObjective, ...] = DEFAULT_OBJECTIVES,
        epoch_source: Optional[Callable[[], int]] = None,
    ) -> None:
        self.interval_s = float(interval_s)
        self.ring = TimeSeriesRing(SAMPLE_FIELDS, capacity)
        self.slo = SLOEvaluator(self.ring, objectives)
        self._epoch_source = epoch_source or (lambda: 0)
        self.exchange: Optional[TelemetryExchange] = None
        self._d_verdicts = CounterDelta()
        self._d_dropped = CounterDelta()
        self._d_shed = CounterDelta()
        self._d_xfer = CounterDelta()
        self._last_ts: Optional[float] = None
        self.last_slo: Optional[Dict] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # -- wiring ---------------------------------------------------------
    def attach_exchange(self, exchange: Optional[TelemetryExchange]) -> None:
        self.exchange = exchange

    # -- one tick -------------------------------------------------------
    def sample_once(self, now: Optional[float] = None) -> Dict:
        """Snapshot the metric families into the ring, re-evaluate the
        SLOs, publish a frame when an exchange is attached. Returns the
        appended sample (tests assert on it)."""
        with self._lock:
            ts = time.monotonic() if now is None else float(now)
            dt = (
                self.interval_s
                if self._last_ts is None
                else max(ts - self._last_ts, 1e-9)
            )
            self._last_ts = ts

            dv = self._d_verdicts.update(_series_sum(_metrics.verdicts_total))
            dd = self._d_dropped.update(
                _series_sum(
                    _metrics.verdicts_total,
                    lambda k: k.get("outcome", "").startswith("dropped"),
                )
            )
            ds = self._d_shed.update(_series_sum(_metrics.admission_shed_total))
            dx = self._d_xfer.update(
                _series_sum(_metrics.device_transfer_bytes_total)
            )
            p50 = _metrics.batch_total_seconds.quantile(0.5)
            p99 = _metrics.batch_total_seconds.quantile(0.99)
            sample = {
                "vps": dv / dt,
                "drop_ratio": (dd / dv) if dv > 0 else 0.0,
                "shed_ratio": (ds / (dv + ds)) if (dv + ds) > 0 else 0.0,
                "verdict_p50_ms": None if p50 is None else p50 * 1e3,
                "verdict_p99_ms": None if p99 is None else p99 * 1e3,
                "pipeline_mode": _metrics.pipeline_mode.get(),
                "epoch_lag": _metrics.cluster_epoch_lag.get(),
                "transfer_bps": dx / dt,
                "restart_downtime_s": _metrics.restart_downtime_seconds.get(),
            }
            self.ring.append(ts, sample)
            _metrics.timeseries_snapshots_total.inc()
            self.last_slo = self.slo.evaluate(now=ts)

            if self.exchange is not None:
                self.exchange.publish(self.frame_body())
                try:
                    self.exchange.pump()
                except _KV_DOWN:
                    pass  # partition: keep sampling; frames age out
            return sample

    def frame_body(self) -> Dict:
        """The compact per-node payload ``aggregate`` consumes."""
        r = self.ring

        def nz(v: Optional[float]) -> float:
            return 0.0 if v is None else round(float(v), 6)

        slo = self.last_slo or {}
        return {
            "vps": nz(r.reduce("vps", "mean", WINDOWS[0][1])),
            "drop_ratio": nz(r.reduce("drop_ratio", "mean", WINDOWS[0][1])),
            "shed_ratio": nz(r.reduce("shed_ratio", "mean", WINDOWS[0][1])),
            "verdict_p50_ms": r.last("verdict_p50_ms"),
            "verdict_p99_ms": r.last("verdict_p99_ms"),
            "pipeline_mode": nz(r.last("pipeline_mode")),
            "epoch_lag": nz(r.last("epoch_lag")),
            "policy_epoch": int(self._epoch_source()),
            "slo": {
                "worst": slo.get("worst"),
                "states": {
                    name: o["state"]
                    for name, o in (slo.get("objectives") or {}).items()
                },
            },
        }

    # -- surfaces -------------------------------------------------------
    def slo_summary(self) -> Dict:
        """The one-line /status block: worst objective + state."""
        slo = self.last_slo or self.slo.evaluate()
        w = slo["worst"]
        return {
            "worst_objective": w["objective"],
            "state": w["state"],
            "ratio": w["ratio"],
            "burning": slo["burning"],
        }

    def local_status(self) -> Dict:
        return {
            "interval_s": self.interval_s,
            "samples": self.ring.appended,
            "capacity": self.ring.capacity,
            "slo": self.slo_summary(),
        }

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # a telemetry tick must never take the process down;
                # the next tick retries with fresh state
                log.exception("fleet sampler tick failed")

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)
        if self.exchange is not None:
            try:
                self.exchange.close()
            except _KV_DOWN:
                pass
            self.exchange = None
