# policyd: hot
"""Flow log ring for verdict attribution (policyd-flows).

Structured per-flow records — who talked to whom, what the verdict
was, WHICH rule decided it and why — sampled from the datapath
pipeline's completion half while the ``FlowAttribution`` runtime
option is on, held in a bounded ring, and served by ``GET /flows`` /
``cilium-tpu flows``. The reference analog is Hubble's flow buffer
over the perf ring (the observe/ side of cilium/hubble), reduced to
the policy-verdict fields this engine actually attributes.

Cost model mirrors observe/tracer.py: the pipeline reads ONE
attribute per batch — ``ring.active`` — and skips everything when
attribution is off. Records are only constructed for the sampled
subset (at most ``SAMPLE_CAP`` per batch, drops preferred), so the
per-batch host cost is O(sample), never O(B).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# Per-batch sampling bound: the completion half records at most this
# many flows per completed batch (drops first — they are the rare,
# interesting ones), so a 4M-flow batch costs the same host time as a
# 64-flow batch.
SAMPLE_CAP = 64


@dataclasses.dataclass
class FlowRecord:
    """One attributed flow. ``verdict`` uses the pipeline outcome codes
    (datapath/pipeline.py FORWARD/DROP_*); ``reason``/``reason_name``
    the stable policyd-flows taxonomy (ops/verdict.py ATTR_* mapped to
    monitor reason codes); ``rule_index``/``rule_origin`` the deciding
    repository rule (-1 / None when no rule matched)."""

    ts: float
    direction: str  # "ingress" | "egress"
    src_identity: int
    dst_identity: int
    src_labels: Tuple[str, ...]
    dst_labels: Tuple[str, ...]
    src_ip: str  # peer address for ingress flows ("" when unknown)
    dst_ip: str  # peer address for egress flows ("" when unknown)
    dport: int
    proto: int
    verdict: int
    verdict_name: str
    reason: int
    reason_name: str
    rule_index: int
    rule_origin: Optional[dict]

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["src_labels"] = list(self.src_labels)
        d["dst_labels"] = list(self.dst_labels)
        return d


class FlowRing:
    """Bounded ring of FlowRecords. ``active`` is a plain attribute
    (the hub/tracer pattern): the pipeline's attribution-off cost is
    one attribute read per batch."""

    def __init__(self, capacity: int = 1024) -> None:
        self.active = False
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.recorded = 0  # total records pushed (sampling visibility)

    def enable(self) -> None:
        self.active = True

    def disable(self) -> None:
        self.active = False

    def push(self, rec: FlowRecord) -> None:
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    def push_many(self, recs: List[FlowRecord]) -> None:
        with self._lock:
            self._ring.extend(recs)
            self.recorded += len(recs)

    def query(
        self,
        limit: int = 64,
        *,
        verdict: Optional[int] = None,
        from_identity: Optional[int] = None,
        reason: Optional[int] = None,
    ) -> List[Dict]:
        """Newest-last records matching every given filter, bounded by
        ``limit`` (filters apply BEFORE the limit, so asking for the
        last 10 drops scans the whole ring, not the last 10 records).
        ``verdict`` is an exact pipeline outcome code, or any negative
        value for "every drop outcome" (the `flows --verdict drop`
        filter; matched via verdict_name so this module stays free of
        pipeline imports)."""
        with self._lock:
            items = list(self._ring)
        if verdict is not None:
            if verdict < 0:
                items = [r for r in items
                         if r.verdict_name.startswith("dropped")]
            else:
                items = [r for r in items if r.verdict == verdict]
        if from_identity is not None:
            items = [r for r in items if r.src_identity == from_identity]
        if reason is not None:
            items = [r for r in items if r.reason == reason]
        if limit is not None and limit >= 0:
            items = items[-limit:]
        return [r.to_dict() for r in items]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def now() -> float:
    return time.time()
