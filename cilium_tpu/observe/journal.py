"""policyd-journal: causally-ordered lifecycle event journal.

Numeric telemetry (observe/fleet.py) answers "how fast, how hot";
chaos and rolling-upgrade assertions need the OTHER half: the discrete
lifecycle transitions — drain → snapshot → kill → restore → rejoin —
as a machine-checkable sequence. Three layers, bottom-up:

- :class:`HLC` — a hybrid logical clock ``(physical_ms, logical)``.
  Local ticks are monotone even when the wall clock steps backwards;
  the receive rule (:meth:`HLC.observe`) folds timestamps seen on peer
  frames so events emitted after hearing from a skewed peer still
  order after that peer's events. Merge order is the total order
  ``(hlc, node, seq)``.

- :class:`EventJournal` — a bounded, schema-versioned ring of
  structured events ``(seq, wall_ts, hlc, node, kind, severity,
  attrs)``. ``kind`` must be a :data:`~..contracts.JOURNAL_KINDS` row
  (lint rule OBS003 pins the emit sites); ``attrs`` carries the
  correlating bases the repo already maintains (policy_epoch,
  _mat_basis, placement generation, pipeline_mode, CT basis_match).
  Ring overflow is accounted in ``journal_dropped_total`` — the tail
  is complete iff that counter stayed zero.

- :class:`JournalExchange` + :class:`JournalPublisher` +
  :func:`merge_timelines` — each daemon publishes its journal tail as
  a compact versioned frame through a federation SharedStore under
  ``CLUSTER_JOURNAL_PATH`` (the telemetry exchange's sibling);
  ``merge_timelines`` folds every live peer frame into one
  HLC-total-ordered fleet timeline (``cilium-tpu fleet timeline``,
  ``GET /fleet/timeline``, bench --fleetobs ``timeline_merge_ok``).

This module is ONLY imported when the ``LifecycleJournal`` runtime
option turns on — the daemon's OFF path never touches it (the
tripwire test pins ``cilium_tpu.observe.journal`` out of
``sys.modules``), and hot modules reach it only through a None-guarded
``on_journal`` slot.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from .. import metrics as _metrics
from ..contracts import JOURNAL_KINDS, JOURNAL_SEVERITIES
from ..kvstore.paths import CLUSTER_JOURNAL_PATH
from ..kvstore.store import SharedStore

log = logging.getLogger(__name__)

_KV_DOWN = (ConnectionError, TimeoutError, OSError, RuntimeError)

# Event record schema version: bumped when the event tuple shape
# changes. Stamped on snapshots, frames, and bugtool events.json so
# offline consumers can diff archives across daemon versions.
SCHEMA_VERSION = 1

_KIND_SET = frozenset(JOURNAL_KINDS)
_SEV_SET = frozenset(JOURNAL_SEVERITIES)


# -- hybrid logical clock ---------------------------------------------------


class HLC:
    """Hybrid logical clock: ``(l, c)`` where ``l`` is the max physical
    millisecond timestamp seen and ``c`` breaks ties. Monotone under
    wall-clock regression; :meth:`observe` is the message-receive rule
    that makes cross-node merge order causally consistent."""

    __slots__ = ("_clock", "_l", "_c", "_lock")

    def __init__(self, clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.time
        self._l = 0
        self._c = 0
        self._lock = threading.Lock()

    def _pt(self) -> int:
        return int(self._clock() * 1000.0)

    def tick(self) -> Tuple[int, int]:
        """Timestamp one local event."""
        pt = self._pt()
        with self._lock:
            if pt > self._l:
                self._l, self._c = pt, 0
            else:
                self._c += 1
            return self._l, self._c

    def observe(self, l: int, c: int) -> Tuple[int, int]:
        """Fold a timestamp seen on a peer's event (receive rule):
        local events emitted after this call order after ``(l, c)``
        even when the peer's wall clock runs ahead of ours."""
        l, c = int(l), int(c)
        pt = self._pt()
        with self._lock:
            nl = max(self._l, l, pt)
            if nl == self._l and nl == l:
                self._c = max(self._c, c) + 1
            elif nl == self._l:
                self._c += 1
            elif nl == l:
                self._c = c + 1
            else:
                self._c = 0
            self._l = nl
            return self._l, self._c

    def read(self) -> Tuple[int, int]:
        with self._lock:
            return self._l, self._c


def order_key(ev: Mapping) -> Tuple[int, int, str, int]:
    """The HLC total order a merged timeline sorts by: ``(l, c, node,
    seq)`` — deterministic for any frame arrival order."""
    hlc = ev.get("hlc") or (0, 0)
    return (
        int(hlc[0]),
        int(hlc[1]),
        str(ev.get("node", "")),
        int(ev.get("seq", 0)),
    )


# -- the journal ring -------------------------------------------------------


class EventJournal:
    """Bounded ring of structured lifecycle events. ``emit`` is safe
    from any thread; eviction of the oldest event is accounted in
    ``journal_dropped_total``."""

    def __init__(
        self,
        *,
        node: str = "local",
        capacity: int = 512,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if capacity < 1:
            raise ValueError("journal capacity must be >= 1")
        self.node = str(node)
        self.capacity = int(capacity)
        self._clock = clock or time.time
        self.hlc = HLC(clock=self._clock)
        self._events: deque = deque()
        self._lock = threading.Lock()
        self.seq = 0
        self.dropped = 0

    def emit(
        self,
        *,
        kind: str,
        severity: str = "info",
        attrs: Optional[Mapping] = None,
    ) -> Dict:
        """Record one event. ``kind`` must be a JOURNAL_KINDS row and
        ``severity`` a JOURNAL_SEVERITIES row — both bound the
        ``journal_events_total`` label space."""
        if kind not in _KIND_SET:
            raise ValueError(f"unknown journal kind {kind!r}")
        if severity not in _SEV_SET:
            raise ValueError(f"unknown journal severity {severity!r}")
        l, c = self.hlc.tick()
        ev: Dict = {
            "seq": 0,
            "wall_ts": round(float(self._clock()), 6),
            "hlc": [l, c],
            "node": self.node,
            "kind": kind,
            "severity": severity,
            "attrs": dict(attrs or {}),
        }
        with self._lock:
            self.seq += 1
            ev["seq"] = self.seq
            self._events.append(ev)
            if len(self._events) > self.capacity:
                self._events.popleft()
                self.dropped += 1
                _metrics.journal_dropped_total.inc()
        _metrics.journal_events_total.inc(
            {"kind": kind, "severity": severity}
        )
        return ev

    def events(
        self,
        limit: int = 64,
        *,
        kind: Optional[str] = None,
        severity: Optional[str] = None,
        since: Optional[float] = None,
    ) -> List[Dict]:
        """The newest ``limit`` events matching the filters, oldest
        first (the GET /events body)."""
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if severity is not None:
            evs = [e for e in evs if e["severity"] == severity]
        if since is not None:
            evs = [e for e in evs if e["wall_ts"] >= float(since)]
        if limit is not None and limit >= 0:
            evs = evs[-int(limit):]
        return [dict(e) for e in evs]

    def tail(self, n: int = 64) -> List[Dict]:
        """The newest ``n`` events, oldest first (the frame payload)."""
        with self._lock:
            evs = list(self._events)[-int(n):]
        return [dict(e) for e in evs]

    def snapshot(self) -> Dict:
        """Ring accounting for /events and status surfaces."""
        with self._lock:
            return {
                "journal_schema": SCHEMA_VERSION,
                "node": self.node,
                "capacity": self.capacity,
                "recorded": self.seq,
                "dropped": self.dropped,
                "hlc": list(self.hlc.read()),
            }


# -- journal frame codec ----------------------------------------------------

FRAME_VERSION = 1


def encode_frame(
    node: str,
    seq: int,
    events: List[Dict],
    *,
    cluster: str = "default",
    ts: Optional[float] = None,
) -> Dict:
    """One wire frame: version stamps + identity + the journal tail."""
    return {
        "v": FRAME_VERSION,
        "journal_schema": SCHEMA_VERSION,
        "node": node,
        "cluster": cluster,
        "seq": int(seq),
        # wall clock on purpose: staleness must compare across
        # processes, which monotonic clocks never do
        "ts": time.time() if ts is None else float(ts),
        "events": list(events),
    }


def decode_frame(rec) -> Optional[Dict]:
    """Validate one stored record back into a frame; None for version
    mismatches and malformed stamps (counted as
    ``journal_frames_total{result="rejected"}`` by the reader)."""
    if not isinstance(rec, dict) or rec.get("v") != FRAME_VERSION:
        return None
    if rec.get("journal_schema") != SCHEMA_VERSION:
        return None
    node = rec.get("node")
    if not isinstance(node, str) or not node:
        return None
    if not isinstance(rec.get("events"), list):
        return None
    try:
        int(rec["seq"])
        float(rec["ts"])
    except (KeyError, TypeError, ValueError):
        return None
    return dict(rec)


# -- the exchange -----------------------------------------------------------


class JournalExchange:
    """One node's journal-tail publication + its view of every peer's
    tails, over a SharedStore under ``CLUSTER_JOURNAL_PATH`` (the
    telemetry exchange's sibling)."""

    def __init__(
        self,
        backend,
        node_name: str,
        *,
        cluster: str = "default",
        base_path: str = CLUSTER_JOURNAL_PATH,
        stale_s: float = 30.0,
    ) -> None:
        self.node_name = node_name
        self.cluster = cluster
        self.stale_s = float(stale_s)
        self.key_name = f"{cluster}/{node_name}"
        self._seq = 0
        self.store = SharedStore(backend, base_path)

    def publish(
        self, events: List[Dict], *, ts: Optional[float] = None
    ) -> bool:
        """Publish one tail frame (lease-bound; dies with the node).
        False when the kvstore is down — the journal keeps recording
        locally and the next successful publish carries a later tail."""
        self._seq += 1
        frame = encode_frame(
            self.node_name, self._seq, events, cluster=self.cluster, ts=ts
        )
        try:
            self.store.update_local_key_sync(self.key_name, frame)
        except _KV_DOWN:
            _metrics.journal_frames_total.inc({"result": "publish_error"})
            return False
        _metrics.journal_frames_total.inc({"result": "published"})
        return True

    def pump(self) -> int:
        """Apply pending peer frame events; returns events applied."""
        return self.store.pump()

    def frames(
        self, *, now: Optional[float] = None, stale_s: Optional[float] = None
    ) -> Dict[str, Dict]:
        """node → live decoded journal frame. Rejects version drift
        and ages out frames past the staleness horizon."""
        ref = time.time() if now is None else float(now)
        horizon = self.stale_s if stale_s is None else float(stale_s)
        out: Dict[str, Dict] = {}
        for rec in dict(self.store.shared).values():
            f = decode_frame(rec)
            if f is None:
                _metrics.journal_frames_total.inc({"result": "rejected"})
                continue
            if f.get("cluster") != self.cluster:
                continue
            if ref - f["ts"] > horizon:
                _metrics.journal_frames_total.inc({"result": "stale"})
                continue
            out[f["node"]] = f
        return out

    def sync(self) -> int:
        """Anti-entropy re-write of our frame (heartbeat path)."""
        return self.store.sync_local_keys()

    def close(self) -> None:
        try:
            self.store.delete_local_key(self.key_name)
        except _KV_DOWN:
            pass  # backend gone; the lease reaps our record
        self.store.close()


# -- fleet timeline merge ---------------------------------------------------


def merge_timelines(
    frames: Mapping[str, object], *, limit: Optional[int] = None
) -> List[Dict]:
    """Fold per-node journals into one HLC-total-ordered timeline.

    ``frames`` maps node → decoded journal frame OR a bare event list
    (the local journal tail rides alongside peer frames). Events are
    deduplicated on ``(node, seq)`` — overlapping tails from a node's
    own frame and the local journal collapse — then sorted by the
    ``(hlc, node, seq)`` total order, deterministic for any arrival
    order of the same frames."""
    merged: List[Dict] = []
    seen = set()
    for node, f in frames.items():
        evs = f.get("events", []) if isinstance(f, Mapping) else f
        for ev in evs:
            if not isinstance(ev, Mapping):
                continue
            ev = dict(ev)
            ev.setdefault("node", node)
            key = (ev["node"], int(ev.get("seq", 0)))
            if key in seen:
                continue
            seen.add(key)
            merged.append(ev)
    merged.sort(key=order_key)
    if limit is not None and limit >= 0:
        merged = merged[-int(limit):]
    return merged


def timeline_consistent(events: List[Mapping]) -> bool:
    """True when a merged timeline is HLC-consistent: globally
    non-decreasing in the ``(hlc, node, seq)`` total order AND
    per-node seq order preserved (no node's events were reordered by
    the merge — the causal guarantee the chaos round asserts)."""
    last_key = None
    last_seq: Dict[str, int] = {}
    for ev in events:
        k = order_key(ev)
        if last_key is not None and k < last_key:
            return False
        last_key = k
        node, seq = str(ev.get("node", "")), int(ev.get("seq", 0))
        if seq <= last_seq.get(node, 0):
            return False
        last_seq[node] = seq
    return True


# -- the publisher ----------------------------------------------------------


class JournalPublisher:
    """The ``LifecycleJournal`` cadence thread: every ``interval_s``
    publish the journal tail through the exchange (when one is
    attached) and fold peer HLC timestamps into the local clock so
    cross-node order stays causal under wall-clock skew.
    ``publish_once`` is the whole tick, directly callable for
    deterministic tests."""

    def __init__(
        self,
        journal: EventJournal,
        *,
        interval_s: float = 1.0,
        tail_n: int = 64,
    ) -> None:
        self.journal = journal
        self.interval_s = float(interval_s)
        self.tail_n = int(tail_n)
        self.exchange: Optional[JournalExchange] = None
        self._published_seq = -1
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # -- wiring ---------------------------------------------------------
    def attach_exchange(self, exchange: Optional[JournalExchange]) -> None:
        with self._lock:
            self.exchange = exchange
            self._published_seq = -1

    # -- one tick -------------------------------------------------------
    def publish_once(self) -> bool:
        """Publish the current tail iff the journal moved since the
        last publish; pump the store and fold peer clocks either way.
        Returns whether a frame went out."""
        with self._lock:
            ex = self.exchange
            if ex is None:
                return False
            published = False
            if self.journal.seq != self._published_seq:
                published = ex.publish(self.journal.tail(self.tail_n))
                if published:
                    self._published_seq = self.journal.seq
            try:
                ex.pump()
            except _KV_DOWN:
                return published  # partition: frames age out
            for node, frame in ex.frames().items():
                if node == self.journal.node:
                    continue
                evs = frame.get("events") or []
                if evs:
                    hlc = evs[-1].get("hlc") or (0, 0)
                    self.journal.hlc.observe(hlc[0], hlc[1])
            return published

    def merged_timeline(self, limit: int = 256) -> List[Dict]:
        """Local tail + every live peer tail, HLC-total-ordered."""
        frames: Dict[str, object] = {}
        ex = self.exchange
        if ex is not None:
            try:
                ex.pump()
            except _KV_DOWN:
                pass
            frames.update(ex.frames())
        # the local journal wins over our own (possibly older) frame
        frames[self.journal.node] = self.journal.tail(
            limit if limit is not None else self.tail_n
        )
        return merge_timelines(frames, limit=limit)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="journal-publisher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.publish_once()
            except Exception:
                # a journal tick must never take the process down;
                # the next tick retries with fresh state
                log.exception("journal publisher tick failed")

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)
        # detach under the lock (a straggling publish_once must see
        # either the live exchange or None, never a closed one), close
        # outside it (close touches the kvstore)
        with self._lock:
            ex, self.exchange = self.exchange, None
        if ex is not None:
            try:
                ex.close()
            except _KV_DOWN:
                pass
