# policyd: hot
"""Device-time profiler for the verdict path (policyd-prof).

The span tracer (tracer.py) attributes HOST wall time: under the
async dispatch discipline the ``dispatch`` phase measures enqueue cost
and ``host_sync`` absorbs everything the device did, so
``dispatch_rtt_ms`` is one opaque number. This module adds the device
side: every Nth completed batch (``DaemonConfig.profile_sample_every``)
is sampled with ``jax.block_until_ready`` sandwiches at the
enqueue/ready edges, splitting the RTT into ``h2d`` / ``device_compute``
/ ``d2h``, recorded alongside the rung-occupancy the tuner chose
(lanes live vs rung, chunk count, pad lanes). A second ledger captures
per-jit-site ``cost_analysis()`` (flops, bytes accessed) once per
stable ladder shape at compile time.

Cost model (the hub's ``active`` pattern, monitor/hub.py): while
``DeviceProfiling`` is off the pipeline holds ``self.profiler = None``
and the hot path's entire cost is that one attribute read — this
module is never even imported on the OFF path. While on, non-sampled
batches pay one attribute read plus one locked counter tick; only the
1-in-N sampled batch pays the synchronizing sandwiches (which is why
sampling, not always-on timing: a block_until_ready at the enqueue
edge serializes the overlap the pipeline exists to create).

Import-light like the rest of observe/: stdlib + metrics only at
module scope; jax is imported lazily inside ``note_jit_cost``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .. import metrics as _metrics


class _DispatchSample:
    """One sampled dispatch: the RTT decomposition accumulators plus
    occupancy notes. Built only on the 1-in-N sampled path — the
    disabled-overhead test monkeypatches this ctor to raise."""

    __slots__ = (
        "site", "batch", "ts", "h2d_s", "device_compute_s", "d2h_s",
        "notes",
    )

    def __init__(self, site: str, batch: int) -> None:
        self.site = site
        self.batch = int(batch)
        self.ts = time.time()
        self.h2d_s = 0.0
        self.device_compute_s = 0.0
        self.d2h_s = 0.0
        self.notes: Dict[str, object] = {}

    def add_h2d(self, seconds: float) -> None:
        self.h2d_s += seconds

    def add_compute(self, seconds: float) -> None:
        self.device_compute_s += seconds

    def add_d2h(self, seconds: float) -> None:
        self.d2h_s += seconds

    def mark(self, **notes) -> None:
        self.notes.update(notes)

    def to_dict(self) -> Dict:
        return {
            "site": self.site,
            "batch": self.batch,
            "ts": self.ts,
            "h2d_ms": self.h2d_s * 1e3,
            "device_compute_ms": self.device_compute_s * 1e3,
            "d2h_ms": self.d2h_s * 1e3,
            "notes": dict(self.notes),
        }


class DeviceProfiler:
    """Sampling profiler + jit-cost ledger. Disabled by default; the
    daemon toggles it through the ``DeviceProfiling`` runtime option
    (pipeline.set_profiling installs/clears the instance)."""

    def __init__(self, sample_every: int = 64, capacity: int = 256) -> None:
        # plain attribute, not a property: the ON-but-unsampled cost is
        # reading this once per batch (pipeline reads self.profiler)
        self.active = True
        self.sample_every = max(1, int(sample_every))
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tick: Dict[str, int] = {}
        # (site, shape-key) → {"flops", "bytes_accessed"} — populated
        # once per stable ladder shape, so steady state never lowers
        self._jit_costs: Dict[str, Dict] = {}

    # -- hot-path API ---------------------------------------------------
    def begin_dispatch(self, site: str, batch: int) -> Optional[_DispatchSample]:
        """Tick the per-site sample counter; every ``sample_every``-th
        call returns a live sample, the rest return None. The caller
        gates every sandwich on that None."""
        with self._lock:
            t = self._tick.get(site, 0) + 1
            self._tick[site] = t
        if t % self.sample_every != 0:
            return None
        return _DispatchSample(site, batch)

    def complete(self, sample: _DispatchSample) -> None:
        """Retire a finished sample into the ring and the registry."""
        with self._lock:
            self._ring.append(sample)
        lbl_site = {"site": sample.site}
        _metrics.profile_samples_total.inc(lbl_site)
        _metrics.profile_phase_seconds.observe(
            sample.h2d_s, {"phase": "h2d"})
        _metrics.profile_phase_seconds.observe(
            sample.device_compute_s, {"phase": "device_compute"})
        _metrics.profile_phase_seconds.observe(
            sample.d2h_s, {"phase": "d2h"})

    # -- compile-time ledger --------------------------------------------
    def note_jit_cost(self, site: str, shape_key, fn, args, kwargs) -> None:
        """Record XLA's cost_analysis for one (jit site, ladder shape),
        once. Lowering an already-compiled shape hits the jit cache's
        tracing machinery, not a device recompile, but it still isn't
        free — which is fine: this runs at most once per stable rung
        key, on a sampled batch. Best-effort: cost_analysis is not
        available on every backend/JAX version, so any failure just
        leaves the entry marked unavailable."""
        key = f"{site}:{shape_key}"
        with self._lock:
            if key in self._jit_costs:
                return
            # reserve before the (slow, lock-free) lowering so a racing
            # sampler doesn't lower the same program twice
            self._jit_costs[key] = {"flops": None, "bytes_accessed": None}
        entry: Dict[str, object] = {"flops": None, "bytes_accessed": None}
        try:
            lowered = fn.lower(*args, **kwargs)
            cost = lowered.compile().cost_analysis()
            # JAX version drift: dict, or a list of per-computation dicts
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if isinstance(cost, dict):
                if "flops" in cost:
                    entry["flops"] = float(cost["flops"])
                if "bytes accessed" in cost:
                    entry["bytes_accessed"] = float(cost["bytes accessed"])
        except Exception:  # policyd-lint: disable=ROBUST001
            # best-effort telemetry by contract (docstring above): a
            # backend without cost_analysis must never fault a dispatch
            pass
        with self._lock:
            self._jit_costs[key] = entry

    # -- cold-path API --------------------------------------------------
    def samples(self, limit: Optional[int] = None) -> List[Dict]:
        with self._lock:
            items = list(self._ring)
        if limit is not None and limit >= 0:
            items = items[-limit:]
        return [s.to_dict() for s in items]

    def jit_costs(self) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._jit_costs.items()}

    def snapshot(self) -> Dict:
        """The /profile payload core: recent samples, per-site device
        time aggregates (the ``cilium-tpu top`` ranking), and the
        compile-time cost ledger."""
        samples = self.samples()
        sites: Dict[str, Dict] = {}
        for s in samples:
            agg = sites.setdefault(s["site"], {
                "samples": 0, "h2d_ms": 0.0, "device_compute_ms": 0.0,
                "d2h_ms": 0.0,
            })
            agg["samples"] += 1
            agg["h2d_ms"] += s["h2d_ms"]
            agg["device_compute_ms"] += s["device_compute_ms"]
            agg["d2h_ms"] += s["d2h_ms"]
        return {
            "enabled": self.active,
            "sample_every": self.sample_every,
            "capacity": self.capacity,
            "sites": sites,
            "samples": samples,
            "jit_costs": self.jit_costs(),
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._tick.clear()
