"""policyd-fleetobs: bounded time-series rings over the metrics layer.

A :class:`TimeSeriesRing` holds the last ``capacity`` snapshots of a
fixed field vocabulary in fixed-size numpy arrays — one row per
sampler tick, NaN for fields a tick could not produce (e.g. a phase
p99 before the first observed batch). The fleet sampler
(observe/fleet.py) appends one row per cadence tick; readers reduce a
field over a trailing window (``rate``/``mean``/``max`` over the
standard 10s/1m/5m windows) without ever copying more than the window.

Memory is bounded by construction: ``capacity × len(fields)`` float64
cells, allocated once at enable time and reused forever — wraparound
overwrites the oldest row. Nothing here imports jax; numpy only.

:class:`CounterDelta` is the reset-safe companion for turning
cumulative counter totals into per-tick deltas: a total that DECREASES
means the counter restarted from zero (process restart, registry
swap), so the new total IS the delta — the standard Prometheus
``rate()`` reset rule, which never produces negative rates.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

# The standard reduction windows (label, seconds) every SLO objective
# and fleet surface quotes: short enough to catch a fast burn, long
# enough to smooth a single slow batch.
WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("10s", 10.0),
    ("1m", 60.0),
    ("5m", 300.0),
)

_REDUCERS = ("mean", "max", "rate", "last")


class CounterDelta:
    """Reset-safe delta over a monotonically-increasing total."""

    __slots__ = ("_prev",)

    def __init__(self) -> None:
        self._prev: Optional[float] = None

    def update(self, total: float) -> float:
        """Delta since the previous ``update``. First call returns 0
        (no interval yet); a decrease is a counter reset and the new
        total counts whole (it accumulated from zero)."""
        prev, self._prev = self._prev, float(total)
        if prev is None:
            return 0.0
        d = float(total) - prev
        return float(total) if d < 0 else d


class TimeSeriesRing:
    """Fixed-capacity ring of (timestamp, field-vector) samples."""

    def __init__(self, fields: Sequence[str], capacity: int = 512) -> None:
        if not fields:
            raise ValueError("TimeSeriesRing needs at least one field")
        if capacity < 2:
            raise ValueError("capacity must be >= 2 (rate needs a pair)")
        self.fields: Tuple[str, ...] = tuple(fields)
        self.capacity = int(capacity)
        self._col = {f: i for i, f in enumerate(self.fields)}
        self._ts = np.full(self.capacity, np.nan)
        self._data = np.full((self.capacity, len(self.fields)), np.nan)
        self._n = 0  # total rows ever appended (wraps via modulo)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    @property
    def appended(self) -> int:
        """Total rows ever appended (wraparound visibility)."""
        return self._n

    def append(self, ts: float, sample: Mapping[str, float]) -> None:
        """Write one snapshot row. Unknown fields are ignored; missing
        fields stay NaN for this row. ``ts`` must be monotonic in the
        caller's clock (the sampler uses time.monotonic())."""
        row = np.full(len(self.fields), np.nan)
        for name, value in sample.items():
            i = self._col.get(name)
            if i is not None and value is not None:
                row[i] = float(value)
        with self._lock:
            at = self._n % self.capacity
            self._ts[at] = float(ts)
            self._data[at] = row
            self._n += 1

    # -- readers --------------------------------------------------------
    def _ordered(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ts, data) oldest-first copies of the live rows."""
        with self._lock:
            n = min(self._n, self.capacity)
            if n == 0:
                return np.empty(0), np.empty((0, len(self.fields)))
            if self._n <= self.capacity:
                return self._ts[:n].copy(), self._data[:n].copy()
            at = self._n % self.capacity  # oldest row position
            order = np.r_[at:self.capacity, 0:at]
            return self._ts[order].copy(), self._data[order].copy()

    def last(self, field: str) -> Optional[float]:
        """Most recent non-NaN value of ``field`` (None when none)."""
        ts, vals = self.window(field, window_s=None)
        if vals.size == 0:
            return None
        return float(vals[-1])

    def window(
        self,
        field: str,
        window_s: Optional[float],
        now: Optional[float] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(ts, values) of the non-NaN samples of ``field`` within the
        trailing ``window_s`` (None: the whole ring), oldest-first.
        ``now`` defaults to the newest sample's timestamp, so replayed
        rings reduce identically to live ones."""
        ts, data = self._ordered()
        if ts.size == 0:
            return ts, np.empty(0)
        vals = data[:, self._col[field]]
        keep = ~np.isnan(vals)
        if window_s is not None:
            ref = float(ts[-1]) if now is None else float(now)
            # both bounds: an explicit ``now`` in the past must not see
            # samples from its future, or replayed reductions diverge
            keep &= (ts >= ref - float(window_s)) & (ts <= ref)
        return ts[keep], vals[keep]

    def reduce(
        self,
        field: str,
        op: str,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """One scalar over the trailing window. ``op``:

        - ``mean`` / ``max``: over the sample values;
        - ``rate``: (last - first) / (t_last - t_first) — for fields
          that carry cumulative values; needs >= 2 samples spanning
          nonzero time;
        - ``last``: newest value in the window.

        None when the window holds no (or, for rate, fewer than 2)
        samples.
        """
        if op not in _REDUCERS:
            raise ValueError(f"unknown reduction {op!r}")
        ts, vals = self.window(field, window_s, now)
        if vals.size == 0:
            return None
        if op == "mean":
            return float(vals.mean())
        if op == "max":
            return float(vals.max())
        if op == "last":
            return float(vals[-1])
        if vals.size < 2:
            return None
        span = float(ts[-1] - ts[0])
        if span <= 0.0:
            return None
        return float(vals[-1] - vals[0]) / span

    def history(self, limit: Optional[int] = None) -> List[Dict]:
        """Newest-last rows as dicts (NaN fields omitted) — the
        ``fleet history`` CLI payload. Bounded by ``limit``."""
        ts, data = self._ordered()
        if limit is not None and limit >= 0:
            ts, data = ts[-limit:], data[-limit:]
        out: List[Dict] = []
        for i in range(ts.size):
            row: Dict = {"ts": float(ts[i])}
            for j, f in enumerate(self.fields):
                v = data[i, j]
                if not np.isnan(v):
                    row[f] = float(v)
            out.append(row)
        return out
