# policyd: hot
"""Span tracer for the verdict path (policyd-trace).

The pipeline's phases (CT pre-pass, LPM, policymap lookup, device
dispatch, host sync) are invisible to /metrics alone — a batch's wall
time is one number with no attribution. This module adds the
attribution layer: monotonic-clock spans grouped into per-batch
traces, a thread-local span stack so helpers (``_dispatch``, the
device-CT path) attach to the enclosing batch without parameter
threading, and a bounded ring buffer of completed traces served by
``GET /traces`` and ``cilium-tpu traces``.

Cost model (the hub's ``active`` pattern, monitor/hub.py): the hot
path reads ONE attribute per batch — ``tracer.active`` — and takes the
no-op branch when tracing is off. The no-op batch/span singletons are
constructed once at import; a disabled batch allocates nothing and
times nothing. When enabled, each completed trace feeds the per-phase
latency histograms in metrics.py and (only while a monitor listener
is attached) publishes one TraceSummary event through the hub.

Phase names are a STABLE API: bench rounds compare waterfalls across
commits, so renaming a phase is a breaking change (observe/README.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import metrics as _metrics


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NoopBatch:
    """Shared do-nothing batch: every method is a constant-time no-op
    so instrumented code never branches on enabled-ness beyond the one
    ``tracer.active`` read that selected this singleton."""

    __slots__ = ()

    def phase(self, name: str):
        return _NOOP_SPAN

    def mark(self, **notes) -> None:
        pass

    def end(self, hub=None):
        return None


_NOOP_SPAN = _NoopSpan()
NOOP_BATCH = _NoopBatch()


class _Span:
    """One timed phase inside a batch trace. Records
    (name, start-offset-ns, duration-ns) into the owning trace on
    exit — offsets make the waterfall renderable without re-deriving
    overlap from wall clocks."""

    __slots__ = ("_trace", "name", "_t0")

    def __init__(self, trace: "BatchTrace", name: str) -> None:
        self._trace = trace
        self.name = name
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        now = time.perf_counter_ns()
        t = self._trace
        t.phases.append((self.name, self._t0 - t.t0_ns, now - self._t0))
        return False


class BatchTrace:
    """All spans of one ``_process`` call. ``phases`` is append-only
    from the owning thread; the trace becomes shared (ring buffer,
    monitor event) only after ``end()``."""

    __slots__ = (
        "tracer", "kind", "batch", "ts", "t0_ns", "total_ns", "phases",
        "notes",
    )

    def __init__(self, tracer: "Tracer", kind: str, batch: int) -> None:
        self.tracer = tracer
        self.kind = kind
        self.batch = int(batch)
        self.ts = time.time()
        self.total_ns = 0
        self.phases: List[Tuple[str, int, int]] = []
        self.notes: Dict[str, object] = {}
        # last: the batch wall clock starts when construction is done
        self.t0_ns = time.perf_counter_ns()

    def phase(self, name: str) -> _Span:
        return _Span(self, name)

    def mark(self, **notes) -> None:
        self.notes.update(notes)

    def end(self, hub=None) -> "BatchTrace":
        self.total_ns = time.perf_counter_ns() - self.t0_ns
        self.tracer._complete(self, hub)
        return self

    def to_dict(self) -> Dict:
        return {
            "kind": self.kind,
            "batch": self.batch,
            "ts": self.ts,
            "total_ns": self.total_ns,
            "phases": [list(p) for p in self.phases],
            "notes": dict(self.notes),
        }


class Tracer:
    """Per-pipeline span tracer with a bounded ring of completed
    traces. Disabled by default; the daemon toggles it through the
    ``PhaseTracing`` runtime option."""

    def __init__(self, capacity: int = 256) -> None:
        # plain attribute, not a property: the hot path's entire
        # disabled cost is reading this once per batch
        self.active = False
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- lifecycle ------------------------------------------------------
    def enable(self) -> None:
        self.active = True

    def disable(self) -> None:
        self.active = False

    # -- hot-path API ---------------------------------------------------
    def begin(self, kind: str, batch: int) -> BatchTrace:
        """Open a batch trace and push it on this thread's span stack
        (so nested helpers find it via ``current()``). Callers gate on
        ``tracer.active`` BEFORE calling — begin() itself allocates."""
        bt = BatchTrace(self, kind, batch)
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(bt)
        return bt

    def current(self):
        """The enclosing batch trace on this thread, or the no-op
        singleton when none is open (e.g. ``_dispatch`` driven
        directly by a test)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else NOOP_BATCH

    def detach(self, bt: BatchTrace) -> None:
        """Remove ``bt`` from this thread's span stack WITHOUT retiring
        it. The pipelined dispatch path parks a submitted batch's trace
        between its enqueue half and its completion half, so spans keep
        attaching to the batch that COMPLETES while ``current()``
        already serves the next submission being prepared."""
        stack = getattr(self._tls, "stack", None)
        if stack is not None:
            try:
                stack.remove(bt)
            except ValueError:
                pass

    def _complete(self, bt: BatchTrace, hub=None) -> None:
        """end() tail: pop the span stack, retire the trace into the
        ring, feed the metrics registry, and (monitor listeners only)
        publish a TraceSummary event."""
        # identity-based removal, not a top-of-stack pop: with depth>1
        # batches complete FIFO while newer traces sit above them (or
        # were already detach()ed), so ``bt`` may be anywhere or gone
        stack = getattr(self._tls, "stack", None)
        if stack is not None:
            try:
                stack.remove(bt)
            except ValueError:
                pass
        with self._lock:
            self._ring.append(bt)
        for name, _rel, dur in bt.phases:
            _metrics.pipeline_phase_seconds.observe(
                dur / 1e9, {"phase": name}
            )
        _metrics.batch_total_seconds.observe(bt.total_ns / 1e9)
        if hub is not None and hub.active:
            from ..monitor.events import TraceSummary

            hub.publish(TraceSummary(
                kind=bt.kind, batch=bt.batch, total_ns=bt.total_ns,
                phases=tuple(bt.phases), timestamp=bt.ts,
            ))

    # -- cold-path API --------------------------------------------------
    def traces(self, limit: Optional[int] = None) -> List[Dict]:
        """Completed traces, oldest→newest, bounded by ``limit``."""
        with self._lock:
            items = list(self._ring)
        if limit is not None and limit >= 0:
            items = items[-limit:]
        return [bt.to_dict() for bt in items]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
