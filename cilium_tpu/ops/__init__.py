"""Device-side kernels (JAX/XLA; Pallas where profiling warrants).

These replace the reference's kernel datapath verdict helpers
(bpf/lib/policy.h) and the userspace resolution loop
(pkg/endpoint/policy.go:317-389) with batched tensor programs.
"""

from .bitmap import compute_selector_matches, pack_bool_bits
from .lookup import PolicymapTables, lookup_batch
from .materialize import EndpointPolicySnapshot, PolicyKey, materialize_endpoints
from .verdict import DeviceTables, DevicePolicy, Verdict, verdict_batch

__all__ = [
    "compute_selector_matches",
    "pack_bool_bits",
    "PolicymapTables",
    "lookup_batch",
    "EndpointPolicySnapshot",
    "PolicyKey",
    "materialize_endpoints",
    "DeviceTables",
    "DevicePolicy",
    "Verdict",
    "verdict_batch",
]
