"""Selector↔identity matching as MXU matmuls.

The core primitive of the whole framework: given identity label bitmaps
``id_bits [N, W]`` (uint32 words) and selector conjunct masks
``conj_req/conj_forbid [S, CPS, W]``, compute the boolean match matrix

    sel_match[n, s] = any_c valid[s,c]
                      & popcount(id & req[s,c])    == req_count[s,c]
                      & popcount(id & forbid[s,c]) == 0

This replaces the reference's per-identity, per-rule label walk
(pkg/endpoint/policy.go:346-389 calling LabelArray matching per pair)
with two int8×int8→int32 matmuls over the unpacked bit axis — the
O(N_ids × selectors × labels) work lands on the systolic array instead
of a Go loop.

The result is bit-packed over the selector axis ([N, ceil(S/32)]
uint32) so downstream verdict kernels pay one 4-byte gather per
(flow, selector-id) test.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def unpack_bits_u32(words: jnp.ndarray) -> jnp.ndarray:
    """[..., W] uint32 → [..., W*32] int8 (bit 0 of word 0 first).

    The single definition of the packed-bitmap bit order — the inverse
    of pack_bool_bits — shared by the selector-match, verdict, and
    policymap-lookup kernels.
    """
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], words.shape[-1] * 32).astype(jnp.int8)


def pack_bool_bits(flags: jnp.ndarray) -> jnp.ndarray:
    """[..., S] bool → [..., ceil(S/32)] uint32 (pads with zeros)."""
    s = flags.shape[-1]
    s_words = (s + 31) // 32
    pad = s_words * 32 - s
    if pad:
        flags = jnp.concatenate(
            [flags, jnp.zeros((*flags.shape[:-1], pad), dtype=flags.dtype)], axis=-1
        )
    grouped = flags.reshape(*flags.shape[:-1], s_words, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (grouped * weights).sum(axis=-1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("row_chunk",))
def compute_selector_matches(
    id_bits: jnp.ndarray,  # [N, W] uint32
    conj_req: jnp.ndarray,  # [S, CPS, W] uint32
    conj_forbid: jnp.ndarray,  # [S, CPS, W] uint32
    conj_valid: jnp.ndarray,  # [S, CPS] bool
    req_count: jnp.ndarray,  # [S, CPS] int32
    row_chunk: int = 2048,
) -> jnp.ndarray:
    """→ packed sel_match [N, ceil(S/32)] uint32.

    Chunked over identity rows with lax.map so the [chunk, S*CPS] int32
    matmul output stays within a bounded HBM footprint at 64k identities.
    """
    n, w = id_bits.shape
    s, cps, _ = conj_req.shape
    l = w * 32

    req_t = unpack_bits_u32(conj_req.reshape(s * cps, w)).T  # [L, S*CPS] int8
    forbid_t = unpack_bits_u32(conj_forbid.reshape(s * cps, w)).T
    req_n = req_count.reshape(1, s * cps)
    valid = conj_valid.reshape(1, s * cps)

    pad_rows = (-n) % row_chunk
    padded = jnp.pad(id_bits, ((0, pad_rows), (0, 0)))
    chunks = padded.reshape(-1, row_chunk, w)

    def one_chunk(chunk_words: jnp.ndarray) -> jnp.ndarray:
        bits = unpack_bits_u32(chunk_words)  # [chunk, L] int8
        hit_req = jax.lax.dot_general(
            bits, req_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        hit_forbid = jax.lax.dot_general(
            bits, forbid_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        ok = valid & (hit_req == req_n) & (hit_forbid == 0)  # [chunk, S*CPS]
        sel = ok.reshape(row_chunk, s, cps).any(axis=-1)
        return pack_bool_bits(sel)

    packed = jax.lax.map(one_chunk, chunks)  # [n_chunks, chunk, S_words]
    return packed.reshape(-1, packed.shape[-1])[:n]
