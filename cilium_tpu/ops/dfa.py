"""Batched multi-pattern DFA execution on device.

The L7 HTTP matcher: strings (method/path/host) walk a combined DFA
(l7/regex_compile.py) whose accept sets are per-state pattern bitmasks.
The walk is a static unroll of chained row-index gathers — length is
shape-bucketed, no data-dependent trip counts. Accept masks come back
as two uint32 words (pattern bit i = pattern i matches).

This is the "vmapped NFA tables" piece of the north star
(BASELINE.json): regex evaluation for a whole request batch in one
dispatch instead of per-request Envoy regex calls
(envoy/cilium_l7policy.cc AccessFilter::decodeHeaders).

policyd-l7batch additions: field DFAs for one policy stack into a
single FusedDFA (per-field start states over one padded transition
tensor) so method/path/host classify in ONE dispatch; walks are
length-bucketed (L7_LEN_LADDER) instead of always unrolling the field
cap; small automata carry a stride-2 pair-transition table that halves
gather depth; and device residence is interned by pattern-set key so N
endpoints with the same policy share one table.
"""
# policyd: hot

from __future__ import annotations

import dataclasses
import functools
import threading
from collections import OrderedDict
from typing import TYPE_CHECKING, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle
    # (l7/__init__ imports http_policy, which imports this module)
    from ..l7.regex_compile import MultiDFA


# Length rungs for the bucketed walk (PR 5 ladder discipline: a FIXED
# rung set so jit keys only on rung shapes, never on live batch dims).
# Strings longer than the top rung walk at the field cap rung.
L7_LEN_LADDER: Tuple[int, ...] = (16, 32, 64, 128)

# Pair-walk pad symbol: alphabet index 256 is the identity transition,
# so a padded tail byte leaves the state untouched in-kernel and the
# packed buffers stay 0-padded (shared with the single-byte walk).
PAIR_ALPHA = 257
PAIR_PAD = 256

# A fused automaton gets a [Q, 257*257] pair table only when it fits
# this element cap (int32 words) — 1<<23 ≈ 32 MiB, i.e. Q ≲ 126.
# Real policies compile to a few dozen states; pathological ones just
# stay on the single-byte walk.
PAIR_TABLE_CAP_ELEMS = 1 << 23


def _pack_u8(strings: Sequence[bytes], max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """Shared packer core → ([B, max_len] uint8, [B] int32 lengths).

    Vectorized: numpy's fixed-width bytes dtype copies every string
    into a zero-padded row in one C-level pass (embedded NULs are
    preserved — only the Python ``len`` is authoritative, so a string
    ending in \\x00 still walks its full length). Overlong strings are
    truncated by the dtype; their rows are zeroed and marked length -1
    (never match — fail closed)."""
    b = len(strings)
    if not b:
        return np.zeros((0, max_len), np.uint8), np.zeros(0, np.int32)
    raw_lens = np.fromiter(map(len, strings), np.int64, b)
    out = (
        np.array(strings, dtype=f"S{max_len}")
        .view(np.uint8)
        .reshape(b, max_len)
    )
    over = raw_lens > max_len
    if over.any():
        out[over] = 0
    lens = np.where(over, -1, raw_lens).astype(np.int32)
    return out, lens


def strings_to_batch(strings: Sequence[bytes], max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """→ (bytes [B, max_len] int32, lengths [B] int32); overlong strings
    are marked length -1 (never match — fail closed). Packs every
    request batch on the proxy hot path — vectorized, no per-string
    Python loop."""
    out, lens = _pack_u8(strings, max_len)
    return out.astype(np.int32), lens


def strings_to_batch_u8(strings: Sequence[bytes], max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """uint8 variant for the fused kernels: half the host packing work
    and a quarter of the host→device transfer of the int32 batch (the
    kernels widen on device). The int32 ``strings_to_batch`` stays the
    pre-PR contract for the unfused programs."""
    return _pack_u8(strings, max_len)


@functools.partial(jax.jit, static_argnames=("max_len",))
def dfa_match_batch(
    trans: jnp.ndarray,  # [Q, 256] int32 (state 0 = dead)
    accept_lo: jnp.ndarray,  # [Q] uint32
    accept_hi: jnp.ndarray,  # [Q] uint32
    start: jnp.ndarray,  # [] int32
    str_bytes: jnp.ndarray,  # [B, max_len] int32
    lengths: jnp.ndarray,  # [B] int32 (-1 = fail closed)
    max_len: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (mask_lo [B] uint32, mask_hi [B] uint32)."""
    b = str_bytes.shape[0]
    flat = trans.reshape(-1)
    state = jnp.full((b,), start, jnp.int32)

    def step(lvl, state):
        byte = str_bytes[:, lvl]
        nxt = jnp.take(flat, state * 256 + byte)
        return jnp.where(lvl < lengths, nxt, state)

    state = jax.lax.fori_loop(0, max_len, step, state)
    ok = lengths >= 0
    lo = jnp.where(ok, jnp.take(accept_lo, state), jnp.uint32(0))
    hi = jnp.where(ok, jnp.take(accept_hi, state), jnp.uint32(0))
    return lo, hi


def device_dfa(dfa: MultiDFA) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Host MultiDFA → device arrays (accept u64 split into u32 words)."""
    lo = (dfa.accept & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (dfa.accept >> np.uint64(32)).astype(np.uint32)
    return (
        jnp.asarray(dfa.trans),
        jnp.asarray(lo),
        jnp.asarray(hi),
        jnp.asarray(np.int32(dfa.start)),
    )


def match_patterns(
    dfa: MultiDFA, strings: Sequence[bytes], max_len: int = 128
) -> np.ndarray:
    """Convenience host API → [B] uint64 accept masks."""
    sb, lens = strings_to_batch(strings, max_len)
    lo, hi = dfa_match_batch(
        *device_dfa(dfa), jnp.asarray(sb), jnp.asarray(lens), max_len
    )
    return np.asarray(lo).astype(np.uint64) | (np.asarray(hi).astype(np.uint64) << np.uint64(32))


# ---------------------------------------------------------------------------
# policyd-l7batch: fused multi-field tables + length-bucketed walks
# ---------------------------------------------------------------------------


def len_rung(needed: int, cap: int) -> int:
    """Smallest ladder rung covering ``needed`` bytes; batches whose
    longest string exceeds the top rung walk at the field cap (itself a
    fixed shape — one extra rung per policy, not per batch)."""
    for rung in L7_LEN_LADDER:
        if needed <= rung and rung <= cap:
            return rung
    return cap


@dataclasses.dataclass(frozen=True)
class FusedDFA:
    """Per-field automata stacked into one transition tensor.

    Field f's states live in rows [f*q_pad, (f+1)*q_pad); transitions
    are rebased to absolute row ids so the flat chained gather of the
    single-DFA walk works unchanged — only the START state becomes
    per-row instead of scalar. ``pair`` (optional) is the stride-2
    table: pair[q, a*257 + b] = trans[trans[q, a], b] with symbol 256
    the identity pad."""

    trans: np.ndarray  # [F*q_pad, 256] int32, absolute row ids
    accept: np.ndarray  # [F*q_pad] uint64
    starts: np.ndarray  # [F] int32 absolute start states
    q_pad: int
    n_fields: int
    pair: Optional[np.ndarray]  # [F*q_pad, 257*257] int32 or None

    @property
    def n_states(self) -> int:
        return self.n_fields * self.q_pad


def _pair_table(trans: np.ndarray) -> np.ndarray:
    """[Q, 256]-step table → [Q, 257*257] double-step table, built
    host-side in one fancy-index composition: two walk levels collapse
    into one gather, halving the chained-gather depth on device."""
    q = trans.shape[0]
    p = np.empty((q, PAIR_ALPHA, PAIR_ALPHA), np.int32)
    p[:, :256, :256] = trans[trans]  # trans[trans[q, a], b]
    p[:, :256, 256] = trans  # (byte, pad): single step
    p[:, 256, :256] = trans  # unreachable mid-string pad; keep total
    p[:, 256, 256] = np.arange(q, dtype=np.int32)  # (pad, pad): identity
    return p.reshape(q, PAIR_ALPHA * PAIR_ALPHA)


def fuse_dfas(
    dfas: Sequence["MultiDFA"], pair_cap_elems: int = PAIR_TABLE_CAP_ELEMS
) -> FusedDFA:
    """Stack one policy's field DFAs (method/path/host, or kafka
    topic/client-id) into a FusedDFA so every field of a request batch
    classifies in a single dispatch."""
    if not dfas:
        raise ValueError("fuse_dfas needs at least one automaton")
    q_pad = max(d.trans.shape[0] for d in dfas)
    f = len(dfas)
    trans = np.empty((f * q_pad, 256), np.int32)
    accept = np.zeros(f * q_pad, np.uint64)
    starts = np.empty(f, np.int32)
    for i, d in enumerate(dfas):
        q = d.trans.shape[0]
        base = i * q_pad
        trans[base : base + q] = d.trans + base
        # padding rows are unreachable; self-loop them into the block's
        # dead state so every row id stays inside its field block
        trans[base + q : base + q_pad] = base
        accept[base : base + q] = d.accept
        starts[i] = base + d.start
    pair = None
    if f * q_pad * PAIR_ALPHA * PAIR_ALPHA <= pair_cap_elems:
        pair = _pair_table(trans)
    return FusedDFA(
        trans=trans, accept=accept, starts=starts, q_pad=q_pad,
        n_fields=f, pair=pair,
    )


@functools.partial(jax.jit, static_argnames=("max_len",))
def dfa_match_batch_fused(
    trans: jnp.ndarray,  # [Q, 256] int32 (stacked fields, absolute ids)
    accept_lo: jnp.ndarray,  # [Q] uint32
    accept_hi: jnp.ndarray,  # [Q] uint32
    starts: jnp.ndarray,  # [B] int32 per-row start state
    str_bytes: jnp.ndarray,  # [B, max_len] uint8 (or int32)
    lengths: jnp.ndarray,  # [B] int32 (-1 = fail closed)
    max_len: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-byte walk with PER-ROW start states: one dispatch
    classifies every field of the whole batch against its own
    sub-automaton of the stacked table."""
    flat = trans.reshape(-1)
    state = starts

    def step(lvl, state):
        byte = str_bytes[:, lvl].astype(jnp.int32)
        nxt = jnp.take(flat, state * 256 + byte)
        return jnp.where(lvl < lengths, nxt, state)

    state = jax.lax.fori_loop(0, max_len, step, state)
    ok = lengths >= 0
    lo = jnp.where(ok, jnp.take(accept_lo, state), jnp.uint32(0))
    hi = jnp.where(ok, jnp.take(accept_hi, state), jnp.uint32(0))
    return lo, hi


@functools.partial(jax.jit, static_argnames=("max_len",))
def dfa_match_batch_pair(
    pair: jnp.ndarray,  # [Q, 257*257] int32 stride-2 table
    accept_lo: jnp.ndarray,  # [Q] uint32
    accept_hi: jnp.ndarray,  # [Q] uint32
    starts: jnp.ndarray,  # [B] int32 per-row start state
    str_bytes: jnp.ndarray,  # [B, max_len] uint8 (or int32), 0-padded
    lengths: jnp.ndarray,  # [B] int32 (-1 = fail closed)
    max_len: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stride-2 walk: ceil(max_len/2) chained gathers instead of
    max_len. Tail bytes past the string length are substituted with the
    identity symbol IN-KERNEL, so the packed buffers stay 0-padded and
    no post-step select is needed."""
    flat = pair.reshape(-1)
    state = starts
    pad = jnp.int32(PAIR_PAD)

    def step(i, state):
        lvl = 2 * i
        b0 = jnp.where(lvl < lengths, str_bytes[:, lvl].astype(jnp.int32), pad)
        b1 = jnp.where(lvl + 1 < lengths, str_bytes[:, lvl + 1].astype(jnp.int32), pad)
        return jnp.take(flat, (state * PAIR_ALPHA + b0) * PAIR_ALPHA + b1)

    state = jax.lax.fori_loop(0, (max_len + 1) // 2, step, state)
    ok = lengths >= 0
    lo = jnp.where(ok, jnp.take(accept_lo, state), jnp.uint32(0))
    hi = jnp.where(ok, jnp.take(accept_hi, state), jnp.uint32(0))
    return lo, hi


class DeviceDFATable:
    """Device residence of one FusedDFA (interned — see below).

    Holds the transfer-once device arrays plus the host-side start
    vector from which per-batch start columns are built."""

    __slots__ = (
        "key", "trans", "accept_lo", "accept_hi", "pair",
        "starts_host", "n_states", "n_fields", "q_pad", "has_pair",
        "device_bytes",
    )

    def __init__(self, key: Tuple, fused: FusedDFA) -> None:
        lo = (fused.accept & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (fused.accept >> np.uint64(32)).astype(np.uint32)
        self.key = key
        self.trans = jnp.asarray(fused.trans)
        self.accept_lo = jnp.asarray(lo)
        self.accept_hi = jnp.asarray(hi)
        self.pair = jnp.asarray(fused.pair) if fused.pair is not None else None
        self.starts_host = np.asarray(fused.starts, np.int32)
        self.n_states = fused.n_states
        self.n_fields = fused.n_fields
        self.q_pad = fused.q_pad
        self.has_pair = fused.pair is not None
        # policyd-prof memory ledger: device-resident bytes of this
        # table (replicated — every device walks the whole automaton)
        self.device_bytes = (
            int(self.trans.nbytes)
            + int(self.accept_lo.nbytes)
            + int(self.accept_hi.nbytes)
            + (int(self.pair.nbytes) if self.pair is not None else 0)
        )


# Interned device tables, keyed by pattern-set key: N endpoints with
# the same policy share ONE device table instead of N copies. Bounded
# LRU — a changed pattern set produces a new key (the PR 7 delta
# discipline: content-addressed, so invalidation is just eviction of
# entries nothing references anymore).
DFA_INTERN_CAP = 32
_intern_lock = threading.Lock()
_interned: "OrderedDict[Tuple, DeviceDFATable]" = OrderedDict()


def intern_fused_table(key: Tuple, build: Callable[[], FusedDFA]) -> DeviceDFATable:
    with _intern_lock:
        tab = _interned.get(key)
        if tab is not None:
            _interned.move_to_end(key)
            metrics.l7_dfa_intern_total.inc({"result": "hit"})
            return tab
    # build + transfer outside the lock (subset construction and the
    # pair-table composition can be slow for big automata)
    tab = DeviceDFATable(key, build())
    with _intern_lock:
        raced = _interned.get(key)
        if raced is not None:
            _interned.move_to_end(key)
            metrics.l7_dfa_intern_total.inc({"result": "hit"})
            return raced
        _interned[key] = tab
        metrics.l7_dfa_intern_total.inc({"result": "miss"})
        while len(_interned) > DFA_INTERN_CAP:
            _interned.popitem(last=False)
            metrics.l7_dfa_intern_total.inc({"result": "evict"})
        metrics.l7_dfa_tables_interned.set(len(_interned))
        # policyd-prof memory ledger: total interned DFA residence
        metrics.device_table_bytes.set(
            float(sum(t.device_bytes for t in _interned.values())),
            {"family": "dfa", "placement": "replicated"},
        )
    return tab


def dfa_intern_stats() -> Tuple[int, int]:
    """→ (live interned tables, cap)."""
    with _intern_lock:
        return len(_interned), DFA_INTERN_CAP


def _reset_intern_for_tests() -> None:
    with _intern_lock:
        _interned.clear()
        metrics.l7_dfa_tables_interned.set(0)
        metrics.device_table_bytes.set(
            0.0, {"family": "dfa", "placement": "replicated"}
        )
