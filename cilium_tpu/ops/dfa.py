"""Batched multi-pattern DFA execution on device.

The L7 HTTP matcher: strings (method/path/host) walk a combined DFA
(l7/regex_compile.py) whose accept sets are per-state pattern bitmasks.
The walk is a static unroll of chained row-index gathers — length is
shape-bucketed, no data-dependent trip counts. Accept masks come back
as two uint32 words (pattern bit i = pattern i matches).

This is the "vmapped NFA tables" piece of the north star
(BASELINE.json): regex evaluation for a whole request batch in one
dispatch instead of per-request Envoy regex calls
(envoy/cilium_l7policy.cc AccessFilter::decodeHeaders).
"""

from __future__ import annotations

import functools
from typing import TYPE_CHECKING, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle
    # (l7/__init__ imports http_policy, which imports this module)
    from ..l7.regex_compile import MultiDFA


def strings_to_batch(strings: Sequence[bytes], max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """→ (bytes [B, max_len] int32, lengths [B] int32); overlong strings
    are marked length -1 (never match — fail closed)."""
    b = len(strings)
    out = np.zeros((b, max_len), np.int32)
    lens = np.zeros(b, np.int32)
    for i, s in enumerate(strings):
        if len(s) > max_len:
            lens[i] = -1
            continue
        out[i, : len(s)] = np.frombuffer(s, np.uint8)
        lens[i] = len(s)
    return out, lens


@functools.partial(jax.jit, static_argnames=("max_len",))
def dfa_match_batch(
    trans: jnp.ndarray,  # [Q, 256] int32 (state 0 = dead)
    accept_lo: jnp.ndarray,  # [Q] uint32
    accept_hi: jnp.ndarray,  # [Q] uint32
    start: jnp.ndarray,  # [] int32
    str_bytes: jnp.ndarray,  # [B, max_len] int32
    lengths: jnp.ndarray,  # [B] int32 (-1 = fail closed)
    max_len: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (mask_lo [B] uint32, mask_hi [B] uint32)."""
    b = str_bytes.shape[0]
    flat = trans.reshape(-1)
    state = jnp.full((b,), start, jnp.int32)

    def step(lvl, state):
        byte = str_bytes[:, lvl]
        nxt = jnp.take(flat, state * 256 + byte)
        return jnp.where(lvl < lengths, nxt, state)

    state = jax.lax.fori_loop(0, max_len, step, state)
    ok = lengths >= 0
    lo = jnp.where(ok, jnp.take(accept_lo, state), jnp.uint32(0))
    hi = jnp.where(ok, jnp.take(accept_hi, state), jnp.uint32(0))
    return lo, hi


def device_dfa(dfa: MultiDFA) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Host MultiDFA → device arrays (accept u64 split into u32 words)."""
    lo = (dfa.accept & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (dfa.accept >> np.uint64(32)).astype(np.uint32)
    return (
        jnp.asarray(dfa.trans),
        jnp.asarray(lo),
        jnp.asarray(hi),
        jnp.asarray(np.int32(dfa.start)),
    )


def match_patterns(
    dfa: MultiDFA, strings: Sequence[bytes], max_len: int = 128
) -> np.ndarray:
    """Convenience host API → [B] uint64 accept masks."""
    sb, lens = strings_to_batch(strings, max_len)
    lo, hi = dfa_match_batch(
        *device_dfa(dfa), jnp.asarray(sb), jnp.asarray(lens), max_len
    )
    return np.asarray(lo).astype(np.uint64) | (np.asarray(hi).astype(np.uint64) << np.uint64(32))
