"""Materialized policymap lookup — the per-packet hot path.

The reference enforces verdicts per packet with ≤3 hash lookups in
eBPF (bpf/lib/policy.h:46-110: exact {id,port,proto} → L3-only {id} →
L4-only {port,proto}). Here the equivalent realized state is dense
device tensors:

    ep_l3      [EP, N_words] uint32   per-endpoint src-identity allow bits
    slot_*     [EP, K]                per-endpoint L4 slots (port, proto)
    col_allow  [C, N_words]  uint32   per-slot src-identity allow bits
    col_redirect [C, N_words] uint32  per-slot proxy-redirect bits

and a verdict is a handful of gathers — fully batched, no hashing, no
per-flow divergence. This is the path that has to beat the kernel's
per-packet cost by amortizing over large flow batches (BASELINE.md:
≥100M verdicts/s @10k rules).
"""

from __future__ import annotations

import functools

import chex
import jax
import jax.numpy as jnp

from .verdict import ALLOW, DENY


@chex.dataclass(frozen=True)
class PolicymapTables:
    ep_l3: jnp.ndarray  # [EP, NW] uint32
    slot_port: jnp.ndarray  # [EP, K] int32
    slot_proto: jnp.ndarray  # [EP, K] int32
    slot_col: jnp.ndarray  # [EP, K] int32
    slot_valid: jnp.ndarray  # [EP, K] bool
    col_allow: jnp.ndarray  # [C, NW] uint32
    col_redirect: jnp.ndarray  # [C, NW] uint32


def _row_bit(packed: jnp.ndarray, row_idx: jnp.ndarray, bit_idx: jnp.ndarray) -> jnp.ndarray:
    """packed [R, NW]; row_idx/bit_idx [B] → bool[B]."""
    nw = packed.shape[1]
    flat = packed.reshape(-1)
    words = jnp.take(flat, row_idx * nw + (bit_idx >> 5))
    return ((words >> (bit_idx & 31).astype(jnp.uint32)) & jnp.uint32(1)).astype(bool)


@functools.partial(jax.jit, static_argnames=("block",))
def lookup_batch(
    t: PolicymapTables,
    ep_idx: jnp.ndarray,  # [B] int32 local endpoint index
    src_rows: jnp.ndarray,  # [B] int32 identity rows
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
    block: int = 65536,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """→ (decision[B] int8, redirect[B] bool)."""
    b = ep_idx.shape[0]
    pad = (-b) % block

    def pad1(x):
        return jnp.pad(x, (0, pad)).reshape(-1, block)

    def one(args):
        ep, src, port, prt = args
        l3 = _row_bit(t.ep_l3, ep, src)
        # [blk, K] slot probe
        sp = jnp.take(t.slot_port, ep, axis=0)
        spr = jnp.take(t.slot_proto, ep, axis=0)
        sc = jnp.take(t.slot_col, ep, axis=0)
        sv = jnp.take(t.slot_valid, ep, axis=0)
        m = sv & (sp == port[:, None]) & (spr == prt[:, None])
        k = sp.shape[1]
        src_k = jnp.broadcast_to(src[:, None], (src.shape[0], k))
        a = _row_bit(t.col_allow, sc.reshape(-1), src_k.reshape(-1)).reshape(-1, k)
        r = _row_bit(t.col_redirect, sc.reshape(-1), src_k.reshape(-1)).reshape(-1, k)
        l4 = (m & a).any(axis=1)
        # Exact-match wins over L3-only (bpf/lib/policy.h lookup order),
        # so a redirecting L4 hit redirects even when L3 also allows.
        red = (m & a & r).any(axis=1)
        dec = jnp.where(l3 | l4, jnp.int8(ALLOW), jnp.int8(DENY))
        return dec, red

    dec, red = jax.lax.map(one, (pad1(ep_idx), pad1(src_rows), pad1(dport), pad1(proto)))
    return dec.reshape(-1)[:b], red.reshape(-1)[:b]
