"""Materialized policymap lookup — the per-packet hot path.

The reference enforces verdicts per packet with ≤3 hash lookups in
eBPF (bpf/lib/policy.h:46-110: exact {id,port,proto} → L3-only {id} →
L4-only {port,proto}). The realized state here is a *column* layout:
every (endpoint, L3) and (endpoint, port, proto) pair in the desired
policy is one column c, and each identity row carries a packed bitmap
of the columns that allow it:

    col_ep/col_port/col_proto/col_is_l3  [C]      column metadata
    id_bits                              [N, 2·C/32] uint32:
                                         allow words ‖ redirect words

A flow verdict is ONE packed row-gather (embedding lookup on the src
identity — XLA lowers small-N takes to a one-hot MXU matmul, which is
why allow and redirect share a single combined table: one matmul
instead of two, measured ~1.3× end-to-end) + broadcast compares of
its (endpoint, port, proto) against the column metadata — no hashing,
no per-element gathers (serial on TPU), fully batched. Per-flow
traffic is O(C) VPU ops with C = total policymap slots, which for
realistic endpoint counts is bandwidth-, not compute-, bound.

(A per-endpoint segmented layout — gathering only the flow's
endpoint's K columns from an [N·E, K] table — was prototyped and is
~2.4× SLOWER: N·E rows push the gather off the one-hot-matmul path
into true scalar gathers. Keep N small and the row wide.)
"""

from __future__ import annotations

import functools

import chex
import jax
import jax.numpy as jnp

from .bitmap import unpack_bits_u32
from .verdict import ALLOW, DENY


@chex.dataclass(frozen=True)
class PolicymapTables:
    col_ep: jnp.ndarray  # [C] int32 (-1 padding)
    col_port: jnp.ndarray  # [C] int32
    col_proto: jnp.ndarray  # [C] int32
    col_is_l3: jnp.ndarray  # [C] bool
    # combined per-identity bitmaps: [N, 2W] uint32, first W words =
    # allow bits, last W = redirect bits (one gather serves both)
    id_bits: jnp.ndarray

    @property
    def id_allow(self) -> jnp.ndarray:  # [N, C/32] uint32 view
        return self.id_bits[:, : self.id_bits.shape[1] // 2]

    @property
    def id_redirect(self) -> jnp.ndarray:
        return self.id_bits[:, self.id_bits.shape[1] // 2:]


def replicate_tables(t: PolicymapTables, sharding=None) -> PolicymapTables:
    """Commit a policymap REPLICATED across a verdict mesh (chex
    dataclasses are pytrees, so one ``device_put`` re-places every
    column/bitmap leaf). The row-gather reads arbitrary identity rows
    per flow, so the bitmap table must be whole on every device a flow
    shard lands on. ``sharding=None`` returns the tables untouched."""
    if sharding is None:
        return t
    return jax.device_put(t, sharding)


def shard_tables_ident(
    t: PolicymapTables, ident_sharding, replicated
) -> PolicymapTables:
    """Commit a policymap with the identity axis SHARDED: the [N, 2W]
    bitmap rows split across the mesh's ``ident`` axis (each device
    holds N/ident rows) while the [C] column metadata — tiny, read by
    every flow — stays replicated. The row-gather then runs as a
    one-hot contraction over the sharded N dim (``ident_gather_rows``)
    with GSPMD inserting the ident-axis reduce; per-device policymap
    bytes drop by the ident factor."""
    return jax.device_put(
        t,
        PolicymapTables(
            col_ep=replicated,
            col_port=replicated,
            col_proto=replicated,
            col_is_l3=replicated,
            id_bits=ident_sharding,
        ),
    )


def _onehot_rows_i32(tab_i32: jnp.ndarray, src: jnp.ndarray) -> jnp.ndarray:
    """[N, W] int32 table, [b] int32 row ids → [b, W] int32 row gather
    expressed as a one-hot matmul. Bit-exact vs ``jnp.take``: each
    one-hot row has EXACTLY one 1 (src is a valid row index), so every
    output word is 0+...+word+...+0 = word — integer adds, no rounding.
    The contraction runs over N, which under ``P("ident", None)`` is
    the sharded dim: XLA keeps each device's partial product local and
    all-reduces over the ident axis, i.e. the gather visits only the
    rows a device owns. (``jnp.take`` on a sharded operand would
    all-gather the whole table first, defeating the sharding.)"""
    n = tab_i32.shape[0]
    onehot = (src[:, None] == jnp.arange(n, dtype=src.dtype)[None, :]).astype(
        jnp.int32
    )
    return jax.lax.dot_general(
        onehot,
        tab_i32,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def ident_gather_rows(tab: jnp.ndarray, src: jnp.ndarray) -> jnp.ndarray:
    """Sharded-friendly row gather for identity tables. uint32 bitmap
    words round-trip through a BITCAST to int32 (astype would be a
    value conversion with implementation-defined wrap; bitcast is the
    identity on the wire) so the one-hot contraction stays on the
    integer MXU path."""
    if tab.dtype == jnp.uint32:
        out = _onehot_rows_i32(jax.lax.bitcast_convert_type(tab, jnp.int32), src)
        return jax.lax.bitcast_convert_type(out, jnp.uint32)
    return _onehot_rows_i32(tab.astype(jnp.int32), src)


@jax.jit
def patch_bitmap_cols(
    tab: jnp.ndarray,  # [N, W]
    col_idx: jnp.ndarray,  # [k] int32
    cols: jnp.ndarray,  # [N, k], dtype of ``tab``
) -> jnp.ndarray:
    """Scatter whole columns into a per-identity table — the column
    dual of materialize._patch_bitmap_rows. Serves both the packed
    ``id_bits`` word columns and the int32 ``rule_tab`` columns on the
    O(delta) rule-patch path (a rule touching k columns uploads
    [N, k] words, not the full table). Duplicate indices are allowed
    when they carry identical values (callers pad to a power of two by
    repeating the last column so the jit cache stays bounded)."""
    return tab.at[:, col_idx].set(cols)


@functools.partial(
    jax.jit, static_argnames=("block", "attrib", "ident_gather")
)
def lookup_batch(
    t: PolicymapTables,
    ep_idx: jnp.ndarray,  # [B] int32 local endpoint index
    src_rows: jnp.ndarray,  # [B] int32 identity rows
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
    block: int = 16384,
    attrib: bool = False,
    rule_tab: jnp.ndarray = None,  # [N, C_pad] int32 (attrib only)
    ident_gather: bool = False,
):
    """→ (decision[B] int8, redirect[B] bool).

    ``attrib=True`` (static; the off path keeps its exact original
    program — ``rule_tab=None`` contributes no leaves) additionally
    returns ``(rule[B] int32, l4_exists[B] bool)``: the deciding-rule
    index gathered from the materializer's per-(row, column) rule table
    (exact per-peer attribution; -1 = no rule decided), and whether an
    L4 column covered the flow's (endpoint, port, proto) at all —
    the no-L4-match vs no-L3-match drop discriminator. Attribution
    columns prefer the exact L4 column over L3-only, mirroring the
    bpf lookup order; for drops the same preference points at the
    column whose sweep recorded the deny rule (or -1 for no-match)."""
    b = ep_idx.shape[0]
    pad = (-b) % block
    w = t.id_bits.shape[1] // 2

    def pad1(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill).reshape(-1, block)

    def one(args):
        ep, port, prt, src = args
        # ident_gather (static): the 2D-mesh row fetch — a one-hot
        # contraction over the ident-sharded N dim instead of a take
        # (which would all-gather the table). False traces the exact
        # historical program: MeshSharding2D's OFF path is pinned.
        if ident_gather:
            rows = ident_gather_rows(t.id_bits, src)
        else:
            rows = jnp.take(t.id_bits, src, axis=0)
        both = unpack_bits_u32(rows).astype(bool)
        allow_bits = both[:, : w * 32]
        red_bits = both[:, w * 32:]
        colsel = (ep[:, None] == t.col_ep[None, :]) & (
            t.col_is_l3[None, :]
            | (
                (port[:, None] == t.col_port[None, :])
                & (prt[:, None] == t.col_proto[None, :])
            )
        )
        hit = colsel & allow_bits
        allow = hit.any(axis=1)
        # Exact-match wins over L3-only (bpf/lib/policy.h lookup order),
        # so a redirecting L4 hit redirects even when L3 also allows.
        red = (hit & red_bits).any(axis=1)
        dec = jnp.where(allow, jnp.int8(ALLOW), jnp.int8(DENY))
        if not attrib:
            return dec, red

        not_l3 = ~t.col_is_l3[None, :]
        l4sel = colsel & not_l3
        l4_hit = hit & not_l3
        # attribution column: allowed-L4 > allowed-L3 > covering-L4 >
        # covering-L3 (the drop fallbacks read the deny rule the sweep
        # recorded on the column that rejected the flow)
        col = jnp.where(
            l4_hit.any(axis=1),
            jnp.argmax(l4_hit, axis=1),
            jnp.where(
                allow,
                jnp.argmax(hit, axis=1),
                jnp.where(
                    l4sel.any(axis=1),
                    jnp.argmax(l4sel, axis=1),
                    jnp.where(
                        colsel.any(axis=1), jnp.argmax(colsel, axis=1), -1
                    ),
                ),
            ),
        )
        if ident_gather:
            rule_rows = ident_gather_rows(rule_tab, src)  # [b, C_pad]
        else:
            rule_rows = jnp.take(rule_tab, src, axis=0)  # [b, C_pad]
        rule_at = jnp.take_along_axis(
            rule_rows, jnp.clip(col, 0, None)[:, None], axis=1
        )[:, 0]
        rule = jnp.where(col >= 0, rule_at, jnp.int32(-1))
        return dec, red, rule, l4sel.any(axis=1)

    out = jax.lax.map(
        one, (pad1(ep_idx, -1), pad1(dport), pad1(proto), pad1(src_rows))
    )
    return tuple(x.reshape(-1)[:b] for x in out)
