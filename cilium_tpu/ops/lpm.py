"""Longest-prefix-match as stride-8 trie tensors.

Replaces the kernel LPM trie maps (bpf/lib/maps.h cilium_ipcache LPM,
bpf/bpf_xdp.c:54-86 CIDR deny tries) with device-resident node tables
walked by chained row-gathers — the gather pattern TPU executes well
(one bounded-size embedding row per flow per level, no data-dependent
loop trip counts; levels are a static unroll).

Layout (per address family):
    child [M, 256] int32   next node id (0 = none; node 0 is the root)
    info  [M, 256] int32   value at this (node, byte) + 1 (0 = none)

A prefix of length ℓ populates ⌈ℓ/8⌉ levels; the last level writes
``info`` into every byte slot the prefix covers (a /12 writes 16 slots
of its level-2 node), so the walk needs no masking. The deepest
non-zero ``info`` seen along the walk is the longest match — exactly
the LPM_TRIE semantics of the kernel map. IPv4 walks 4 levels, IPv6 16.

Values are small ints (identity rows for ipcache, 1 for deny sets).
"""

from __future__ import annotations

import functools
import ipaddress
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class TrieBuilder:
    """Host-side incremental stride-8 trie. Rebuild-on-change is cheap
    (ms for 100k prefixes); the device arrays are immutable snapshots."""

    def __init__(self, levels: int) -> None:
        self.levels = levels
        # node storage: list of dicts byte→child_id / (value+1, plen)
        self._children: List[Dict[int, int]] = [{}]
        self._info: List[Dict[int, Tuple[int, int]]] = [{}]

    def _new_node(self) -> int:
        self._children.append({})
        self._info.append({})
        return len(self._children) - 1

    def _write(self, node: int, slot: int, value: int, plen: int) -> None:
        # Within one level, slots covered by several prefixes keep the
        # longest writer (a /0 expansion must not clobber a /8 entry) —
        # insert-order independence like the kernel LPM trie.
        old = self._info[node].get(slot)
        if old is None or plen >= old[1]:
            self._info[node][slot] = (value + 1, plen)

    def insert(self, prefix_bytes: bytes, prefix_len: int, value: int) -> None:
        """value ≥ 0; stored as value+1 internally."""
        node = 0
        full, rem = divmod(prefix_len, 8)
        for i in range(full):
            b = prefix_bytes[i]
            if rem == 0 and i == full - 1:
                self._write(node, b, value, prefix_len)
                return
            nxt = self._children[node].get(b)
            if nxt is None:
                nxt = self._new_node()
                self._children[node][b] = nxt
            node = nxt
        # partial byte: populate all covered slots at this level
        b = prefix_bytes[full] if full < len(prefix_bytes) else 0
        lo = b & (0xFF << (8 - rem)) & 0xFF
        for slot in range(lo, lo + (1 << (8 - rem))):
            self._write(node, slot, value, prefix_len)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        m = len(self._children)
        child = np.zeros((m, 256), np.int32)
        info = np.zeros((m, 256), np.int32)
        for n in range(m):
            for b, c in self._children[n].items():
                child[n, b] = c
            for b, (v, _plen) in self._info[n].items():
                info[n, b] = v
        return child, info


def build_trie(
    prefixes: Iterable[Tuple[str, int]], *, ipv6: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """[(cidr_string, value)] → (child, info) arrays for one family."""
    levels = 16 if ipv6 else 4
    t = TrieBuilder(levels)
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != ipv6:
            continue
        t.insert(net.network_address.packed, net.prefixlen, value)
    return t.arrays()


def build_trie_elided(
    prefixes: Iterable[Tuple[str, int]], *, ipv6: bool = True
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[(cidr_string, value)] → (child, info, common_bytes) with the
    longest shared whole-byte prefix ELIDED from the trie.

    IPv6 pod allocations share a long prefix (everything under one
    /48-/64), so a full 16-level byte walk wastes most of its chained
    gathers traversing single-child nodes. The shared K bytes come
    back as ``common_bytes`` ([K] int32): the lookup compares them
    against the batch in one vectorized equality (no gathers) and
    walks only the remaining 16-K levels. Elision applies only while
    EVERY prefix is at least K whole bytes long (a shorter deny CIDR
    disables it), and K is capped one byte short so at least one walk
    level remains."""
    size = 16 if ipv6 else 4
    entries = []
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != ipv6:
            continue
        entries.append((net.network_address.packed, net.prefixlen, value))
    k = 0
    if entries:
        first = entries[0][0]
        k = min(min(p for _, p, _ in entries) // 8, size - 1)
        for packed, _p, _v in entries:
            while k and packed[:k] != first[:k]:
                k -= 1
    t = TrieBuilder(size - k)
    for packed, plen, value in entries:
        t.insert(packed[k:], plen - 8 * k, value)
    child, info = t.arrays()
    common = (
        np.frombuffer(entries[0][0][:k], np.uint8).astype(np.int32)
        if k
        else np.zeros(0, np.int32)
    )
    return child, info, common


@functools.partial(jax.jit, static_argnames=("levels",))
def lpm_lookup(
    child: jnp.ndarray,  # [M, 256] int32
    info: jnp.ndarray,  # [M, 256] int32
    addr_bytes: jnp.ndarray,  # [B, levels] int32 (byte per level)
    levels: int = 4,
) -> jnp.ndarray:
    """→ [B] int32: matched value+1, 0 = no match (longest wins)."""
    b = addr_bytes.shape[0]
    node = jnp.zeros(b, jnp.int32)
    alive = jnp.ones(b, jnp.bool_)
    best = jnp.zeros(b, jnp.int32)
    for lvl in range(levels):
        byte = addr_bytes[:, lvl]
        flat = node * 256 + byte
        # bounded static unroll: `levels` is a jit-static argument (4 or
        # 16), so this traces ONCE into `levels` fused gathers — it is
        # not a per-call dispatch loop
        hit = jnp.take(info.reshape(-1), flat)  # policyd-lint: disable=TPU002
        best = jnp.where(alive & (hit > 0), hit, best)
        nxt = jnp.take(child.reshape(-1), flat)
        alive = alive & (nxt > 0)
        node = jnp.where(alive, nxt, node)
    return best


class _DenseRoot:
    """Shared 16-bit dense first stride (root_info/root_child +
    per-slot plen precedence) for both wide-trie layouts — one copy of
    the masking and longest-prefix tie-break semantics."""

    def __init__(self) -> None:
        self.root_info = np.zeros(65536, np.int32)
        self._root_plen = np.full(65536, -1, np.int32)
        self.root_child = np.zeros(65536, np.int32)

    @staticmethod
    def _mask(addr_u32: int, plen: int) -> int:
        return (
            addr_u32 & ((0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF)
            if plen else 0
        )

    def _root_insert(self, addr_u32: int, plen: int, value: int) -> None:
        """plen ≤ 16: fill the covered root range, longest plen wins."""
        hi = addr_u32 >> 16
        span = 1 << (16 - plen)
        sl = slice(hi, hi + span)
        mask = self._root_plen[sl] <= plen
        self.root_info[sl] = np.where(mask, value + 1, self.root_info[sl])
        self._root_plen[sl] = np.where(mask, plen, self._root_plen[sl])


class WideTrieBuilder(_DenseRoot):
    """IPv4 LPM with a DENSE 16-bit first stride: level 1 is one
    [65536] direct-indexed table (the DIR-24-8 idea, sized 16-8-8 so
    the dense level stays 256KB), levels 2-3 are stride-8 nodes. The
    walk is 3 gathers instead of 4 — measured ~1.8× over the stride-8
    trie at 50k prefixes — and the first gather indexes a small dense
    array, the TPU-friendliest access pattern of the three."""

    def __init__(self) -> None:
        super().__init__()
        # stride-8 node storage (node 0 reserved = "none")
        self._children: List[Dict[int, int]] = [{}]
        self._infos: List[Dict[int, Tuple[int, int]]] = [{}]

    def _new_node(self) -> int:
        self._children.append({})
        self._infos.append({})
        return len(self._children) - 1

    def _write(self, node: int, base: int, span: int, value: int, plen: int) -> None:
        for s in range(base, base + span):
            old = self._infos[node].get(s)
            if old is None or plen >= old[1]:
                self._infos[node][s] = (value + 1, plen)

    def insert(self, addr_u32: int, plen: int, value: int) -> None:
        addr_u32 = self._mask(addr_u32, plen)
        hi = addr_u32 >> 16
        if plen <= 16:
            self._root_insert(addr_u32, plen, value)
            return
        node = self.root_child[hi]
        if node == 0:
            node = self._new_node()
            self.root_child[hi] = node
        b2 = (addr_u32 >> 8) & 0xFF
        rem = plen - 16
        if rem <= 8:
            span = 1 << (8 - rem)
            self._write(node, b2 & (0xFF << (8 - rem)) & 0xFF, span, value, plen)
            return
        nxt = self._children[node].get(b2)
        if nxt is None:
            nxt = self._new_node()
            self._children[node][b2] = nxt
        rem2 = rem - 8
        span = 1 << (8 - rem2)
        base = (addr_u32 & 0xFF) & (0xFF << (8 - rem2)) & 0xFF
        self._write(nxt, base, span, value, plen)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        m = len(self._children)
        sub_child = np.zeros((m, 256), np.int32)
        sub_info = np.zeros((m, 256), np.int32)
        for n in range(m):
            for b, c in self._children[n].items():
                sub_child[n, b] = c
            for b, (v, _plen) in self._infos[n].items():
                sub_info[n, b] = v
        return self.root_info.copy(), self.root_child.copy(), sub_child, sub_info


class FlatTrieBuilder(_DenseRoot):
    """IPv4 LPM with TWO dense 16-bit strides: level 1 is the [65536]
    root table, level 2 is one [65536] table per hi-16 that carries
    longer-than-/16 prefixes. The walk is 2 chained gathers (vs 3 for
    the 16-8-8 layout) — the LPM walk is the whole-pipeline bottleneck,
    so one fewer dependent gather is ~1/3 more end-to-end throughput.

    Memory/rebuild cost: 256KB per level-2 node, re-uploaded on every
    trie rebuild (identity row churn included). That is comparable to
    the 16-8-8 layout at production scale — 50k scattered prefixes
    build ~37k stride-8 nodes = ~76MB of child+info arrays, vs ≤33MB
    here at the node budget — so the flat layout is capped where it
    stops being the cheaper transfer, not grown until it fits."""

    def __init__(self) -> None:
        super().__init__()
        # node id → (info [65536], plen [65536]); id 0 reserved = none
        self._nodes: List[Tuple[np.ndarray, np.ndarray]] = []

    def _node(self, hi: int) -> Tuple[np.ndarray, np.ndarray]:
        nid = self.root_child[hi]
        if nid == 0:
            self._nodes.append((
                np.zeros(65536, np.int32), np.full(65536, -1, np.int32)
            ))
            nid = len(self._nodes)  # 1-based
            self.root_child[hi] = nid
        return self._nodes[nid - 1]

    def insert(self, addr_u32: int, plen: int, value: int) -> None:
        addr_u32 = self._mask(addr_u32, plen)
        hi = addr_u32 >> 16
        if plen <= 16:
            self._root_insert(addr_u32, plen, value)
            return
        info, plens = self._node(hi)
        base = addr_u32 & 0xFFFF
        span = 1 << (32 - plen)
        sl = slice(base, base + span)
        mask = plens[sl] <= plen
        info[sl] = np.where(mask, value + 1, info[sl])
        plens[sl] = np.where(mask, plen, plens[sl])

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        m = len(self._nodes) + 1  # row 0 = "no node", all zeros
        sub_info = np.zeros((m, 65536), np.int32)
        for i, (info, _plens) in enumerate(self._nodes):
            sub_info[i + 1] = info
        # sub_child is unused in this layout (its [*, 65536] shape is
        # what routes lpm_lookup_wide onto the 2-gather branch)
        sub_child = np.zeros((1, 65536), np.int32)
        return self.root_info.copy(), self.root_child.copy(), sub_child, sub_info


# level-2 node budget for the flat layout: 128 nodes = 33MB per trie
# (rebuilt + re-uploaded on ipcache/identity churn); past that the
# 16-8-8 pointer structure wins on transfer size
FLAT_TRIE_MAX_NODES = 128


def build_wide_trie(
    prefixes: Iterable[Tuple[str, int]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """[(v4 cidr_string, value)] → wide-trie arrays (v6 entries are
    skipped — the wide layout is IPv4-only). Picks the 2-gather flat
    16+16 layout when the deep prefixes cluster into few /16s (the
    normal pod-CIDR shape), else the 16-8-8 layout."""
    parsed = []
    deep_hi16 = set()
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            continue
        addr, plen = int(net.network_address), net.prefixlen
        parsed.append((addr, plen, value))
        if plen > 16:
            deep_hi16.add(addr >> 16)
    t = (
        FlatTrieBuilder()
        if len(deep_hi16) <= FLAT_TRIE_MAX_NODES
        else WideTrieBuilder()
    )
    for addr, plen, value in parsed:
        t.insert(addr, plen, value)
    return t.arrays()


@jax.jit
def lpm_lookup_wide(
    root_info: jnp.ndarray,  # [65536] int32
    root_child: jnp.ndarray,  # [65536] int32
    sub_child: jnp.ndarray,  # [M, 256] int32
    sub_info: jnp.ndarray,  # [M, 256] int32
    addr_u32: jnp.ndarray,  # [B] uint32/int32 host-order addresses
) -> jnp.ndarray:
    """→ [B] int32: matched value+1, 0 = no match (longest wins).
    Semantics identical to lpm_lookup on the equivalent prefix set.
    The sub-table shape (static at trace time) routes between the
    flat 16+16 layout (2 chained gathers) and 16-8-8 (3)."""
    q = addr_u32.astype(jnp.uint32)
    hi = (q >> 16).astype(jnp.int32)
    if sub_info.shape[-1] == 65536:  # flat second stride
        lo = (q & 0xFFFF).astype(jnp.int32)
        best = jnp.take(root_info, hi)
        node = jnp.take(root_child, hi)
        v1 = jnp.take(sub_info.reshape(-1), node * 65536 + lo)
        return jnp.where((node > 0) & (v1 > 0), v1, best)
    b2 = ((q >> 8) & 0xFF).astype(jnp.int32)
    b3 = (q & 0xFF).astype(jnp.int32)
    best = jnp.take(root_info, hi)
    node = jnp.take(root_child, hi)
    flat_c = sub_child.reshape(-1)
    flat_i = sub_info.reshape(-1)
    idx1 = node * 256 + b2
    v1 = jnp.take(flat_i, idx1)
    n1 = jnp.take(flat_c, idx1)
    best = jnp.where((node > 0) & (v1 > 0), v1, best)
    v2 = jnp.take(flat_i, n1 * 256 + b3)
    best = jnp.where((node > 0) & (n1 > 0) & (v2 > 0), v2, best)
    return best


# -- fused deny+identity walk (v6 stride-8 elided tries) --------------------


class _HostLPM:
    """Host-side LPM oracle over one prefix set: per-plen exact-match
    dicts, queried longest-first. O(#distinct plens) per query — the
    merge below asks it once per union prefix."""

    def __init__(self, entries) -> None:  # [(packed_bytes, plen, value)]
        self._by_plen: Dict[int, Dict[bytes, int]] = {}
        for packed, plen, value in entries:
            masked = _mask_bytes(packed, plen)
            self._by_plen.setdefault(plen, {})[masked] = value
        self._plens = sorted(self._by_plen, reverse=True)

    def lookup(self, packed: bytes, plen: int) -> int:
        """Longest match covering prefix (packed/plen) → value+1, 0 =
        none. Only prefixes of length ≤ plen can cover it."""
        for p in self._plens:
            if p > plen:
                continue
            hit = self._by_plen[p].get(_mask_bytes(packed, p))
            if hit is not None:
                return hit + 1
        return 0


def _mask_bytes(packed: bytes, plen: int) -> bytes:
    full, rem = divmod(plen, 8)
    out = bytearray(len(packed))
    out[:full] = packed[:full]
    if rem and full < len(packed):
        out[full] = packed[full] & (0xFF << (8 - rem)) & 0xFF
    return bytes(out)


def merge_trie_entries(ip_prefixes, deny_prefixes, *, ipv6=True):
    """[(cidr, value)] identity + [(cidr, _)] deny → ONE packed prefix
    list [(cidr, packed_value)] whose LPM equals BOTH sides' LPMs at
    every address: packed = (identity value+1) | DENY_BIT·denied.

    Every union prefix carries the OTHER side's LPM answer at that
    point, so a longer prefix from one side cannot shadow the other
    side's match (the correctness trap of a naive set union). Feed the
    result to build_trie_elided for the fused stride-8 walk."""
    def parse(prefixes):
        out = []
        for cidr, value in prefixes:
            net = ipaddress.ip_network(cidr, strict=False)
            if (net.version == 6) != ipv6:
                continue
            out.append((net.network_address.packed, net.prefixlen, value))
        return out

    ip_entries = parse(ip_prefixes)
    deny_entries = parse(deny_prefixes)
    ip_lpm = _HostLPM(ip_entries)
    deny_lpm = _HostLPM(deny_entries)
    union: Dict[Tuple[bytes, int], int] = {}
    for packed, plen, _v in ip_entries + deny_entries:
        key = (_mask_bytes(packed, plen), plen)
        if key in union:
            continue
        ip_v = ip_lpm.lookup(packed, plen)  # value+1, 0 = none
        if ip_v >= int(DENY_BIT) - 1:
            # packing range: the trie stores (ip_v | DENY_BIT) + 1,
            # which must stay inside int32 — the -1 keeps the denied
            # boundary case from overflowing
            return None
        denied = deny_lpm.lookup(packed, plen) > 0
        union[key] = ip_v | (int(DENY_BIT) if denied else 0)
    out = []
    for (packed, plen), pv in union.items():
        addr = ipaddress.ip_address(packed)
        out.append((f"{addr}/{plen}", pv))
    return out


# -- fused deny+identity walk (flat 16+16 layouts only) ---------------------
#
# The datapath's two v4 LPM walks — XDP deny trie and ipcache identity
# trie — consume the same address bytes (bpf_xdp.c:97-156 then
# bpf_netdev.c secctx). When BOTH tries use the dense flat layout their
# tables merge ELEMENT-WISE into one packed table: identity row+1 in
# the low bits, the deny verdict in one high bit — one 2-gather walk
# returns both results, halving the pipeline's gather count.

DENY_BIT = np.int32(1 << 30)
MERGED_VALUE_MASK = np.int32((1 << 30) - 1)


def _flat_value_grid(root_info, root_child, sub_info, his):
    """For each hi16 in ``his`` → [len(his), 65536] resolved LPM values
    (node entry where present, else the root's value — the flat
    layout's exact lookup semantics, vectorized)."""
    nodes = root_child[his]  # [H] node ids (0 = none)
    grid = sub_info[nodes]  # [H, 65536] (row 0 is all-zero)
    root_vals = root_info[his][:, None]  # [H, 1]
    return np.where(grid > 0, grid, root_vals)


def merge_flat_tries(ip_arrays, deny_arrays):
    """(ip flat-trie arrays, deny flat-trie arrays) → merged flat
    arrays, or None when either side uses the 16-8-8 pointer layout
    (merging needs the dense form). Identity values must stay below
    DENY_BIT."""
    # host-side table prep: the merge needs fancy indexing and in-place
    # writes, so pin the inputs to numpy up front — a device array
    # slipping in would otherwise turn every reduction below into a
    # blocking transfer (and int(...) on it into a device sync)
    ip_ri, ip_rc, ip_sc, ip_si = (np.asarray(a) for a in ip_arrays)
    d_ri, d_rc, d_sc, d_si = (np.asarray(a) for a in deny_arrays)
    if ip_si.shape[-1] != 65536 or d_si.shape[-1] != 65536:
        return None
    if (
        np.max(ip_si, initial=0) >= DENY_BIT
        or np.max(ip_ri, initial=0) >= DENY_BIT
    ):
        return None

    # hi16 buckets where either side holds longer-than-/16 prefixes
    his = np.union1d(np.nonzero(ip_rc)[0], np.nonzero(d_rc)[0]).astype(
        np.int64
    )
    if len(his) > FLAT_TRIE_MAX_NODES:
        # the UNION can exceed the per-trie transfer budget even when
        # each side fits — past it, the merged table costs more to
        # rebuild/upload per churn than the second walk saves
        return None
    m = len(his) + 1
    root_info = ip_ri.astype(np.int32).copy()
    root_info |= np.where(d_ri > 0, DENY_BIT, 0).astype(np.int32)
    root_child = np.zeros(65536, np.int32)
    sub_info = np.zeros((m, 65536), np.int32)
    if len(his):
        root_child[his] = np.arange(1, m, dtype=np.int32)
        ip_grid = _flat_value_grid(ip_ri, ip_rc, ip_si, his)
        d_grid = _flat_value_grid(d_ri, d_rc, d_si, his)
        sub_info[1:] = ip_grid | np.where(d_grid > 0, DENY_BIT, 0)
        # a merged node must never fall back to the root (its grid is
        # fully resolved); keep zero cells zero so "no match" stays 0 —
        # they already are, because _flat_value_grid resolves them to
        # the root value, which IS the correct fallback. But a cell
        # whose resolved value is 0 (no identity, no deny) must not
        # shadow the merged ROOT value either — it cannot, because the
        # root fallback only applies when the node cell is 0, and the
        # resolved grid equals that root fallback by construction.
    sub_child = np.zeros((1, 65536), np.int32)  # flat-layout marker
    return root_info, root_child, sub_child, sub_info


def place_table(a, sharding=None):
    """Upload one trie array to device. With a ``NamedSharding`` the
    array is committed REPLICATED across the verdict mesh (every LPM
    walk reads the whole trie regardless of which flow shard it
    serves); without one this is the classic single-device upload.
    Centralized here so every trie consumer places tables the same way
    under VerdictSharding."""
    if sharding is None:
        return jnp.asarray(a)
    return jax.device_put(np.asarray(a), sharding)


def ipv4_to_bytes(addrs: np.ndarray) -> np.ndarray:
    """[B] uint32 host-order IPv4 → [B, 4] int32 big-endian bytes."""
    a = addrs.astype(np.uint32)
    return np.stack(
        [(a >> 24) & 0xFF, (a >> 16) & 0xFF, (a >> 8) & 0xFF, a & 0xFF], axis=1
    ).astype(np.int32)


def ip_strings_to_u32(ips: Iterable[str]) -> np.ndarray:
    return np.array([int(ipaddress.IPv4Address(ip)) for ip in ips], np.uint32)


def ipv6_to_bytes(ips: Iterable[str]) -> np.ndarray:
    return np.array(
        [list(ipaddress.IPv6Address(ip).packed) for ip in ips], np.int32
    )
