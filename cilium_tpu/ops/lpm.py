"""Longest-prefix-match as stride-8 trie tensors.

Replaces the kernel LPM trie maps (bpf/lib/maps.h cilium_ipcache LPM,
bpf/bpf_xdp.c:54-86 CIDR deny tries) with device-resident node tables
walked by chained row-gathers — the gather pattern TPU executes well
(one bounded-size embedding row per flow per level, no data-dependent
loop trip counts; levels are a static unroll).

Layout (per address family):
    child [M, 256] int32   next node id (0 = none; node 0 is the root)
    info  [M, 256] int32   value at this (node, byte) + 1 (0 = none)

A prefix of length ℓ populates ⌈ℓ/8⌉ levels; the last level writes
``info`` into every byte slot the prefix covers (a /12 writes 16 slots
of its level-2 node), so the walk needs no masking. The deepest
non-zero ``info`` seen along the walk is the longest match — exactly
the LPM_TRIE semantics of the kernel map. IPv4 walks 4 levels, IPv6 16.

Values are small ints (identity rows for ipcache, 1 for deny sets).
"""

from __future__ import annotations

import functools
import ipaddress
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class TrieBuilder:
    """Host-side incremental stride-8 trie. Rebuild-on-change is cheap
    (ms for 100k prefixes); the device arrays are immutable snapshots."""

    def __init__(self, levels: int) -> None:
        self.levels = levels
        # node storage: list of dicts byte→child_id / (value+1, plen)
        self._children: List[Dict[int, int]] = [{}]
        self._info: List[Dict[int, Tuple[int, int]]] = [{}]

    def _new_node(self) -> int:
        self._children.append({})
        self._info.append({})
        return len(self._children) - 1

    def _write(self, node: int, slot: int, value: int, plen: int) -> None:
        # Within one level, slots covered by several prefixes keep the
        # longest writer (a /0 expansion must not clobber a /8 entry) —
        # insert-order independence like the kernel LPM trie.
        old = self._info[node].get(slot)
        if old is None or plen >= old[1]:
            self._info[node][slot] = (value + 1, plen)

    def insert(self, prefix_bytes: bytes, prefix_len: int, value: int) -> None:
        """value ≥ 0; stored as value+1 internally."""
        node = 0
        full, rem = divmod(prefix_len, 8)
        for i in range(full):
            b = prefix_bytes[i]
            if rem == 0 and i == full - 1:
                self._write(node, b, value, prefix_len)
                return
            nxt = self._children[node].get(b)
            if nxt is None:
                nxt = self._new_node()
                self._children[node][b] = nxt
            node = nxt
        # partial byte: populate all covered slots at this level
        b = prefix_bytes[full] if full < len(prefix_bytes) else 0
        lo = b & (0xFF << (8 - rem)) & 0xFF
        for slot in range(lo, lo + (1 << (8 - rem))):
            self._write(node, slot, value, prefix_len)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        m = len(self._children)
        child = np.zeros((m, 256), np.int32)
        info = np.zeros((m, 256), np.int32)
        for n in range(m):
            for b, c in self._children[n].items():
                child[n, b] = c
            for b, (v, _plen) in self._info[n].items():
                info[n, b] = v
        return child, info


def build_trie(
    prefixes: Iterable[Tuple[str, int]], *, ipv6: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """[(cidr_string, value)] → (child, info) arrays for one family."""
    levels = 16 if ipv6 else 4
    t = TrieBuilder(levels)
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != ipv6:
            continue
        t.insert(net.network_address.packed, net.prefixlen, value)
    return t.arrays()


def build_trie_elided(
    prefixes: Iterable[Tuple[str, int]], *, ipv6: bool = True
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[(cidr_string, value)] → (child, info, common_bytes) with the
    longest shared whole-byte prefix ELIDED from the trie.

    IPv6 pod allocations share a long prefix (everything under one
    /48-/64), so a full 16-level byte walk wastes most of its chained
    gathers traversing single-child nodes. The shared K bytes come
    back as ``common_bytes`` ([K] int32): the lookup compares them
    against the batch in one vectorized equality (no gathers) and
    walks only the remaining 16-K levels. Elision applies only while
    EVERY prefix is at least K whole bytes long (a shorter deny CIDR
    disables it), and K is capped one byte short so at least one walk
    level remains."""
    size = 16 if ipv6 else 4
    entries = []
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != ipv6:
            continue
        entries.append((net.network_address.packed, net.prefixlen, value))
    k = 0
    if entries:
        first = entries[0][0]
        k = min(min(p for _, p, _ in entries) // 8, size - 1)
        for packed, _p, _v in entries:
            while k and packed[:k] != first[:k]:
                k -= 1
    t = TrieBuilder(size - k)
    for packed, plen, value in entries:
        t.insert(packed[k:], plen - 8 * k, value)
    child, info = t.arrays()
    common = (
        np.frombuffer(entries[0][0][:k], np.uint8).astype(np.int32)
        if k
        else np.zeros(0, np.int32)
    )
    return child, info, common


@functools.partial(jax.jit, static_argnames=("levels",))
def lpm_lookup(
    child: jnp.ndarray,  # [M, 256] int32
    info: jnp.ndarray,  # [M, 256] int32
    addr_bytes: jnp.ndarray,  # [B, levels] int32 (byte per level)
    levels: int = 4,
) -> jnp.ndarray:
    """→ [B] int32: matched value+1, 0 = no match (longest wins)."""
    b = addr_bytes.shape[0]
    node = jnp.zeros(b, jnp.int32)
    alive = jnp.ones(b, jnp.bool_)
    best = jnp.zeros(b, jnp.int32)
    for lvl in range(levels):
        byte = addr_bytes[:, lvl]
        flat = node * 256 + byte
        # bounded static unroll: `levels` is a jit-static argument (4 or
        # 16), so this traces ONCE into `levels` fused gathers — it is
        # not a per-call dispatch loop
        hit = jnp.take(info.reshape(-1), flat)  # policyd-lint: disable=TPU002
        best = jnp.where(alive & (hit > 0), hit, best)
        nxt = jnp.take(child.reshape(-1), flat)
        alive = alive & (nxt > 0)
        node = jnp.where(alive, nxt, node)
    return best


class _DenseRoot:
    """Shared 16-bit dense first stride (root_info/root_child +
    per-slot plen precedence) for both wide-trie layouts — one copy of
    the masking and longest-prefix tie-break semantics."""

    def __init__(self) -> None:
        self.root_info = np.zeros(65536, np.int32)
        self._root_plen = np.full(65536, -1, np.int32)
        self.root_child = np.zeros(65536, np.int32)

    @staticmethod
    def _mask(addr_u32: int, plen: int) -> int:
        return (
            addr_u32 & ((0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF)
            if plen else 0
        )

    def _root_insert(self, addr_u32: int, plen: int, value: int) -> None:
        """plen ≤ 16: fill the covered root range, longest plen wins."""
        hi = addr_u32 >> 16
        span = 1 << (16 - plen)
        sl = slice(hi, hi + span)
        mask = self._root_plen[sl] <= plen
        self.root_info[sl] = np.where(mask, value + 1, self.root_info[sl])
        self._root_plen[sl] = np.where(mask, plen, self._root_plen[sl])


class WideTrieBuilder(_DenseRoot):
    """IPv4 LPM with a DENSE 16-bit first stride: level 1 is one
    [65536] direct-indexed table (the DIR-24-8 idea, sized 16-8-8 so
    the dense level stays 256KB), levels 2-3 are stride-8 nodes. The
    walk is 3 gathers instead of 4 — measured ~1.8× over the stride-8
    trie at 50k prefixes — and the first gather indexes a small dense
    array, the TPU-friendliest access pattern of the three."""

    def __init__(self) -> None:
        super().__init__()
        # stride-8 node storage (node 0 reserved = "none")
        self._children: List[Dict[int, int]] = [{}]
        self._infos: List[Dict[int, Tuple[int, int]]] = [{}]

    def _new_node(self) -> int:
        self._children.append({})
        self._infos.append({})
        return len(self._children) - 1

    def _write(self, node: int, base: int, span: int, value: int, plen: int) -> None:
        for s in range(base, base + span):
            old = self._infos[node].get(s)
            if old is None or plen >= old[1]:
                self._infos[node][s] = (value + 1, plen)

    def insert(self, addr_u32: int, plen: int, value: int) -> None:
        addr_u32 = self._mask(addr_u32, plen)
        hi = addr_u32 >> 16
        if plen <= 16:
            self._root_insert(addr_u32, plen, value)
            return
        node = self.root_child[hi]
        if node == 0:
            node = self._new_node()
            self.root_child[hi] = node
        b2 = (addr_u32 >> 8) & 0xFF
        rem = plen - 16
        if rem <= 8:
            span = 1 << (8 - rem)
            self._write(node, b2 & (0xFF << (8 - rem)) & 0xFF, span, value, plen)
            return
        nxt = self._children[node].get(b2)
        if nxt is None:
            nxt = self._new_node()
            self._children[node][b2] = nxt
        rem2 = rem - 8
        span = 1 << (8 - rem2)
        base = (addr_u32 & 0xFF) & (0xFF << (8 - rem2)) & 0xFF
        self._write(nxt, base, span, value, plen)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        m = len(self._children)
        sub_child = np.zeros((m, 256), np.int32)
        sub_info = np.zeros((m, 256), np.int32)
        for n in range(m):
            for b, c in self._children[n].items():
                sub_child[n, b] = c
            for b, (v, _plen) in self._infos[n].items():
                sub_info[n, b] = v
        return self.root_info.copy(), self.root_child.copy(), sub_child, sub_info


class FlatTrieBuilder(_DenseRoot):
    """IPv4 LPM with TWO dense 16-bit strides: level 1 is the [65536]
    root table, level 2 is one [65536] table per hi-16 that carries
    longer-than-/16 prefixes. The walk is 2 chained gathers (vs 3 for
    the 16-8-8 layout) — the LPM walk is the whole-pipeline bottleneck,
    so one fewer dependent gather is ~1/3 more end-to-end throughput.

    Memory/rebuild cost: 256KB per level-2 node, re-uploaded on every
    trie rebuild (identity row churn included). That is comparable to
    the 16-8-8 layout at production scale — 50k scattered prefixes
    build ~37k stride-8 nodes = ~76MB of child+info arrays, vs ≤33MB
    here at the node budget — so the flat layout is capped where it
    stops being the cheaper transfer, not grown until it fits."""

    def __init__(self) -> None:
        super().__init__()
        # node id → (info [65536], plen [65536]); id 0 reserved = none
        self._nodes: List[Tuple[np.ndarray, np.ndarray]] = []

    def _node(self, hi: int) -> Tuple[np.ndarray, np.ndarray]:
        nid = self.root_child[hi]
        if nid == 0:
            self._nodes.append((
                np.zeros(65536, np.int32), np.full(65536, -1, np.int32)
            ))
            nid = len(self._nodes)  # 1-based
            self.root_child[hi] = nid
        return self._nodes[nid - 1]

    def insert(self, addr_u32: int, plen: int, value: int) -> None:
        addr_u32 = self._mask(addr_u32, plen)
        hi = addr_u32 >> 16
        if plen <= 16:
            self._root_insert(addr_u32, plen, value)
            return
        info, plens = self._node(hi)
        base = addr_u32 & 0xFFFF
        span = 1 << (32 - plen)
        sl = slice(base, base + span)
        mask = plens[sl] <= plen
        info[sl] = np.where(mask, value + 1, info[sl])
        plens[sl] = np.where(mask, plen, plens[sl])

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        m = len(self._nodes) + 1  # row 0 = "no node", all zeros
        sub_info = np.zeros((m, 65536), np.int32)
        for i, (info, _plens) in enumerate(self._nodes):
            sub_info[i + 1] = info
        # sub_child is unused in this layout (its [*, 65536] shape is
        # what routes lpm_lookup_wide onto the 2-gather branch)
        sub_child = np.zeros((1, 65536), np.int32)
        return self.root_info.copy(), self.root_child.copy(), sub_child, sub_info


# level-2 node budget for the flat layout: 128 nodes = 33MB per trie
# (rebuilt + re-uploaded on ipcache/identity churn); past that the
# 16-8-8 pointer structure wins on transfer size
FLAT_TRIE_MAX_NODES = 128


def build_wide_trie(
    prefixes: Iterable[Tuple[str, int]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """[(v4 cidr_string, value)] → wide-trie arrays (v6 entries are
    skipped — the wide layout is IPv4-only). Picks the 2-gather flat
    16+16 layout when the deep prefixes cluster into few /16s (the
    normal pod-CIDR shape), else the 16-8-8 layout."""
    parsed = []
    deep_hi16 = set()
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            continue
        addr, plen = int(net.network_address), net.prefixlen
        parsed.append((addr, plen, value))
        if plen > 16:
            deep_hi16.add(addr >> 16)
    t = (
        FlatTrieBuilder()
        if len(deep_hi16) <= FLAT_TRIE_MAX_NODES
        else WideTrieBuilder()
    )
    for addr, plen, value in parsed:
        t.insert(addr, plen, value)
    return t.arrays()


@jax.jit
def lpm_lookup_wide(
    root_info: jnp.ndarray,  # [65536] int32
    root_child: jnp.ndarray,  # [65536] int32
    sub_child: jnp.ndarray,  # [M, 256] int32
    sub_info: jnp.ndarray,  # [M, 256] int32
    addr_u32: jnp.ndarray,  # [B] uint32/int32 host-order addresses
) -> jnp.ndarray:
    """→ [B] int32: matched value+1, 0 = no match (longest wins).
    Semantics identical to lpm_lookup on the equivalent prefix set.
    The sub-table shape (static at trace time) routes between the
    flat 16+16 layout (2 chained gathers) and 16-8-8 (3)."""
    q = addr_u32.astype(jnp.uint32)
    hi = (q >> 16).astype(jnp.int32)
    if sub_info.shape[-1] == 65536:  # flat second stride
        lo = (q & 0xFFFF).astype(jnp.int32)
        best = jnp.take(root_info, hi)
        node = jnp.take(root_child, hi)
        v1 = jnp.take(sub_info.reshape(-1), node * 65536 + lo)
        return jnp.where((node > 0) & (v1 > 0), v1, best)
    b2 = ((q >> 8) & 0xFF).astype(jnp.int32)
    b3 = (q & 0xFF).astype(jnp.int32)
    best = jnp.take(root_info, hi)
    node = jnp.take(root_child, hi)
    flat_c = sub_child.reshape(-1)
    flat_i = sub_info.reshape(-1)
    idx1 = node * 256 + b2
    v1 = jnp.take(flat_i, idx1)
    n1 = jnp.take(flat_c, idx1)
    best = jnp.where((node > 0) & (v1 > 0), v1, best)
    v2 = jnp.take(flat_i, n1 * 256 + b3)
    best = jnp.where((node > 0) & (n1 > 0) & (v2 > 0), v2, best)
    return best


# -- fused deny+identity walk (v6 stride-8 elided tries) --------------------


class _HostLPM:
    """Host-side LPM oracle over one prefix set: per-plen exact-match
    dicts, queried longest-first. O(#distinct plens) per query — the
    merge below asks it once per union prefix."""

    def __init__(self, entries) -> None:  # [(packed_bytes, plen, value)]
        self._by_plen: Dict[int, Dict[bytes, int]] = {}
        for packed, plen, value in entries:
            masked = _mask_bytes(packed, plen)
            self._by_plen.setdefault(plen, {})[masked] = value
        self._plens = sorted(self._by_plen, reverse=True)

    def lookup(self, packed: bytes, plen: int) -> int:
        """Longest match covering prefix (packed/plen) → value+1, 0 =
        none. Only prefixes of length ≤ plen can cover it."""
        for p in self._plens:
            if p > plen:
                continue
            hit = self._by_plen[p].get(_mask_bytes(packed, p))
            if hit is not None:
                return hit + 1
        return 0


def _mask_bytes(packed: bytes, plen: int) -> bytes:
    full, rem = divmod(plen, 8)
    out = bytearray(len(packed))
    out[:full] = packed[:full]
    if rem and full < len(packed):
        out[full] = packed[full] & (0xFF << (8 - rem)) & 0xFF
    return bytes(out)


def merge_trie_entries(ip_prefixes, deny_prefixes, *, ipv6=True):
    """[(cidr, value)] identity + [(cidr, _)] deny → ONE packed prefix
    list [(cidr, packed_value)] whose LPM equals BOTH sides' LPMs at
    every address: packed = (identity value+1) | DENY_BIT·denied.

    Every union prefix carries the OTHER side's LPM answer at that
    point, so a longer prefix from one side cannot shadow the other
    side's match (the correctness trap of a naive set union). Feed the
    result to build_trie_elided for the fused stride-8 walk."""
    def parse(prefixes):
        out = []
        for cidr, value in prefixes:
            net = ipaddress.ip_network(cidr, strict=False)
            if (net.version == 6) != ipv6:
                continue
            out.append((net.network_address.packed, net.prefixlen, value))
        return out

    ip_entries = parse(ip_prefixes)
    deny_entries = parse(deny_prefixes)
    ip_lpm = _HostLPM(ip_entries)
    deny_lpm = _HostLPM(deny_entries)
    union: Dict[Tuple[bytes, int], int] = {}
    for packed, plen, _v in ip_entries + deny_entries:
        key = (_mask_bytes(packed, plen), plen)
        if key in union:
            continue
        ip_v = ip_lpm.lookup(packed, plen)  # value+1, 0 = none
        if ip_v >= int(DENY_BIT) - 1:
            # packing range: the trie stores (ip_v | DENY_BIT) + 1,
            # which must stay inside int32 — the -1 keeps the denied
            # boundary case from overflowing
            return None
        denied = deny_lpm.lookup(packed, plen) > 0
        union[key] = ip_v | (int(DENY_BIT) if denied else 0)
    out = []
    for (packed, plen), pv in union.items():
        addr = ipaddress.ip_address(packed)
        out.append((f"{addr}/{plen}", pv))
    return out


# -- fused deny+identity walk (flat 16+16 layouts only) ---------------------
#
# The datapath's two v4 LPM walks — XDP deny trie and ipcache identity
# trie — consume the same address bytes (bpf_xdp.c:97-156 then
# bpf_netdev.c secctx). When BOTH tries use the dense flat layout their
# tables merge ELEMENT-WISE into one packed table: identity row+1 in
# the low bits, the deny verdict in one high bit — one 2-gather walk
# returns both results, halving the pipeline's gather count.

DENY_BIT = np.int32(1 << 30)
MERGED_VALUE_MASK = np.int32((1 << 30) - 1)


def _flat_value_grid(root_info, root_child, sub_info, his):
    """For each hi16 in ``his`` → [len(his), 65536] resolved LPM values
    (node entry where present, else the root's value — the flat
    layout's exact lookup semantics, vectorized)."""
    nodes = root_child[his]  # [H] node ids (0 = none)
    grid = sub_info[nodes]  # [H, 65536] (row 0 is all-zero)
    root_vals = root_info[his][:, None]  # [H, 1]
    return np.where(grid > 0, grid, root_vals)


def merge_flat_tries(ip_arrays, deny_arrays):
    """(ip flat-trie arrays, deny flat-trie arrays) → merged flat
    arrays, or None when either side uses the 16-8-8 pointer layout
    (merging needs the dense form). Identity values must stay below
    DENY_BIT."""
    # host-side table prep: the merge needs fancy indexing and in-place
    # writes, so pin the inputs to numpy up front — a device array
    # slipping in would otherwise turn every reduction below into a
    # blocking transfer (and int(...) on it into a device sync)
    ip_ri, ip_rc, ip_sc, ip_si = (np.asarray(a) for a in ip_arrays)
    d_ri, d_rc, d_sc, d_si = (np.asarray(a) for a in deny_arrays)
    if ip_si.shape[-1] != 65536 or d_si.shape[-1] != 65536:
        return None
    if (
        np.max(ip_si, initial=0) >= DENY_BIT
        or np.max(ip_ri, initial=0) >= DENY_BIT
    ):
        return None

    # hi16 buckets where either side holds longer-than-/16 prefixes
    his = np.union1d(np.nonzero(ip_rc)[0], np.nonzero(d_rc)[0]).astype(
        np.int64
    )
    if len(his) > FLAT_TRIE_MAX_NODES:
        # the UNION can exceed the per-trie transfer budget even when
        # each side fits — past it, the merged table costs more to
        # rebuild/upload per churn than the second walk saves
        return None
    m = len(his) + 1
    root_info = ip_ri.astype(np.int32).copy()
    root_info |= np.where(d_ri > 0, DENY_BIT, 0).astype(np.int32)
    root_child = np.zeros(65536, np.int32)
    sub_info = np.zeros((m, 65536), np.int32)
    if len(his):
        root_child[his] = np.arange(1, m, dtype=np.int32)
        ip_grid = _flat_value_grid(ip_ri, ip_rc, ip_si, his)
        d_grid = _flat_value_grid(d_ri, d_rc, d_si, his)
        sub_info[1:] = ip_grid | np.where(d_grid > 0, DENY_BIT, 0)
        # a merged node must never fall back to the root (its grid is
        # fully resolved); keep zero cells zero so "no match" stays 0 —
        # they already are, because _flat_value_grid resolves them to
        # the root value, which IS the correct fallback. But a cell
        # whose resolved value is 0 (no identity, no deny) must not
        # shadow the merged ROOT value either — it cannot, because the
        # root fallback only applies when the node cell is 0, and the
        # resolved grid equals that root fallback by construction.
    sub_child = np.zeros((1, 65536), np.int32)  # flat-layout marker
    return root_info, root_child, sub_child, sub_info


# -- O(delta) trie patching (policyd-sparse) --------------------------------
#
# ToFQDN-style small-CIDR storms churn the ipcache a few /32s//128s at a
# time; rebuilding + re-uploading whole tries per change is the
# reference's per-key LPM map write turned into a table rebuild. These
# builders keep HOST mirrors of the device trie tensors plus enough
# writer bookkeeping to insert/delete individual prefixes in place, and
# flush only the touched node rows / dense spans to the device copies —
# O(delta) words per churn instead of the whole trie. Node pools carry
# power-of-two headroom; exhaustion (or a layout/elision violation)
# returns False and the caller falls back to the classic full rebuild.
#
# Correctness bar: for any applied prefix set, the host mirrors are
# value-identical to what build_wide_trie / build_trie_elided would
# produce for that set (modulo zero-padded pool rows, which the walks
# never reach) — (prefix, plen) keys must be unique per trie, which the
# ipcache guarantees (normalized CIDR keys).


@jax.jit
def _patch_trie_rows(
    child: jnp.ndarray,
    info: jnp.ndarray,
    idx: jnp.ndarray,  # [k] int32 node rows (pow2-padded, dup = last)
    cvals: jnp.ndarray,  # [k, 256]
    ivals: jnp.ndarray,  # [k, 256]
):
    """Scatter dirty stride-8 node rows into both trie tensors in ONE
    dispatch (duplicate indices carry identical values). No donation:
    concurrent LPM walks may hold the old buffers."""
    return child.at[idx].set(cvals), info.at[idx].set(ivals)


@jax.jit
def _patch_span1(a: jnp.ndarray, start: jnp.ndarray, vals: jnp.ndarray):
    """Dense-root span update (flat v4 layout): spans are naturally
    power-of-two (1 << (16 - plen)), so widths bound the program count;
    the traced start keeps one program per width."""
    return jax.lax.dynamic_update_slice(a, vals, (start,))


@jax.jit
def _patch_span_row(
    a: jnp.ndarray, row: jnp.ndarray, start: jnp.ndarray, vals: jnp.ndarray
):
    return jax.lax.dynamic_update_slice(a, vals[None, :], (row, start))


@jax.jit
def _patch_elems(a: jnp.ndarray, idx: jnp.ndarray, vals: jnp.ndarray):
    return a.at[idx].set(vals)


def _pow2_pad_rows(rows: np.ndarray) -> np.ndarray:
    """Pad a row-index list to a power-of-two bucket (min 8) by
    repeating the last row — the engine _pow2_rows discipline."""
    k = rows.shape[0]
    bucket = 8
    while bucket < k:
        bucket <<= 1
    if bucket == k:
        return rows
    return np.concatenate([rows, np.repeat(rows[-1:], bucket - k)])


class PatchableElidedTrie:
    """Patchable host mirror of one build_trie_elided trie (v6 ip
    tries; also correct for v4 stride-8, unused there). Per-(node,
    slot) writers keyed by plen make deletes exact: at one slot of the
    final level, distinct covering prefixes necessarily carry distinct
    plens (same plen + same covered slot ⇒ same masked prefix ⇒ same
    ipcache key), so the remaining longest plen is the new winner."""

    def __init__(self, prefixes: Iterable[Tuple[str, int]], *, ipv6: bool = True):
        size = 16 if ipv6 else 4
        self._ipv6 = ipv6
        entries = []
        for cidr, value in prefixes:
            net = ipaddress.ip_network(cidr, strict=False)
            if (net.version == 6) != ipv6:
                continue
            entries.append((net.network_address.packed, net.prefixlen, value))
        k = 0
        if entries:
            first = entries[0][0]
            k = min(min(p for _, p, _ in entries) // 8, size - 1)
            for packed, _p, _v in entries:
                while k and packed[:k] != first[:k]:
                    k -= 1
        self._k = k
        self._levels = size - k
        self._common = entries[0][0][:k] if k else b""
        # node storage: byte→child dicts + per-slot {plen: value} writers
        self._children: List[Dict[int, int]] = [{}]
        self._writers: List[Dict[int, Dict[int, int]]] = [{}]
        self._live = False  # arrays not materialized yet
        self.child_h = np.zeros((0, 256), np.int32)
        self.info_h = np.zeros((0, 256), np.int32)
        self._dirty: set = set()
        for packed, plen, value in entries:
            self._ins(packed[k:], plen - 8 * k, value)
        m = len(self._children)
        cap = 8
        while cap < m + 1:  # ≥1 spare row for live inserts
            cap <<= 1
        self.child_h = np.zeros((cap, 256), np.int32)
        self.info_h = np.zeros((cap, 256), np.int32)
        for n in range(m):
            for b, c in self._children[n].items():
                self.child_h[n, b] = c
            for slot, w in self._writers[n].items():
                if w:
                    self.info_h[n, slot] = w[max(w)] + 1
        self._live = True

    # -- host structure ------------------------------------------------
    def _new_node(self) -> Optional[int]:
        nid = len(self._children)
        if self._live and nid >= self.child_h.shape[0]:
            return None  # pool exhausted → caller full-rebuilds
        self._children.append({})
        self._writers.append({})
        return nid

    def _write(self, node: int, slot: int, value: int, plen: int) -> None:
        w = self._writers[node].setdefault(slot, {})
        w[plen] = value
        if self._live:
            self.info_h[node, slot] = w[max(w)] + 1
            self._dirty.add(node)

    def _unwrite(self, node: int, slot: int, plen: int) -> None:
        w = self._writers[node].get(slot)
        if not w or plen not in w:
            return
        del w[plen]
        self.info_h[node, slot] = (w[max(w)] + 1) if w else 0
        self._dirty.add(node)

    def _ins(self, pb: bytes, plen: int, value: int) -> bool:
        node = 0
        full, rem = divmod(plen, 8)
        for i in range(full):
            b = pb[i]
            if rem == 0 and i == full - 1:
                self._write(node, b, value, plen)
                return True
            nxt = self._children[node].get(b)
            if nxt is None:
                nxt = self._new_node()
                if nxt is None:
                    return False
                self._children[node][b] = nxt
                if self._live:
                    self.child_h[node, b] = nxt
                    self._dirty.add(node)
            node = nxt
        b = pb[full] if full < len(pb) else 0
        lo = b & (0xFF << (8 - rem)) & 0xFF
        for slot in range(lo, lo + (1 << (8 - rem))):
            self._write(node, slot, value, plen)
        return True

    # -- public ops ----------------------------------------------------
    def _parse(self, cidr: str):
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != self._ipv6:
            return None
        return net.network_address.packed, net.prefixlen

    def insert(self, cidr: str, value: int) -> bool:
        """Upsert one prefix. False → not expressible in place (family
        mismatch, elision violation, node-pool exhaustion): rebuild."""
        p = self._parse(cidr)
        if p is None:
            return False
        packed, plen = p
        if self._k and (plen < 8 * self._k or packed[: self._k] != self._common):
            return False  # would break the elided shared prefix
        return self._ins(packed[self._k:], plen - 8 * self._k, value)

    def delete(self, cidr: str) -> bool:
        """Remove one prefix (no-op when absent — e.g. its identity
        never had a device row). Deletes cannot violate elision or grow
        the pool, so this never demands a rebuild."""
        p = self._parse(cidr)
        if p is None:
            return True
        packed, plen = p
        if self._k and (plen < 8 * self._k or packed[: self._k] != self._common):
            return True  # was never inserted
        pb = packed[self._k:]
        plen -= 8 * self._k
        node = 0
        full, rem = divmod(plen, 8)
        for i in range(full):
            b = pb[i]
            if rem == 0 and i == full - 1:
                self._unwrite(node, b, plen)
                return True
            nxt = self._children[node].get(b)
            if nxt is None:
                return True  # path absent → prefix absent
            node = nxt
        b = pb[full] if full < len(pb) else 0
        lo = b & (0xFF << (8 - rem)) & 0xFF
        for slot in range(lo, lo + (1 << (8 - rem))):
            self._unwrite(node, slot, plen)
        return True

    @property
    def dirty(self) -> bool:
        return bool(self._dirty)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(child, info, common_bytes) — build_trie_elided layout with
        the pow2-padded node pool (zero rows the walk never reaches)."""
        common = (
            np.frombuffer(self._common, np.uint8).astype(np.int32)
            if self._k
            else np.zeros(0, np.int32)
        )
        return self.child_h.copy(), self.info_h.copy(), common

    def flush(self, child_dev, info_dev):
        """Scatter the dirty node rows into the device copies →
        ((child, info), logical h2d bytes), or None when the device
        shape does not match the mirror (caller re-places wholesale)."""
        if not self._dirty:
            return (child_dev, info_dev), 0
        if tuple(getattr(child_dev, "shape", ())) != self.child_h.shape:
            return None
        rows = _pow2_pad_rows(np.asarray(sorted(self._dirty), np.int32))
        cvals = self.child_h[rows]
        ivals = self.info_h[rows]
        child_dev, info_dev = _patch_trie_rows(
            child_dev, info_dev, jnp.asarray(rows), jnp.asarray(cvals),
            jnp.asarray(ivals),
        )
        self._dirty.clear()
        nbytes = int(rows.nbytes) + int(cvals.nbytes) + int(ivals.nbytes)
        return (child_dev, info_dev), nbytes


class _FlatNode:
    """One level-2 dense node of the patchable flat v4 trie: resolved
    info/plen arrays + the raw entry dict the delete path recomputes
    spans from."""

    __slots__ = ("info", "plen", "entries")

    def __init__(self) -> None:
        self.info = np.zeros(65536, np.int32)
        self.plen = np.full(65536, -1, np.int16)
        self.entries: Dict[Tuple[int, int], int] = {}


class PatchableFlatTrie:
    """Patchable host mirror of one flat 16+16 v4 trie
    (FlatTrieBuilder layout). Root precedence keeps a per-plen [17,
    65536] value table (≤16 plens ⇒ winner recompute is 17 vectorized
    selects over the touched span); deep nodes recompute deleted spans
    from their entry dicts. Dirty state flushes as power-of-two dense
    spans (dynamic_update_slice — one program per span width)."""

    def __init__(self, prefixes: Iterable[Tuple[int, int, int]]):
        # prefixes: parsed (addr_u32, plen, value) v4 entries
        self._root_by_plen = np.zeros((17, 65536), np.int32)  # value+1
        self.root_info = np.zeros(65536, np.int32)
        self.root_child = np.zeros(65536, np.int32)
        self._nodes: List[_FlatNode] = []
        entries = list(prefixes)
        n_deep = len({a >> 16 for a, p, _v in entries if p > 16})
        cap = 4
        while cap < n_deep + 2:  # ≥1 spare node row (row 0 = none)
            cap <<= 1
        self._cap_rows = min(cap, FLAT_TRIE_MAX_NODES * 2)
        # (start, pow2 width) dense-root spans / node ids / (nid, base,
        # pow2 width) node spans touched since the last flush
        self._dirty_root: Dict[Tuple[int, int], None] = {}
        self._dirty_child: Dict[int, None] = {}
        self._dirty_sub: Dict[Tuple[int, int, int], None] = {}
        for addr, plen, value in entries:
            ok = self._ins(addr, plen, value)
            assert ok  # cap covers the build set by construction
        self._clear_dirty()

    def _clear_dirty(self) -> None:
        self._dirty_root.clear()
        self._dirty_child.clear()
        self._dirty_sub.clear()

    @staticmethod
    def _mask(addr_u32: int, plen: int) -> int:
        return (
            addr_u32 & ((0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF)
            if plen else 0
        )

    def _root_recompute(self, sl: slice) -> None:
        out = np.zeros(sl.stop - sl.start, np.int32)
        for p in range(17):  # ascending: longer plen overwrites
            v = self._root_by_plen[p, sl]
            out = np.where(v > 0, v, out)
        self.root_info[sl] = out

    def _ins(self, addr: int, plen: int, value: int) -> bool:
        addr = self._mask(addr, plen)
        hi = addr >> 16
        if plen <= 16:
            span = 1 << (16 - plen)
            sl = slice(hi, hi + span)
            self._root_by_plen[plen, sl] = value + 1
            self._root_recompute(sl)
            self._dirty_root[(hi, span)] = None
            return True
        nid = int(self.root_child[hi])
        if nid == 0:
            if (
                len(self._nodes) + 2 > self._cap_rows
                or len(self._nodes) >= FLAT_TRIE_MAX_NODES
            ):
                return False  # pool exhausted / past the flat budget
            self._nodes.append(_FlatNode())
            nid = len(self._nodes)
            self.root_child[hi] = nid
            self._dirty_child[hi] = None
        node = self._nodes[nid - 1]
        node.entries[(addr, plen)] = value
        base = addr & 0xFFFF
        span = 1 << (32 - plen)
        sl = slice(base, base + span)
        m = node.plen[sl] <= plen
        node.info[sl] = np.where(m, value + 1, node.info[sl])
        node.plen[sl] = np.where(m, np.int16(plen), node.plen[sl])
        self._dirty_sub[(nid, base, span)] = None
        return True

    # -- public ops ----------------------------------------------------
    @staticmethod
    def _parse(cidr: str):
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            return None
        return int(net.network_address), net.prefixlen

    def insert(self, cidr: str, value: int) -> bool:
        p = self._parse(cidr)
        if p is None:
            return False
        return self._ins(p[0], p[1], value)

    def delete(self, cidr: str) -> bool:
        """Remove one prefix (no-op when absent). Never demands a
        rebuild: spans recompute from the surviving writers."""
        p = self._parse(cidr)
        if p is None:
            return True
        addr, plen = self._mask(p[0], p[1]), p[1]
        hi = addr >> 16
        if plen <= 16:
            span = 1 << (16 - plen)
            sl = slice(hi, hi + span)
            if not self._root_by_plen[plen, sl].any():
                return True  # absent
            self._root_by_plen[plen, sl] = 0
            self._root_recompute(sl)
            self._dirty_root[(hi, span)] = None
            return True
        nid = int(self.root_child[hi])
        if nid == 0:
            return True
        node = self._nodes[nid - 1]
        if node.entries.pop((addr, plen), None) is None:
            return True
        base = addr & 0xFFFF
        span = 1 << (32 - plen)
        sl = slice(base, base + span)
        node.info[sl] = 0
        node.plen[sl] = -1
        for (a2, p2), v2 in node.entries.items():
            b2 = a2 & 0xFFFF
            s2 = 1 << (32 - p2)
            lo, hi2 = max(base, b2), min(base + span, b2 + s2)
            if lo < hi2:
                ssl = slice(lo, hi2)
                m = node.plen[ssl] <= p2
                node.info[ssl] = np.where(m, v2 + 1, node.info[ssl])
                node.plen[ssl] = np.where(m, np.int16(p2), node.plen[ssl])
        self._dirty_sub[(nid, base, span)] = None
        return True

    @property
    def dirty(self) -> bool:
        return bool(self._dirty_root or self._dirty_child or self._dirty_sub)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """build_wide_trie flat-layout arrays with the pow2-padded node
        pool (zero rows resolve to the root fallback, exactly like an
        unallocated node)."""
        sub_info = np.zeros((self._cap_rows, 65536), np.int32)
        for i, node in enumerate(self._nodes):
            sub_info[i + 1] = node.info
        sub_child = np.zeros((1, 65536), np.int32)  # flat-layout marker
        return (
            self.root_info.copy(), self.root_child.copy(), sub_child,
            sub_info,
        )

    def flush(self, root_info_dev, root_child_dev, sub_child_dev, sub_info_dev):
        """Upload the dirty spans → ((root_info, root_child, sub_child,
        sub_info), logical h2d bytes), or None on a device/mirror shape
        mismatch (caller re-places wholesale)."""
        if not self.dirty:
            return (root_info_dev, root_child_dev, sub_child_dev, sub_info_dev), 0
        if tuple(getattr(sub_info_dev, "shape", ())) != (self._cap_rows, 65536):
            return None
        nbytes = 0
        for start, span in self._dirty_root:
            vals = np.ascontiguousarray(self.root_info[start:start + span])
            root_info_dev = _patch_span1(
                # bounded control-plane unroll: one dispatch per dirty
                # root span (spans coalesce adjacent edits), at rebuild
                # cadence — never per flow
                root_info_dev, jnp.int32(start), jnp.asarray(vals)  # policyd-lint: disable=TPU002
            )
            nbytes += int(vals.nbytes) + 4
        if self._dirty_child:
            idx = _pow2_pad_rows(
                np.asarray(sorted(self._dirty_child), np.int32)
            )
            vals = self.root_child[idx]
            root_child_dev = _patch_elems(
                root_child_dev, jnp.asarray(idx), jnp.asarray(vals)
            )
            nbytes += int(idx.nbytes) + int(vals.nbytes)
        for nid, base, span in self._dirty_sub:
            vals = np.ascontiguousarray(
                self._nodes[nid - 1].info[base:base + span]
            )
            sub_info_dev = _patch_span_row(
                # bounded control-plane unroll: one dispatch per dirty
                # sub-node span, bounded by the patch budget before the
                # mirror falls back to a full rebuild
                sub_info_dev, jnp.int32(nid), jnp.int32(base),  # policyd-lint: disable=TPU002
                jnp.asarray(vals),
            )
            nbytes += int(vals.nbytes) + 8
        self._clear_dirty()
        return (
            (root_info_dev, root_child_dev, sub_child_dev, sub_info_dev),
            nbytes,
        )


def make_patchable_wide(
    prefixes: Iterable[Tuple[str, int]]
) -> Optional[PatchableFlatTrie]:
    """PatchableFlatTrie over the v4 entries, or None when
    build_wide_trie would pick the 16-8-8 pointer layout (too many
    deep /16 buckets) — that layout is not patched; callers fall back
    to full rebuilds."""
    parsed = []
    deep_hi16 = set()
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            continue
        addr, plen = int(net.network_address), net.prefixlen
        parsed.append((addr, plen, value))
        if plen > 16:
            deep_hi16.add(addr >> 16)
    if len(deep_hi16) > FLAT_TRIE_MAX_NODES:
        return None
    return PatchableFlatTrie(parsed)


def place_table(a, sharding=None):
    """Upload one trie array to device. With a ``NamedSharding`` the
    array is committed REPLICATED across the verdict mesh (every LPM
    walk reads the whole trie regardless of which flow shard it
    serves); without one this is the classic single-device upload.
    Centralized here so every trie consumer places tables the same way
    under VerdictSharding."""
    if sharding is None:
        return jnp.asarray(a)
    return jax.device_put(np.asarray(a), sharding)


def ipv4_to_bytes(addrs: np.ndarray) -> np.ndarray:
    """[B] uint32 host-order IPv4 → [B, 4] int32 big-endian bytes."""
    a = addrs.astype(np.uint32)
    return np.stack(
        [(a >> 24) & 0xFF, (a >> 16) & 0xFF, (a >> 8) & 0xFF, a & 0xFF], axis=1
    ).astype(np.int32)


def ip_strings_to_u32(ips: Iterable[str]) -> np.ndarray:
    return np.array([int(ipaddress.IPv4Address(ip)) for ip in ips], np.uint32)


def ipv6_to_bytes(ips: Iterable[str]) -> np.ndarray:
    return np.array(
        [list(ipaddress.IPv6Address(ip).packed) for ip in ips], np.int32
    )
