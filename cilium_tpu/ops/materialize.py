"""Policymap materialization: full verdict engine → realized lookup state.

The TPU replacement for the reference's hottest control-plane loop,
computeDesiredL3PolicyMapEntries (pkg/endpoint/policy.go:317-389): for
every local endpoint, evaluate the full policy for *every known
identity* (and every L4 slot) and emit the dense lookup tables of
ops/lookup.py plus host-visible policymap entries (pkg/maps/policymap
key format) for the datapath front-end.

The whole sweep — endpoints × identities × (L3 + each L4 slot) — is
flattened into ONE batched device call, so a full regeneration costs a
single dispatch regardless of endpoint count (the reference pays a
per-endpoint per-identity Go loop; we pay one kernel launch).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..compiler.program import CompiledPolicy
from .bitmap import pack_bool_bits
from .lookup import PolicymapTables
from .verdict import ALLOW, DevicePolicy, verdict_batch

TRAFFIC_INGRESS = 0
TRAFFIC_EGRESS = 1


@dataclasses.dataclass(frozen=True)
class PolicyKey:
    """pkg/maps/policymap PolicyKey (policymap.go:64): identity, dport
    (0 = L3-only), nexthdr (0 = L3-only), traffic direction."""

    identity: int
    dport: int
    nexthdr: int
    direction: int


@dataclasses.dataclass
class EndpointPolicySnapshot:
    """Desired policymap for one endpoint + its slot layout. Entry value
    is the proxy-redirect flag (proxy port binding happens at the proxy
    layer, pkg/proxy/proxy.go port allocator)."""

    entries: Dict[PolicyKey, int]
    slots: List[Tuple[int, int]]


def _endpoint_slots(compiled: CompiledPolicy, subj_sel_row: np.ndarray, ingress: bool):
    """Distinct (port, proto) L4 slots this endpoint's policy can
    reference: L4 entries whose subject selector matches, plus
    L7-parser ports (always TCP)."""
    d = compiled.ingress if ingress else compiled.egress

    def sel_hit(sids: np.ndarray) -> np.ndarray:
        return (subj_sel_row[sids >> 5] >> (sids & 31)) & 1

    slots = set()
    valid = d.e_valid & (sel_hit(d.e_subj.astype(np.int64)) == 1)
    for port, proto in zip(d.e_port[valid], d.e_proto[valid]):
        slots.add((int(port), int(proto)))
    lv = d.l7_valid & (sel_hit(d.l7_subj.astype(np.int64)) == 1)
    for port in d.l7_port[lv]:
        slots.add((int(port), 6))
    return sorted(slots)


def materialize_endpoints(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    slot_bucket: int = 8,
    block: int = 65536,
) -> Tuple[PolicymapTables, List[EndpointPolicySnapshot]]:
    n = compiled.id_bits.shape[0]
    nw = (n + 31) // 32
    ep_rows = compiled.rows_for(endpoint_identity_ids)
    sel_match_host = np.asarray(device.sel_match)
    live = compiled.row_live
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    # Flatten (endpoint L3 sweep) + (endpoint, slot) sweeps into one batch.
    ep_slots: List[List[Tuple[int, int]]] = [
        _endpoint_slots(compiled, sel_match_host[row], ingress) for row in ep_rows
    ]
    seg_subj: List[np.ndarray] = []
    seg_port: List[int] = []
    seg_proto: List[int] = []
    seg_l4: List[bool] = []
    for e, row in enumerate(ep_rows):
        seg_subj.append(np.full(n, row, np.int32))
        seg_port.append(0)
        seg_proto.append(0)
        seg_l4.append(False)
        for port, proto in ep_slots[e]:
            seg_subj.append(np.full(n, row, np.int32))
            seg_port.append(port)
            seg_proto.append(proto)
            seg_l4.append(True)

    n_seg = len(seg_subj)
    all_rows = np.arange(n, dtype=np.int32)
    subj = np.concatenate(seg_subj)
    peer = np.tile(all_rows, n_seg)
    dport = np.repeat(np.asarray(seg_port, np.int32), n)
    proto = np.repeat(np.asarray(seg_proto, np.int32), n)
    has_l4 = np.repeat(np.asarray(seg_l4, bool), n)

    v = verdict_batch(
        device,
        jnp.asarray(subj),
        jnp.asarray(peer),
        jnp.asarray(dport),
        jnp.asarray(proto),
        jnp.asarray(has_l4),
        ingress=ingress,
        block=block,
    )
    dec = np.asarray(v.decision).reshape(n_seg, n)
    l3d = np.asarray(v.l3).reshape(n_seg, n)
    red = np.asarray(v.l7_redirect).reshape(n_seg, n)

    ep_l3_bits: List[np.ndarray] = []
    slot_meta: List[List[Tuple[int, int, int]]] = []
    col_allow: List[np.ndarray] = []
    col_redirect: List[np.ndarray] = []
    snapshots: List[EndpointPolicySnapshot] = []

    seg = 0
    for e, row in enumerate(ep_rows):
        l3_allow = (l3d[seg] == 1) & live
        seg += 1
        ep_l3_bits.append(l3_allow)
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(compiled.row_ids[r_idx]), 0, 0, direction)] = 0
        meta: List[Tuple[int, int, int]] = []
        for port, proto_n in ep_slots[e]:
            allow = (dec[seg] == ALLOW) & live
            redirect = red[seg] & live
            seg += 1
            col = len(col_allow)
            col_allow.append(allow)
            col_redirect.append(redirect)
            meta.append((port, proto_n, col))
            # Exact {id, port, proto} entries: the datapath consults the
            # exact key first (bpf/lib/policy.h:46), so L3-allowed
            # identities still need one when the filter redirects.
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(compiled.row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        slot_meta.append(meta)
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=ep_slots[e]))

    # Pack device tables.
    ep = len(ep_rows)
    k = slot_bucket
    while any(len(m) > k for m in slot_meta):
        k *= 2
    ncols = max(1, len(col_allow))
    slot_port = np.zeros((ep, k), np.int32)
    slot_proto = np.zeros((ep, k), np.int32)
    slot_col = np.zeros((ep, k), np.int32)
    slot_valid = np.zeros((ep, k), bool)
    for e, meta in enumerate(slot_meta):
        for j, (port, proto_n, col) in enumerate(meta):
            slot_port[e, j], slot_proto[e, j], slot_col[e, j] = port, proto_n, col
            slot_valid[e, j] = True

    def pack_rows(rows: List[np.ndarray], count: int) -> jnp.ndarray:
        if not rows:
            return jnp.zeros((count, nw), jnp.uint32)
        return pack_bool_bits(jnp.asarray(np.stack(rows)))

    tables = PolicymapTables(
        ep_l3=pack_rows(ep_l3_bits, ep),
        slot_port=jnp.asarray(slot_port),
        slot_proto=jnp.asarray(slot_proto),
        slot_col=jnp.asarray(slot_col),
        slot_valid=jnp.asarray(slot_valid),
        col_allow=pack_rows(col_allow, ncols),
        col_redirect=pack_rows(col_redirect, ncols),
    )
    return tables, snapshots
