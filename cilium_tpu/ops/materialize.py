"""Policymap materialization: full verdict engine → realized lookup state.

The TPU replacement for the reference's hottest control-plane loop,
computeDesiredL3PolicyMapEntries (pkg/endpoint/policy.go:317-389): for
every local endpoint, evaluate the full policy for *every known
identity* (and every L4 slot) and emit the column-bitmap lookup tables
of ops/lookup.py plus host-visible policymap entries
(pkg/maps/policymap key format) for the datapath front-end.

The whole sweep — endpoints × identities × (L3 + each L4 slot) — is
flattened into ONE batched device call, so a full regeneration costs a
single dispatch regardless of endpoint count (the reference pays a
per-endpoint per-identity Go loop; we pay one kernel launch of int8
matmuls).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from typing import Optional

from ..compiler.program import CompiledPolicy, PROTO_TCP_N
from .bitmap import pack_bool_bits, unpack_bits_u32
from .lookup import PolicymapTables, patch_bitmap_cols
from .verdict import ALLOW, AttribTables, DevicePolicy, _mm, verdict_batch

TRAFFIC_INGRESS = 0
TRAFFIC_EGRESS = 1


@dataclasses.dataclass(frozen=True)
class PolicyKey:
    """pkg/maps/policymap PolicyKey (policymap.go:64): identity, dport
    (0 = L3-only), nexthdr (0 = L3-only), traffic direction."""

    identity: int
    dport: int
    nexthdr: int
    direction: int


@dataclasses.dataclass
class EndpointPolicySnapshot:
    """Desired policymap for one endpoint + its slot layout. Entry value
    is the proxy-redirect flag (proxy port binding happens at the proxy
    layer, pkg/proxy/proxy.go port allocator)."""

    entries: Dict[PolicyKey, int]
    slots: List[Tuple[int, int]]


def _endpoint_slots(compiled: CompiledPolicy, subj_sel_row: np.ndarray, ingress: bool):
    """Distinct (port, proto) L4 slots this endpoint's policy can
    reference: L4 entries whose subject selector matches, plus
    L7-parser ports (always TCP)."""
    d = compiled.ingress if ingress else compiled.egress

    def sel_hit(sids: np.ndarray) -> np.ndarray:
        return (subj_sel_row[sids >> 5] >> (sids & 31)) & 1

    slots = set()
    if d.e_subj.size:
        hit = sel_hit(d.e_subj.astype(np.int64)) == 1
        for port, proto in zip(d.e_port[hit], d.e_proto[hit]):
            slots.add((int(port), int(proto)))
    if d.l7_subj.size:
        hit = sel_hit(d.l7_subj.astype(np.int64)) == 1
        for port in d.l7_port[hit]:
            slots.add((int(port), PROTO_TCP_N))
    return sorted(slots)


@dataclasses.dataclass
class MaterializedState:
    """Host mirror of the realized policymap: unpacked column bitmaps +
    metadata, enabling **row patches** for identity churn (the
    incremental half of syncPolicyMap, pkg/endpoint/endpoint.go:2572)
    without re-sweeping every (endpoint, identity) pair."""

    tables: PolicymapTables
    snapshots: List[EndpointPolicySnapshot]
    ingress: bool
    endpoint_identity_ids: List[int]
    ep_rows: np.ndarray  # [E] int32
    ep_slots: List[List[Tuple[int, int]]]
    allow_nc: np.ndarray  # [N, C_pad] bool (host, mutable)
    red_nc: np.ndarray  # [N, C_pad] bool
    n_cols: int
    # Verdict attribution (policyd-flows): per-(identity row, column)
    # deciding-rule index from an attrib=True sweep — EXACT per-peer
    # attribution for the pipeline's lookup path (-1 = no rule; deny
    # drops carry the deny rule even though their allow bit is 0).
    # None when the sweep ran without attribution (FlowAttribution off
    # or snapshot-restored compile with no rule-origin state).
    rule_nc: Optional[np.ndarray] = None  # [N, C_pad] int32 (host)
    rule_tab: Optional[jnp.ndarray] = None  # [N, C_pad] int32 (device)


def materialize_endpoints(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    block: int = 8192,
) -> Tuple[PolicymapTables, List[EndpointPolicySnapshot]]:
    st = materialize_endpoints_state(
        compiled, device, endpoint_identity_ids, ingress=ingress, block=block
    )
    return st.tables, st.snapshots


def _seg_bucket(n_seg: int) -> int:
    b = 8
    while b < n_seg:
        b <<= 1
    return b


@functools.partial(jax.jit, static_argnames=("n", "ingress", "block"))
def _sweep_device(
    policy: DevicePolicy,
    seg_row: jnp.ndarray,  # [n_seg] int32
    seg_port: jnp.ndarray,
    seg_proto: jnp.ndarray,
    seg_l4: jnp.ndarray,  # [n_seg] bool
    n: int,
    ingress: bool,
    block: int,
):
    """The endpoints × identities × slots sweep with the flattened
    index arrays generated ON DEVICE and results bit-packed before
    leaving it — the host⇄device traffic is [n_seg] in and
    3 × [n_seg, n/32] out instead of 5 × [n_seg·n] in and
    3 × [n_seg·n] out (the host-built repeat/tile arrays made the
    sweep upload-bound: ~600MB at the 100k-identity stretch scale)."""
    n_seg = seg_row.shape[0]
    subj = jnp.repeat(seg_row, n)
    peer = jnp.tile(jnp.arange(n, dtype=jnp.int32), n_seg)
    v = verdict_batch(
        policy,
        subj,
        peer,
        jnp.repeat(seg_port, n),
        jnp.repeat(seg_proto, n),
        jnp.repeat(seg_l4, n),
        ingress=ingress,
        block=block,
    )
    allow = pack_bool_bits((v.decision == ALLOW).reshape(n_seg, n))
    l3a = pack_bool_bits((v.l3 == 1).reshape(n_seg, n))
    red = pack_bool_bits(v.l7_redirect.reshape(n_seg, n))
    return allow, l3a, red


@functools.partial(
    jax.jit, static_argnames=("n", "ingress", "block", "n_rules")
)
def _sweep_device_attrib(
    policy: DevicePolicy,
    seg_row: jnp.ndarray,
    seg_port: jnp.ndarray,
    seg_proto: jnp.ndarray,
    seg_l4: jnp.ndarray,
    origin: AttribTables,
    n: int,
    ingress: bool,
    block: int,
    n_rules: int,
):
    """_sweep_device plus the attribution tail: also returns the
    [n_seg, n] int32 deciding-rule index per (segment, identity row) —
    the source of MaterializedState.rule_tab. A SEPARATE jitted entry
    so the attribution-off sweep keeps its exact original program."""
    n_seg = seg_row.shape[0]
    subj = jnp.repeat(seg_row, n)
    peer = jnp.tile(jnp.arange(n, dtype=jnp.int32), n_seg)
    v, at, _hits = verdict_batch(
        policy,
        subj,
        peer,
        jnp.repeat(seg_port, n),
        jnp.repeat(seg_proto, n),
        jnp.repeat(seg_l4, n),
        ingress=ingress,
        block=block,
        attrib=True,
        origin=origin,
        n_rules=n_rules,
    )
    allow = pack_bool_bits((v.decision == ALLOW).reshape(n_seg, n))
    l3a = pack_bool_bits((v.l3 == 1).reshape(n_seg, n))
    red = pack_bool_bits(v.l7_redirect.reshape(n_seg, n))
    return allow, l3a, red, at.rule.reshape(n_seg, n)


@functools.partial(jax.jit, static_argnames=("n", "ingress", "nblock"))
def _sweep_device_matrix(
    policy: DevicePolicy,
    seg_row: jnp.ndarray,  # [g] int32
    seg_port: jnp.ndarray,
    seg_proto: jnp.ndarray,
    seg_l4: jnp.ndarray,  # [g] bool
    n: int,
    ingress: bool,
    nblock: int,
):
    """Identity-major matrix formulation of the segment sweep.

    The flow-major sweep evaluates each (segment, identity) pair as an
    independent flow: every peer row re-contracts the [S, S]/[S, K1]
    relation matrices per segment, costing O(g·N·S²). But within one
    sweep the segment side (subject selector row, port one-hot, combo
    and L7-filter coverage) is FIXED per segment — so hoist it: compute
    the per-peer term vectors once per identity block (O(N·S²) total)
    and contract them against the [·, g] segment matrices (O(g·N·S)).
    At the 100k-identity stretch scale that is a ~n_seg× FLOP cut over
    the flow sweep for identical outputs.

    Bit-identity with _verdict_block: every reduction here is
    ``any(a ∧ b) == (Σ a·b) > 0`` over 0/1 int8 operands with int32
    accumulation (S < 2³¹, no overflow), and the one per-flow data
    dependence — group_ok folding req_ok — is handled by evaluating
    both req_ok phases and selecting per (peer, segment) cell on the
    deny matrix. Returns the same packed (allow, l3, redirect)
    [g, ceil(n/32)] words as _sweep_device."""
    t = policy.ingress if ingress else policy.egress
    subj8 = unpack_bits_u32(jnp.take(policy.sel_match, seg_row, axis=0))  # [g, S]
    pp = (
        (seg_port[:, None] == t.ports[None, :])
        & (seg_proto[:, None] == t.protos[None, :])
        & seg_l4[:, None]
    ).astype(jnp.int8)  # [g, P4]
    subj_t8 = subj8.T  # [S, g]
    combo_t = (_mm(subj8, t.s1_mat) & _mm(pp, t.p1_mat)).astype(jnp.int8).T  # [K1, g]
    sp7_t = (_mm(subj8, t.s7_mat) & _mm(pp, t.p7_mat)).astype(jnp.int8).T  # [K7, g]
    has_l4 = seg_l4[None, :]  # [1, g]

    n_pad = -(-n // nblock) * nblock
    row_blocks = jnp.arange(n_pad, dtype=jnp.int32).reshape(-1, nblock)

    def blk(rows):
        # (jnp.take clips the padded tail rows; their outputs are
        # sliced off below)
        peer8 = unpack_bits_u32(jnp.take(policy.sel_match, rows, axis=0))  # [nb, S]
        peer_deny = _mm(jnp.int8(1) - peer8, t.deny_t).astype(jnp.int8)  # [nb, S]
        peer_allow = _mm(peer8, t.allow_t).astype(jnp.int8)
        peer_en = _mm(peer8, t.en_t).astype(jnp.int8)  # [nb, K1]
        peer_ee = _mm(peer8, t.ee_t).astype(jnp.int8)
        deny = _mm(peer_deny, subj_t8)  # [nb, g] bool
        l3_allow = _mm(peer_allow, subj_t8)
        en_any = _mm(peer_en, combo_t)  # [nb, g]
        ee_any = _mm(peer_ee, combo_t)
        l4_allow = en_any | (~deny & ee_any)

        gpn_hit = _mm(peer8, t.gpn_mat)  # [nb, G]
        gpe_hit = _mm(peer8, t.gpe_mat)
        gok_true = (gpn_hit | gpe_hit | t.group_no_peers[None, :]).astype(jnp.int8)
        gok_false = (gpn_hit | t.group_no_peers[None, :]).astype(jnp.int8)
        l7_true = _mm(_mm(gok_true, t.g7_mat).astype(jnp.int8), sp7_t)  # [nb, g]
        l7_false = _mm(_mm(gok_false, t.g7_mat).astype(jnp.int8), sp7_t)
        l7_present = jnp.where(deny, l7_false, l7_true)

        l3_pass = l3_allow & ~deny
        allow_b = l3_pass | (has_l4 & l4_allow)
        red_b = has_l4 & l4_allow & l7_present
        return allow_b, l3_pass, red_b

    allow_b, l3_b, red_b = jax.lax.map(blk, row_blocks)  # [blocks, nb, g]

    def fin(x):
        return pack_bool_bits(x.reshape(n_pad, -1)[:n].T)

    return fin(allow_b), fin(l3_b), fin(red_b)


def _unpack_rows(words: np.ndarray, n: int) -> np.ndarray:
    """[n_seg, ceil(n/32)] uint32 → [n_seg, n] bool (pack_bool_bits
    inverse, host-side)."""
    words = np.ascontiguousarray(words)
    bits = np.unpackbits(
        words.view(np.uint8).reshape(words.shape[0], -1),
        axis=1,
        bitorder="little",
    )
    return bits[:, :n].astype(bool)


# Identity rows per matrix-sweep block: bounds the [nblock, S]
# peer-term activations while keeping the MXU contraction dims full.
_MATRIX_NBLOCK = 1024


def _sweep_segments(
    device: DevicePolicy,
    sr: np.ndarray,  # [n_seg] int32 subject rows
    sp: np.ndarray,  # [n_seg] int32 ports
    spr: np.ndarray,  # [n_seg] int32 protos
    sl: np.ndarray,  # [n_seg] bool has_l4
    n: int,
    *,
    ingress: bool,
    block: int,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
    sweep: str = "auto",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Chunked segments × all-identities sweep shared by the full
    materializer and the delta column-patch path → unpacked
    (allow_sn, l3_sn, red_sn) [n_seg, n] bool + rule_sn [n_seg, n]
    int32 (-1 when no attribution ran).

    ``sweep`` picks the kernel: "auto" routes attribution-free sweeps
    through the identity-major matrix kernel (_sweep_device_matrix —
    the O(N·S²) formulation); "flow" forces the original per-flow
    kernel (the parity suite diffs the two bit-for-bit). Attribution
    sweeps always take the flow kernel: the first-match rule tail needs
    the per-flow term vectors the matrix form contracts away."""
    n_seg = len(sr)
    # Chunk the segment axis so one dispatch's flattened row count
    # stays bounded (~big-batch sized) regardless of endpoint count ×
    # identity capacity, then pad each chunk to a bucket (dummy L3
    # segs against row 0) so repeated materializations reuse the
    # compiled sweep.
    budget = max(8, (1 << 23) // max(1, n))
    seg_chunk = 1 << (budget.bit_length() - 1)  # power of two ≤ budget
    seg_chunk = min(seg_chunk, _seg_bucket(n_seg))
    use_matrix = sweep != "flow" and attrib_origin is None
    aw_parts: List[np.ndarray] = []
    l3_parts: List[np.ndarray] = []
    rw_parts: List[np.ndarray] = []
    rl_parts: List[np.ndarray] = []
    for lo in range(0, n_seg, seg_chunk):
        hi = min(lo + seg_chunk, n_seg)
        pad = min(_seg_bucket(hi - lo), seg_chunk) - (hi - lo)
        chunk = (
            # control-plane rebuild: VRAM-bounded chunking over the
            # segment sweep — a handful of large device calls, not a
            # per-flow dispatch loop (the serving path never runs this)
            jnp.asarray(np.pad(sr[lo:hi], (0, pad))),  # policyd-lint: disable=TPU002
            jnp.asarray(np.pad(sp[lo:hi], (0, pad))),
            jnp.asarray(np.pad(spr[lo:hi], (0, pad))),
            jnp.asarray(np.pad(sl[lo:hi], (0, pad))),
        )
        if attrib_origin is not None:
            aw, l3w, rw, rl = _sweep_device_attrib(
                device, *chunk, attrib_origin, n, ingress, block, n_rules
            )
            # control-plane rebuild pull, same cadence as the aw/l3w
            # pulls below (baselined) — never on the serving path
            rl_parts.append(np.asarray(rl)[: hi - lo])  # policyd-lint: disable=TPU001
        elif use_matrix:
            aw, l3w, rw = _sweep_device_matrix(
                device, *chunk, n, ingress, _MATRIX_NBLOCK
            )
        else:
            aw, l3w, rw = _sweep_device(device, *chunk, n, ingress, block)
        aw_parts.append(np.asarray(aw)[: hi - lo])
        l3_parts.append(np.asarray(l3w)[: hi - lo])
        rw_parts.append(np.asarray(rw)[: hi - lo])
    if aw_parts:
        allow_sn = _unpack_rows(np.concatenate(aw_parts), n)
        l3_sn = _unpack_rows(np.concatenate(l3_parts), n)
        red_sn = _unpack_rows(np.concatenate(rw_parts), n)
    else:  # zero endpoints: nothing to sweep
        allow_sn = l3_sn = red_sn = np.zeros((0, n), bool)
    rule_sn = (
        np.concatenate(rl_parts)
        if rl_parts
        else np.full((n_seg, n), -1, np.int32)
    )
    return allow_sn, l3_sn, red_sn, rule_sn


# policyd: refresh-path
def materialize_endpoints_state(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    block: int = 8192,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
    sweep: str = "auto",
) -> MaterializedState:
    """``attrib_origin`` (with ``n_rules``) switches the sweep to the
    attribution kernel variant: the result additionally carries
    rule_nc/rule_tab, the exact per-(identity row, column) deciding-rule
    index the pipeline's lookup path gathers from under
    FlowAttribution. Off (None), the sweep and its jit program are
    untouched."""
    n = compiled.id_bits.shape[0]
    ep_rows = compiled.rows_for(endpoint_identity_ids)
    # Bounded [E, S/32] pull of just the endpoint subject rows — never
    # the full [N, S/32] matrix (at the 100k stretch that pull alone
    # moved ~1.2GB per `policy explain`).
    ep_sel = np.asarray(  # policyd-lint: disable=TPU001,TPU005
        jnp.take(device.sel_match, jnp.asarray(ep_rows, np.int32), axis=0)
    )
    live = compiled.row_live
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    # Flatten (endpoint L3 sweep) + (endpoint, slot) sweeps into one batch.
    ep_slots: List[List[Tuple[int, int]]] = [
        _endpoint_slots(compiled, ep_sel[i], ingress) for i in range(len(ep_rows))
    ]
    seg_row: List[int] = []
    seg_port: List[int] = []
    seg_proto: List[int] = []
    seg_l4: List[bool] = []
    for e, row in enumerate(ep_rows):
        seg_row.append(int(row))
        seg_port.append(0)
        seg_proto.append(0)
        seg_l4.append(False)
        for port, proto in ep_slots[e]:
            seg_row.append(int(row))
            seg_port.append(port)
            seg_proto.append(proto)
            seg_l4.append(True)

    n_seg = len(seg_row)
    allow_sn, l3_sn, red_sn, rule_sn = _sweep_segments(
        device,
        np.asarray(seg_row, np.int32),
        np.asarray(seg_port, np.int32),
        np.asarray(seg_proto, np.int32),
        np.asarray(seg_l4, bool),
        n,
        ingress=ingress,
        block=block,
        attrib_origin=attrib_origin,
        n_rules=n_rules,
        sweep=sweep,
    )

    # Column layout: one column per (endpoint, L3) + (endpoint, slot).
    col_ep: List[int] = []
    col_port: List[int] = []
    col_proto: List[int] = []
    col_is_l3: List[bool] = []
    col_allow: List[np.ndarray] = []
    col_red: List[np.ndarray] = []
    col_rule: List[np.ndarray] = []
    snapshots: List[EndpointPolicySnapshot] = []

    seg = 0
    for e, row in enumerate(ep_rows):
        l3_allow = l3_sn[seg] & live
        col_rule.append(rule_sn[seg])
        seg += 1
        col_ep.append(e)
        col_port.append(0)
        col_proto.append(0)
        col_is_l3.append(True)
        col_allow.append(l3_allow)
        col_red.append(np.zeros(n, bool))
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(compiled.row_ids[r_idx]), 0, 0, direction)] = 0
        for port, proto_n in ep_slots[e]:
            allow = allow_sn[seg] & live
            redirect = red_sn[seg] & live
            col_rule.append(rule_sn[seg])
            seg += 1
            col_ep.append(e)
            col_port.append(port)
            col_proto.append(proto_n)
            col_is_l3.append(False)
            col_allow.append(allow)
            col_red.append(redirect)
            # Exact {id, port, proto} entries: the datapath consults the
            # exact key first (bpf/lib/policy.h:46), so L3-allowed
            # identities still need one when the filter redirects.
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(compiled.row_ids[r_idx]), port, proto_n, direction)
                # ``redirect`` is the host np column computed above —
                # no device RTT, just a scalar off the sweep result
                entries[key] = int(redirect[r_idx])  # policyd-lint: disable=TPU005
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=ep_slots[e]))

    c = len(col_ep)
    c_pad = max(32, ((c + 31) // 32) * 32)
    pad = c_pad - c
    allow_nc = np.zeros((n, c_pad), bool)
    red_nc = np.zeros((n, c_pad), bool)
    rule_nc = None
    if attrib_origin is not None:
        rule_nc = np.full((n, c_pad), -1, np.int32)
    if c:
        allow_nc[:, :c] = np.stack(col_allow, axis=1)
        red_nc[:, :c] = np.stack(col_red, axis=1)
        if rule_nc is not None:
            rule_nc[:, :c] = np.stack(col_rule, axis=1)

    tables = PolicymapTables(
        col_ep=jnp.asarray(np.pad(np.asarray(col_ep, np.int32), (0, pad), constant_values=-1)),
        col_port=jnp.asarray(np.pad(np.asarray(col_port, np.int32), (0, pad))),
        col_proto=jnp.asarray(np.pad(np.asarray(col_proto, np.int32), (0, pad))),
        col_is_l3=jnp.asarray(np.pad(np.asarray(col_is_l3, bool), (0, pad))),
        # allow ‖ redirect in one table: the lookup kernel's row gather
        # lowers to a single one-hot matmul serving both bitmaps
        id_bits=pack_bool_bits(
            jnp.asarray(np.concatenate([allow_nc, red_nc], axis=1))
        ),
    )
    return MaterializedState(
        tables=tables,
        snapshots=snapshots,
        ingress=ingress,
        endpoint_identity_ids=list(endpoint_identity_ids),
        ep_rows=ep_rows,
        ep_slots=ep_slots,
        allow_nc=allow_nc,
        red_nc=red_nc,
        n_cols=c,
        rule_nc=rule_nc,
        rule_tab=jnp.asarray(rule_nc) if rule_nc is not None else None,
    )


def state_from_snapshot(row_ids: np.ndarray, fields: dict) -> MaterializedState:
    """Rebuild a MaterializedState from compiler/snapshot.py fields —
    the restore half of the pinned-map persistence analog. The column
    bitmaps are authoritative; per-endpoint snapshots (policymap dump
    surface) are re-derived from them, and the device tables re-packed
    and uploaded. No policy sweep runs: this is a load, not a derive."""
    allow_nc = np.asarray(fields["allow_nc"], bool)
    red_nc = np.asarray(fields["red_nc"], bool)
    col_ep = np.asarray(fields["col_ep"], np.int32)
    col_port = np.asarray(fields["col_port"], np.int32)
    col_proto = np.asarray(fields["col_proto"], np.int32)
    col_is_l3 = np.asarray(fields["col_is_l3"], bool)
    ep_slots = fields["ep_slots"]
    ingress = bool(fields["ingress"])
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    snapshots: List[EndpointPolicySnapshot] = []
    col = 0
    for e, slots in enumerate(ep_slots):
        l3_allow = allow_nc[:, col]
        col += 1
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(row_ids[r_idx]), 0, 0, direction)] = 0
        for port, proto_n in slots:
            allow = allow_nc[:, col]
            redirect = red_nc[:, col]
            col += 1
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=slots))

    tables = PolicymapTables(
        col_ep=jnp.asarray(col_ep),
        col_port=jnp.asarray(col_port),
        col_proto=jnp.asarray(col_proto),
        col_is_l3=jnp.asarray(col_is_l3),
        id_bits=pack_bool_bits(
            jnp.asarray(np.concatenate([allow_nc, red_nc], axis=1))
        ),
    )
    return MaterializedState(
        tables=tables,
        snapshots=snapshots,
        ingress=ingress,
        endpoint_identity_ids=list(fields["endpoint_identity_ids"]),
        ep_rows=np.asarray(fields["ep_rows"], np.int32),
        ep_slots=ep_slots,
        allow_nc=allow_nc,
        red_nc=red_nc,
        n_cols=int(fields["n_cols"]),
    )


@jax.jit
def _patch_bitmap_rows(
    id_bits: jnp.ndarray,
    idx: jnp.ndarray,
    comb_rows: jnp.ndarray,
):
    return id_bits.at[idx].set(comb_rows)


@dataclasses.dataclass
class PlacedTables:
    """Mutable holder for the mesh-placed copies of a materialized
    direction's device tables (the pipeline's per-direction cache).
    The patch paths scatter the SAME idx/vals into these copies so the
    O(delta) discipline survives placement: a jit ``.at[].set`` on a
    sharded operand keeps the operand's sharding (GSPMD propagates it
    through the scatter), so a row patch under ``P("ident", None)``
    stays O(delta) per device — no re-place, no all-gather."""

    tables: PolicymapTables
    rule_tab: Optional[jnp.ndarray] = None


def patch_identity_rows(
    state: MaterializedState,
    compiled: CompiledPolicy,
    device: DevicePolicy,
    row_events: Sequence[Tuple[int, int, bool]],
    *,
    block: int = 8192,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
    placed: Optional[PlacedTables] = None,
) -> None:
    """Apply identity-churn row updates to a materialized policymap.

    ``row_events``: (row, identity_id, live) in order. Dead rows zero
    out; live rows get a fresh verdict sweep over every column segment
    of every endpoint — n_seg × k flows instead of the full n_seg × N
    re-materialization. Snapshots (host policymap dicts) are patched in
    place, so fastpath caches holding references see the update.

    When the state carries attribution (rule_nc/rule_tab) the patch
    sweep runs the attrib kernel variant too (pass ``attrib_origin``/
    ``n_rules`` from the engine); without an origin the patched rows'
    rule entries degrade to -1 (unattributed) rather than going stale."""
    if not row_events:
        return
    direction = TRAFFIC_INGRESS if state.ingress else TRAFFIC_EGRESS
    # last event per row wins for the verdict sweep; all ids seen on a
    # row get their stale snapshot entries dropped
    stale_ids = {int(ident) for _r, ident, _l in row_events}
    final: Dict[int, Tuple[int, bool]] = {}
    for row, ident, live in row_events:
        final[int(row)] = (int(ident), bool(live))

    for snap in state.snapshots:
        for key in [k for k in snap.entries if k.identity in stale_ids]:
            del snap.entries[key]

    rows = sorted(final)
    live_rows = [r for r in rows if final[r][1]]
    if live_rows:
        seg_subj: List[int] = []
        seg_port: List[int] = []
        seg_proto: List[int] = []
        seg_l4: List[bool] = []
        seg_col: List[int] = []
        seg_ep: List[int] = []
        col = 0
        for e, ep_row in enumerate(state.ep_rows):
            seg_subj.append(int(ep_row))
            seg_port.append(0)
            seg_proto.append(0)
            seg_l4.append(False)
            seg_col.append(col)
            seg_ep.append(e)
            col += 1
            for port, proto in state.ep_slots[e]:
                seg_subj.append(int(ep_row))
                seg_port.append(port)
                seg_proto.append(proto)
                seg_l4.append(True)
                seg_col.append(col)
                seg_ep.append(e)
                col += 1
        n_seg = len(seg_subj)
        k = len(live_rows)
        # Fit the verdict-batch block to the sweep: a single-identity
        # patch is n_seg·k ≈ E·(1+slots) flows, and padding that to the
        # dispatch-sized 8192 block makes the [block, S] matmuls ~100×
        # larger than the work (policyd-sparse: the O(k) update budget
        # is dominated by exactly this pad waste). Pow2 buckets (min
        # 64) keep the jit program count bounded by the ladder between
        # 64 and ``block``.
        block = min(block, max(64, _seg_bucket(n_seg * k)))
        peer = np.tile(np.asarray(live_rows, np.int32), n_seg)
        sweep_args = (
            device,
            jnp.asarray(np.repeat(np.asarray(seg_subj, np.int32), k)),
            jnp.asarray(peer),
            jnp.asarray(np.repeat(np.asarray(seg_port, np.int32), k)),
            jnp.asarray(np.repeat(np.asarray(seg_proto, np.int32), k)),
            jnp.asarray(np.repeat(np.asarray(seg_l4, bool), k)),
        )
        rl = None
        if state.rule_nc is not None and attrib_origin is not None:
            v, at, _hits = verdict_batch(
                *sweep_args,
                ingress=state.ingress,
                block=block,
                attrib=True,
                origin=attrib_origin,
                n_rules=n_rules,
            )
            # patch-path pull, same cadence as the dec/l3d/red pulls
            # below (baselined) — control plane, never per-flow
            rl = np.asarray(at.rule).reshape(n_seg, k)  # policyd-lint: disable=TPU001
        else:
            v = verdict_batch(*sweep_args, ingress=state.ingress, block=block)
        dec = np.asarray(v.decision).reshape(n_seg, k)
        l3d = np.asarray(v.l3).reshape(n_seg, k)
        red = np.asarray(v.l7_redirect).reshape(n_seg, k)

    for r in rows:
        state.allow_nc[r] = False
        state.red_nc[r] = False
        if state.rule_nc is not None:
            state.rule_nc[r] = -1

    if live_rows:
        row_pos = {r: i for i, r in enumerate(live_rows)}
        # per-endpoint L3 allow for the exact-entry condition
        ep_l3 = {}
        seg_i = 0
        for e in range(len(state.ep_rows)):
            ep_l3[e] = l3d[seg_i] == 1
            seg_i += 1 + len(state.ep_slots[e])
        seg_i = 0
        for e in range(len(state.ep_rows)):
            snap = state.snapshots[e]
            l3_allow = ep_l3[e]
            # L3 column
            ci = seg_col[seg_i]
            for r in live_rows:
                i = row_pos[r]
                allowed = l3_allow[i]
                state.allow_nc[r, ci] = allowed
                if rl is not None:
                    state.rule_nc[r, ci] = rl[seg_i, i]
                if allowed:
                    ident = final[r][0]
                    snap.entries[PolicyKey(ident, 0, 0, direction)] = 0
            seg_i += 1
            for port, proto in state.ep_slots[e]:
                ci = seg_col[seg_i]
                for r in live_rows:
                    i = row_pos[r]
                    allowed = dec[seg_i, i] == ALLOW
                    redir = bool(red[seg_i, i])
                    state.allow_nc[r, ci] = allowed
                    state.red_nc[r, ci] = allowed and redir
                    if rl is not None:
                        state.rule_nc[r, ci] = rl[seg_i, i]
                    if allowed and (not l3_allow[i] or redir):
                        ident = final[r][0]
                        snap.entries[PolicyKey(ident, port, proto, direction)] = int(redir)
                seg_i += 1

    idx = np.asarray(rows, np.int32)
    comb_rows = _pack_rows(
        np.concatenate([state.allow_nc[idx], state.red_nc[idx]], axis=1)
    )
    new_bits = _patch_bitmap_rows(
        state.tables.id_bits, jnp.asarray(idx), jnp.asarray(comb_rows)
    )
    state.tables = state.tables.replace(id_bits=new_bits)
    if placed is not None:
        # same scatter onto the mesh-placed copy: sharding propagates
        # through .at[].set, so the placed tables stay placed
        placed.tables = placed.tables.replace(
            id_bits=_patch_bitmap_rows(
                placed.tables.id_bits,
                jnp.asarray(idx),
                jnp.asarray(comb_rows),
            )
        )
    if state.rule_nc is not None and state.rule_tab is not None:
        rvals = jnp.asarray(state.rule_nc[idx])
        state.rule_tab = _patch_bitmap_rows(
            state.rule_tab, jnp.asarray(idx), rvals
        )
        if placed is not None and placed.rule_tab is not None:
            placed.rule_tab = _patch_bitmap_rows(
                placed.rule_tab, jnp.asarray(idx), rvals
            )


def _pack_rows(rows_bool: np.ndarray) -> np.ndarray:
    """[k, C_pad] bool → [k, C_pad/32] uint32 (C_pad is a multiple of
    32 by construction)."""
    packed = np.packbits(rows_bool, axis=1, bitorder="little")
    return packed.view(np.uint32).reshape(rows_bool.shape[0], rows_bool.shape[1] // 32)


def _pack_col_word(cols_bool: np.ndarray) -> np.ndarray:
    """[N, ≤32] bool column block → [N] uint32 (one packed id_bits
    word; short tails zero-pad, matching pack_bool_bits)."""
    n, w = cols_bool.shape
    if w < 32:
        cols_bool = np.concatenate(
            [cols_bool, np.zeros((n, 32 - w), bool)], axis=1
        )
    return np.packbits(cols_bool, axis=1, bitorder="little").view(np.uint32)[:, 0]


def _pad_cols_pow2(idx: np.ndarray, vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a column scatter to a power-of-two width by repeating the
    LAST column (duplicate .set with identical values is deterministic)
    so patch_bitmap_cols compiles per bucket, not per delta width."""
    k = idx.shape[0]
    bucket = 1
    while bucket < k:
        bucket <<= 1
    if bucket == k:
        return idx, vals
    return (
        np.concatenate([idx, np.repeat(idx[-1:], bucket - k)]),
        np.concatenate(
            [vals, np.repeat(vals[:, -1:], bucket - k, axis=1)], axis=1
        ),
    )


def patch_endpoints_state(
    state: MaterializedState,
    compiled: CompiledPolicy,
    device: DevicePolicy,
    touched_sids: Sequence[int],
    *,
    block: int = 8192,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
    sweep: str = "auto",
    placed: Optional[PlacedTables] = None,
) -> bool:
    """O(delta) column rematerialization for a rule append/delete.

    Every verdict term is gated on the SUBJECT selector (deny/allow
    cells, combos through s1, L7 filters through s7 — see
    _verdict_block), so a rule delta can only change policymap cells in
    columns belonging to endpoints whose identity matches one of the
    rule's subject selectors (``touched_sids``, from the engine's delta
    log). Re-sweep exactly those endpoints' column segments against the
    already-patched device tables and scatter the changed id_bits words
    / rule_tab columns — O(affected · N) instead of the full
    E × N re-materialization.

    Returns False when the delta is NOT expressible as a column patch
    and the caller must fall back to ``materialize_endpoints_state``:
    identity row capacity moved, attribution state mismatched, or an
    affected endpoint's slot set GREW (a new (port, proto) needs new
    columns — shrunken slot sets keep their stale columns, which
    re-sweep to the correct now-denied values). Snapshots of affected
    endpoints are rebuilt in place so fastpath caches holding
    references observe the update, mirroring patch_identity_rows."""
    n = compiled.id_bits.shape[0]
    if state.allow_nc.shape[0] != n:
        return False  # row-bucket crossing — full rebuild
    if (state.rule_nc is not None) != (attrib_origin is not None):
        return False
    sids = sorted({int(s) for s in touched_sids})
    n_ep = len(state.ep_rows)
    if not sids or n_ep == 0:
        return True
    s_words = device.sel_match.shape[1]
    if any(s >> 5 >= s_words for s in sids):
        return False  # selector axis outgrew the device tables

    # Affected endpoints: subject row matches any touched selector.
    # Bounded [E, S/32] control-plane pull of just the endpoint rows —
    # the O(delta) point of this path (never the [N, S/32] matrix).
    ep_sel = np.asarray(  # policyd-lint: disable=TPU001
        jnp.take(
            device.sel_match, jnp.asarray(state.ep_rows, np.int32), axis=0
        )
    )
    word = np.asarray([s >> 5 for s in sids])
    bit = np.asarray([s & 31 for s in sids], np.uint32)
    hit = ((ep_sel[:, word] >> bit[None, :]) & 1).astype(bool).any(axis=1)
    affected = np.nonzero(hit)[0]
    if affected.size == 0:
        return True  # no local endpoint matches the rule's subject

    # Canonical column offsets (the materializer's layout: one L3
    # column then one per slot, endpoint-major).
    col_of = np.zeros(n_ep + 1, np.int64)
    for e in range(n_ep):
        col_of[e + 1] = col_of[e] + 1 + len(state.ep_slots[e])
    if int(col_of[n_ep]) != state.n_cols:
        return False

    # Slot-layout guard: the patch reuses the existing columns.
    for e in affected:
        new_slots = _endpoint_slots(compiled, ep_sel[e], state.ingress)
        if not set(new_slots) <= set(state.ep_slots[e]):
            return False

    seg_row: List[int] = []
    seg_port: List[int] = []
    seg_proto: List[int] = []
    seg_l4: List[bool] = []
    for e in affected:
        row = int(state.ep_rows[e])
        seg_row.append(row)
        seg_port.append(0)
        seg_proto.append(0)
        seg_l4.append(False)
        for port, proto in state.ep_slots[e]:
            seg_row.append(row)
            seg_port.append(port)
            seg_proto.append(proto)
            seg_l4.append(True)

    allow_sn, l3_sn, red_sn, rule_sn = _sweep_segments(
        device,
        np.asarray(seg_row, np.int32),
        np.asarray(seg_port, np.int32),
        np.asarray(seg_proto, np.int32),
        np.asarray(seg_l4, bool),
        n,
        ingress=state.ingress,
        block=block,
        attrib_origin=attrib_origin,
        n_rules=n_rules,
        sweep=sweep,
    )

    live = compiled.row_live
    direction = TRAFFIC_INGRESS if state.ingress else TRAFFIC_EGRESS
    touched_cols: List[int] = []
    seg = 0
    for e in affected:
        snap = state.snapshots[e]
        l3_allow = l3_sn[seg] & live
        ci = int(col_of[e])
        state.allow_nc[:, ci] = l3_allow
        state.red_nc[:, ci] = False
        if state.rule_nc is not None:
            state.rule_nc[:, ci] = rule_sn[seg]
        touched_cols.append(ci)
        seg += 1
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(compiled.row_ids[r_idx]), 0, 0, direction)] = 0
        for j, (port, proto_n) in enumerate(state.ep_slots[e]):
            allow = allow_sn[seg] & live
            redirect = red_sn[seg] & live
            cj = ci + 1 + j
            state.allow_nc[:, cj] = allow
            state.red_nc[:, cj] = redirect
            if state.rule_nc is not None:
                state.rule_nc[:, cj] = rule_sn[seg]
            touched_cols.append(cj)
            seg += 1
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(compiled.row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        # in-place: fastpath caches hold references to this dict
        snap.entries.clear()
        snap.entries.update(entries)

    # Device scatter: only the packed words the touched columns live
    # in. Allow word w holds columns 32w..32w+31; the redirect copy of
    # word w sits c_pad/32 words later (id_bits = allow ‖ redirect).
    c_pad = state.allow_nc.shape[1]
    word_idx: List[int] = []
    word_vals: List[np.ndarray] = []
    for w in sorted({c >> 5 for c in touched_cols}):
        cols = slice(w * 32, min((w + 1) * 32, c_pad))
        word_idx.append(w)
        word_vals.append(_pack_col_word(state.allow_nc[:, cols]))
        word_idx.append(c_pad // 32 + w)
        word_vals.append(_pack_col_word(state.red_nc[:, cols]))
    idx, vals = _pad_cols_pow2(
        np.asarray(word_idx, np.int32), np.stack(word_vals, axis=1)
    )
    state.tables = state.tables.replace(
        id_bits=patch_bitmap_cols(
            state.tables.id_bits, jnp.asarray(idx), jnp.asarray(vals)
        )
    )
    if placed is not None:
        placed.tables = placed.tables.replace(
            id_bits=patch_bitmap_cols(
                placed.tables.id_bits, jnp.asarray(idx), jnp.asarray(vals)
            )
        )
    if state.rule_nc is not None and state.rule_tab is not None:
        ridx, rvals = _pad_cols_pow2(
            np.asarray(touched_cols, np.int32),
            state.rule_nc[:, touched_cols],
        )
        state.rule_tab = patch_bitmap_cols(
            state.rule_tab, jnp.asarray(ridx), jnp.asarray(rvals)
        )
        if placed is not None and placed.rule_tab is not None:
            placed.rule_tab = patch_bitmap_cols(
                placed.rule_tab, jnp.asarray(ridx), jnp.asarray(rvals)
            )
    return True


# -- sparse sel_match patching (policyd-sparse) -----------------------------
#
# The engine keeps the authoritative device sel_match; the pipeline keeps
# PLACED copies (replicated or P("ident")-sharded under MeshSharding2D).
# These helpers re-apply the engine's delta-log events to a placed copy
# as O(k) scatters instead of re-placing the full [N, S/32] matrix: a
# jit ``.at[].set`` on a sharded operand keeps the operand's sharding
# (GSPMD propagates it through the scatter), so the patch is O(delta)
# per device and the placed jit caches survive.


@jax.jit
def _scatter_sel_rows(
    sel_match: jnp.ndarray,
    idx: jnp.ndarray,  # [k] int32
    rows: jnp.ndarray,  # [k, S/32] uint32
) -> jnp.ndarray:
    # No donation: concurrent verdict readers may hold the old buffer.
    return sel_match.at[idx].set(rows)


@jax.jit
def _scatter_sel_cols(
    sel_match: jnp.ndarray,
    rows: jnp.ndarray,  # [k] int32
    cols: jnp.ndarray,  # [w] int32
    vals: jnp.ndarray,  # [k, w] uint32
) -> jnp.ndarray:
    return sel_match.at[rows[:, None], cols[None, :]].set(vals)


def _pow2_rows_vals(
    rows: np.ndarray, vals: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a (row indices, per-row values) scatter to a power-of-two
    bucket (min 8) by repeating the LAST entry — duplicate indices with
    identical values keep the scatter deterministic, and the bucket
    bounds jit recompiles to O(log k) programs per value width."""
    k = rows.shape[0]
    bucket = 8
    while bucket < k:
        bucket <<= 1
    if bucket == k:
        return rows, vals
    return (
        np.concatenate([rows, np.repeat(rows[-1:], bucket - k)]),
        np.concatenate([vals, np.repeat(vals[-1:], bucket - k, axis=0)]),
    )


def patch_selector_rows(
    sel_match: jnp.ndarray,
    ident_rows: Sequence[int],
    row_words: np.ndarray,  # [k, S/32] uint32 final-state packed rows
) -> jnp.ndarray:
    """Scatter whole packed sel_match rows (identity-churn deltas:
    engine ``"rows"`` events) into a device/placed copy. O(k · S/32)
    payload; returns the patched array (same placement as the input)."""
    rows = np.asarray(ident_rows, np.int32)
    if rows.size == 0:
        return sel_match
    vals = np.ascontiguousarray(row_words, dtype=np.uint32)
    rows, vals = _pow2_rows_vals(rows, vals)
    return _scatter_sel_rows(sel_match, jnp.asarray(rows), jnp.asarray(vals))


def patch_selector_cols(
    sel_match: jnp.ndarray,
    ident_rows: Sequence[int],
    word_cols: Sequence[int],
    vals: np.ndarray,  # [k, w] uint32 final-state packed words
) -> jnp.ndarray:
    """Scatter a CSR column-delta (selector-append deltas: engine
    ``"cols"`` events, built by compiler.selectors.selector_col_delta)
    into a device/placed sel_match copy: k touched identity rows × the
    appended selectors' word window. O(k · w) payload — for a selector
    matching k identities at N=1M this moves kilobytes where the dense
    re-place moved the full [N, S/32] matrix."""
    rows = np.asarray(ident_rows, np.int32)
    cols = np.asarray(word_cols, np.int32)
    if rows.size == 0 or cols.size == 0:
        return sel_match
    v = np.ascontiguousarray(vals, dtype=np.uint32)
    rows, v = _pow2_rows_vals(rows, v)
    return _scatter_sel_cols(
        sel_match, jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(v)
    )
