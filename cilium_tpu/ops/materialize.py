"""Policymap materialization: full verdict engine → realized lookup state.

The TPU replacement for the reference's hottest control-plane loop,
computeDesiredL3PolicyMapEntries (pkg/endpoint/policy.go:317-389): for
every local endpoint, evaluate the full policy for *every known
identity* (and every L4 slot) and emit the column-bitmap lookup tables
of ops/lookup.py plus host-visible policymap entries
(pkg/maps/policymap key format) for the datapath front-end.

The whole sweep — endpoints × identities × (L3 + each L4 slot) — is
flattened into ONE batched device call, so a full regeneration costs a
single dispatch regardless of endpoint count (the reference pays a
per-endpoint per-identity Go loop; we pay one kernel launch of int8
matmuls).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from typing import Optional

from ..compiler.program import CompiledPolicy, PROTO_TCP_N
from .bitmap import pack_bool_bits
from .lookup import PolicymapTables
from .verdict import ALLOW, AttribTables, DevicePolicy, verdict_batch

TRAFFIC_INGRESS = 0
TRAFFIC_EGRESS = 1


@dataclasses.dataclass(frozen=True)
class PolicyKey:
    """pkg/maps/policymap PolicyKey (policymap.go:64): identity, dport
    (0 = L3-only), nexthdr (0 = L3-only), traffic direction."""

    identity: int
    dport: int
    nexthdr: int
    direction: int


@dataclasses.dataclass
class EndpointPolicySnapshot:
    """Desired policymap for one endpoint + its slot layout. Entry value
    is the proxy-redirect flag (proxy port binding happens at the proxy
    layer, pkg/proxy/proxy.go port allocator)."""

    entries: Dict[PolicyKey, int]
    slots: List[Tuple[int, int]]


def _endpoint_slots(compiled: CompiledPolicy, subj_sel_row: np.ndarray, ingress: bool):
    """Distinct (port, proto) L4 slots this endpoint's policy can
    reference: L4 entries whose subject selector matches, plus
    L7-parser ports (always TCP)."""
    d = compiled.ingress if ingress else compiled.egress

    def sel_hit(sids: np.ndarray) -> np.ndarray:
        return (subj_sel_row[sids >> 5] >> (sids & 31)) & 1

    slots = set()
    if d.e_subj.size:
        hit = sel_hit(d.e_subj.astype(np.int64)) == 1
        for port, proto in zip(d.e_port[hit], d.e_proto[hit]):
            slots.add((int(port), int(proto)))
    if d.l7_subj.size:
        hit = sel_hit(d.l7_subj.astype(np.int64)) == 1
        for port in d.l7_port[hit]:
            slots.add((int(port), PROTO_TCP_N))
    return sorted(slots)


@dataclasses.dataclass
class MaterializedState:
    """Host mirror of the realized policymap: unpacked column bitmaps +
    metadata, enabling **row patches** for identity churn (the
    incremental half of syncPolicyMap, pkg/endpoint/endpoint.go:2572)
    without re-sweeping every (endpoint, identity) pair."""

    tables: PolicymapTables
    snapshots: List[EndpointPolicySnapshot]
    ingress: bool
    endpoint_identity_ids: List[int]
    ep_rows: np.ndarray  # [E] int32
    ep_slots: List[List[Tuple[int, int]]]
    allow_nc: np.ndarray  # [N, C_pad] bool (host, mutable)
    red_nc: np.ndarray  # [N, C_pad] bool
    n_cols: int
    # Verdict attribution (policyd-flows): per-(identity row, column)
    # deciding-rule index from an attrib=True sweep — EXACT per-peer
    # attribution for the pipeline's lookup path (-1 = no rule; deny
    # drops carry the deny rule even though their allow bit is 0).
    # None when the sweep ran without attribution (FlowAttribution off
    # or snapshot-restored compile with no rule-origin state).
    rule_nc: Optional[np.ndarray] = None  # [N, C_pad] int32 (host)
    rule_tab: Optional[jnp.ndarray] = None  # [N, C_pad] int32 (device)


def materialize_endpoints(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    block: int = 8192,
) -> Tuple[PolicymapTables, List[EndpointPolicySnapshot]]:
    st = materialize_endpoints_state(
        compiled, device, endpoint_identity_ids, ingress=ingress, block=block
    )
    return st.tables, st.snapshots


def _seg_bucket(n_seg: int) -> int:
    b = 8
    while b < n_seg:
        b <<= 1
    return b


@functools.partial(jax.jit, static_argnames=("n", "ingress", "block"))
def _sweep_device(
    policy: DevicePolicy,
    seg_row: jnp.ndarray,  # [n_seg] int32
    seg_port: jnp.ndarray,
    seg_proto: jnp.ndarray,
    seg_l4: jnp.ndarray,  # [n_seg] bool
    n: int,
    ingress: bool,
    block: int,
):
    """The endpoints × identities × slots sweep with the flattened
    index arrays generated ON DEVICE and results bit-packed before
    leaving it — the host⇄device traffic is [n_seg] in and
    3 × [n_seg, n/32] out instead of 5 × [n_seg·n] in and
    3 × [n_seg·n] out (the host-built repeat/tile arrays made the
    sweep upload-bound: ~600MB at the 100k-identity stretch scale)."""
    n_seg = seg_row.shape[0]
    subj = jnp.repeat(seg_row, n)
    peer = jnp.tile(jnp.arange(n, dtype=jnp.int32), n_seg)
    v = verdict_batch(
        policy,
        subj,
        peer,
        jnp.repeat(seg_port, n),
        jnp.repeat(seg_proto, n),
        jnp.repeat(seg_l4, n),
        ingress=ingress,
        block=block,
    )
    allow = pack_bool_bits((v.decision == ALLOW).reshape(n_seg, n))
    l3a = pack_bool_bits((v.l3 == 1).reshape(n_seg, n))
    red = pack_bool_bits(v.l7_redirect.reshape(n_seg, n))
    return allow, l3a, red


@functools.partial(
    jax.jit, static_argnames=("n", "ingress", "block", "n_rules")
)
def _sweep_device_attrib(
    policy: DevicePolicy,
    seg_row: jnp.ndarray,
    seg_port: jnp.ndarray,
    seg_proto: jnp.ndarray,
    seg_l4: jnp.ndarray,
    origin: AttribTables,
    n: int,
    ingress: bool,
    block: int,
    n_rules: int,
):
    """_sweep_device plus the attribution tail: also returns the
    [n_seg, n] int32 deciding-rule index per (segment, identity row) —
    the source of MaterializedState.rule_tab. A SEPARATE jitted entry
    so the attribution-off sweep keeps its exact original program."""
    n_seg = seg_row.shape[0]
    subj = jnp.repeat(seg_row, n)
    peer = jnp.tile(jnp.arange(n, dtype=jnp.int32), n_seg)
    v, at, _hits = verdict_batch(
        policy,
        subj,
        peer,
        jnp.repeat(seg_port, n),
        jnp.repeat(seg_proto, n),
        jnp.repeat(seg_l4, n),
        ingress=ingress,
        block=block,
        attrib=True,
        origin=origin,
        n_rules=n_rules,
    )
    allow = pack_bool_bits((v.decision == ALLOW).reshape(n_seg, n))
    l3a = pack_bool_bits((v.l3 == 1).reshape(n_seg, n))
    red = pack_bool_bits(v.l7_redirect.reshape(n_seg, n))
    return allow, l3a, red, at.rule.reshape(n_seg, n)


def _unpack_rows(words: np.ndarray, n: int) -> np.ndarray:
    """[n_seg, ceil(n/32)] uint32 → [n_seg, n] bool (pack_bool_bits
    inverse, host-side)."""
    words = np.ascontiguousarray(words)
    bits = np.unpackbits(
        words.view(np.uint8).reshape(words.shape[0], -1),
        axis=1,
        bitorder="little",
    )
    return bits[:, :n].astype(bool)


def materialize_endpoints_state(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    block: int = 8192,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
) -> MaterializedState:
    """``attrib_origin`` (with ``n_rules``) switches the sweep to the
    attribution kernel variant: the result additionally carries
    rule_nc/rule_tab, the exact per-(identity row, column) deciding-rule
    index the pipeline's lookup path gathers from under
    FlowAttribution. Off (None), the sweep and its jit program are
    untouched."""
    n = compiled.id_bits.shape[0]
    ep_rows = compiled.rows_for(endpoint_identity_ids)
    sel_match_host = np.asarray(device.sel_match)
    live = compiled.row_live
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    # Flatten (endpoint L3 sweep) + (endpoint, slot) sweeps into one batch.
    ep_slots: List[List[Tuple[int, int]]] = [
        _endpoint_slots(compiled, sel_match_host[row], ingress) for row in ep_rows
    ]
    seg_row: List[int] = []
    seg_port: List[int] = []
    seg_proto: List[int] = []
    seg_l4: List[bool] = []
    for e, row in enumerate(ep_rows):
        seg_row.append(int(row))
        seg_port.append(0)
        seg_proto.append(0)
        seg_l4.append(False)
        for port, proto in ep_slots[e]:
            seg_row.append(int(row))
            seg_port.append(port)
            seg_proto.append(proto)
            seg_l4.append(True)

    n_seg = len(seg_row)
    # Chunk the segment axis so one dispatch's flattened row count
    # stays bounded (~big-batch sized) regardless of endpoint count ×
    # identity capacity, then pad each chunk to a bucket (dummy L3
    # segs against row 0) so repeated materializations reuse the
    # compiled sweep.
    budget = max(8, (1 << 23) // max(1, n))
    seg_chunk = 1 << (budget.bit_length() - 1)  # power of two ≤ budget
    seg_chunk = min(seg_chunk, _seg_bucket(n_seg))
    aw_parts: List[np.ndarray] = []
    l3_parts: List[np.ndarray] = []
    rw_parts: List[np.ndarray] = []
    rl_parts: List[np.ndarray] = []
    sr = np.asarray(seg_row, np.int32)
    sp = np.asarray(seg_port, np.int32)
    spr = np.asarray(seg_proto, np.int32)
    sl = np.asarray(seg_l4, bool)
    for lo in range(0, n_seg, seg_chunk):
        hi = min(lo + seg_chunk, n_seg)
        pad = min(_seg_bucket(hi - lo), seg_chunk) - (hi - lo)
        chunk = (
            # control-plane rebuild: VRAM-bounded chunking over the
            # segment sweep — a handful of large device calls, not a
            # per-flow dispatch loop (the serving path never runs this)
            jnp.asarray(np.pad(sr[lo:hi], (0, pad))),  # policyd-lint: disable=TPU002
            jnp.asarray(np.pad(sp[lo:hi], (0, pad))),
            jnp.asarray(np.pad(spr[lo:hi], (0, pad))),
            jnp.asarray(np.pad(sl[lo:hi], (0, pad))),
        )
        if attrib_origin is None:
            aw, l3w, rw = _sweep_device(device, *chunk, n, ingress, block)
        else:
            aw, l3w, rw, rl = _sweep_device_attrib(
                device, *chunk, attrib_origin, n, ingress, block, n_rules
            )
            # control-plane rebuild pull, same cadence as the aw/l3w
            # pulls below (baselined) — never on the serving path
            rl_parts.append(np.asarray(rl)[: hi - lo])  # policyd-lint: disable=TPU001
        aw_parts.append(np.asarray(aw)[: hi - lo])
        l3_parts.append(np.asarray(l3w)[: hi - lo])
        rw_parts.append(np.asarray(rw)[: hi - lo])
    if aw_parts:
        allow_sn = _unpack_rows(np.concatenate(aw_parts), n)
        l3_sn = _unpack_rows(np.concatenate(l3_parts), n)
        red_sn = _unpack_rows(np.concatenate(rw_parts), n)
    else:  # zero endpoints: nothing to sweep
        allow_sn = l3_sn = red_sn = np.zeros((0, n), bool)
    rule_sn = (
        np.concatenate(rl_parts)
        if rl_parts
        else np.full((n_seg, n), -1, np.int32)
    )

    # Column layout: one column per (endpoint, L3) + (endpoint, slot).
    col_ep: List[int] = []
    col_port: List[int] = []
    col_proto: List[int] = []
    col_is_l3: List[bool] = []
    col_allow: List[np.ndarray] = []
    col_red: List[np.ndarray] = []
    col_rule: List[np.ndarray] = []
    snapshots: List[EndpointPolicySnapshot] = []

    seg = 0
    for e, row in enumerate(ep_rows):
        l3_allow = l3_sn[seg] & live
        col_rule.append(rule_sn[seg])
        seg += 1
        col_ep.append(e)
        col_port.append(0)
        col_proto.append(0)
        col_is_l3.append(True)
        col_allow.append(l3_allow)
        col_red.append(np.zeros(n, bool))
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(compiled.row_ids[r_idx]), 0, 0, direction)] = 0
        for port, proto_n in ep_slots[e]:
            allow = allow_sn[seg] & live
            redirect = red_sn[seg] & live
            col_rule.append(rule_sn[seg])
            seg += 1
            col_ep.append(e)
            col_port.append(port)
            col_proto.append(proto_n)
            col_is_l3.append(False)
            col_allow.append(allow)
            col_red.append(redirect)
            # Exact {id, port, proto} entries: the datapath consults the
            # exact key first (bpf/lib/policy.h:46), so L3-allowed
            # identities still need one when the filter redirects.
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(compiled.row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=ep_slots[e]))

    c = len(col_ep)
    c_pad = max(32, ((c + 31) // 32) * 32)
    pad = c_pad - c
    allow_nc = np.zeros((n, c_pad), bool)
    red_nc = np.zeros((n, c_pad), bool)
    rule_nc = None
    if attrib_origin is not None:
        rule_nc = np.full((n, c_pad), -1, np.int32)
    if c:
        allow_nc[:, :c] = np.stack(col_allow, axis=1)
        red_nc[:, :c] = np.stack(col_red, axis=1)
        if rule_nc is not None:
            rule_nc[:, :c] = np.stack(col_rule, axis=1)

    tables = PolicymapTables(
        col_ep=jnp.asarray(np.pad(np.asarray(col_ep, np.int32), (0, pad), constant_values=-1)),
        col_port=jnp.asarray(np.pad(np.asarray(col_port, np.int32), (0, pad))),
        col_proto=jnp.asarray(np.pad(np.asarray(col_proto, np.int32), (0, pad))),
        col_is_l3=jnp.asarray(np.pad(np.asarray(col_is_l3, bool), (0, pad))),
        # allow ‖ redirect in one table: the lookup kernel's row gather
        # lowers to a single one-hot matmul serving both bitmaps
        id_bits=pack_bool_bits(
            jnp.asarray(np.concatenate([allow_nc, red_nc], axis=1))
        ),
    )
    return MaterializedState(
        tables=tables,
        snapshots=snapshots,
        ingress=ingress,
        endpoint_identity_ids=list(endpoint_identity_ids),
        ep_rows=ep_rows,
        ep_slots=ep_slots,
        allow_nc=allow_nc,
        red_nc=red_nc,
        n_cols=c,
        rule_nc=rule_nc,
        rule_tab=jnp.asarray(rule_nc) if rule_nc is not None else None,
    )


def state_from_snapshot(row_ids: np.ndarray, fields: dict) -> MaterializedState:
    """Rebuild a MaterializedState from compiler/snapshot.py fields —
    the restore half of the pinned-map persistence analog. The column
    bitmaps are authoritative; per-endpoint snapshots (policymap dump
    surface) are re-derived from them, and the device tables re-packed
    and uploaded. No policy sweep runs: this is a load, not a derive."""
    allow_nc = np.asarray(fields["allow_nc"], bool)
    red_nc = np.asarray(fields["red_nc"], bool)
    col_ep = np.asarray(fields["col_ep"], np.int32)
    col_port = np.asarray(fields["col_port"], np.int32)
    col_proto = np.asarray(fields["col_proto"], np.int32)
    col_is_l3 = np.asarray(fields["col_is_l3"], bool)
    ep_slots = fields["ep_slots"]
    ingress = bool(fields["ingress"])
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    snapshots: List[EndpointPolicySnapshot] = []
    col = 0
    for e, slots in enumerate(ep_slots):
        l3_allow = allow_nc[:, col]
        col += 1
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(row_ids[r_idx]), 0, 0, direction)] = 0
        for port, proto_n in slots:
            allow = allow_nc[:, col]
            redirect = red_nc[:, col]
            col += 1
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=slots))

    tables = PolicymapTables(
        col_ep=jnp.asarray(col_ep),
        col_port=jnp.asarray(col_port),
        col_proto=jnp.asarray(col_proto),
        col_is_l3=jnp.asarray(col_is_l3),
        id_bits=pack_bool_bits(
            jnp.asarray(np.concatenate([allow_nc, red_nc], axis=1))
        ),
    )
    return MaterializedState(
        tables=tables,
        snapshots=snapshots,
        ingress=ingress,
        endpoint_identity_ids=list(fields["endpoint_identity_ids"]),
        ep_rows=np.asarray(fields["ep_rows"], np.int32),
        ep_slots=ep_slots,
        allow_nc=allow_nc,
        red_nc=red_nc,
        n_cols=int(fields["n_cols"]),
    )


@jax.jit
def _patch_bitmap_rows(
    id_bits: jnp.ndarray,
    idx: jnp.ndarray,
    comb_rows: jnp.ndarray,
):
    return id_bits.at[idx].set(comb_rows)


def patch_identity_rows(
    state: MaterializedState,
    compiled: CompiledPolicy,
    device: DevicePolicy,
    row_events: Sequence[Tuple[int, int, bool]],
    *,
    block: int = 8192,
    attrib_origin: Optional[AttribTables] = None,
    n_rules: int = 0,
) -> None:
    """Apply identity-churn row updates to a materialized policymap.

    ``row_events``: (row, identity_id, live) in order. Dead rows zero
    out; live rows get a fresh verdict sweep over every column segment
    of every endpoint — n_seg × k flows instead of the full n_seg × N
    re-materialization. Snapshots (host policymap dicts) are patched in
    place, so fastpath caches holding references see the update.

    When the state carries attribution (rule_nc/rule_tab) the patch
    sweep runs the attrib kernel variant too (pass ``attrib_origin``/
    ``n_rules`` from the engine); without an origin the patched rows'
    rule entries degrade to -1 (unattributed) rather than going stale."""
    if not row_events:
        return
    direction = TRAFFIC_INGRESS if state.ingress else TRAFFIC_EGRESS
    # last event per row wins for the verdict sweep; all ids seen on a
    # row get their stale snapshot entries dropped
    stale_ids = {int(ident) for _r, ident, _l in row_events}
    final: Dict[int, Tuple[int, bool]] = {}
    for row, ident, live in row_events:
        final[int(row)] = (int(ident), bool(live))

    for snap in state.snapshots:
        for key in [k for k in snap.entries if k.identity in stale_ids]:
            del snap.entries[key]

    rows = sorted(final)
    live_rows = [r for r in rows if final[r][1]]
    if live_rows:
        seg_subj: List[int] = []
        seg_port: List[int] = []
        seg_proto: List[int] = []
        seg_l4: List[bool] = []
        seg_col: List[int] = []
        seg_ep: List[int] = []
        col = 0
        for e, ep_row in enumerate(state.ep_rows):
            seg_subj.append(int(ep_row))
            seg_port.append(0)
            seg_proto.append(0)
            seg_l4.append(False)
            seg_col.append(col)
            seg_ep.append(e)
            col += 1
            for port, proto in state.ep_slots[e]:
                seg_subj.append(int(ep_row))
                seg_port.append(port)
                seg_proto.append(proto)
                seg_l4.append(True)
                seg_col.append(col)
                seg_ep.append(e)
                col += 1
        n_seg = len(seg_subj)
        k = len(live_rows)
        peer = np.tile(np.asarray(live_rows, np.int32), n_seg)
        sweep_args = (
            device,
            jnp.asarray(np.repeat(np.asarray(seg_subj, np.int32), k)),
            jnp.asarray(peer),
            jnp.asarray(np.repeat(np.asarray(seg_port, np.int32), k)),
            jnp.asarray(np.repeat(np.asarray(seg_proto, np.int32), k)),
            jnp.asarray(np.repeat(np.asarray(seg_l4, bool), k)),
        )
        rl = None
        if state.rule_nc is not None and attrib_origin is not None:
            v, at, _hits = verdict_batch(
                *sweep_args,
                ingress=state.ingress,
                block=block,
                attrib=True,
                origin=attrib_origin,
                n_rules=n_rules,
            )
            # patch-path pull, same cadence as the dec/l3d/red pulls
            # below (baselined) — control plane, never per-flow
            rl = np.asarray(at.rule).reshape(n_seg, k)  # policyd-lint: disable=TPU001
        else:
            v = verdict_batch(*sweep_args, ingress=state.ingress, block=block)
        dec = np.asarray(v.decision).reshape(n_seg, k)
        l3d = np.asarray(v.l3).reshape(n_seg, k)
        red = np.asarray(v.l7_redirect).reshape(n_seg, k)

    for r in rows:
        state.allow_nc[r] = False
        state.red_nc[r] = False
        if state.rule_nc is not None:
            state.rule_nc[r] = -1

    if live_rows:
        row_pos = {r: i for i, r in enumerate(live_rows)}
        # per-endpoint L3 allow for the exact-entry condition
        ep_l3 = {}
        seg_i = 0
        for e in range(len(state.ep_rows)):
            ep_l3[e] = l3d[seg_i] == 1
            seg_i += 1 + len(state.ep_slots[e])
        seg_i = 0
        for e in range(len(state.ep_rows)):
            snap = state.snapshots[e]
            l3_allow = ep_l3[e]
            # L3 column
            ci = seg_col[seg_i]
            for r in live_rows:
                i = row_pos[r]
                allowed = l3_allow[i]
                state.allow_nc[r, ci] = allowed
                if rl is not None:
                    state.rule_nc[r, ci] = rl[seg_i, i]
                if allowed:
                    ident = final[r][0]
                    snap.entries[PolicyKey(ident, 0, 0, direction)] = 0
            seg_i += 1
            for port, proto in state.ep_slots[e]:
                ci = seg_col[seg_i]
                for r in live_rows:
                    i = row_pos[r]
                    allowed = dec[seg_i, i] == ALLOW
                    redir = bool(red[seg_i, i])
                    state.allow_nc[r, ci] = allowed
                    state.red_nc[r, ci] = allowed and redir
                    if rl is not None:
                        state.rule_nc[r, ci] = rl[seg_i, i]
                    if allowed and (not l3_allow[i] or redir):
                        ident = final[r][0]
                        snap.entries[PolicyKey(ident, port, proto, direction)] = int(redir)
                seg_i += 1

    idx = np.asarray(rows, np.int32)
    comb_rows = _pack_rows(
        np.concatenate([state.allow_nc[idx], state.red_nc[idx]], axis=1)
    )
    new_bits = _patch_bitmap_rows(
        state.tables.id_bits, jnp.asarray(idx), jnp.asarray(comb_rows)
    )
    state.tables = state.tables.replace(id_bits=new_bits)
    if state.rule_nc is not None and state.rule_tab is not None:
        state.rule_tab = _patch_bitmap_rows(
            state.rule_tab, jnp.asarray(idx), jnp.asarray(state.rule_nc[idx])
        )


def _pack_rows(rows_bool: np.ndarray) -> np.ndarray:
    """[k, C_pad] bool → [k, C_pad/32] uint32 (C_pad is a multiple of
    32 by construction)."""
    packed = np.packbits(rows_bool, axis=1, bitorder="little")
    return packed.view(np.uint32).reshape(rows_bool.shape[0], rows_bool.shape[1] // 32)
