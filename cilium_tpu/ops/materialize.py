"""Policymap materialization: full verdict engine → realized lookup state.

The TPU replacement for the reference's hottest control-plane loop,
computeDesiredL3PolicyMapEntries (pkg/endpoint/policy.go:317-389): for
every local endpoint, evaluate the full policy for *every known
identity* (and every L4 slot) and emit the column-bitmap lookup tables
of ops/lookup.py plus host-visible policymap entries
(pkg/maps/policymap key format) for the datapath front-end.

The whole sweep — endpoints × identities × (L3 + each L4 slot) — is
flattened into ONE batched device call, so a full regeneration costs a
single dispatch regardless of endpoint count (the reference pays a
per-endpoint per-identity Go loop; we pay one kernel launch of int8
matmuls).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..compiler.program import CompiledPolicy, PROTO_TCP_N
from .bitmap import pack_bool_bits
from .lookup import PolicymapTables
from .verdict import ALLOW, DevicePolicy, verdict_batch

TRAFFIC_INGRESS = 0
TRAFFIC_EGRESS = 1


@dataclasses.dataclass(frozen=True)
class PolicyKey:
    """pkg/maps/policymap PolicyKey (policymap.go:64): identity, dport
    (0 = L3-only), nexthdr (0 = L3-only), traffic direction."""

    identity: int
    dport: int
    nexthdr: int
    direction: int


@dataclasses.dataclass
class EndpointPolicySnapshot:
    """Desired policymap for one endpoint + its slot layout. Entry value
    is the proxy-redirect flag (proxy port binding happens at the proxy
    layer, pkg/proxy/proxy.go port allocator)."""

    entries: Dict[PolicyKey, int]
    slots: List[Tuple[int, int]]


def _endpoint_slots(compiled: CompiledPolicy, subj_sel_row: np.ndarray, ingress: bool):
    """Distinct (port, proto) L4 slots this endpoint's policy can
    reference: L4 entries whose subject selector matches, plus
    L7-parser ports (always TCP)."""
    d = compiled.ingress if ingress else compiled.egress

    def sel_hit(sids: np.ndarray) -> np.ndarray:
        return (subj_sel_row[sids >> 5] >> (sids & 31)) & 1

    slots = set()
    if d.e_subj.size:
        hit = sel_hit(d.e_subj.astype(np.int64)) == 1
        for port, proto in zip(d.e_port[hit], d.e_proto[hit]):
            slots.add((int(port), int(proto)))
    if d.l7_subj.size:
        hit = sel_hit(d.l7_subj.astype(np.int64)) == 1
        for port in d.l7_port[hit]:
            slots.add((int(port), PROTO_TCP_N))
    return sorted(slots)


def materialize_endpoints(
    compiled: CompiledPolicy,
    device: DevicePolicy,
    endpoint_identity_ids: Sequence[int],
    *,
    ingress: bool = True,
    block: int = 8192,
) -> Tuple[PolicymapTables, List[EndpointPolicySnapshot]]:
    n = compiled.id_bits.shape[0]
    ep_rows = compiled.rows_for(endpoint_identity_ids)
    sel_match_host = np.asarray(device.sel_match)
    live = compiled.row_live
    direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS

    # Flatten (endpoint L3 sweep) + (endpoint, slot) sweeps into one batch.
    ep_slots: List[List[Tuple[int, int]]] = [
        _endpoint_slots(compiled, sel_match_host[row], ingress) for row in ep_rows
    ]
    seg_row: List[int] = []
    seg_port: List[int] = []
    seg_proto: List[int] = []
    seg_l4: List[bool] = []
    for e, row in enumerate(ep_rows):
        seg_row.append(int(row))
        seg_port.append(0)
        seg_proto.append(0)
        seg_l4.append(False)
        for port, proto in ep_slots[e]:
            seg_row.append(int(row))
            seg_port.append(port)
            seg_proto.append(proto)
            seg_l4.append(True)

    n_seg = len(seg_row)
    all_rows = np.arange(n, dtype=np.int32)
    v = verdict_batch(
        device,
        jnp.asarray(np.repeat(np.asarray(seg_row, np.int32), n)),
        jnp.asarray(np.tile(all_rows, n_seg)),
        jnp.asarray(np.repeat(np.asarray(seg_port, np.int32), n)),
        jnp.asarray(np.repeat(np.asarray(seg_proto, np.int32), n)),
        jnp.asarray(np.repeat(np.asarray(seg_l4, bool), n)),
        ingress=ingress,
        block=block,
    )
    dec = np.asarray(v.decision).reshape(n_seg, n)
    l3d = np.asarray(v.l3).reshape(n_seg, n)
    red = np.asarray(v.l7_redirect).reshape(n_seg, n)

    # Column layout: one column per (endpoint, L3) + (endpoint, slot).
    col_ep: List[int] = []
    col_port: List[int] = []
    col_proto: List[int] = []
    col_is_l3: List[bool] = []
    col_allow: List[np.ndarray] = []
    col_red: List[np.ndarray] = []
    snapshots: List[EndpointPolicySnapshot] = []

    seg = 0
    for e, row in enumerate(ep_rows):
        l3_allow = (l3d[seg] == 1) & live
        seg += 1
        col_ep.append(e)
        col_port.append(0)
        col_proto.append(0)
        col_is_l3.append(True)
        col_allow.append(l3_allow)
        col_red.append(np.zeros(n, bool))
        entries: Dict[PolicyKey, int] = {}
        for r_idx in np.nonzero(l3_allow)[0]:
            entries[PolicyKey(int(compiled.row_ids[r_idx]), 0, 0, direction)] = 0
        for port, proto_n in ep_slots[e]:
            allow = (dec[seg] == ALLOW) & live
            redirect = red[seg] & live
            seg += 1
            col_ep.append(e)
            col_port.append(port)
            col_proto.append(proto_n)
            col_is_l3.append(False)
            col_allow.append(allow)
            col_red.append(redirect)
            # Exact {id, port, proto} entries: the datapath consults the
            # exact key first (bpf/lib/policy.h:46), so L3-allowed
            # identities still need one when the filter redirects.
            for r_idx in np.nonzero(allow & (~l3_allow | redirect))[0]:
                key = PolicyKey(int(compiled.row_ids[r_idx]), port, proto_n, direction)
                entries[key] = int(redirect[r_idx])
        snapshots.append(EndpointPolicySnapshot(entries=entries, slots=ep_slots[e]))

    c = len(col_ep)
    c_pad = max(32, ((c + 31) // 32) * 32)
    pad = c_pad - c
    allow_nc = np.zeros((n, c_pad), bool)
    red_nc = np.zeros((n, c_pad), bool)
    if c:
        allow_nc[:, :c] = np.stack(col_allow, axis=1)
        red_nc[:, :c] = np.stack(col_red, axis=1)

    tables = PolicymapTables(
        col_ep=jnp.asarray(np.pad(np.asarray(col_ep, np.int32), (0, pad), constant_values=-1)),
        col_port=jnp.asarray(np.pad(np.asarray(col_port, np.int32), (0, pad))),
        col_proto=jnp.asarray(np.pad(np.asarray(col_proto, np.int32), (0, pad))),
        col_is_l3=jnp.asarray(np.pad(np.asarray(col_is_l3, bool), (0, pad))),
        id_allow=pack_bool_bits(jnp.asarray(allow_nc)),
        id_redirect=pack_bool_bits(jnp.asarray(red_nc)),
    )
    return tables, snapshots
