"""Batched policy-verdict kernel.

Evaluates, entirely on device, the verdict semantics of
pkg/policy/repository.go AllowsIngressRLocked/AllowsEgressRLocked for a
batch of flows (subject identity row, peer identity row, dport, proto):

    deny      = any deny-pair (subject selected & requirement unmatched)
    l3_allow  = any allow-pair (subject selected & peer matched)
    req_ok    = ¬deny                       # folded-requirements term
    l4_allow  = any L4 entry | any wildcard-L3L4 entry
    verdict   = ALLOW  if l3_allow & ¬deny
              | ALLOW  if flow has L4 context & l4_allow
              | DENY   otherwise

All selector tests are single-gather bit probes into the precomputed
``sel_match`` matrix (ops/bitmap.py), so per-flow cost is a fixed set
of gathers + reductions — no data-dependent control flow, fully
batchable and shardable.
"""

from __future__ import annotations

import dataclasses
import functools

import chex
import jax
import jax.numpy as jnp

from ..compiler.program import CompiledPolicy, DirectionProgram
from ..policy.search import Decision

ALLOW = int(Decision.ALLOWED)
DENY = int(Decision.DENIED)


@chex.dataclass(frozen=True)
class Verdict:
    """Per-flow results. ``decision``: 1 allow / 2 deny. ``l3`` is the
    pure-L3 stage decision (0 undecided / 1 allowed / 2 denied) used by
    the policymap materializer; ``l7_redirect`` flags flows whose allow
    came only from L7-bearing entries (proxy redirect candidates)."""

    decision: jnp.ndarray
    l3: jnp.ndarray
    l7_redirect: jnp.ndarray


@chex.dataclass(frozen=True)
class DeviceTables:
    """DirectionProgram as device arrays (a pytree leaf bundle)."""

    deny_subj: jnp.ndarray
    deny_req: jnp.ndarray
    deny_valid: jnp.ndarray
    allow_subj: jnp.ndarray
    allow_peer: jnp.ndarray
    allow_valid: jnp.ndarray
    e_subj: jnp.ndarray
    e_peer: jnp.ndarray
    e_port: jnp.ndarray
    e_proto: jnp.ndarray
    e_explicit: jnp.ndarray
    e_group: jnp.ndarray
    e_valid: jnp.ndarray
    group_no_peers: jnp.ndarray
    gp_group: jnp.ndarray
    gp_sel: jnp.ndarray
    gp_explicit: jnp.ndarray
    gp_valid: jnp.ndarray
    l7_subj: jnp.ndarray
    l7_port: jnp.ndarray
    l7_group: jnp.ndarray
    l7_valid: jnp.ndarray

    @classmethod
    def from_host(cls, d: DirectionProgram) -> "DeviceTables":
        return cls(**{
            f.name: jnp.asarray(getattr(d, f.name))
            for f in dataclasses.fields(DirectionProgram)
        })


@chex.dataclass(frozen=True)
class DevicePolicy:
    """Fully device-resident compiled policy."""

    id_bits: jnp.ndarray  # [N, W] uint32
    sel_match: jnp.ndarray  # [N, S_words] uint32 (bit-packed over selectors)
    ingress: DeviceTables
    egress: DeviceTables


def _sel_bit(
    sel_flat: jnp.ndarray, s_words: int, rows: jnp.ndarray, sel_ids: jnp.ndarray
) -> jnp.ndarray:
    """[B] rows × [P] selector ids → [B, P] bool membership probes."""
    word = sel_ids >> 5
    shift = (sel_ids & 31).astype(jnp.uint32)
    flat_idx = rows[:, None] * s_words + word[None, :]
    words = jnp.take(sel_flat, flat_idx, axis=0)
    return ((words >> shift[None, :]) & jnp.uint32(1)).astype(bool)


def _verdict_block(
    sel_match: jnp.ndarray,
    t: DeviceTables,
    subj_rows: jnp.ndarray,
    peer_rows: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    has_l4: jnp.ndarray,
) -> Verdict:
    s_words = sel_match.shape[1]
    sf = sel_match.reshape(-1)
    b = subj_rows.shape[0]

    deny = (
        _sel_bit(sf, s_words, subj_rows, t.deny_subj)
        & ~_sel_bit(sf, s_words, peer_rows, t.deny_req)
        & t.deny_valid[None, :]
    ).any(axis=1)
    l3_allow = (
        _sel_bit(sf, s_words, subj_rows, t.allow_subj)
        & _sel_bit(sf, s_words, peer_rows, t.allow_peer)
        & t.allow_valid[None, :]
    ).any(axis=1)
    req_ok = ~deny

    peer_hit = _sel_bit(sf, s_words, peer_rows, t.e_peer)
    entry_ok = (
        _sel_bit(sf, s_words, subj_rows, t.e_subj)
        & (dport[:, None] == t.e_port[None, :])
        & (proto[:, None] == t.e_proto[None, :])
        & peer_hit
        & (~t.e_explicit[None, :] | req_ok[:, None])
        & t.e_valid[None, :]
    )
    l4_allow = entry_ok.any(axis=1)

    # Pre-check per directional-rule group (rule.go:133-138): a one-hot
    # matmul instead of scatter-max (cheaper to compile, MXU-friendly).
    gp_hit = (
        _sel_bit(sf, s_words, peer_rows, t.gp_sel)
        & (~t.gp_explicit[None, :] | req_ok[:, None])
        & t.gp_valid[None, :]
    ).astype(jnp.int8)
    g = t.group_no_peers.shape[0]
    onehot = (t.gp_group[:, None] == jnp.arange(g)[None, :]).astype(jnp.int8)
    group_ok = (
        jax.lax.dot_general(
            gp_hit, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        > 0
    ) | t.group_no_peers[None, :]

    # Merged-filter parser presence at (port, TCP) — the redirect gate.
    l7_present = (
        _sel_bit(sf, s_words, subj_rows, t.l7_subj)
        & (dport[:, None] == t.l7_port[None, :])
        & (proto[:, None] == jnp.int32(6))
        & jnp.take(group_ok, t.l7_group, axis=1)
        & t.l7_valid[None, :]
    ).any(axis=1)

    l3 = jnp.where(deny, jnp.int8(2), jnp.where(l3_allow, jnp.int8(1), jnp.int8(0)))
    decision = jnp.where(
        l3_allow & ~deny,
        jnp.int8(ALLOW),
        jnp.where(has_l4 & l4_allow, jnp.int8(ALLOW), jnp.int8(DENY)),
    )
    # Datapath redirect semantics (bpf/lib/policy.h lookup order: the
    # exact {id,port,proto} entry wins over the L3-only entry): a flow
    # allowed at L4 through a parser-bearing filter redirects even when
    # L3 also allows it.
    l7_redirect = has_l4 & l4_allow & l7_present
    return Verdict(decision=decision, l3=l3, l7_redirect=l7_redirect)


@functools.partial(jax.jit, static_argnames=("ingress", "block"))
def verdict_batch(
    policy: DevicePolicy,
    subj_rows: jnp.ndarray,  # [B] int32 identity rows
    peer_rows: jnp.ndarray,  # [B] int32
    dport: jnp.ndarray,  # [B] int32 (with has_l4)
    proto: jnp.ndarray,  # [B] int32 IANA proto (6/17)
    has_l4: jnp.ndarray,  # [B] bool — False = pure-L3 query
    ingress: bool = True,
    block: int = 4096,
) -> Verdict:
    """Batch verdicts; blocks the batch with lax.map to bound the
    [block, table_len] gather intermediates."""
    t = policy.ingress if ingress else policy.egress
    b = subj_rows.shape[0]
    pad = (-b) % block

    def pad1(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill).reshape(-1, block)

    args = (pad1(subj_rows), pad1(peer_rows), pad1(dport), pad1(proto), pad1(has_l4))
    out = jax.lax.map(
        lambda xs: _verdict_block(policy.sel_match, t, *xs), args
    )
    return jax.tree_util.tree_map(lambda x: x.reshape(-1)[:b], out)
