"""Batched policy-verdict kernel (matmul formulation).

Evaluates, entirely on device, the verdict semantics of
pkg/policy/repository.go AllowsIngressRLocked/AllowsEgressRLocked for a
batch of flows (subject identity row, peer identity row, dport, proto):

    deny      = any(subj ∧ ((1-peer) @ deny_matᵀ > 0))
    l3_allow  = any(subj ∧ (peer @ allow_matᵀ > 0))
    req_ok    = ¬deny                        # folded-requirements term
    combo     = (subj @ s1) ∧ (port_onehot @ p1)
    l4_allow  = any(combo ∧ peer@enᵀ) | req_ok ∧ any(combo ∧ peer@eeᵀ)
    l7_present= any((subj @ s7) ∧ (port @ p7) ∧ (group_ok @ g7))
    verdict   = ALLOW  if l3_allow ∧ ¬deny
              | ALLOW  if flow has L4 context ∧ l4_allow
              | DENY   otherwise

Per flow the only data-dependent access is ONE packed row-gather from
``sel_match`` (an embedding lookup); everything else is int8 matmuls on
the MXU plus elementwise logic on the VPU. This is deliberate: TPU
executes per-element dynamic gathers essentially serially, so the
earlier gather-per-(flow, rule-pair) formulation ran ~1000× slower than
this one.
"""

from __future__ import annotations

import functools

import chex
import jax
import jax.numpy as jnp

from ..compiler.program import DirectionProgram
from ..policy.search import Decision
from .bitmap import unpack_bits_u32

ALLOW = int(Decision.ALLOWED)
DENY = int(Decision.DENIED)


@chex.dataclass(frozen=True)
class Verdict:
    """Per-flow results. ``decision``: 1 allow / 2 deny. ``l3`` is the
    pure-L3 stage decision (0 undecided / 1 allowed / 2 denied) used by
    the policymap materializer; ``l7_redirect`` flags flows whose L4
    allow passes through a parser-bearing filter (proxy redirect)."""

    decision: jnp.ndarray
    l3: jnp.ndarray
    l7_redirect: jnp.ndarray


@chex.dataclass(frozen=True)
class DeviceTables:
    """DirectionProgram matrices as device arrays. Transposed copies of
    the peer-side relations are stored so the kernel's contractions all
    run with the contracted axis leading (no per-call transpose)."""

    deny_t: jnp.ndarray  # [S, S]  deny_matᵀ
    allow_t: jnp.ndarray  # [S, S]  allow_matᵀ
    ports: jnp.ndarray  # [P4]
    protos: jnp.ndarray  # [P4]
    s1_mat: jnp.ndarray  # [S, K1]
    p1_mat: jnp.ndarray  # [P4, K1]
    en_t: jnp.ndarray  # [S, K1]  en_matᵀ
    ee_t: jnp.ndarray  # [S, K1]  ee_matᵀ
    gpn_mat: jnp.ndarray  # [S, G]
    gpe_mat: jnp.ndarray  # [S, G]
    group_no_peers: jnp.ndarray  # [G]
    s7_mat: jnp.ndarray  # [S, K7]
    p7_mat: jnp.ndarray  # [P4, K7]
    g7_mat: jnp.ndarray  # [G, K7]

    @classmethod
    def from_host(cls, d: DirectionProgram) -> "DeviceTables":
        return cls(
            deny_t=jnp.asarray(d.deny_mat.T),
            allow_t=jnp.asarray(d.allow_mat.T),
            ports=jnp.asarray(d.ports),
            protos=jnp.asarray(d.protos),
            s1_mat=jnp.asarray(d.s1_mat),
            p1_mat=jnp.asarray(d.p1_mat),
            en_t=jnp.asarray(d.en_mat.T),
            ee_t=jnp.asarray(d.ee_mat.T),
            gpn_mat=jnp.asarray(d.gpn_mat),
            gpe_mat=jnp.asarray(d.gpe_mat),
            group_no_peers=jnp.asarray(d.group_no_peers),
            s7_mat=jnp.asarray(d.s7_mat),
            p7_mat=jnp.asarray(d.p7_mat),
            g7_mat=jnp.asarray(d.g7_mat),
        )


@chex.dataclass(frozen=True)
class DevicePolicy:
    """Fully device-resident compiled policy."""

    id_bits: jnp.ndarray  # [N, W] uint32
    sel_match: jnp.ndarray  # [N, S/32] uint32 (bit-packed selector matches)
    ingress: DeviceTables
    egress: DeviceTables


def _mm(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """int8 [B, A] @ int8 [A, C] → bool [B, C] (int32 accumulate)."""
    return (
        jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )
        > 0
    )


def _verdict_block(
    sel_match: jnp.ndarray,
    t: DeviceTables,
    subj_rows: jnp.ndarray,
    peer_rows: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    has_l4: jnp.ndarray,
) -> Verdict:
    subj8 = unpack_bits_u32(jnp.take(sel_match, subj_rows, axis=0))  # [b, S]
    peer8 = unpack_bits_u32(jnp.take(sel_match, peer_rows, axis=0))
    subj_b = subj8.astype(bool)

    deny = (subj_b & _mm(jnp.int8(1) - peer8, t.deny_t)).any(axis=1)
    l3_allow = (subj_b & _mm(peer8, t.allow_t)).any(axis=1)
    req_ok = ~deny

    pp = (
        (dport[:, None] == t.ports[None, :])
        & (proto[:, None] == t.protos[None, :])
        & has_l4[:, None]
    ).astype(jnp.int8)

    combo = _mm(subj8, t.s1_mat) & _mm(pp, t.p1_mat)  # [b, K1]
    l4_allow = (combo & _mm(peer8, t.en_t)).any(axis=1) | (
        req_ok & (combo & _mm(peer8, t.ee_t)).any(axis=1)
    )

    group_ok = (
        _mm(peer8, t.gpn_mat)
        | (_mm(peer8, t.gpe_mat) & req_ok[:, None])
        | t.group_no_peers[None, :]
    )  # [b, G]
    l7_present = (
        _mm(subj8, t.s7_mat)
        & _mm(pp, t.p7_mat)
        & _mm(group_ok.astype(jnp.int8), t.g7_mat)
    ).any(axis=1)

    l3 = jnp.where(deny, jnp.int8(2), jnp.where(l3_allow, jnp.int8(1), jnp.int8(0)))
    decision = jnp.where(
        l3_allow & ~deny,
        jnp.int8(ALLOW),
        jnp.where(has_l4 & l4_allow, jnp.int8(ALLOW), jnp.int8(DENY)),
    )
    # Datapath redirect semantics (bpf/lib/policy.h lookup order: the
    # exact {id,port,proto} entry wins over the L3-only entry): a flow
    # allowed at L4 through a parser-bearing filter redirects even when
    # L3 also allows it.
    l7_redirect = has_l4 & l4_allow & l7_present
    return Verdict(decision=decision, l3=l3, l7_redirect=l7_redirect)


@functools.partial(jax.jit, static_argnames=("ingress", "block"))
def verdict_batch(
    policy: DevicePolicy,
    subj_rows: jnp.ndarray,  # [B] int32 identity rows
    peer_rows: jnp.ndarray,  # [B] int32
    dport: jnp.ndarray,  # [B] int32 (with has_l4)
    proto: jnp.ndarray,  # [B] int32 IANA proto (u8proto)
    has_l4: jnp.ndarray,  # [B] bool — False = pure-L3 query
    ingress: bool = True,
    block: int = 8192,
) -> Verdict:
    """Batch verdicts; blocks the batch with lax.map to bound the
    [block, S] activation footprint."""
    t = policy.ingress if ingress else policy.egress
    b = subj_rows.shape[0]
    pad = (-b) % block

    def pad1(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill).reshape(-1, block)

    args = (pad1(subj_rows), pad1(peer_rows), pad1(dport), pad1(proto), pad1(has_l4))
    out = jax.lax.map(
        lambda xs: _verdict_block(policy.sel_match, t, *xs), args
    )
    return jax.tree_util.tree_map(lambda x: x.reshape(-1)[:b], out)
