"""Batched policy-verdict kernel (matmul formulation).

Evaluates, entirely on device, the verdict semantics of
pkg/policy/repository.go AllowsIngressRLocked/AllowsEgressRLocked for a
batch of flows (subject identity row, peer identity row, dport, proto):

    deny      = any(subj ∧ ((1-peer) @ deny_matᵀ > 0))
    l3_allow  = any(subj ∧ (peer @ allow_matᵀ > 0))
    req_ok    = ¬deny                        # folded-requirements term
    combo     = (subj @ s1) ∧ (port_onehot @ p1)
    l4_allow  = any(combo ∧ peer@enᵀ) | req_ok ∧ any(combo ∧ peer@eeᵀ)
    l7_present= any((subj @ s7) ∧ (port @ p7) ∧ (group_ok @ g7))
    verdict   = ALLOW  if l3_allow ∧ ¬deny
              | ALLOW  if flow has L4 context ∧ l4_allow
              | DENY   otherwise

Per flow the only data-dependent access is ONE packed row-gather from
``sel_match`` (an embedding lookup); everything else is int8 matmuls on
the MXU plus elementwise logic on the VPU. This is deliberate: TPU
executes per-element dynamic gathers essentially serially, so the
earlier gather-per-(flow, rule-pair) formulation ran ~1000× slower than
this one.
"""

from __future__ import annotations

import functools

import chex
import jax
import jax.numpy as jnp

from ..compiler.program import DirectionProgram
from ..policy.search import Decision
from .bitmap import unpack_bits_u32

ALLOW = int(Decision.ALLOWED)
DENY = int(Decision.DENIED)

# -- verdict attribution (policyd-flows) ---------------------------------
# Per-flow attribution reason codes emitted by the attrib=True kernel
# variant. These classify WHICH term decided the flow; the pipeline maps
# them onto the monitor's DropNotify reason taxonomy
# (monitor/events.py REASON_POLICY_*).
ATTR_ALLOW = 0  # allowed (rule = the first-match allowing rule)
ATTR_DENY_RULE = 1  # an explicit deny (FromRequires) rule matched
ATTR_NO_L3 = 2  # dropped: no L3 allow covered the peer
ATTR_NO_L4 = 3  # dropped: L4 coverage existed, peer not allowed
ATTR_L7 = 4  # allowed via a parser-bearing filter (proxy redirect)

ATTR_NAMES = {
    ATTR_ALLOW: "allowed",
    ATTR_DENY_RULE: "deny-rule",
    ATTR_NO_L3: "no-l3-match",
    ATTR_NO_L4: "no-l4-match",
    ATTR_L7: "l7-redirect",
}

# Sentinel for "no rule contributes to this term" in the origin arrays
# (min-reduction identity; converted to -1 in the per-flow output).
NO_RULE = 2**31 - 1


@chex.dataclass(frozen=True)
class AttribTables:
    """Term→rule origin arrays for the attribution kernel variant:
    the FIRST (lowest-index) repository rule contributing each deny
    subject-selector, pure-L3-allow subject-selector, and L4 combo —
    first-contributing-rule-wins mirrors the reference's in-order rule
    walk. Entries with no contributing rule hold ``NO_RULE``. Built by
    ``compiler.program.build_attrib_tables``."""

    deny_rule: jnp.ndarray  # [S] int32
    allow_rule: jnp.ndarray  # [S] int32
    combo_rule: jnp.ndarray  # [K1] int32


@chex.dataclass(frozen=True)
class Attribution:
    """Per-flow attribution (attrib=True only). ``rule``: repository
    rule index that decided the flow (-1 = no rule — a no-match drop).
    ``reason``: ATTR_* code."""

    rule: jnp.ndarray  # [B] int32
    reason: jnp.ndarray  # [B] int8


@chex.dataclass(frozen=True)
class Verdict:
    """Per-flow results. ``decision``: 1 allow / 2 deny. ``l3`` is the
    pure-L3 stage decision (0 undecided / 1 allowed / 2 denied) used by
    the policymap materializer; ``l7_redirect`` flags flows whose L4
    allow passes through a parser-bearing filter (proxy redirect)."""

    decision: jnp.ndarray
    l3: jnp.ndarray
    l7_redirect: jnp.ndarray


@chex.dataclass(frozen=True)
class DeviceTables:
    """DirectionProgram matrices as device arrays. Transposed copies of
    the peer-side relations are stored so the kernel's contractions all
    run with the contracted axis leading (no per-call transpose)."""

    deny_t: jnp.ndarray  # [S, S]  deny_matᵀ
    allow_t: jnp.ndarray  # [S, S]  allow_matᵀ
    ports: jnp.ndarray  # [P4]
    protos: jnp.ndarray  # [P4]
    s1_mat: jnp.ndarray  # [S, K1]
    p1_mat: jnp.ndarray  # [P4, K1]
    en_t: jnp.ndarray  # [S, K1]  en_matᵀ
    ee_t: jnp.ndarray  # [S, K1]  ee_matᵀ
    gpn_mat: jnp.ndarray  # [S, G]
    gpe_mat: jnp.ndarray  # [S, G]
    group_no_peers: jnp.ndarray  # [G]
    s7_mat: jnp.ndarray  # [S, K7]
    p7_mat: jnp.ndarray  # [P4, K7]
    g7_mat: jnp.ndarray  # [G, K7]

    @classmethod
    def from_host(cls, d: DirectionProgram) -> "DeviceTables":
        return cls(
            deny_t=jnp.asarray(d.deny_mat.T),
            allow_t=jnp.asarray(d.allow_mat.T),
            ports=jnp.asarray(d.ports),
            protos=jnp.asarray(d.protos),
            s1_mat=jnp.asarray(d.s1_mat),
            p1_mat=jnp.asarray(d.p1_mat),
            en_t=jnp.asarray(d.en_mat.T),
            ee_t=jnp.asarray(d.ee_mat.T),
            gpn_mat=jnp.asarray(d.gpn_mat),
            gpe_mat=jnp.asarray(d.gpe_mat),
            group_no_peers=jnp.asarray(d.group_no_peers),
            s7_mat=jnp.asarray(d.s7_mat),
            p7_mat=jnp.asarray(d.p7_mat),
            g7_mat=jnp.asarray(d.g7_mat),
        )


@chex.dataclass(frozen=True)
class DevicePolicy:
    """Fully device-resident compiled policy."""

    id_bits: jnp.ndarray  # [N, W] uint32
    sel_match: jnp.ndarray  # [N, S/32] uint32 (bit-packed selector matches)
    ingress: DeviceTables
    egress: DeviceTables


def _mm(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """int8 [B, A] @ int8 [A, C] → bool [B, C] (int32 accumulate)."""
    return (
        jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )
        > 0
    )


def _verdict_block(
    sel_match: jnp.ndarray,
    t: DeviceTables,
    subj_rows: jnp.ndarray,
    peer_rows: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    has_l4: jnp.ndarray,
    origin: "AttribTables" = None,
):
    subj8 = unpack_bits_u32(jnp.take(sel_match, subj_rows, axis=0))  # [b, S]
    peer8 = unpack_bits_u32(jnp.take(sel_match, peer_rows, axis=0))
    subj_b = subj8.astype(bool)

    deny_vec = subj_b & _mm(jnp.int8(1) - peer8, t.deny_t)  # [b, S]
    allow_vec = subj_b & _mm(peer8, t.allow_t)  # [b, S]
    deny = deny_vec.any(axis=1)
    l3_allow = allow_vec.any(axis=1)
    req_ok = ~deny

    pp = (
        (dport[:, None] == t.ports[None, :])
        & (proto[:, None] == t.protos[None, :])
        & has_l4[:, None]
    ).astype(jnp.int8)

    combo = _mm(subj8, t.s1_mat) & _mm(pp, t.p1_mat)  # [b, K1]
    en_hit = combo & _mm(peer8, t.en_t)  # [b, K1]
    ee_hit = combo & _mm(peer8, t.ee_t)  # [b, K1]
    l4_allow = en_hit.any(axis=1) | (req_ok & ee_hit.any(axis=1))

    group_ok = (
        _mm(peer8, t.gpn_mat)
        | (_mm(peer8, t.gpe_mat) & req_ok[:, None])
        | t.group_no_peers[None, :]
    )  # [b, G]
    l7_present = (
        _mm(subj8, t.s7_mat)
        & _mm(pp, t.p7_mat)
        & _mm(group_ok.astype(jnp.int8), t.g7_mat)
    ).any(axis=1)

    l3 = jnp.where(deny, jnp.int8(2), jnp.where(l3_allow, jnp.int8(1), jnp.int8(0)))
    decision = jnp.where(
        l3_allow & ~deny,
        jnp.int8(ALLOW),
        jnp.where(has_l4 & l4_allow, jnp.int8(ALLOW), jnp.int8(DENY)),
    )
    # Datapath redirect semantics (bpf/lib/policy.h lookup order: the
    # exact {id,port,proto} entry wins over the L3-only entry): a flow
    # allowed at L4 through a parser-bearing filter redirects even when
    # L3 also allows it.
    l7_redirect = has_l4 & l4_allow & l7_present
    verdict = Verdict(decision=decision, l3=l3, l7_redirect=l7_redirect)
    if origin is None:
        return verdict

    # -- attribution (policyd-flows): first-match rule + reason ----------
    # Masked min over the pre-reduction term vectors picks the LOWEST
    # repository rule index whose cell fired — the reference's in-order
    # rule walk stops at the first decider. All [b, S]/[b, K1] operands
    # already exist above; this adds three where+min reductions and a
    # select chain, no extra matmuls or gathers.
    def _first(mask, rule_of):
        return jnp.min(
            jnp.where(mask, rule_of[None, :], jnp.int32(NO_RULE)), axis=1
        )

    deny_rule = _first(deny_vec, origin.deny_rule)
    allow_rule = _first(allow_vec, origin.allow_rule)
    combo_fired = en_hit | (req_ok[:, None] & ee_hit)  # [b, K1]
    l4_rule = _first(combo_fired, origin.combo_rule)

    # Attribute by what actually DECIDED: pure-L3 allow wins over the
    # L4 path (repository walk order); a deny only decides when the
    # flow really dropped (an en-side L4 entry can allow past a deny).
    allowed = decision == jnp.int8(ALLOW)
    l3_decides = l3_allow & ~deny
    rule = jnp.where(
        allowed,
        jnp.where(l3_decides, allow_rule, l4_rule),
        jnp.where(deny, deny_rule, jnp.int32(NO_RULE)),
    )
    rule = jnp.where(rule == NO_RULE, jnp.int32(-1), rule)

    # Drop refinement: with L4 context and any combo covering the
    # subject at this port, the peer was the missing half (no-L4);
    # otherwise nothing covered the flow at all (no-L3).
    l4_covered = has_l4 & combo.any(axis=1)
    dropped = decision == jnp.int8(DENY)
    reason = jnp.where(
        dropped,
        jnp.where(
            deny,
            jnp.int8(ATTR_DENY_RULE),
            jnp.where(l4_covered, jnp.int8(ATTR_NO_L4), jnp.int8(ATTR_NO_L3)),
        ),
        jnp.where(l7_redirect, jnp.int8(ATTR_L7), jnp.int8(ATTR_ALLOW)),
    )
    return verdict, Attribution(rule=rule, reason=reason)


@functools.partial(
    jax.jit, static_argnames=("ingress", "block", "attrib", "n_rules")
)
def verdict_batch(
    policy: DevicePolicy,
    subj_rows: jnp.ndarray,  # [B] int32 identity rows
    peer_rows: jnp.ndarray,  # [B] int32
    dport: jnp.ndarray,  # [B] int32 (with has_l4)
    proto: jnp.ndarray,  # [B] int32 IANA proto (u8proto)
    has_l4: jnp.ndarray,  # [B] bool — False = pure-L3 query
    ingress: bool = True,
    block: int = 8192,
    attrib: bool = False,
    origin: AttribTables = None,
    n_rules: int = 0,
):
    """Batch verdicts; blocks the batch with lax.map to bound the
    [block, S] activation footprint.

    With ``attrib=False`` (default) this traces exactly the program it
    always has — ``origin=None`` contributes no leaves to the jaxpr and
    the attribution tail is never staged. With ``attrib=True`` (static,
    so the off path keeps its own executable) returns
    ``(Verdict, Attribution, hits)`` where ``hits`` is the [n_rules]
    int32 per-rule hit counter, segment-summed on device so the host
    pulls R scalars instead of B."""
    t = policy.ingress if ingress else policy.egress
    b = subj_rows.shape[0]
    pad = (-b) % block

    def pad1(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill).reshape(-1, block)

    args = (pad1(subj_rows), pad1(peer_rows), pad1(dport), pad1(proto), pad1(has_l4))
    out = jax.lax.map(
        lambda xs: _verdict_block(
            policy.sel_match, t, *xs, origin=origin if attrib else None
        ),
        args,
    )
    out = jax.tree_util.tree_map(lambda x: x.reshape(-1)[:b], out)
    if not attrib:
        return out
    verdict, attribution = out
    valid = attribution.rule >= 0
    idx = jnp.clip(attribution.rule, 0, max(n_rules - 1, 0))
    hits = jnp.zeros((max(n_rules, 1),), jnp.int32).at[idx].add(
        valid.astype(jnp.int32)
    )[:n_rules]
    return verdict, attribution, hits
