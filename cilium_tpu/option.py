"""Daemon configuration + mutable runtime options.

Reference: pkg/option — a frozen daemon `Config` (config.go:142,
populated from flags/env/file at boot, `Validate` :297) plus a
*mutable* option map (option.go) patchable at runtime via
`PATCH /config` and per-endpoint (`cilium endpoint config`), each
option with parse/verify hooks; endpoints inherit daemon options
(pkg/endpoint applyOptsLocked).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class DaemonConfig:
    """Boot-frozen configuration (option.Config equivalent)."""

    cluster_name: str = "default"
    cluster_id: int = 0
    enable_ipv4: bool = True
    enable_ipv6: bool = False
    enforcement_mode: str = "default"  # default | always | never
    identity_row_bucket: int = 256
    verdict_block: int = 8192
    lookup_block: int = 65536
    kvstore: str = ""  # "" = disabled, "memory" for tests
    monitor_queue_size: int = 4096
    proxy_port_min: int = 10000
    proxy_port_max: int = 20000
    # Max verdict batches in flight on device before the pipeline
    # blocks pulling the oldest: depth 1 = fully synchronous, depth 2
    # overlaps host prep of batch N+1 with device execution of batch N.
    verdict_pipeline_depth: int = 2
    # Ceiling for the DispatchAutoTune depth controller (policyd-
    # autotune): while the runtime option is on, the effective depth
    # moves in [1, verdict_pipeline_max_depth]; off keeps the static
    # verdict_pipeline_depth. Part of the stable tuner contract
    # (ROADMAP).
    verdict_pipeline_max_depth: int = 4
    # Boot-time value of the VerdictSharding runtime option (flow
    # batches split across jax.devices(), tables replicated). Only
    # takes effect with >1 visible device.
    verdict_sharding: bool = False
    # Boot-time value of the MeshSharding2D runtime option (policyd-
    # mesh): the verdict mesh splits into explicit flows×ident axes
    # and the identity dimension of the policymaps / rule tables /
    # sel_match bitmaps shards over "ident". Requires VerdictSharding
    # and ≥2 eligible devices with an even factor.
    mesh_sharding_2d: bool = False
    # Requested ident-axis extent for the 2D mesh; the placement plan
    # shrinks it to the largest factor of the eligible device count.
    mesh_ident_axis: int = 2
    # Explicit device subset for the placement plan: comma-separated
    # device ids ("" = all visible devices).
    mesh_devices: str = ""
    # On multi-host platforms, restrict the plan to devices owned by
    # this process index (single-host: 0 matches everything).
    mesh_process_index: int = 0
    # Capacity of the sampled flow-log ring (observe/flows.py) serving
    # GET /flows while FlowAttribution is on.
    flow_ring_capacity: int = 1024
    # Boot-time value of the EpochSwap runtime option (policyd-delta):
    # full re-materializations build on a shadow thread and swap in at
    # a batch boundary instead of stopping the verdict world.
    policy_epoch_swap: bool = False
    # Boot-time value of the L7DeviceBatch runtime option (policyd-
    # l7batch): batched L7 classification runs fused (one dispatch for
    # every request field) through the overlapped submit() pipeline.
    l7_device_batch: bool = False
    # In-flight bound for that L7 pipeline (same semantics as
    # verdict_pipeline_depth: 2 overlaps host packing with the device
    # walk).
    l7_pipeline_depth: int = 2
    # Per-batch verdict deadline in milliseconds (policyd-overload).
    # 0 disables deadlines: the admission controller still bounds the
    # queue by its AIMD limit but never sheds on latency budget. With a
    # deadline set, batches the controller cannot place within budget
    # route through the prefilter shed stage instead of queueing.
    verdict_deadline_ms: float = 0.0
    # Stuck-dispatch threshold in milliseconds (policyd-overload). 0
    # disables the watchdog thread; >0 starts a monitor that treats any
    # in-flight batch (or registered attach/compile wait) older than
    # this as stalled, classifies it via faults.classify(), and drives
    # the failsafe quarantine + degradation ladder instead of hanging.
    dispatch_stall_ms: float = 0.0
    # Sampling period of the DeviceProfiling runtime option (policyd-
    # prof): every Nth completed batch pays the block_until_ready
    # sandwiches that decompose dispatch RTT into h2d / device_compute
    # / d2h. 1 = profile every batch (bench --prof); 64 keeps sampled
    # overhead under the <2% budget on pipeline_e2e_vps.
    profile_sample_every: int = 64
    # Boot-time values of the remaining datapath-gated runtime options.
    # Every OPTION_SPECS entry maps to exactly one of these fields (or
    # an annotated None) in contracts.OPTION_BOOT_FIELDS, and rule
    # OPT001 machine-checks the pairing — a new option without a boot
    # field (or a field the daemon never seeds from) fails the lint
    # gate, which is how the L7DeviceBatch dead-toggle bug class dies.
    policy_verdict_notification: bool = False
    phase_tracing: bool = False
    flow_attribution: bool = False
    dispatch_autotune: bool = False
    fail_open: bool = False
    admission_control: bool = False
    prefilter_shed: bool = False
    sparse_deltas: bool = False
    device_profiling: bool = False
    fault_injection: bool = False
    # Boot-time value of the FleetTelemetry runtime option (policyd-
    # fleetobs): the cadence sampler snapshots metric families into
    # the fleet time-series ring, evaluates SLO burn rates, and (with
    # a federation membership attached) publishes telemetry frames.
    fleet_telemetry: bool = False
    # FleetTelemetry sampler cadence in seconds and ring capacity in
    # rows; together they bound the observable history window
    # (capacity × sample_s seconds).
    telemetry_sample_s: float = 1.0
    telemetry_ring_rows: int = 600
    # Boot-time value of the LifecycleJournal runtime option (policyd-
    # journal): a bounded ring of structured lifecycle events (boot /
    # restore / epoch swap / ladder / drain / ...) with hybrid-logical-
    # clock stamps, published as journal-tail frames when a federation
    # membership is attached.
    lifecycle_journal: bool = False
    # Journal ring capacity in events and publisher cadence / frame
    # tail length; capacity bounds GET /events history, tail_n bounds
    # the per-node contribution to the merged fleet timeline.
    journal_ring_capacity: int = 512
    journal_publish_s: float = 1.0
    journal_tail_n: int = 64

    def validate(self) -> None:
        if self.enforcement_mode not in ("default", "always", "never"):
            raise ValueError(f"invalid enforcement mode {self.enforcement_mode!r}")
        if self.cluster_id < 0 or self.cluster_id > 255:
            raise ValueError("cluster-id must be 0-255")
        if self.proxy_port_min >= self.proxy_port_max:
            raise ValueError("invalid proxy port range")
        if not 1 <= self.verdict_pipeline_depth <= 64:
            raise ValueError("verdict-pipeline-depth must be 1-64")
        if not self.verdict_pipeline_depth <= self.verdict_pipeline_max_depth <= 64:
            raise ValueError(
                "verdict-pipeline-max-depth must be in "
                "[verdict-pipeline-depth, 64]"
            )
        if self.flow_ring_capacity < 1:
            raise ValueError("flow-ring-capacity must be >= 1")
        if not 1 <= self.l7_pipeline_depth <= 64:
            raise ValueError("l7-pipeline-depth must be 1-64")
        if self.verdict_deadline_ms < 0:
            raise ValueError("verdict-deadline-ms must be >= 0")
        if self.dispatch_stall_ms < 0:
            raise ValueError("dispatch-stall-ms must be >= 0")
        if self.profile_sample_every < 1:
            raise ValueError("profile-sample-every must be >= 1")
        if self.telemetry_sample_s <= 0:
            raise ValueError("telemetry-sample-s must be > 0")
        if self.telemetry_ring_rows < 2:
            raise ValueError("telemetry-ring-rows must be >= 2")
        if self.journal_ring_capacity < 1:
            raise ValueError("journal-ring-capacity must be >= 1")
        if self.journal_publish_s <= 0:
            raise ValueError("journal-publish-s must be > 0")
        if self.journal_tail_n < 1:
            raise ValueError("journal-tail-n must be >= 1")
        if not 2 <= self.mesh_ident_axis <= 64:
            raise ValueError("mesh-ident-axis must be 2-64")
        if self.mesh_process_index < 0:
            raise ValueError("mesh-process-index must be >= 0")
        if self.mesh_devices:
            try:
                ids = [int(x) for x in self.mesh_devices.split(",")]
            except ValueError:
                raise ValueError(
                    "mesh-devices must be comma-separated device ids"
                )
            if len(ids) != len(set(ids)) or any(i < 0 for i in ids):
                raise ValueError(
                    "mesh-devices must be distinct non-negative ids"
                )


_config = DaemonConfig()


def get_config() -> DaemonConfig:
    return _config


def set_config(cfg: DaemonConfig) -> None:
    cfg.validate()
    global _config
    _config = cfg


# -- mutable runtime options (pkg/option/option.go) -----------------------

BoolParser = Callable[[str], bool]


def _parse_bool(v: str) -> bool:
    lv = str(v).lower()
    if lv in ("true", "enabled", "1", "on"):
        return True
    if lv in ("false", "disabled", "0", "off"):
        return False
    raise ValueError(f"invalid option value {v!r}")


@dataclasses.dataclass(frozen=True)
class OptionSpec:
    name: str
    description: str = ""
    requires: tuple = ()  # options force-enabled alongside this one


# The runtime-mutable option set (defaults mirror the reference's
# endpoint options: Conntrack, Policy, Debug, DropNotify, TraceNotify).
OPTION_SPECS: Dict[str, OptionSpec] = {
    o.name: o
    for o in (
        OptionSpec("Conntrack", "Connection tracking"),
        OptionSpec("Debug", "Debug event emission"),
        OptionSpec("DropNotification", "Drop notification events"),
        OptionSpec("TraceNotification", "Trace notification events"),
        OptionSpec("Policy", "Policy enforcement"),
        OptionSpec("PolicyVerdictNotification", "Per-verdict events"),
        OptionSpec("PhaseTracing", "Verdict-path phase tracing (observe/)"),
        OptionSpec(
            "VerdictSharding",
            "Flow-sharded verdict dispatch across jax.devices() "
            "(tables replicated, batches split; needs >1 device)",
        ),
        OptionSpec(
            "MeshSharding2D",
            "2D flows×ident verdict mesh (policyd-mesh): the placement "
            "plan splits the device grid into explicit flows and ident "
            "axes and shards the identity dimension of the policymap / "
            "rule-table / sel_match device tables over ident (per-device "
            "table bytes divide by the ident factor); off keeps the "
            "exact 1D/replicated pre-option programs",
            requires=("VerdictSharding",),
        ),
        OptionSpec(
            "FlowAttribution",
            "On-device verdict attribution (policyd-flows): matched-rule "
            "index, drop-reason codes, per-rule hit counters, and the "
            "sampled flow-log ring",
        ),
        OptionSpec(
            "DispatchAutoTune",
            "Adaptive verdict pipeline depth (policyd-autotune): an EWMA "
            "controller steps the in-flight bound between 1 and "
            "verdict-pipeline-max-depth from per-batch enqueue/complete "
            "timings; off keeps the static configured depth",
        ),
        OptionSpec(
            "FailOpen",
            "Degraded-mode verdict policy (policyd-failsafe): when the "
            "pipeline cannot resolve a batch (quarantine, ladder "
            "exhaustion), forward instead of the default fail-closed "
            "deny with drop reason pipeline-degraded (155)",
        ),
        OptionSpec(
            "EpochSwap",
            "Epoch-swapped device tables (policyd-delta): full policy "
            "re-materializations build into a shadow generation on a "
            "background thread while batches keep serving the current "
            "one, then swap atomically at a batch boundary; off runs "
            "full rebuilds synchronously inside rebuild()",
        ),
        OptionSpec(
            "L7DeviceBatch",
            "Fused batched L7 classification (policyd-l7batch): "
            "method/path/host (and kafka topic/client-id) walk one "
            "stacked, interned DFA table in a single length-bucketed "
            "dispatch through an overlapped submit() pipeline; off "
            "keeps the per-field pre-option programs",
        ),
        OptionSpec(
            "FaultInjection",
            "Enable the cilium_tpu/faults.py hub: deterministic, seeded "
            "fault injection at the named verdict-path sites (h2d, "
            "dispatch, complete, ct_epoch, kvstore, attach, queue_full, "
            "stall); off keeps the hot path at one attribute read per "
            "site",
        ),
        OptionSpec(
            "AdmissionControl",
            "Deadline-aware admission control (policyd-overload): an "
            "AIMD controller keyed on queue wait + EWMA completion "
            "latency bounds the submit queue; over budget, flows route "
            "through the prefilter shed stage (if Prefilter is on) or "
            "defer within the verdict-deadline-ms budget, resolving "
            "via the fail-closed 155 / FailOpen semantics — never "
            "silently dropped. Off keeps the exact pre-option submit "
            "path",
        ),
        OptionSpec(
            "DeviceProfiling",
            "Device-time sampling profiler (policyd-prof): every "
            "profile-sample-every-th batch is timed with "
            "block_until_ready sandwiches at the enqueue/ready edges, "
            "splitting dispatch RTT into h2d / device_compute / d2h "
            "alongside rung occupancy, plus a per-jit-site "
            "cost_analysis ledger keyed on the stable ladder shapes; "
            "off keeps the exact pre-option programs and the hot path "
            "at one attribute read per batch",
        ),
        OptionSpec(
            "ClusterFederation",
            "Federated identity plane (policyd-fed): identity "
            "allocation routes through the attached federation "
            "membership's kvstore reserve/confirm CAS allocator so N "
            "daemon nodes converge on one identity numbering and "
            "exchange policy epochs; off restores the local registry "
            "allocator — numbering is the only difference, compiled "
            "device programs are bit-identical either way",
        ),
        OptionSpec(
            "FleetTelemetry",
            "Fleet telemetry plane (policyd-fleetobs): a cadence "
            "sampler thread snapshots verdict/drop/shed rates, phase "
            "quantiles, pipeline mode and epoch lag into a bounded "
            "time-series ring, evaluates multi-window SLO burn rates "
            "(slo_burn_ratio gauges, /status summary), and — when a "
            "federation membership is attached — publishes versioned "
            "telemetry frames for the fleet scoreboard (GET /fleet); "
            "off starts no thread and never imports the frame codec — "
            "the verdict path is bit-identical",
        ),
        OptionSpec(
            "LifecycleJournal",
            "Lifecycle event journal (policyd-journal): a bounded, "
            "schema-versioned ring of structured lifecycle events "
            "(boot, CT restore verdict, rebuild/epoch swap, ladder "
            "moves, quarantine incl. CT rescue, shed episodes, drain "
            "brackets, watchdog stalls, federation lease/reap, "
            "snapshot saves) stamped with a hybrid logical clock; "
            "with a federation membership attached a cadence thread "
            "publishes the journal tail so fleet timeline merges "
            "per-node journals into one HLC-total-ordered view; off "
            "starts no thread and never imports the journal module — "
            "hot paths stay at one attribute read and the verdict "
            "path is bit-identical",
        ),
        OptionSpec(
            "SparseDeltas",
            "O(k) sparse device deltas (policyd-sparse): selector "
            "column patches from the engine delta log scatter into the "
            "ident-placed sel_match copies (placement preserved, jit "
            "caches survive) instead of re-placing the full [N, S/32] "
            "matrix, and ipcache churn patches individual prefixes "
            "into the placed LPM trie tensors through pow2-headroom "
            "host mirrors instead of rebuilding + re-uploading whole "
            "tries; any non-patchable gap (log truncation, pool "
            "exhaustion, live deny trie, layout/elision violation) "
            "falls back to the classic full rebuild. Off compiles the "
            "exact pre-option programs — dense re-placement, classic "
            "unpadded trie builds",
        ),
        OptionSpec(
            "Prefilter",
            "Device prefilter shed stage (policyd-overload): a coarse "
            "[identity, proto/port-class] drop table compiled from "
            "deny-heavy policy, walked as one cheap gather AHEAD of "
            "the full verdict path so DoS-heavy mixes shed at a "
            "multiple of full-pipeline rate with drop reason 144; off "
            "compiles no shed table and the full path is bit-identical "
            "to pre-option programs",
        ),
    )
}


class OptionMap:
    """Mutable option set with change callbacks + inheritance."""

    def __init__(self, parent: Optional["OptionMap"] = None) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, bool] = {}
        self._parent = parent
        self._on_change: Optional[Callable[[str, bool], None]] = None

    def on_change(self, fn: Callable[[str, bool], None]) -> None:
        self._on_change = fn

    def get(self, name: str) -> bool:
        with self._lock:
            if name in self._values:
                return self._values[name]
        if self._parent is not None:
            return self._parent.get(name)
        return False

    def set(self, name: str, value) -> bool:
        """Returns True when the value changed; raises on unknown option
        (option.go Validate)."""
        spec = OPTION_SPECS.get(name)
        if spec is None:
            raise KeyError(f"unknown option {name!r}")
        b = value if isinstance(value, bool) else _parse_bool(value)
        with self._lock:
            old = self._values.get(name)
            self._values[name] = b
        changed = old != b
        if changed and self._on_change:
            self._on_change(name, b)
        if b:
            for req in spec.requires:
                self.set(req, True)
        return changed

    def snapshot(self) -> Dict[str, bool]:
        out = dict(self._parent.snapshot()) if self._parent else {}
        with self._lock:
            out.update(self._values)
        return out
