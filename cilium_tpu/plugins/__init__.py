"""Orchestrator plumbing: the CNI-shaped endpoint lifecycle
(plugins/cilium-cni role)."""

from .cni import CNIError, CNIResult, cni_add, cni_del, endpoint_id_for

__all__ = ["CNIError", "CNIResult", "cni_add", "cni_del", "endpoint_id_for"]
