"""CNI-shaped endpoint plumbing.

Reference: plugins/cilium-cni/cilium-cni.go — ADD creates the veth
pair, asks the daemon for an IP (POST /ipam), then registers the
endpoint (PUT /endpoint/{id}); DEL is symmetric.

Interfaces are REAL when a target netns is given and the host allows
it (plugins/netns.py: veth pair, container end as eth0 with the
allocated address, default route via the host end — the cilium
point-to-point LXC device model); without a netns (or capability) the
flow stays virtual with the same command sequence, result shape, and
failure cleanup.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class CNIResult:
    """CNI ADD result (the types.Result subset we produce)."""

    endpoint_id: int
    ipv4: Optional[str]
    interface: str
    gateway: Optional[str] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class CNIError(Exception):
    pass


def cni_add(
    daemon,
    container_id: str,
    *,
    labels: Optional[List[str]] = None,
    ifname: str = "eth0",
    netns: Optional[str] = None,
) -> CNIResult:
    """CNI ADD: allocate an IP, register the endpoint, return the
    result. With ``netns``, also create the REAL veth pair (host side
    lxc<epid>, container side ``ifname`` inside the netns carrying the
    address). On any failure, everything already created is rolled
    back (the reference releases IPAM and deletes the link on error
    too)."""
    ep_id = endpoint_id_for(container_id)
    ip = daemon.ipam.allocate_next(owner=container_id)
    host_if = host_ifname(ep_id)
    gateway = gateway_for(daemon.ipam.net)
    if netns is not None:
        from . import netns as nsmod

        try:
            # /32 on the container side: the cilium point-to-point LXC
            # model — NO connected subnet route, so even same-pod-CIDR
            # peers route via the gateway (the host veth), which is
            # where enforcement sits (cilium-cni.go configures the
            # endpoint address exactly this way)
            nsmod.create_endpoint_veth(
                host_if, netns, f"{ip}/32",
                container_if=ifname, gateway=gateway,
            )
        except Exception as e:
            daemon.ipam.release(ip)
            raise CNIError(f"interface create failed: {e}") from e
    try:
        daemon.endpoint_add(
            ep_id,
            labels or [f"container:id={container_id[:12]}"],
            ipv4=ip,
            pod_name=container_id,
        )
    except Exception as e:
        if netns is not None:
            from . import netns as nsmod

            nsmod.delete_link(host_if)
        daemon.ipam.release(ip)
        raise CNIError(f"endpoint create failed: {e}") from e
    return CNIResult(
        endpoint_id=ep_id,
        ipv4=ip,
        interface=host_if,
        gateway=gateway,
    )


def cni_del(daemon, container_id: str) -> bool:
    """CNI DEL: tear down the endpoint, its host interface (if one was
    plumbed — deleting the host end kills both sides of the veth), and
    release its IP. Idempotent (the CNI spec requires DEL to succeed
    for unknown containers)."""
    ep_id = endpoint_id_for(container_id)
    from . import netns as nsmod

    # unconditional: delete_link never raises (no-op on ip-less hosts),
    # and gating on the capability probe could leak veths if the probe
    # false-negatives after ADDs succeeded
    nsmod.delete_link(host_ifname(ep_id))
    # endpoint_delete releases the endpoint's IPAM address itself; a
    # second release here would race a concurrent ADD that was just
    # handed the freed address and release it out from under the new
    # endpoint.
    return daemon.endpoint_delete(ep_id)


def host_ifname(ep_id: int) -> str:
    """The host-side veth name for an endpoint — ONE definition so
    ADD and DEL (in-process and the cni_exec binary) always agree
    (a divergent name would leak the veth on DEL)."""
    return f"lxc{ep_id}"[:15]  # IFNAMSIZ


def gateway_for(net) -> str:
    """The pod-CIDR gateway address (the host ends of every veth)."""
    import ipaddress as _ipa

    if not hasattr(net, "network_address"):
        net = _ipa.ip_network(str(net))
    return str(net.network_address + 1)


def endpoint_id_for(container_id: str) -> int:
    """Stable endpoint id from a container id (the reference derives
    endpoint ids from the interface; here a stable hash keeps ADD/DEL
    symmetric without shared state)."""
    import hashlib

    h = hashlib.sha256(container_id.encode()).digest()
    return 4096 + (int.from_bytes(h[:4], "big") % (2**20))
