"""CNI-shaped endpoint plumbing.

Reference: plugins/cilium-cni/cilium-cni.go — ADD creates the veth
pair, asks the daemon for an IP (POST /ipam), then registers the
endpoint (PUT /endpoint/{id}); DEL is symmetric. Here the "interface"
is virtual (no kernel), but the command flow, result shape, and
failure cleanup mirror the CNI contract so an orchestrator-side
integration drives the same steps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class CNIResult:
    """CNI ADD result (the types.Result subset we produce)."""

    endpoint_id: int
    ipv4: Optional[str]
    interface: str
    gateway: Optional[str] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class CNIError(Exception):
    pass


def cni_add(
    daemon,
    container_id: str,
    *,
    labels: Optional[List[str]] = None,
    ifname: str = "eth0",
) -> CNIResult:
    """CNI ADD: allocate an IP, register the endpoint, return the
    result. On endpoint-registration failure the allocated IP is
    released (the reference releases IPAM on error too)."""
    ep_id = endpoint_id_for(container_id)
    ip = daemon.ipam.allocate_next(owner=container_id)
    try:
        daemon.endpoint_add(
            ep_id,
            labels or [f"container:id={container_id[:12]}"],
            ipv4=ip,
            pod_name=container_id,
        )
    except Exception as e:
        daemon.ipam.release(ip)
        raise CNIError(f"endpoint create failed: {e}") from e
    return CNIResult(
        endpoint_id=ep_id,
        ipv4=ip,
        interface=f"lxc{ep_id}",
        gateway=str(daemon.ipam.net.network_address + 1),
    )


def cni_del(daemon, container_id: str) -> bool:
    """CNI DEL: tear down the endpoint and release its IP. Idempotent
    (the CNI spec requires DEL to succeed for unknown containers)."""
    ep_id = endpoint_id_for(container_id)
    # endpoint_delete releases the endpoint's IPAM address itself; a
    # second release here would race a concurrent ADD that was just
    # handed the freed address and release it out from under the new
    # endpoint.
    return daemon.endpoint_delete(ep_id)


def endpoint_id_for(container_id: str) -> int:
    """Stable endpoint id from a container id (the reference derives
    endpoint ids from the interface; here a stable hash keeps ADD/DEL
    symmetric without shared state)."""
    import hashlib

    h = hashlib.sha256(container_id.encode()).digest()
    return 4096 + (int.from_bytes(h[:4], "big") % (2**20))
