"""The CNI EXECUTABLE protocol — what kubelet actually invokes.

Reference: plugins/cilium-cni/cilium-cni.go is a binary speaking the
CNI spec: command in ``CNI_COMMAND``, container/netns/ifname in env,
network config JSON on stdin, result (or structured error) JSON on
stdout. This module is that binary:

    CNI_COMMAND=ADD CNI_CONTAINERID=abc \
    CNI_NETNS=/var/run/netns/pod1 CNI_IFNAME=eth0 \
    python -m cilium_tpu.plugins.cni_exec < net.conf

It talks to the local agent over its API socket (the reference's
client → cilium-agent flow): IPAM allocation + endpoint registration
remotely, interface plumbing locally (plugins/netns.py). Config keys:
``socket`` (agent API socket path; default /var/run/cilium-tpu.sock).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

CNI_VERSION = "0.4.0"
SUPPORTED = ["0.3.0", "0.3.1", "0.4.0"]

# CNI well-known error codes (spec §Error)
ERR_INCOMPATIBLE_VERSION = 1
ERR_UNSUPPORTED_FIELD = 2
ERR_UNKNOWN_CONTAINER = 3
ERR_INVALID_ENV = 4
ERR_IO = 5
ERR_DECODE = 6
ERR_INTERNAL = 7
ERR_TRY_LATER = 11


class CNIFault(Exception):
    def __init__(self, code: int, msg: str, details: str = "") -> None:
        super().__init__(msg)
        self.code = code
        self.msg = msg
        self.details = details


def _emit(obj: Dict) -> None:
    sys.stdout.write(json.dumps(obj))
    sys.stdout.flush()


def _fail(e: CNIFault) -> int:
    _emit({
        "cniVersion": CNI_VERSION,
        "code": e.code,
        "msg": e.msg,
        "details": e.details,
    })
    return 1


def _alias_for(cni_netns: str) -> str:
    """DETERMINISTIC alias for a non-named netns path: retries and DEL
    must land on the same name (a per-process hash would mint a new
    never-detached bind mount per invocation, pinning the pod's netns
    alive in the kernel)."""
    import hashlib

    return "cni-" + hashlib.sha256(cni_netns.encode()).hexdigest()[:10]


def _netns_name(cni_netns: str) -> str:
    """CNI hands a PATH; iproute2 wants a NAME. /var/run/netns/<name>
    (and /run/netns/<name>) map directly; any other path (e.g.
    /proc/<pid>/ns/net) is aliased via ``ip netns attach`` and
    detached again by _detach_alias (DEL / ADD-failure paths)."""
    from . import netns as nsmod

    for prefix in ("/var/run/netns/", "/run/netns/"):
        if cni_netns.startswith(prefix):
            return cni_netns[len(prefix):]
    alias = _alias_for(cni_netns)
    proc = nsmod._run("netns", "attach", alias, cni_netns, check=False)
    # EEXIST from a prior invocation's attach is fine — same alias
    # name means the same path by construction
    if proc.returncode != 0 and "File exists" not in proc.stderr:
        raise CNIFault(
            ERR_INVALID_ENV,
            f"cannot use netns path {cni_netns!r}",
            proc.stderr.strip(),
        )
    return alias


def _detach_alias(cni_netns: str) -> None:
    """Remove the attach-created bind mount (no-op for named paths)."""
    from . import netns as nsmod

    for prefix in ("/var/run/netns/", "/run/netns/"):
        if cni_netns.startswith(prefix):
            return
    nsmod.delete_netns(_alias_for(cni_netns))


def _labels_from_args(cni_args: str, container_id: str) -> List[str]:
    """CNI_ARGS K8S_POD_NAMESPACE/K8S_POD_NAME → the identity labels
    the reference derives for the pod (cilium-cni.go + pkg/k8s)."""
    kv = dict(
        part.split("=", 1) for part in cni_args.split(";")
        if "=" in part
    )
    labels = [f"container:id={container_id[:12]}"]
    ns = kv.get("K8S_POD_NAMESPACE")
    name = kv.get("K8S_POD_NAME")
    if ns:
        labels.append(f"k8s:io.kubernetes.pod.namespace={ns}")
    if name:
        labels.append(f"k8s:io.kubernetes.pod.name={name}")
    return labels


def _agent(conf: Dict):
    from ..api.client import APIClient

    sock = conf.get("socket") or "/var/run/cilium-tpu.sock"
    if not os.path.exists(sock):
        raise CNIFault(
            ERR_TRY_LATER, f"agent socket {sock} not present"
        )
    return APIClient(sock, timeout=30.0)


def _cmd_add(env: Dict[str, str], conf: Dict) -> Dict:
    from . import netns as nsmod
    from .cni import endpoint_id_for, gateway_for, host_ifname

    container_id = env["CNI_CONTAINERID"]
    ifname = env.get("CNI_IFNAME", "eth0")
    netns = _netns_name(env["CNI_NETNS"])
    client = _agent(conf)
    ep_id = endpoint_id_for(container_id)
    try:
        alloc = client.ipam_allocate(owner=container_id)
    except Exception as e:
        raise CNIFault(ERR_TRY_LATER, f"IPAM allocation failed: {e}")
    ip = alloc["ip"]
    gateway = gateway_for(alloc["cidr"])
    host_if = host_ifname(ep_id)

    def rollback(release_ip: bool, drop_link: bool) -> None:
        if drop_link:
            nsmod.delete_link(host_if)
        if release_ip:
            try:
                client.ipam_release(ip)
            except Exception:
                pass
        _detach_alias(env["CNI_NETNS"])

    try:
        nsmod.create_endpoint_veth(
            host_if, netns, f"{ip}/32",
            container_if=ifname, gateway=gateway,
        )
    except Exception as e:
        rollback(release_ip=True, drop_link=False)
        raise CNIFault(ERR_INTERNAL, f"interface create failed: {e}")
    try:
        client.endpoint_put(
            ep_id,
            _labels_from_args(env.get("CNI_ARGS", ""), container_id),
            ipv4=ip,
        )
    except Exception as e:
        rollback(release_ip=True, drop_link=True)
        raise CNIFault(ERR_INTERNAL, f"endpoint create failed: {e}")
    return {
        "cniVersion": conf.get("cniVersion", CNI_VERSION),
        "interfaces": [
            {"name": host_if},
            {"name": ifname, "sandbox": env["CNI_NETNS"]},
        ],
        "ips": [{
            "version": "4",
            "interface": 1,
            "address": f"{ip}/32",
            "gateway": gateway,
        }],
        "routes": [{"dst": "0.0.0.0/0", "gw": gateway}],
        "dns": {},
    }


def _cmd_del(env: Dict[str, str], conf: Dict) -> Dict:
    from . import netns as nsmod
    from .cni import endpoint_id_for, host_ifname

    container_id = env["CNI_CONTAINERID"]
    ep_id = endpoint_id_for(container_id)
    nsmod.delete_link(host_ifname(ep_id))
    if env.get("CNI_NETNS"):  # detach any attach-created alias mount
        _detach_alias(env["CNI_NETNS"])
    # DEL must succeed even when the agent never saw this container
    # (CNI spec) — and even when the agent is down, interface cleanup
    # above already happened
    try:
        _agent(conf).endpoint_delete(ep_id)
    except Exception:
        pass
    return {}


def main(environ=None, stdin=None) -> int:
    env = dict(environ if environ is not None else os.environ)
    command = env.get("CNI_COMMAND", "")
    try:
        if command == "VERSION":
            _emit({
                "cniVersion": CNI_VERSION,
                "supportedVersions": SUPPORTED,
            })
            return 0
        raw = (stdin if stdin is not None else sys.stdin).read()
        try:
            conf = json.loads(raw) if raw.strip() else {}
        except ValueError as e:
            raise CNIFault(ERR_DECODE, f"bad network config: {e}")
        if command not in ("ADD", "DEL", "CHECK"):
            raise CNIFault(
                ERR_INVALID_ENV, f"unsupported CNI_COMMAND {command!r}"
            )
        want = conf.get("cniVersion")
        if want and want not in SUPPORTED:
            # a later spec's result schema differs — returning a
            # 0.4.0-shaped result stamped with their version would
            # break libcni parsing; the spec mandates error code 1
            raise CNIFault(
                ERR_INCOMPATIBLE_VERSION,
                f"cniVersion {want!r} not supported "
                f"(supported: {', '.join(SUPPORTED)})",
            )
        for key in ("CNI_CONTAINERID",) + (
            ("CNI_NETNS",) if command == "ADD" else ()
        ):
            if not env.get(key):
                raise CNIFault(ERR_INVALID_ENV, f"missing {key}")
        if command == "ADD":
            _emit(_cmd_add(env, conf))
        elif command == "DEL":
            _cmd_del(env, conf)
        else:  # CHECK: the endpoint must exist
            from ..api.client import APIError
            from .cni import endpoint_id_for

            ep_id = endpoint_id_for(env["CNI_CONTAINERID"])
            client = _agent(conf)  # CNIFault(TRY_LATER) when absent
            try:
                client.endpoint_get(ep_id)
            except APIError as e:
                if e.status == 404:
                    raise CNIFault(
                        ERR_UNKNOWN_CONTAINER,
                        f"no endpoint for {env['CNI_CONTAINERID'][:12]}",
                    )
                raise CNIFault(ERR_TRY_LATER, f"agent error: {e}")
            except OSError as e:
                # agent restarting/unreachable is NOT "unknown
                # container" — that answer would make the runtime tear
                # down a healthy pod instead of retrying
                raise CNIFault(ERR_TRY_LATER, f"agent unreachable: {e}")
        return 0
    except CNIFault as e:
        return _fail(e)
    except Exception as e:  # never tracebacks at kubelet
        return _fail(CNIFault(ERR_INTERNAL, f"{type(e).__name__}: {e}"))


if __name__ == "__main__":
    sys.exit(main())
