"""Docker libnetwork remote driver + IPAM driver.

Reference: /root/reference/plugins/cilium-docker/driver/{driver,ipam}.go
— a plugin process serving the libnetwork plugin protocol (JSON POSTs
over a unix socket under /run/docker/plugins/) and fronting the agent:
``NetworkDriver`` endpoints create/join/leave endpoints via the daemon
(endpoint registration + identity allocation), ``IpamDriver``
endpoints allocate addresses from the daemon's pool.

Protocol notes (docker/libnetwork remote + ipam driver specs):
every call is ``POST /<Driver>.<Method>`` with a JSON body; errors are
``{"Err": "..."}`` with HTTP 200 (libnetwork reads Err, not status).
"""

from __future__ import annotations

import json
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..utils.logging import get_logger

log = get_logger("docker-plugin")

POOL_V4 = "CiliumPoolv4"
ADDRESS_SPACE_LOCAL = "CiliumLocal"
ADDRESS_SPACE_GLOBAL = "CiliumGlobal"
CONTAINER_IF_PREFIX = "eth"


class _UnixHTTPServer(ThreadingHTTPServer):
    address_family = socket.AF_UNIX
    daemon_threads = True
    allow_reuse_address = False

    def server_bind(self):
        path = self.server_address
        if isinstance(path, str) and os.path.exists(path):
            os.unlink(path)
        self.socket.bind(path)

    def server_activate(self):
        self.socket.listen(16)


class _Handler(BaseHTTPRequestHandler):
    def address_string(self) -> str:
        return "unix"

    def log_message(self, fmt, *args):
        pass

    def _reply(self, payload: Dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/vnd.docker.plugins.v1+json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> Dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw.decode()) if raw else {}

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        plugin = self.server.plugin_obj  # type: ignore[attr-defined]
        method = self.path.lstrip("/")
        # ALWAYS drain the request body before replying: an early
        # error response with unread bytes in the socket makes the
        # close send RST and the client sees a broken pipe mid-request
        try:
            body = self._body()
        except (ValueError, OSError):
            body = {}
        fn = plugin.routes.get(method)
        if fn is None:
            self._reply({"Err": f"unknown method {method}"})
            return
        try:
            self._reply(fn(body))
        except Exception as e:  # protocol: errors ride the Err field
            self._reply({"Err": f"{type(e).__name__}: {e}"})


class DockerPlugin:
    """The libnetwork plugin endpoint set over a daemon instance."""

    def __init__(self, daemon, socket_path: str) -> None:
        self.daemon = daemon
        self.socket_path = socket_path
        # libnetwork EndpointID → allocated state
        self._lock = threading.Lock()
        self._endpoints: Dict[str, Dict] = {}
        self.routes = {
            "Plugin.Activate": self.activate,
            "NetworkDriver.GetCapabilities": self.get_capabilities,
            "NetworkDriver.CreateNetwork": self.create_network,
            "NetworkDriver.DeleteNetwork": self.delete_network,
            "NetworkDriver.CreateEndpoint": self.create_endpoint,
            "NetworkDriver.DeleteEndpoint": self.delete_endpoint,
            "NetworkDriver.EndpointOperInfo": self.endpoint_info,
            "NetworkDriver.Join": self.join,
            "NetworkDriver.Leave": self.leave,
            "IpamDriver.GetCapabilities": self.ipam_capabilities,
            "IpamDriver.GetDefaultAddressSpaces": self.address_spaces,
            "IpamDriver.RequestPool": self.request_pool,
            "IpamDriver.ReleasePool": self.release_pool,
            "IpamDriver.RequestAddress": self.request_address,
            "IpamDriver.ReleaseAddress": self.release_address,
        }
        self._server: Optional[_UnixHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- plugin handshake ----------------------------------------------
    def activate(self, _req: Dict) -> Dict:
        return {"Implements": ["NetworkDriver", "IpamDriver"]}

    def get_capabilities(self, _req: Dict) -> Dict:
        return {"Scope": "local"}  # driver.go:238

    # -- NetworkDriver --------------------------------------------------
    def create_network(self, req: Dict) -> Dict:
        log.info("docker network created",
                 fields={"network": req.get("NetworkID", "")[:12]})
        return {}

    def delete_network(self, _req: Dict) -> Dict:
        return {}

    def create_endpoint(self, req: Dict) -> Dict:
        """CreateEndpoint: libnetwork hands the address the IPAM driver
        allocated; register the endpoint with the daemon (the reference
        defers daemon registration to Join, but carries the address
        from here)."""
        eid = req["EndpointID"]
        iface = req.get("Interface") or {}
        address = (iface.get("Address") or "").split("/")[0]
        with self._lock:
            if eid in self._endpoints:
                raise ValueError(f"endpoint {eid[:12]} exists")
            self._endpoints[eid] = {"ipv4": address, "joined": False}
        # respond with an empty Interface: we accepted theirs
        return {"Interface": {}}

    def delete_endpoint(self, req: Dict) -> Dict:
        eid = req["EndpointID"]
        with self._lock:
            st = self._endpoints.pop(eid, None)
        if st and st.get("ep_id") is not None:
            self.daemon.endpoint_delete(st["ep_id"])
        return {}

    def endpoint_info(self, req: Dict) -> Dict:
        eid = req["EndpointID"]
        with self._lock:
            st = self._endpoints.get(eid)
        return {"Value": dict(st or {})}

    def join(self, req: Dict) -> Dict:
        """Join: the sandbox attaches — register with the daemon
        (identity allocation + ipcache + regeneration; the reference
        PUTs /endpoint/{id} here) and describe the veth interface."""
        eid = req["EndpointID"]
        from .cni import endpoint_id_for

        ep_id = endpoint_id_for(eid)
        with self._lock:
            st = self._endpoints.get(eid)
            if st is None:
                raise ValueError(f"unknown endpoint {eid[:12]}")
            ipv4 = st.get("ipv4") or None
        labels = [f"container:io.docker.network.endpoint={eid[:12]}"]
        self.daemon.endpoint_add(ep_id, labels=labels, ipv4=ipv4)
        with self._lock:
            st["ep_id"] = ep_id
            st["joined"] = True
        return {
            "InterfaceName": {
                "SrcName": f"tmp{ep_id % 100000}",
                "DstPrefix": CONTAINER_IF_PREFIX,  # driver.go:414
            },
            "Gateway": "",
        }

    def leave(self, req: Dict) -> Dict:
        eid = req["EndpointID"]
        with self._lock:
            st = self._endpoints.get(eid)
            ep_id = st.get("ep_id") if st else None
            if st:
                st["joined"] = False
                st["ep_id"] = None
        if ep_id is not None:
            self.daemon.endpoint_delete(ep_id)
        return {}

    # -- IpamDriver -----------------------------------------------------
    def ipam_capabilities(self, _req: Dict) -> Dict:
        return {"RequiresMACAddress": False}

    def address_spaces(self, _req: Dict) -> Dict:
        return {
            "LocalDefaultAddressSpace": ADDRESS_SPACE_LOCAL,
            "GlobalDefaultAddressSpace": ADDRESS_SPACE_GLOBAL,
        }

    def request_pool(self, req: Dict) -> Dict:
        if req.get("V6"):
            raise ValueError("IPv6 pools not provided by this node")
        return {
            "PoolID": POOL_V4,
            "Pool": str(self.daemon.ipam.net),
            "Data": {},
        }

    def release_pool(self, _req: Dict) -> Dict:
        return {}

    def request_address(self, req: Dict) -> Dict:
        if req.get("PoolID") not in (POOL_V4, "", None):
            raise ValueError(f"unknown pool {req.get('PoolID')}")
        want = req.get("Address") or ""
        if want:
            ip = self.daemon.ipam.allocate(want, owner="docker")
        else:
            ip = self.daemon.ipam.allocate_next(owner="docker")
        prefixlen = self.daemon.ipam.net.prefixlen
        return {"Address": f"{ip}/{prefixlen}", "Data": {}}

    def release_address(self, req: Dict) -> Dict:
        addr = (req.get("Address") or "").split("/")[0]
        if addr:
            self.daemon.ipam.release(addr)
        return {}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "DockerPlugin":
        self._server = _UnixHTTPServer(self.socket_path, _Handler)
        self._server.plugin_obj = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
