"""Real network-interface plumbing for the CNI layer.

Reference: plugins/cilium-cni/cilium-cni.go — ADD creates a veth pair,
moves the container end into the target netns, configures addresses,
and hands the HOST end to the datapath. The reference drives netlink
directly (vishvananda/netlink); here the portable equivalent is
iproute2 (`ip ...` subprocesses) — same kernel objects, same shapes:

    host side:       lxc<epid>  (the bpf_lxc attachment point)
    container side:  eth0 inside the netns, carrying the IPAM address

Everything degrades cleanly: ``have_netns()`` probes capability
(CAP_NET_ADMIN + iproute2) so deployments without it keep the virtual
CNI flow, exactly as before.
"""

from __future__ import annotations

import subprocess
from typing import List, Optional

from ..utils.logging import get_logger

log = get_logger("netns")

_IP = "ip"


class NetnsError(Exception):
    pass


def _run(*args: str, check: bool = True) -> subprocess.CompletedProcess:
    proc = subprocess.run(
        [_IP, *args], capture_output=True, text=True, timeout=10
    )
    if check and proc.returncode != 0:
        raise NetnsError(
            f"ip {' '.join(args)}: rc={proc.returncode} "
            f"{proc.stderr.strip()}"
        )
    return proc


_have: Optional[bool] = None


def have_netns() -> bool:
    """Capability probe (cached): can this process create netns +
    veth? False on unprivileged or ip-less hosts — callers fall back
    to the virtual flow."""
    global _have
    if _have is not None:
        return _have
    import os
    import uuid

    # unique per-probe name: a fixed name could collide with a crashed
    # prior probe's leftover (or a concurrent prober) and cache a
    # false negative for the whole process lifetime
    probe = f"ctpu-probe-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    try:
        _run("netns", "add", probe)
        _run("netns", "del", probe)
        _have = True
    except (NetnsError, OSError, subprocess.TimeoutExpired):
        _have = False
    return _have


def create_netns(name: str) -> None:
    _run("netns", "add", name)


def delete_netns(name: str) -> None:
    _run("netns", "del", name, check=False)


def list_netns() -> List[str]:
    out = _run("netns", "list", check=False).stdout
    return [line.split()[0] for line in out.splitlines() if line.split()]


def create_endpoint_veth(
    host_if: str,
    netns: str,
    ipv4_cidr: str,
    *,
    container_if: str = "eth0",
    gateway: Optional[str] = None,
) -> None:
    """The CNI ADD interface sequence (cilium-cni.go): veth pair, peer
    into the netns as eth0 with the endpoint address, both ends up,
    default route via the gateway. Cleans the host link up on any
    mid-sequence failure so a retry starts fresh."""
    tmp_peer = f"{host_if}_p"[:15]  # IFNAMSIZ
    _run("link", "add", host_if, "type", "veth", "peer", "name", tmp_peer)
    try:
        _run("link", "set", tmp_peer, "netns", netns)
        _run("-n", netns, "link", "set", tmp_peer, "name", container_if)
        _run("-n", netns, "addr", "add", ipv4_cidr, "dev", container_if)
        _run("-n", netns, "link", "set", container_if, "up")
        _run("-n", netns, "link", "set", "lo", "up")
        _run("link", "set", host_if, "up")
        if gateway:
            # the host end is the endpoint's next hop (cilium's
            # point-to-point LXC device model): give it the gateway
            # address scoped to the link and route everything there
            _run("addr", "add", f"{gateway}/32", "dev", host_if,
                 check=False)
            _run("-n", netns, "route", "add", gateway, "dev", container_if)
            _run("-n", netns, "route", "add", "default", "via", gateway)
    except (NetnsError, OSError, subprocess.TimeoutExpired):
        # ANY mid-sequence failure must remove the host link — a
        # leaked lxc* would make every ADD retry fail with EEXIST
        delete_link(host_if)
        raise


def delete_link(host_if: str) -> bool:
    """Remove the host-side veth (kills both ends). Idempotent; never
    raises (DEL must succeed on hosts without iproute2 too)."""
    try:
        return _run("link", "del", host_if, check=False).returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def netns_run(netns: str, argv: List[str], timeout: float = 15.0):
    """Run a command inside the netns (tests use this as the
    'container process')."""
    return subprocess.run(
        [_IP, "netns", "exec", netns, *argv],
        capture_output=True, text=True, timeout=timeout,
    )
