"""Policy engine: rule model, repository, resolved L4/CIDR policy.

The host side is the semantic oracle (reference: pkg/policy); the
device side (compiler + verdict kernels) lives in cilium_tpu.models and
cilium_tpu.ops and is differential-tested against this package.
"""

from .search import Decision, PortContext, SearchContext, Trace
from .repository import Repository
from .l4 import L4Filter, L4Policy, L4PolicyMap, MergeConflict, PARSER_HTTP, PARSER_KAFKA, PARSER_NONE
from .cidr import CIDRPolicy, CIDRPolicyMap, compute_resultant_cidr_set

__all__ = [
    "Decision",
    "PortContext",
    "SearchContext",
    "Trace",
    "Repository",
    "L4Filter",
    "L4Policy",
    "L4PolicyMap",
    "MergeConflict",
    "PARSER_HTTP",
    "PARSER_KAFKA",
    "PARSER_NONE",
    "CIDRPolicy",
    "CIDRPolicyMap",
    "compute_resultant_cidr_set",
]
