"""L7 rule models: HTTP and Kafka.

Reference: pkg/policy/api/http.go (PortRuleHTTP — Path/Method/Host are
POSIX extended regexes, Headers are exact-presence matches) and
pkg/policy/api/kafka.go (PortRuleKafka — Role/APIKey/APIVersion/
ClientID/Topic with produce/consume role expansion,
pkg/kafka/policy.go:144).

These are pure data; compilation to DFA transition tables / ACL tables
lives in cilium_tpu.l7.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Tuple

# Kafka api-keys (kafka protocol numbers, pkg/policy/api/kafka.go:71-117)
KAFKA_API_KEYS = {
    "produce": 0,
    "fetch": 1,
    "offsets": 2,
    "metadata": 3,
    "leaderandisr": 4,
    "stopreplica": 5,
    "updatemetadata": 6,
    "controlledshutdown": 7,
    "offsetcommit": 8,
    "offsetfetch": 9,
    "findcoordinator": 10,
    "joingroup": 11,
    "heartbeat": 12,
    "leavegroup": 13,
    "syncgroup": 14,
    "describegroups": 15,
    "listgroups": 16,
    "saslhandshake": 17,
    "apiversions": 18,
    "createtopics": 19,
    "deletetopics": 20,
}

# Role → api-key expansion (pkg/policy/api/kafka.go RoleProduce/RoleConsume)
KAFKA_ROLE_PRODUCE = ("produce", "metadata", "apiversions")
KAFKA_ROLE_CONSUME = (
    "fetch",
    "offsets",
    "metadata",
    "offsetcommit",
    "offsetfetch",
    "findcoordinator",
    "joingroup",
    "heartbeat",
    "leavegroup",
    "syncgroup",
    "apiversions",
)

KAFKA_MAX_TOPIC_LEN = 255
_KAFKA_TOPIC_RE = re.compile(r"^[a-zA-Z0-9\._\-]+$")


@dataclasses.dataclass(frozen=True)
class HTTPRule:
    """One HTTP allow clause; empty fields are wildcards. All present
    fields must match for the clause to match (http.go Sanitize)."""

    path: str = ""  # regex, anchored both ends at compile time
    method: str = ""  # regex
    host: str = ""  # regex
    headers: Tuple[str, ...] = ()  # "Name[: value]" exact matches

    def sanitize(self) -> None:
        for pattern, what in ((self.path, "path"), (self.method, "method"), (self.host, "host")):
            if pattern:
                try:
                    re.compile(pattern)
                except re.error as e:
                    raise ValueError(f"invalid {what} regex {pattern!r}: {e}") from e

    def matches(self, method: str, path: str, host: str = "", headers: Optional[dict] = None) -> bool:
        """Host-side oracle evaluation (full-anchored like the envoy-side
        matcher, envoy/cilium_network_policy.h HttpNetworkPolicyRule)."""
        if self.method and not re.fullmatch(self.method, method):
            return False
        if self.path and not re.fullmatch(self.path, path):
            return False
        if self.host and not re.fullmatch(self.host, host):
            return False
        for h in self.headers:
            name, _, want = h.partition(":")
            got = (headers or {}).get(name.strip().lower())
            if got is None:
                return False
            if want and got.strip() != want.strip():
                return False
        return True


@dataclasses.dataclass(frozen=True)
class KafkaRule:
    """One Kafka allow clause (kafka.go PortRuleKafka)."""

    role: str = ""  # "produce" | "consume" (expands to api-key sets)
    api_key: str = ""  # named api key, mutually exclusive with role
    api_version: str = ""  # exact numeric match when set
    client_id: str = ""
    topic: str = ""

    def sanitize(self) -> None:
        if self.role and self.api_key:
            raise ValueError("Kafka rule: role and api_key are mutually exclusive")
        if self.role and self.role.lower() not in ("produce", "consume"):
            raise ValueError(f"invalid Kafka role {self.role!r}")
        if self.api_key and self.api_key.lower() not in KAFKA_API_KEYS:
            raise ValueError(f"unknown Kafka api_key {self.api_key!r}")
        if self.api_version:
            int(self.api_version)  # raises if non-numeric
        if self.topic:
            if len(self.topic) > KAFKA_MAX_TOPIC_LEN:
                raise ValueError("Kafka topic too long")
            if not _KAFKA_TOPIC_RE.match(self.topic):
                raise ValueError(f"invalid Kafka topic {self.topic!r}")

    def allowed_api_keys(self) -> Tuple[int, ...]:
        """Expand role/api_key to the set of allowed protocol numbers;
        empty tuple = all keys allowed (kafka.go GetAPIKeys)."""
        if self.api_key:
            return (KAFKA_API_KEYS[self.api_key.lower()],)
        if self.role.lower() == "produce":
            return tuple(KAFKA_API_KEYS[k] for k in KAFKA_ROLE_PRODUCE)
        if self.role.lower() == "consume":
            return tuple(KAFKA_API_KEYS[k] for k in KAFKA_ROLE_CONSUME)
        return ()

    def matches(self, api_key: int, api_version: int, client_id: str, topic: str) -> bool:
        """Host-side oracle (pkg/kafka/policy.go RequestMessage.MatchesRule)."""
        allowed = self.allowed_api_keys()
        if allowed and api_key not in allowed:
            return False
        if self.api_version and int(self.api_version) != api_version:
            return False
        if self.client_id and self.client_id != client_id:
            return False
        if self.topic and self.topic != topic:
            return False
        return True


@dataclasses.dataclass(frozen=True)
class L7Rules:
    """Union container: at most one protocol may be populated
    (pkg/policy/api/l4.go L7Rules)."""

    http: Tuple[HTTPRule, ...] = ()
    kafka: Tuple[KafkaRule, ...] = ()

    def sanitize(self) -> None:
        if self.http and self.kafka:
            raise ValueError("only one L7 protocol per port rule")
        for r in self.http:
            r.sanitize()
        for r in self.kafka:
            r.sanitize()

    @property
    def parser(self) -> str:
        if self.http:
            return "http"
        if self.kafka:
            return "kafka"
        return ""

    def __bool__(self) -> bool:
        return bool(self.http or self.kafka)
