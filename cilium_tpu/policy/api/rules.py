"""The user-facing policy rule model.

Reference: pkg/policy/api/rule.go (Rule), ingress.go (IngressRule),
egress.go (EgressRule), l4.go (PortRule/PortProtocol), cidr.go
(CIDRRule), entity.go (entities), rule_validation.go (Sanitize).

Semantics preserved from the reference (v1.2 is allow-only):
- a Rule applies to endpoints selected by ``endpoint_selector``;
- IngressRule: allow from peers matching any ``from_endpoints`` /
  ``from_cidr{_set}`` / ``from_entities``; ``from_requires`` adds
  *constraints* (ANDed across all rules selecting the endpoint);
- EgressRule mirrors with to_*;
- ``to_ports`` restricts the allow to L4 ports and optionally attaches
  L7 rules enforced by the proxy layer.
"""

from __future__ import annotations

import dataclasses
import ipaddress
from typing import Iterable, Optional, Sequence, Tuple

from ...labels import LabelArray, parse_label_array
from .l7 import L7Rules
from .selector import EndpointSelector

PROTO_TCP = "TCP"
PROTO_UDP = "UDP"
PROTO_ANY = "ANY"
_PROTOCOLS = (PROTO_TCP, PROTO_UDP, PROTO_ANY)

# Entities (pkg/policy/api/entity.go): named peers that expand to
# reserved-label selectors.
ENTITY_HOST = "host"
ENTITY_WORLD = "world"
ENTITY_CLUSTER = "cluster"
ENTITY_ALL = "all"
ENTITY_INIT = "init"  # initializing endpoints (entity.go:41)
_ENTITY_SELECTORS = {
    ENTITY_HOST: EndpointSelector.make(["reserved:host"]),
    ENTITY_WORLD: EndpointSelector.make(["reserved:world"]),
    ENTITY_CLUSTER: EndpointSelector.make(["reserved:cluster"]),
    ENTITY_ALL: EndpointSelector.wildcard(),
    ENTITY_INIT: EndpointSelector.make(["reserved:init"]),
}


def entity_selector(entity: str) -> EndpointSelector:
    try:
        return _ENTITY_SELECTORS[entity.lower()]
    except KeyError:
        raise ValueError(f"unknown entity {entity!r}") from None


@dataclasses.dataclass(frozen=True)
class PortProtocol:
    """One L4 port (l4.go PortProtocol). Ports are matched literally
    throughout (L4PolicyMap keys "port/proto" exactly), including 0."""

    port: int
    protocol: str = PROTO_ANY

    def sanitize(self) -> None:
        if not (0 <= self.port <= 65535):
            raise ValueError(f"invalid port {self.port}")
        if self.protocol.upper() not in _PROTOCOLS:
            raise ValueError(f"invalid protocol {self.protocol!r}")

    @property
    def proto(self) -> str:
        return self.protocol.upper()

    def __str__(self) -> str:
        return f"{self.port}/{self.proto}"


@dataclasses.dataclass(frozen=True)
class PortRule:
    """L4 allow with optional L7 refinement (l4.go PortRule)."""

    ports: Tuple[PortProtocol, ...]
    rules: L7Rules = L7Rules()
    redirect_port: int = 0  # legacy explicit proxy port (l4.go:52)

    def sanitize(self) -> None:
        if not self.ports:
            raise ValueError("PortRule needs at least one port")
        for p in self.ports:
            p.sanitize()
        self.rules.sanitize()
        if self.rules:
            for p in self.ports:
                if p.port == 0:
                    raise ValueError("L7 rules require a concrete port")


def host_cidr(ip: str) -> str:
    """ip → its single-address CIDR (/32 or /128) — shared by the
    translators that synthesize per-address CIDRRules (ToServices,
    ToFQDNs) so their generated entries stay mutually comparable."""
    addr = ipaddress.ip_address(ip)
    return f"{ip}/{32 if addr.version == 4 else 128}"


@dataclasses.dataclass(frozen=True)
class CIDRRule:
    """CIDR with carve-outs (cidr.go CIDRRule). ``generated`` marks
    entries synthesized by a translator (ToServices/ToFQDNs expansion,
    rule_translate.go CIDRRule.Generated) so reverts only remove what
    translation added."""

    cidr: str
    except_cidrs: Tuple[str, ...] = ()
    generated: bool = False
    # which translator synthesized this entry ("fqdn", "service", "")
    # — each translator replaces only its own entries on re-translate
    generated_by: str = ""

    def sanitize(self) -> None:
        net = ipaddress.ip_network(self.cidr, strict=False)
        for ex in self.except_cidrs:
            ex_net = ipaddress.ip_network(ex, strict=False)
            if ex_net.version != net.version or not ex_net.subnet_of(net):
                raise ValueError(f"except CIDR {ex} not contained in {self.cidr}")


@dataclasses.dataclass(frozen=True)
class IngressRule:
    from_endpoints: Tuple[EndpointSelector, ...] = ()
    from_requires: Tuple[EndpointSelector, ...] = ()
    from_cidr: Tuple[str, ...] = ()
    from_cidr_set: Tuple[CIDRRule, ...] = ()
    from_entities: Tuple[str, ...] = ()
    to_ports: Tuple[PortRule, ...] = ()

    def sanitize(self) -> None:
        for c in self.from_cidr:
            ipaddress.ip_network(c, strict=False)
        for cs in self.from_cidr_set:
            cs.sanitize()
        for e in self.from_entities:
            entity_selector(e)
        for pr in self.to_ports:
            pr.sanitize()

    def peer_selectors(self) -> Tuple[EndpointSelector, ...]:
        """All L3 peer selectors this rule allows (endpoints + entities);
        CIDR peers are resolved separately through CIDR identities."""
        return self.from_endpoints + tuple(entity_selector(e) for e in self.from_entities)

    @property
    def allows_all_l3(self) -> bool:
        """True when no L3 restriction is present (an empty from_* list
        with to_ports means 'any peer on these ports', ingress.go)."""
        return not (
            self.from_endpoints or self.from_cidr or self.from_cidr_set or self.from_entities
        )


@dataclasses.dataclass(frozen=True)
class EgressRule:
    to_endpoints: Tuple[EndpointSelector, ...] = ()
    to_requires: Tuple[EndpointSelector, ...] = ()
    to_cidr: Tuple[str, ...] = ()
    to_cidr_set: Tuple[CIDRRule, ...] = ()
    to_entities: Tuple[str, ...] = ()
    to_ports: Tuple[PortRule, ...] = ()
    to_services: Tuple["ServiceSelector", ...] = ()
    to_fqdns: Tuple[str, ...] = ()  # DNS names → generated to_cidr_set (pkg/fqdn)

    def sanitize(self) -> None:
        for c in self.to_cidr:
            ipaddress.ip_network(c, strict=False)
        for cs in self.to_cidr_set:
            cs.sanitize()
        for e in self.to_entities:
            entity_selector(e)
        for pr in self.to_ports:
            pr.sanitize()

    def peer_selectors(self) -> Tuple[EndpointSelector, ...]:
        return self.to_endpoints + tuple(entity_selector(e) for e in self.to_entities)

    @property
    def allows_all_l3(self) -> bool:
        return not (
            self.to_endpoints
            or self.to_cidr
            or self.to_cidr_set
            or self.to_entities
            or self.to_services
            or self.to_fqdns
        )


@dataclasses.dataclass(frozen=True)
class ServiceSelector:
    """k8s service reference (pkg/policy/api/service.go Service):
    either a direct name+namespace (K8sService) or a label selector over
    service labels (K8sServiceSelector). Resolved by the orchestrator
    layer (k8s/rule_translate.py) into endpoint IPs → CIDR set."""

    name: str = ""
    namespace: str = ""
    selector: Optional["EndpointSelector"] = None


@dataclasses.dataclass(frozen=True)
class Rule:
    """One policy rule (rule.go Rule)."""

    endpoint_selector: EndpointSelector
    ingress: Tuple[IngressRule, ...] = ()
    egress: Tuple[EgressRule, ...] = ()
    labels: LabelArray = dataclasses.field(default_factory=LabelArray)
    description: str = ""

    def sanitize(self) -> None:
        """Validation (rule_validation.go Sanitize)."""
        if self.endpoint_selector is None:
            raise ValueError("rule needs an endpoint selector")
        for r in self.ingress:
            r.sanitize()
        for r in self.egress:
            r.sanitize()


def rule(
    selector: Sequence[str],
    ingress: Iterable[IngressRule] = (),
    egress: Iterable[EgressRule] = (),
    labels: Optional[Sequence[str]] = None,
    description: str = "",
) -> Rule:
    """Convenience constructor from label strings."""
    return Rule(
        endpoint_selector=EndpointSelector.make(list(selector)),
        ingress=tuple(ingress),
        egress=tuple(egress),
        labels=parse_label_array(labels or []),
        description=description,
    )
