"""Endpoint selectors.

Reference: pkg/policy/api/selector.go — EndpointSelector wraps a k8s
LabelSelector (matchLabels + matchExpressions with In/NotIn/Exists/
DoesNotExist), with label keys optionally carrying a ``source:`` prefix
(default wildcard source ``any``).

TPU-first compilation contract: a selector lowers to a small list of
*conjuncts* ``(require_bits, forbid_bits)`` over the LabelVocab such that

    sel.matches(id) == any(id ⊇ require and id ∩ forbid = ∅ for conjunct)

- matchLabels / In(v)    → require kv-bit(s); multi-value In expands the
                           conjunct list (cross product, OR-of-ANDs)
- Exists                 → require exists-bit
- NotIn(vs)              → forbid kv-bit per value (k8s semantics: match
                           when key absent or value not listed)
- DoesNotExist           → forbid exists-bit
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from ...labels import Label, LabelArray, LabelVocab, parse_label

_DEFAULT_SELECTOR_SOURCE = "any"

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
_OPERATORS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST)


def _parse_selector_label(text: str, value: str = "") -> Label:
    lbl = parse_label(text if not value else f"{text}={value}")
    if lbl.source == "unspec":
        lbl = Label(source=_DEFAULT_SELECTOR_SOURCE, key=lbl.key, value=lbl.value)
    return lbl


@dataclasses.dataclass(frozen=True)
class MatchExpression:
    key: str
    operator: str
    values: Tuple[str, ...] = ()

    def __post_init__(self):
        if self.operator not in _OPERATORS:
            raise ValueError(f"invalid selector operator {self.operator!r}")
        if self.operator in (EXISTS, DOES_NOT_EXIST) and self.values:
            raise ValueError(f"{self.operator} takes no values")
        if self.operator in (IN, NOT_IN) and not self.values:
            raise ValueError(f"{self.operator} requires values")


@dataclasses.dataclass(frozen=True)
class EndpointSelector:
    """Immutable selector. ``match_labels`` maps (possibly source-
    prefixed) keys to values; empty selector selects everything
    (wildcard, like the reference's NewWildcardEndpointSelector)."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[MatchExpression, ...] = ()

    @classmethod
    def make(
        cls,
        match_labels: Union[Dict[str, str], Sequence[str], None] = None,
        match_expressions: Iterable[MatchExpression] = (),
    ) -> "EndpointSelector":
        if match_labels is None:
            pairs: Tuple[Tuple[str, str], ...] = ()
        elif isinstance(match_labels, dict):
            pairs = tuple(sorted(match_labels.items()))
        else:  # sequence of "key=value" strings
            parsed = [parse_label(s) for s in match_labels]
            pairs = tuple(sorted((f"{l.source}:{l.key}" if l.source != "unspec" else l.key, l.value) for l in parsed))
        return cls(pairs, tuple(match_expressions))

    @classmethod
    def wildcard(cls) -> "EndpointSelector":
        return cls()

    @property
    def is_wildcard(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def has_key(self, key: str) -> bool:
        """True if the selector matches on ``key`` (selector.go HasKey):
        either a matchLabels entry or any matchExpression keyed on it."""
        return any(k == key for k, _ in self.match_labels) or any(
            e.key == key for e in self.match_expressions
        )

    def has_key_prefix(self, prefix: str) -> bool:
        """True if any match key starts with ``prefix`` (HasKeyPrefix)."""
        return any(k.startswith(prefix) for k, _ in self.match_labels) or any(
            e.key.startswith(prefix) for e in self.match_expressions
        )

    def get_match(self, key: str) -> Optional[str]:
        """Value matched for ``key`` in matchLabels, else None (GetMatch)."""
        for k, v in self.match_labels:
            if k == key:
                return v
        return None

    def with_match(self, key: str, value: str) -> "EndpointSelector":
        """New selector with ``key=value`` added to matchLabels
        (selector.go AddMatch; immutable here)."""
        if self.get_match(key) == value:
            return self
        pairs = tuple(sorted(dict(self.match_labels, **{key: value}).items()))
        return EndpointSelector(pairs, self.match_expressions)

    def with_expression(self, expr: MatchExpression) -> "EndpointSelector":
        if expr in self.match_expressions:
            return self
        return EndpointSelector(self.match_labels, self.match_expressions + (expr,))

    # -- host-side evaluation (the oracle path) -------------------------
    def matches(self, labels: LabelArray) -> bool:
        for key, value in self.match_labels:
            if not labels.has(_parse_selector_label(key, value)):
                return False
        for expr in self.match_expressions:
            probe = _parse_selector_label(expr.key)
            has_key = any(
                l.key == probe.key and (probe.source == "any" or probe.source == l.source)
                for l in labels
            )
            if expr.operator == EXISTS:
                if not has_key:
                    return False
            elif expr.operator == DOES_NOT_EXIST:
                if has_key:
                    return False
            elif expr.operator == IN:
                if not any(labels.has(_parse_selector_label(expr.key, v)) for v in expr.values):
                    return False
            elif expr.operator == NOT_IN:
                if any(labels.has(_parse_selector_label(expr.key, v)) for v in expr.values):
                    return False
        return True

    # -- device-side lowering -------------------------------------------
    def conjuncts(self, vocab: LabelVocab) -> List[Tuple[List[int], List[int]]]:
        """Lower to [(require_bits, forbid_bits), ...] (OR over entries)."""
        require: List[int] = []
        forbid: List[int] = []
        or_groups: List[List[int]] = []
        for key, value in self.match_labels:
            require.append(vocab.kv_bit(_parse_selector_label(key, value)))
        for expr in self.match_expressions:
            probe = _parse_selector_label(expr.key)
            if expr.operator == EXISTS:
                require.append(vocab.exists_bit(probe.source, probe.key))
            elif expr.operator == DOES_NOT_EXIST:
                forbid.append(vocab.exists_bit(probe.source, probe.key))
            elif expr.operator == IN:
                or_groups.append(
                    [vocab.kv_bit(_parse_selector_label(expr.key, v)) for v in expr.values]
                )
            elif expr.operator == NOT_IN:
                forbid.extend(
                    vocab.kv_bit(_parse_selector_label(expr.key, v)) for v in expr.values
                )
        if not or_groups:
            return [(require, forbid)]
        out = []
        for combo in itertools.product(*or_groups):
            out.append((require + list(combo), list(forbid)))
        return out

    def __str__(self) -> str:
        parts = [f"{k}={v}" if v else k for k, v in self.match_labels]
        parts += [f"{e.key} {e.operator} {list(e.values)}" for e in self.match_expressions]
        return "Selector(" + ", ".join(parts) + ")" if parts else "Selector(*)"


def selector_from_labels(*label_strings: str) -> EndpointSelector:
    """Convenience: selector requiring every given ``source:key=value``."""
    return EndpointSelector.make(list(label_strings))
