"""JSON (de)serialization of policy rules.

Wire format follows the reference's JSON policy documents (the format
accepted by ``cilium policy import``, pkg/policy/api JSON tags):
camelCase keys, k8s-style LabelSelector for endpointSelector, e.g.::

    [{
      "endpointSelector": {"matchLabels": {"app": "web"}},
      "ingress": [{
        "fromEndpoints": [{"matchLabels": {"role": "frontend"}}],
        "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                     "rules": {"http": [{"method": "GET", "path": "/public.*"}]}}]
      }],
      "labels": ["k8s:name=web-policy"]
    }]
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Sequence

from ...labels import parse_label_array
from .l7 import HTTPRule, KafkaRule, L7Rules
from .rules import (
    CIDRRule,
    EgressRule,
    IngressRule,
    PortProtocol,
    PortRule,
    Rule,
    ServiceSelector,
)
from .selector import EndpointSelector, MatchExpression


def _selector_from_dict(d: Dict[str, Any]) -> EndpointSelector:
    exprs = tuple(
        MatchExpression(
            key=e["key"], operator=e["operator"], values=tuple(e.get("values") or ())
        )
        for e in d.get("matchExpressions") or ()
    )
    return EndpointSelector.make(d.get("matchLabels") or {}, exprs)


def _selector_to_dict(s: EndpointSelector) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if s.match_labels:
        out["matchLabels"] = dict(s.match_labels)
    if s.match_expressions:
        out["matchExpressions"] = [
            {"key": e.key, "operator": e.operator, **({"values": list(e.values)} if e.values else {})}
            for e in s.match_expressions
        ]
    return out


def _ports_from_dict(entries: Iterable[Dict[str, Any]]) -> tuple:
    out = []
    for pr in entries or ():
        ports = tuple(
            PortProtocol(port=int(p.get("port", 0) or 0), protocol=p.get("protocol", "ANY") or "ANY")
            for p in pr.get("ports") or ()
        )
        rules_d = pr.get("rules") or {}
        l7 = L7Rules(
            http=tuple(
                HTTPRule(
                    path=h.get("path", ""),
                    method=h.get("method", ""),
                    host=h.get("host", ""),
                    headers=tuple(h.get("headers") or ()),
                )
                for h in rules_d.get("http") or ()
            ),
            kafka=tuple(
                KafkaRule(
                    role=k.get("role", ""),
                    api_key=k.get("apiKey", ""),
                    api_version=str(k.get("apiVersion", "") or ""),
                    client_id=k.get("clientID", ""),
                    topic=k.get("topic", ""),
                )
                for k in rules_d.get("kafka") or ()
            ),
        )
        out.append(PortRule(ports=ports, rules=l7, redirect_port=int(pr.get("redirectPort", 0) or 0)))
    return tuple(out)


def _ports_to_dict(port_rules: Sequence[PortRule]) -> List[Dict[str, Any]]:
    out = []
    for pr in port_rules:
        d: Dict[str, Any] = {
            "ports": [{"port": str(p.port), "protocol": p.proto} for p in pr.ports]
        }
        rules: Dict[str, Any] = {}
        if pr.rules.http:
            rules["http"] = [
                {
                    k: v
                    for k, v in (
                        ("path", h.path),
                        ("method", h.method),
                        ("host", h.host),
                        ("headers", list(h.headers)),
                    )
                    if v
                }
                for h in pr.rules.http
            ]
        if pr.rules.kafka:
            rules["kafka"] = [
                {
                    k: v
                    for k, v in (
                        ("role", kr.role),
                        ("apiKey", kr.api_key),
                        ("apiVersion", kr.api_version),
                        ("clientID", kr.client_id),
                        ("topic", kr.topic),
                    )
                    if v
                }
                for kr in pr.rules.kafka
            ]
        if rules:
            d["rules"] = rules
        if pr.redirect_port:
            d["redirectPort"] = pr.redirect_port
        out.append(d)
    return out


def _cidr_set(entries: Iterable[Dict[str, Any]]) -> tuple:
    return tuple(
        CIDRRule(
            cidr=c["cidr"],
            except_cidrs=tuple(c.get("except") or ()),
            generated=bool(c.get("generated", False)),
            generated_by=str(c.get("generatedBy", "")),
        )
        for c in entries or ()
    )


def rule_from_dict(d: Dict[str, Any]) -> Rule:
    ingress = tuple(
        IngressRule(
            from_endpoints=tuple(_selector_from_dict(s) for s in r.get("fromEndpoints") or ()),
            from_requires=tuple(_selector_from_dict(s) for s in r.get("fromRequires") or ()),
            from_cidr=tuple(r.get("fromCIDR") or ()),
            from_cidr_set=_cidr_set(r.get("fromCIDRSet")),
            from_entities=tuple(r.get("fromEntities") or ()),
            to_ports=_ports_from_dict(r.get("toPorts")),
        )
        for r in d.get("ingress") or ()
    )
    egress = tuple(
        EgressRule(
            to_endpoints=tuple(_selector_from_dict(s) for s in r.get("toEndpoints") or ()),
            to_requires=tuple(_selector_from_dict(s) for s in r.get("toRequires") or ()),
            to_cidr=tuple(r.get("toCIDR") or ()),
            to_cidr_set=_cidr_set(r.get("toCIDRSet")),
            to_entities=tuple(r.get("toEntities") or ()),
            to_ports=_ports_from_dict(r.get("toPorts")),
            to_services=tuple(
                ServiceSelector(
                    name=(s.get("k8sService") or {}).get("serviceName", ""),
                    namespace=(s.get("k8sService") or {}).get("namespace", "")
                    or (s.get("k8sServiceSelector") or {}).get("namespace", ""),
                    selector=(
                        _selector_from_dict((s.get("k8sServiceSelector") or {}).get("selector") or {})
                        if s.get("k8sServiceSelector")
                        else None
                    ),
                )
                for s in r.get("toServices") or ()
            ),
            to_fqdns=tuple(f.get("matchName", "") for f in r.get("toFQDNs") or ()),
        )
        for r in d.get("egress") or ()
    )
    return Rule(
        endpoint_selector=_selector_from_dict(d.get("endpointSelector") or {}),
        ingress=ingress,
        egress=egress,
        labels=parse_label_array(_label_strings(d.get("labels") or [])),
        description=d.get("description", ""),
    )


def _label_strings(entries: Iterable[Any]) -> List[str]:
    """Labels appear either as strings ("k8s:name=web") or as decoded
    Label objects ({"key": ..., "value": ..., "source": ...} — the
    reference's labels.Label JSON shape)."""
    out: List[str] = []
    for e in entries:
        if isinstance(e, str):
            out.append(e)
        else:
            src = e.get("source") or "unspec"
            kv = e.get("key", "")
            if e.get("value"):
                kv = f"{kv}={e['value']}"
            out.append(f"{src}:{kv}" if src != "unspec" else kv)
    return out


def rule_to_dict(r: Rule) -> Dict[str, Any]:
    d: Dict[str, Any] = {"endpointSelector": _selector_to_dict(r.endpoint_selector)}
    if r.ingress:
        d["ingress"] = []
        for ing in r.ingress:
            rd: Dict[str, Any] = {}
            if ing.from_endpoints:
                rd["fromEndpoints"] = [_selector_to_dict(s) for s in ing.from_endpoints]
            if ing.from_requires:
                rd["fromRequires"] = [_selector_to_dict(s) for s in ing.from_requires]
            if ing.from_cidr:
                rd["fromCIDR"] = list(ing.from_cidr)
            if ing.from_cidr_set:
                rd["fromCIDRSet"] = [
                    {
                        "cidr": c.cidr,
                        **({"except": list(c.except_cidrs)} if c.except_cidrs else {}),
                        **({"generated": True} if c.generated else {}),
                        **({"generatedBy": c.generated_by} if c.generated_by else {}),
                    }
                    for c in ing.from_cidr_set
                ]
            if ing.from_entities:
                rd["fromEntities"] = list(ing.from_entities)
            if ing.to_ports:
                rd["toPorts"] = _ports_to_dict(ing.to_ports)
            d["ingress"].append(rd)
    if r.egress:
        d["egress"] = []
        for eg in r.egress:
            rd = {}
            if eg.to_endpoints:
                rd["toEndpoints"] = [_selector_to_dict(s) for s in eg.to_endpoints]
            if eg.to_requires:
                rd["toRequires"] = [_selector_to_dict(s) for s in eg.to_requires]
            if eg.to_cidr:
                rd["toCIDR"] = list(eg.to_cidr)
            if eg.to_cidr_set:
                rd["toCIDRSet"] = [
                    {
                        "cidr": c.cidr,
                        **({"except": list(c.except_cidrs)} if c.except_cidrs else {}),
                        **({"generated": True} if c.generated else {}),
                        **({"generatedBy": c.generated_by} if c.generated_by else {}),
                    }
                    for c in eg.to_cidr_set
                ]
            if eg.to_entities:
                rd["toEntities"] = list(eg.to_entities)
            if eg.to_ports:
                rd["toPorts"] = _ports_to_dict(eg.to_ports)
            if eg.to_services:
                rd["toServices"] = [
                    (
                        {
                            "k8sServiceSelector": {
                                "selector": _selector_to_dict(s.selector),
                                **({"namespace": s.namespace} if s.namespace else {}),
                            }
                        }
                        if s.selector is not None
                        else {"k8sService": {"serviceName": s.name, "namespace": s.namespace}}
                    )
                    for s in eg.to_services
                ]
            if eg.to_fqdns:
                rd["toFQDNs"] = [{"matchName": f} for f in eg.to_fqdns]
            d["egress"].append(rd)
    if len(r.labels):
        d["labels"] = list(r.labels.to_strings())
    if r.description:
        d["description"] = r.description
    return d


def rules_from_json(text: str) -> List[Rule]:
    data = json.loads(text)
    if isinstance(data, dict):
        data = [data]
    rules = [rule_from_dict(d) for d in data]
    for r in rules:
        r.sanitize()
    return rules


def rules_to_json(rules: Iterable[Rule], indent: int | None = 2) -> str:
    return json.dumps([rule_to_dict(r) for r in rules], indent=indent)
