"""CIDR policy resolution.

Reference: pkg/policy/cidr.go (CIDRPolicy with per-prefix-length
bookkeeping), pkg/policy/api/cidr.go (ComputeResultantCIDRSet — a
CIDRRule with exceptions is flattened into the covering set minus the
excepted subnets), pkg/policy/rule.go resolveCIDRPolicy/mergeCIDR.

The per-prefix-length map feeds the LPM tensor builder
(cilium_tpu.ops.lpm) and the prefilter, mirroring how the reference
feeds cidrmap/ipcache prefixes.
"""

from __future__ import annotations

import dataclasses
import ipaddress
from typing import Dict, Iterable, List, Set, Tuple

from ..labels import LabelArray
from .api import CIDRRule, EndpointSelector


def compute_resultant_cidr_set(rules: Iterable[CIDRRule]) -> List[str]:
    """CIDRRule slice → flat allowed CIDR strings with exceptions carved
    out (api/cidr.go ComputeResultantCIDRSet)."""
    out: List[str] = []
    for r in rules:
        net = ipaddress.ip_network(r.cidr, strict=False)
        if not r.except_cidrs:
            out.append(str(net))
            continue
        remaining = [net]
        for ex in r.except_cidrs:
            ex_net = ipaddress.ip_network(ex, strict=False)
            next_remaining = []
            for n in remaining:
                if ex_net.version != n.version or not ex_net.subnet_of(n):
                    next_remaining.append(n)
                elif ex_net == n:
                    continue
                else:
                    next_remaining.extend(n.address_exclude(ex_net))
            remaining = next_remaining
        out.extend(str(n) for n in sorted(remaining))
    return out


def cidr_selectors(cidrs: Iterable[str], cidr_rules: Iterable[CIDRRule]) -> List[EndpointSelector]:
    """CIDR allows as label selectors over ``cidr:`` identity labels
    (api/cidr.go GetAsEndpointSelectors) — this is how CIDR peers join
    the same bitmap-matching path as label peers. The selector key must
    stay byte-identical to the identity-side label key, so both derive
    from labels.cidr.ip_string_to_label."""
    from ..labels.cidr import ip_string_to_label

    sels = []
    for c in list(cidrs) + compute_resultant_cidr_set(cidr_rules):
        lbl = ip_string_to_label(c)
        sels.append(EndpointSelector.make([f"{lbl.source}:{lbl.key}"]))
    return sels


@dataclasses.dataclass
class CIDRPolicyMap:
    """Allowed prefixes + the rules they derive from, with prefix-length
    reference counts (pkg/policy/cidr.go CIDRPolicyMapRule + counter)."""

    entries: Dict[str, List[LabelArray]] = dataclasses.field(default_factory=dict)

    def insert(self, cidr: str, rule_labels: LabelArray) -> int:
        net = ipaddress.ip_network(cidr, strict=False)
        key = str(net)
        if key in self.entries:
            self.entries[key].append(rule_labels)
            return 0
        self.entries[key] = [rule_labels]
        return 1

    def __len__(self) -> int:
        return len(self.entries)

    def prefixes(self) -> List[str]:
        return list(self.entries)

    def prefix_lengths(self) -> Set[Tuple[int, int]]:
        """{(ip_version, prefix_len)} — drives datapath shape decisions
        the way pkg/counter PrefixLengthCounter drives recompiles."""
        out = set()
        for key in self.entries:
            net = ipaddress.ip_network(key)
            out.add((net.version, net.prefixlen))
        return out


@dataclasses.dataclass
class CIDRPolicy:
    ingress: CIDRPolicyMap = dataclasses.field(default_factory=CIDRPolicyMap)
    egress: CIDRPolicyMap = dataclasses.field(default_factory=CIDRPolicyMap)
