"""Resolved L4 policy: filters keyed by port/proto.

Reference: pkg/policy/l4.go — L4Filter{Port, Protocol, L7Parser,
L7RulesPerEp, Endpoints, DerivedFromRules} and L4PolicyMap keyed
"port/proto", with the merge rules of pkg/policy/rule.go
mergeL4IngressPort/mergeL4EgressPort:

- an empty peer-selector list selects all endpoints (wildcard);
- merging a wildcard filter with anything yields wildcard;
- L7 parsers must agree per port; L7 rules merge per peer selector.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..labels import LabelArray
from .api import EndpointSelector, HTTPRule, KafkaRule, L7Rules
from .search import Decision, PortContext, SearchContext

PARSER_NONE = ""
PARSER_HTTP = "http"
PARSER_KAFKA = "kafka"

WILDCARD = EndpointSelector.wildcard()


class MergeConflict(ValueError):
    """L7 parser or rule-type conflict while merging port rules."""


@dataclasses.dataclass
class L4Filter:
    port: int
    protocol: str  # "TCP" | "UDP"
    ingress: bool
    endpoints: List[EndpointSelector] = dataclasses.field(default_factory=list)
    l7_parser: str = PARSER_NONE
    l7_rules_per_ep: Dict[EndpointSelector, L7Rules] = dataclasses.field(default_factory=dict)
    derived_from: List[LabelArray] = dataclasses.field(default_factory=list)

    @property
    def allows_all_at_l3(self) -> bool:
        return not self.endpoints or any(s.is_wildcard for s in self.endpoints)

    @property
    def is_redirect(self) -> bool:
        return self.l7_parser != PARSER_NONE

    def matches_labels(self, labels: LabelArray) -> bool:
        if self.allows_all_at_l3:
            return True
        if len(labels) == 0:
            return False
        return any(sel.matches(labels) for sel in self.endpoints)

    def key(self) -> str:
        return f"{self.port}/{self.protocol}"


def create_l4_filter(
    peer_endpoints: List[EndpointSelector],
    l7: L7Rules,
    port: int,
    protocol: str,
    rule_labels: LabelArray,
    ingress: bool,
    l3_override_endpoints: Tuple[EndpointSelector, ...] = (),
) -> L4Filter:
    """CreateL4{Ingress,Egress}Filter (pkg/policy/l4.go:148,210)."""
    endpoints = list(peer_endpoints)
    if not endpoints or any(s.is_wildcard for s in endpoints):
        endpoints = [WILDCARD]
    f = L4Filter(
        port=port,
        protocol=protocol,
        ingress=ingress,
        endpoints=endpoints,
        derived_from=[rule_labels],
    )
    if protocol == "TCP" and l7:
        f.l7_parser = l7.parser
        for sel in endpoints:
            f.l7_rules_per_ep[sel] = l7
        # Endpoints the daemon force-allows at L3 (host/world) get their
        # L7 rules wildcarded so traffic still flows through the proxy.
        for sel in l3_override_endpoints:
            f.l7_rules_per_ep[sel] = L7Rules()
    return f


def _merge_l7(existing: L7Rules, new: L7Rules) -> L7Rules:
    if new.http:
        if existing.kafka:
            raise MergeConflict("cannot merge conflicting L7 rule types")
        http = list(existing.http)
        for r in new.http:
            if r not in http:
                http.append(r)
        return L7Rules(http=tuple(http), kafka=existing.kafka)
    if new.kafka:
        if existing.http:
            raise MergeConflict("cannot merge conflicting L7 rule types")
        kafka = list(existing.kafka)
        for r in new.kafka:
            if r not in kafka:
                kafka.append(r)
        return L7Rules(http=existing.http, kafka=tuple(kafka))
    return existing


class L4PolicyMap:
    """port/proto → L4Filter with reference merge semantics."""

    def __init__(self) -> None:
        self.filters: Dict[str, L4Filter] = {}

    def __len__(self) -> int:
        return len(self.filters)

    def __iter__(self):
        return iter(self.filters.values())

    def get(self, port: int, protocol: str) -> Optional[L4Filter]:
        return self.filters.get(f"{port}/{protocol}")

    def merge(self, new: L4Filter) -> None:
        """mergeL4IngressPort (pkg/policy/rule.go:46-122)."""
        key = new.key()
        existing = self.filters.get(key)
        if existing is None:
            self.filters[key] = new
            return
        if existing.allows_all_at_l3 or new.allows_all_at_l3:
            existing.endpoints = [WILDCARD]
        else:
            existing.endpoints.extend(new.endpoints)
        if new.l7_parser != PARSER_NONE:
            if existing.l7_parser == PARSER_NONE:
                existing.l7_parser = new.l7_parser
            elif existing.l7_parser != new.l7_parser:
                raise MergeConflict(
                    f"cannot merge conflicting L7 parsers ({new.l7_parser}/{existing.l7_parser})"
                )
        for sel, rules in new.l7_rules_per_ep.items():
            if sel in existing.l7_rules_per_ep:
                existing.l7_rules_per_ep[sel] = _merge_l7(existing.l7_rules_per_ep[sel], rules)
            else:
                existing.l7_rules_per_ep[sel] = rules
        existing.derived_from.extend(new.derived_from)

    def has_redirect(self) -> bool:
        return any(f.is_redirect for f in self)

    def wildcard_l3l4(
        self, protocol: str, port: int, endpoints: List[EndpointSelector], rule_labels: LabelArray
    ) -> None:
        """wildcardL3L4Rule (pkg/policy/repository.go:128): L3-only /
        L3L4-only allows wildcard the L7 rules of matching filters so
        that broader allows aren't narrowed by L7 restrictions."""
        for f in self.filters.values():
            if protocol != f.protocol or (port != 0 and port != f.port):
                continue
            if f.l7_parser == PARSER_NONE:
                continue
            wildcard_rules = (
                L7Rules(http=(HTTPRule(),))
                if f.l7_parser == PARSER_HTTP
                else L7Rules(kafka=(KafkaRule(),))
            )
            # Exactly the given selectors — an empty list is a no-op
            # (an ingress rule with no From fields allows nothing at L3,
            # so it must not wildcard anyone, repository.go:128-158).
            for sel in endpoints:
                f.l7_rules_per_ep[sel] = wildcard_rules
            f.endpoints.extend(endpoints)
            f.derived_from.append(rule_labels)

    # -- trace-path coverage (containsAllL3L4, pkg/policy/l4.go:286) ----
    def covers_context(self, peer_labels: LabelArray, dports: Tuple[PortContext, ...]) -> Decision:
        if not self.filters:
            return Decision.ALLOWED
        if not dports:
            return Decision.DENIED
        for pc in dports:
            proto = (pc.protocol or "ANY").upper()
            if proto == "ANY":
                candidates = [self.get(pc.port, "TCP"), self.get(pc.port, "UDP")]
                if not any(f is not None and f.matches_labels(peer_labels) for f in candidates):
                    return Decision.DENIED
            else:
                f = self.get(pc.port, proto)
                if f is None or not f.matches_labels(peer_labels):
                    return Decision.DENIED
        return Decision.ALLOWED


@dataclasses.dataclass
class L4Policy:
    ingress: L4PolicyMap = dataclasses.field(default_factory=L4PolicyMap)
    egress: L4PolicyMap = dataclasses.field(default_factory=L4PolicyMap)
    revision: int = 0

    def has_redirect(self) -> bool:
        return self.ingress.has_redirect() or self.egress.has_redirect()

    def requires_conntrack(self) -> bool:
        return len(self.ingress) > 0 or len(self.egress) > 0
