"""The policy repository: ordered rules + revision + verdict evaluation.

Reference: pkg/policy/repository.go and pkg/policy/rule.go. This module
is the *host-side oracle*: the scalar, trace-producing evaluator whose
semantics the TPU compiler (cilium_tpu.models.compiler) must reproduce
bit-for-bit. Differential tests assert oracle == device engine.

Verdict semantics preserved (v1.2 is allow-only):

- ``can_reach_ingress`` (repository.go:80, rule.go:323): walk rules in
  order; a rule whose selector matches dst with an unsatisfied
  FromRequires → DENIED (stop); a matching FromEndpoints/entity/CIDR
  selector with no ToPorts → ALLOWED; with ToPorts → stay UNDECIDED
  (defer to L4).
- ``allows_ingress`` (repository.go:392): L3 ALLOWED short-circuits;
  otherwise, when dports are given, resolve the L4 policy (with
  FromRequires folded into every FromEndpoints selector,
  repository.go:249-261) and require it to cover the context; anything
  not ALLOWED becomes DENIED.
- L4 resolution merges PortRules per "port/proto" with wildcarding of
  L7 rules by broader L3/L4-only allows (repository.go wildcardL3L4Rules).
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Optional, Sequence, Tuple

from ..labels import LabelArray
from .api import (
    EgressRule,
    EndpointSelector,
    IngressRule,
    MatchExpression,
    PortProtocol,
    Rule,
    IN,
)
from .cidr import CIDRPolicy, cidr_selectors, compute_resultant_cidr_set
from .l4 import L4Policy, L4PolicyMap, create_l4_filter
from .search import Decision, SearchContext


def _with_requirements(
    sel: EndpointSelector, requirements: Tuple[MatchExpression, ...]
) -> EndpointSelector:
    if not requirements:
        return sel
    return EndpointSelector(
        match_labels=sel.match_labels,
        match_expressions=sel.match_expressions + requirements,
    )


def _requirement_expressions(selectors: Iterable[EndpointSelector]) -> Tuple[MatchExpression, ...]:
    """Flatten FromRequires selectors into matchExpressions that can be
    ANDed onto peer selectors (repository.go:249-261 converts each
    requirement via ConvertToLabelSelectorRequirementSlice)."""
    exprs: List[MatchExpression] = []
    for sel in selectors:
        for key, value in sel.match_labels:
            exprs.append(MatchExpression(key=key, operator=IN, values=(value,)))
        exprs.extend(sel.match_expressions)
    return tuple(exprs)


def _ingress_peer_selectors(r: IngressRule) -> List[EndpointSelector]:
    """GetSourceEndpointSelectors (api/ingress.go:111): endpoints +
    entities + CIDR-derived label selectors."""
    sels = list(r.peer_selectors())
    sels.extend(cidr_selectors(r.from_cidr, r.from_cidr_set))
    return sels


def _egress_peer_selectors(r: EgressRule) -> List[EndpointSelector]:
    sels = list(r.peer_selectors())
    sels.extend(cidr_selectors(r.to_cidr, r.to_cidr_set))
    return sels


def _is_label_based_ingress(r: IngressRule) -> bool:
    return not (r.from_cidr or r.from_cidr_set)


def _is_label_based_egress(r: EgressRule) -> bool:
    return not (r.to_cidr or r.to_cidr_set or r.to_services or r.to_fqdns)


class Repository:
    """Ordered rule list with a monotonic revision counter."""

    # Change-log ring: compilers consult changes_since(rev) to apply a
    # pure-append delta instead of a full recompile (the incremental
    # half of the reference's per-revision regeneration protocol,
    # pkg/endpoint/policy.go:506-552).
    LOG_CAP = 256

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.rules: List[Rule] = []
        self._revision = 1
        self._log: List[Tuple[int, str, tuple]] = []

    # ------------------------------------------------------------------
    @property
    def revision(self) -> int:
        return self._revision

    def _bump(self) -> int:
        self._revision += 1
        return self._revision

    def _log_op(self, op: str, payload: tuple) -> None:
        self._log.append((self._revision, op, payload))
        if len(self._log) > self.LOG_CAP:
            del self._log[: len(self._log) - self.LOG_CAP]

    def changes_since(self, revision: int):
        """Ops with revision > ``revision``, oldest first — or None when
        the log no longer reaches back that far (caller must do a full
        rebuild)."""
        with self._lock:
            if revision >= self._revision:
                return []
            # Every revision in the gap must be accounted for by a log
            # entry — out-of-band bumps or a truncated ring mean the
            # caller can't know what changed.
            covered = {rev for rev, _, _ in self._log}
            if not all(r in covered for r in range(revision + 1, self._revision + 1)):
                return None
            return [e for e in self._log if e[0] > revision]

    def add_list(self, rules: Sequence[Rule]) -> int:
        """Sanitize + append (repository.go AddListLocked:521)."""
        for r in rules:
            r.sanitize()
        with self._lock:
            self.rules.extend(rules)
            rev = self._bump()
            self._log_op("add", tuple(rules))
            return rev

    def delete_by_labels(self, labels: LabelArray) -> Tuple[int, int]:
        """Remove rules carrying every given label; returns (revision,
        n_deleted) (repository.go DeleteByLabels:286)."""
        rev, deleted = self.take_by_labels(labels)
        return rev, len(deleted)

    def _take_locked(self, labels: LabelArray) -> List[Rule]:
        """Remove + return every rule carrying all ``labels`` (caller
        holds the lock). Logs the delete op with the removed Rule
        objects themselves: incremental compilers retract exactly
        these (their cell attribution is keyed by object identity)."""
        kept: List[Rule] = []
        deleted: List[Rule] = []
        for r in self.rules:
            if len(labels) and all(r.labels.has(l) for l in labels):
                deleted.append(r)
            else:
                kept.append(r)
        self.rules = kept
        if deleted:
            self._bump()
            self._log_op("delete", (labels, tuple(deleted)))
        return deleted

    def take_by_labels(self, labels: LabelArray) -> Tuple[int, List[Rule]]:
        """delete_by_labels returning the removed rules themselves —
        callers tracking derived state (prefix-length counter) need
        the exact rule set removed under THIS lock hold, not a
        separately computed snapshot that can race a concurrent add."""
        with self._lock:
            deleted = self._take_locked(labels)
            return self._revision, deleted

    def replace_by_labels(
        self, labels: LabelArray, rules: Sequence[Rule]
    ) -> Tuple[int, int]:
        """Atomically swap every rule carrying ``labels`` for
        ``rules`` under ONE lock hold — no window where the object has
        no rules (the upsert the k8s watcher needs for MODIFIED
        events; reference: repository replace-by-labels on re-import).
        Returns (revision, n_deleted). Logged as a delete op + an add
        op at consecutive revisions so incremental compilers retract
        then append without a full rebuild."""
        for r in rules:
            r.sanitize()
        with self._lock:
            deleted = self._take_locked(labels)
            self.rules = self.rules + list(rules)
            if rules:
                self._bump()
                self._log_op("add", tuple(rules))
            return self._revision, len(deleted)

    def translate_rules(self, translator) -> Tuple[int, int]:
        """Run a rule translator (e.g. k8s ToServices→ToCIDR,
        pkg/policy.Translator / repository.go TranslateRules) over every
        rule. The translator's ``translate(rule) -> Rule`` must be pure;
        changed rules are swapped in place. Returns (revision,
        n_changed). Logged as a non-append op so incremental compilers
        fall back to a full rebuild."""
        with self._lock:
            changed = 0
            for i, r in enumerate(self.rules):
                nr = translator.translate(r)
                if nr is not r and nr != r:
                    nr.sanitize()
                    self.rules[i] = nr
                    changed += 1
            if changed:
                self._bump()
                self._log_op("translate", (changed,))
            return self._revision, changed

    def get_rules_matching(self, labels: LabelArray) -> Tuple[List[Rule], bool]:
        """(rules selecting `labels`, any-match) — used for the
        enforcement pre-check (daemon/policy.go:85-93)."""
        with self._lock:
            matched = [r for r in self.rules if r.endpoint_selector.matches(labels)]
        return matched, bool(matched)

    def rule_origins(self) -> List[dict]:
        """Stable rule-origin table for verdict attribution
        (policyd-flows): one entry per rule IN REPOSITORY ORDER, so a
        matched-rule index from the device kernel maps back to the rule
        a human can recognize. The index is only stable for a fixed
        (revision) — consumers pair it with ``revision`` and re-fetch
        when the repository moves."""
        with self._lock:
            return [
                {
                    "index": i,
                    "labels": list(r.labels.to_strings()),
                    "description": getattr(r, "description", "") or "",
                }
                for i, r in enumerate(self.rules)
            ]

    def origin_names(self) -> List[str]:
        """Compact per-rule origin strings (metrics label values for
        ``rule_hits_total{origin=...}``): the rule's first label, else
        its description, else ``rule-<index>``."""
        with self._lock:
            out = []
            for i, r in enumerate(self.rules):
                labels = list(r.labels.to_strings())
                desc = getattr(r, "description", "") or ""
                out.append(labels[0] if labels else (desc or f"rule-{i}"))
            return out

    def __len__(self) -> int:
        return len(self.rules)

    # -- L3 label verdicts ---------------------------------------------
    def _rule_can_reach(self, r: Rule, ctx: SearchContext, ingress: bool) -> Decision:
        """Per-rule L3 decision (rule.go canReachIngress:323 /
        canReachEgress:370). Caller has already checked the rule selects
        the subject. FromRequires failure takes precedence over allows."""
        peer = ctx.src if ingress else ctx.dst
        directional = r.ingress if ingress else r.egress
        for dr in directional:
            for sel in dr.from_requires if ingress else dr.to_requires:
                ctx.policy_trace("    Requires %s labels %s", "from" if ingress else "to", sel)
                if not sel.matches(peer):
                    ctx.policy_trace("-     Labels %s not found\n", peer)
                    return Decision.DENIED
                ctx.policy_trace("+     Found all required labels\n")
        for dr in directional:
            sels = _ingress_peer_selectors(dr) if ingress else _egress_peer_selectors(dr)
            for sel in sels:
                ctx.policy_trace("    Allows %s labels %s", "from" if ingress else "to", sel)
                if sel.matches(peer):
                    ctx.policy_trace("      Found all required labels")
                    if not dr.to_ports:
                        ctx.policy_trace("+       No L4 restrictions\n")
                        return Decision.ALLOWED
                    ctx.policy_trace(
                        "        Rule restricts traffic to specific L4 destinations; "
                        "deferring policy decision to L4 policy stage\n"
                    )
                else:
                    ctx.policy_trace("      Labels %s not found\n", peer)
        return Decision.UNDECIDED

    def _can_reach(self, ctx: SearchContext, ingress: bool) -> Decision:
        """Walk rules in order: DENIED stops the walk; ALLOWED is
        remembered but later rules may still deny (repository.go:84-103)."""
        decision = Decision.UNDECIDED
        subject = ctx.dst if ingress else ctx.src
        selected = 0
        for r in self.rules:
            if not r.endpoint_selector.matches(subject):
                ctx.policy_trace_verbose("  Rule %s: did not select %s\n", r.description or "", subject)
                continue
            selected += 1
            ctx.policy_trace("* Rule %s: selected\n", r.description or str(r.endpoint_selector))
            verdict = self._rule_can_reach(r, ctx, ingress)
            if verdict == Decision.DENIED:
                decision = Decision.DENIED
                break
            if verdict == Decision.ALLOWED:
                decision = Decision.ALLOWED
        ctx.policy_trace("%d/%d rules selected\n", selected, len(self.rules))
        if decision == Decision.DENIED:
            ctx.policy_trace("Found unsatisfied FromRequires constraint\n")
        elif decision == Decision.ALLOWED:
            ctx.policy_trace("Found allow rule\n")
        else:
            ctx.policy_trace("Found no allow rule\n")
        return decision

    def can_reach_ingress(self, ctx: SearchContext) -> Decision:
        with self._lock:
            return self._can_reach(ctx, ingress=True)

    def can_reach_egress(self, ctx: SearchContext) -> Decision:
        with self._lock:
            return self._can_reach(ctx, ingress=False)

    # -- L4 resolution --------------------------------------------------
    def _collect_requirements(self, subject: LabelArray, ingress: bool) -> Tuple[MatchExpression, ...]:
        reqs: List[EndpointSelector] = []
        for r in self.rules:
            if not r.endpoint_selector.matches(subject):
                continue
            for dr in r.ingress if ingress else r.egress:
                reqs.extend(dr.from_requires if ingress else dr.to_requires)
        return _requirement_expressions(reqs)

    def _resolve_l4(self, ctx: SearchContext, ingress: bool) -> L4PolicyMap:
        subject = ctx.dst if ingress else ctx.src
        peer = ctx.src if ingress else ctx.dst
        requirements = self._collect_requirements(subject, ingress)
        result = L4PolicyMap()
        for r in self.rules:
            if not r.endpoint_selector.matches(subject):
                continue
            for dr in r.ingress if ingress else r.egress:
                if not dr.to_ports:
                    continue
                # Requirements fold into the explicit peer selectors only
                # (rule.go:198-232 modifies FromEndpoints, not entities/CIDRs).
                explicit_raw = dr.from_endpoints if ingress else dr.to_endpoints
                explicit = tuple(_with_requirements(s, requirements) for s in explicit_raw)
                entity_sels = dr.peer_selectors()[len(explicit_raw):]
                cidr_sels = (
                    cidr_selectors(dr.from_cidr, dr.from_cidr_set)
                    if ingress
                    else cidr_selectors(dr.to_cidr, dr.to_cidr_set)
                )
                peer_sels = list(explicit) + list(entity_sels) + list(cidr_sels)
                # mergeL4Ingress pre-check (rule.go:133-138): when the
                # context names a concrete peer, skip rules whose peers
                # can't match it.
                if len(peer) and peer_sels and not any(s.matches(peer) for s in peer_sels):
                    continue
                for pr in dr.to_ports:
                    for pp in pr.ports:
                        protos = ("TCP", "UDP") if pp.proto == "ANY" else (pp.proto,)
                        for proto in protos:
                            result.merge(
                                create_l4_filter(
                                    peer_sels, pr.rules, pp.port, proto, r.labels, ingress
                                )
                            )
        self._wildcard_l3l4(subject, ingress, result)
        return result

    def _wildcard_l3l4(self, subject: LabelArray, ingress: bool, l4map: L4PolicyMap) -> None:
        """wildcardL3L4Rules (repository.go:168): label-based L3-only and
        L3/L4-only allows wildcard L7 restrictions on matching ports."""
        for r in self.rules:
            if not r.endpoint_selector.matches(subject):
                continue
            for dr in r.ingress if ingress else r.egress:
                if not (_is_label_based_ingress(dr) if ingress else _is_label_based_egress(dr)):
                    continue
                peer_sels = list(dr.peer_selectors())
                if not dr.to_ports:
                    l4map.wildcard_l3l4("TCP", 0, peer_sels, r.labels)
                    l4map.wildcard_l3l4("UDP", 0, peer_sels, r.labels)
                else:
                    for pr in dr.to_ports:
                        if pr.rules:
                            continue
                        for pp in pr.ports:
                            protos = ("TCP", "UDP") if pp.proto == "ANY" else (pp.proto,)
                            for proto in protos:
                                l4map.wildcard_l3l4(proto, pp.port, peer_sels, r.labels)

    def resolve_l4_ingress_policy(self, ctx: SearchContext) -> L4PolicyMap:
        ctx.policy_trace("\nResolving ingress port policy for %s\n", ctx.dst)
        with self._lock:
            return self._resolve_l4(ctx, ingress=True)

    def resolve_l4_egress_policy(self, ctx: SearchContext) -> L4PolicyMap:
        ctx.policy_trace("\nResolving egress port policy for %s\n", ctx.src)
        with self._lock:
            return self._resolve_l4(ctx, ingress=False)

    def resolve_l4_policy(self, ep_labels: LabelArray) -> L4Policy:
        """Full L4 policy for an endpoint (both directions, no peer
        filter) — the DesiredL4Policy input to endpoint regeneration."""
        with self._lock:
            pol = L4Policy(revision=self._revision)
            pol.ingress = self._resolve_l4(SearchContext(dst=ep_labels), ingress=True)
            pol.egress = self._resolve_l4(SearchContext(src=ep_labels), ingress=False)
            return pol

    # -- CIDR resolution ------------------------------------------------
    def resolve_cidr_policy(self, ep_labels: LabelArray) -> CIDRPolicy:
        """ResolveCIDRPolicy (repository.go:335, rule.go:267). Ingress
        counts only L3 CIDR rules; egress counts CIDR+L4 too (for
        ipcache prefix-length bookkeeping, rule.go:295-309)."""
        result = CIDRPolicy()
        with self._lock:
            rules = list(self.rules)
        for r in rules:
            if not r.endpoint_selector.matches(ep_labels):
                continue
            for ing in r.ingress:
                if ing.to_ports:
                    continue  # ingress counts only L3-only CIDR rules
                for c in list(ing.from_cidr) + compute_resultant_cidr_set(ing.from_cidr_set):
                    result.ingress.insert(c, r.labels)
            for eg in r.egress:
                for c in list(eg.to_cidr) + compute_resultant_cidr_set(eg.to_cidr_set):
                    result.egress.insert(c, r.labels)
        return result

    # -- full verdicts (the `policy trace` semantics) -------------------
    def _allows(self, ctx: SearchContext, ingress: bool) -> Decision:
        # One lock span for the whole verdict: L3 + L4 must see a single
        # rule-list snapshot (reference holds Repository.Mutex across
        # AllowsIngressRLocked).
        self._lock.acquire()
        try:
            return self._allows_locked(ctx, ingress)
        finally:
            self._lock.release()

    def _allows_locked(self, ctx: SearchContext, ingress: bool) -> Decision:
        ctx.policy_trace("Tracing %s\n", ctx)
        decision = self._can_reach(ctx, ingress)
        ctx.policy_trace("%s verdict: %s", "Label" if ingress else "Egress label", decision)
        if decision == Decision.ALLOWED:
            ctx.policy_trace("L4 %s policies skipped", "ingress" if ingress else "egress")
            return decision
        if ctx.dports:
            l4map = (
                self.resolve_l4_ingress_policy(ctx) if ingress else self.resolve_l4_egress_policy(ctx)
            )
            peer = ctx.src if ingress else ctx.dst
            decision = Decision.UNDECIDED
            if len(l4map) > 0:
                decision = l4map.covers_context(peer, ctx.dports)
            ctx.policy_trace("L4 %s verdict: %s", "ingress" if ingress else "egress", decision)
        if decision != Decision.ALLOWED:
            decision = Decision.DENIED
        return decision

    def allows_ingress(self, ctx: SearchContext) -> Decision:
        return self._allows(ctx, ingress=True)

    def allows_egress(self, ctx: SearchContext) -> Decision:
        return self._allows(ctx, ingress=False)
