"""Search context and verdicts.

Reference: pkg/policy/policy.go (SearchContext, Trace levels) and
pkg/policy/api/decision.go (Decision).
"""

from __future__ import annotations

import dataclasses
import enum
import io
from typing import List, Optional, Tuple

from ..labels import LabelArray


class Decision(enum.IntEnum):
    UNDECIDED = 0
    ALLOWED = 1
    DENIED = 2

    def __str__(self) -> str:  # matches api.Decision.String()
        return {0: "undecided", 1: "allowed", 2: "denied"}[int(self)]


class Trace(enum.IntEnum):
    DISABLED = 0
    ENABLED = 1
    VERBOSE = 2


@dataclasses.dataclass(frozen=True)
class PortContext:
    """One destination port under trace (models.Port equivalent)."""

    port: int
    protocol: str = "ANY"  # "TCP" | "UDP" | "ANY" | ""


@dataclasses.dataclass
class SearchContext:
    """The question being asked of the policy repository: may traffic
    flow From → To (optionally on DPorts)?"""

    src: LabelArray = dataclasses.field(default_factory=LabelArray)
    dst: LabelArray = dataclasses.field(default_factory=LabelArray)
    dports: Tuple[PortContext, ...] = ()
    trace: Trace = Trace.DISABLED
    _log: Optional[io.StringIO] = None

    def __post_init__(self):
        if self.trace != Trace.DISABLED and self._log is None:
            self._log = io.StringIO()

    def policy_trace(self, fmt: str, *args) -> None:
        if self.trace != Trace.DISABLED and self._log is not None:
            self._log.write(fmt % args if args else fmt)
            if not fmt.endswith("\n"):
                self._log.write("\n")

    def policy_trace_verbose(self, fmt: str, *args) -> None:
        if self.trace == Trace.VERBOSE:
            self.policy_trace(fmt, *args)

    def log(self) -> str:
        return self._log.getvalue() if self._log is not None else ""

    def __str__(self) -> str:
        src = " ".join(self.src.to_strings()) or "[no labels]"
        dst = " ".join(self.dst.to_strings()) or "[no labels]"
        ports = ",".join(f"{p.port}/{p.protocol}" for p in self.dports)
        s = f"From: [{src}] => To: [{dst}]"
        if ports:
            s += f" Ports: [{ports}]"
        return s
