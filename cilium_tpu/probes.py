"""Node capability probes + graceful degradation flags.

Reference: /root/reference/bpf/run_probes.sh + bpf/probes/*.t — at
agent boot the reference probes the kernel for BPF features and writes
``bpf_features.h`` so the datapath compiles against what the node
actually supports, degrading gracefully (e.g. hash-fallback ipcache on
non-LPM kernels). Same stance here: probe the accelerator + toolchain
once at boot, expose the result in ``cilium status``/debuginfo, and
let subsystems gate on it instead of crashing mid-datapath.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_lock = threading.Lock()
_cached: Optional[Dict] = None


def _probe_device() -> Dict:
    try:
        import jax

        devs = jax.devices()
        d0 = devs[0]
        return {
            "ok": True,
            "platform": d0.platform,
            "device_kind": getattr(d0, "device_kind", str(d0)),
            "device_count": len(devs),
            "accelerator": d0.platform not in ("cpu",),
        }
    except Exception as e:  # no usable backend: host-only mode
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def _probe_donation() -> bool:
    """Buffer donation (the in-place device CT update path)."""
    try:
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1, donate_argnums=(0,))
        jax.block_until_ready(f(jnp.zeros(8, jnp.int32)))
        return True
    except Exception:
        return False


def _probe_native() -> Dict:
    """The C++ front-end toolchain (g++ + dlopen), the run_probes
    analog for SURVEY native census item 1."""
    try:
        from .native import build

        build.load()
        return {"ok": True, "so": build._so_path()}
    except Exception as e:
        return {"ok": False, "error": str(e)[:200]}


def _probe_dfa() -> bool:
    """L7 regex → DFA compilation (device L7 offload)."""
    try:
        from .l7.regex_compile import compile_patterns

        compile_patterns(["/probe/[a-z]+"])
        return True
    except Exception:
        return False


def _probe_sqlite_kvstore() -> bool:
    try:
        import sqlite3

        conn = sqlite3.connect(":memory:")
        conn.execute("create table t (k text primary key, v blob)")
        conn.close()
        return True
    except Exception:
        return False


def probe_features(force: bool = False) -> Dict:
    """Run (or return the cached) node capability probe set. Cheap
    probes run eagerly; the native build probe compiles at most once
    (cached by source hash in native/build.py)."""
    global _cached
    with _lock:
        if _cached is not None and not force:
            return _cached
        device = _probe_device()
        native = _probe_native()
        feats = {
            "device": device,
            "device_donation": _probe_donation() if device.get("ok") else False,
            "native_fastpath": native,
            "l7_dfa": _probe_dfa(),
            "kvstore_sqlite": _probe_sqlite_kvstore(),
        }
        feats["degraded"] = sorted(
            name
            for name, ok in (
                ("accelerator", bool(device.get("accelerator"))),
                ("native_fastpath", bool(native.get("ok"))),
                ("l7_dfa", feats["l7_dfa"]),
                ("kvstore_sqlite", feats["kvstore_sqlite"]),
            )
            if not ok
        )
        _cached = feats
        return feats


def peek_features() -> Optional[Dict]:
    """The cached probe result, or None while probing hasn't finished —
    the non-blocking read a status endpoint wants (the first probe can
    pay a g++ compile + backend init)."""
    with _lock:
        return _cached


def probe_in_background() -> None:
    """Kick off the probe set on a daemon thread (the agent-boot
    analog of running bpf/run_probes.sh once at startup)."""
    threading.Thread(target=probe_features, daemon=True).start()


def reset_cache() -> None:
    global _cached
    with _lock:
        _cached = None
