"""L7 proxy management (reference: pkg/proxy)."""

from .accesslog import LogRecord, AccessLogServer
from .proxy import Proxy, Redirect

__all__ = ["Proxy", "Redirect", "LogRecord", "AccessLogServer"]
