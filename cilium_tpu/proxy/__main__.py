"""``python -m cilium_tpu.proxy`` — the external L7 proxy process."""

import sys

from .standalone import main

if __name__ == "__main__":
    sys.exit(main())
