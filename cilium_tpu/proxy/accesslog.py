"""L7 access-log records + collection.

Reference: pkg/proxy/accesslog/record.go:140,200,223 (LogRecord with
request/response type, verdict, endpoint info, HTTP/Kafka detail) and
pkg/envoy/accesslog_server.go (the unix-socket server receiving entries
from the C++ filter). Here records are produced in-process by the
enforcement hooks and fanned out to subscribers (monitor, logfile).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

TYPE_REQUEST = "Request"
TYPE_RESPONSE = "Response"

VERDICT_FORWARDED = "Forwarded"
VERDICT_DENIED = "Denied"
VERDICT_ERROR = "Error"


@dataclasses.dataclass
class LogRecord:
    type: str
    verdict: str
    timestamp: float
    src_identity: int = 0
    dst_identity: int = 0
    src_ep_id: int = 0
    dst_port: int = 0
    proto: str = ""
    http: Optional[Dict] = None  # {method, path, host, code}
    kafka: Optional[Dict] = None  # {api_key, topic, error_code}

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


class AccessLogServer:
    """In-process record sink with ring buffer + subscriber fan-out."""

    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[LogRecord] = deque(maxlen=capacity)
        self._subs: List[Callable[[LogRecord], None]] = []

    def subscribe(self, fn: Callable[[LogRecord], None]) -> None:
        with self._lock:
            self._subs.append(fn)

    def log(self, record: LogRecord) -> None:
        with self._lock:
            self._ring.append(record)
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(record)
            except Exception:  # noqa: BLE001 — log sinks never break enforcement
                pass

    def recent(self, n: int = 100) -> List[LogRecord]:
        with self._lock:
            return list(self._ring)[-n:]


class AccessLogSocketServer:
    """Unix-socket receiver for records streamed by out-of-process
    proxies (pkg/envoy/accesslog_server.go:50: the agent-side server
    the C++ accesslog sink connects to). Each frame is a JSON LogRecord
    dict; valid records land in the in-process AccessLogServer ring so
    monitor/REST consumers see external-proxy traffic identically to
    in-process enforcement."""

    def __init__(self, sink: AccessLogServer, socket_path: str) -> None:
        import os
        import socket as _socket

        self.sink = sink
        self.socket_path = socket_path
        self._stop = threading.Event()
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        self._sock.bind(socket_path)
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)

    def start(self) -> "AccessLogSocketServer":
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        import socket as _socket

        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn) -> None:
        import socket as _socket

        from ..xds.server import _recv_msg

        conn.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    msg = _recv_msg(conn, self._stop)
                except _socket.timeout:
                    continue
                except (ValueError, OSError):
                    return
                if msg is None:
                    return
                try:
                    known = {f.name for f in dataclasses.fields(LogRecord)}
                    self.sink.log(
                        LogRecord(**{k: v for k, v in msg.items() if k in known})
                    )
                except (TypeError, ValueError):
                    continue  # malformed record: drop, keep the stream
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
