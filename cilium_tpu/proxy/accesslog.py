"""L7 access-log records + collection.

Reference: pkg/proxy/accesslog/record.go:140,200,223 (LogRecord with
request/response type, verdict, endpoint info, HTTP/Kafka detail) and
pkg/envoy/accesslog_server.go (the unix-socket server receiving entries
from the C++ filter). Here records are produced in-process by the
enforcement hooks and fanned out to subscribers (monitor, logfile).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

TYPE_REQUEST = "Request"
TYPE_RESPONSE = "Response"

VERDICT_FORWARDED = "Forwarded"
VERDICT_DENIED = "Denied"
VERDICT_ERROR = "Error"


@dataclasses.dataclass
class LogRecord:
    type: str
    verdict: str
    timestamp: float
    src_identity: int = 0
    dst_identity: int = 0
    src_ep_id: int = 0
    dst_port: int = 0
    proto: str = ""
    http: Optional[Dict] = None  # {method, path, host, code}
    kafka: Optional[Dict] = None  # {api_key, topic, error_code}

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


class AccessLogServer:
    """In-process record sink with ring buffer + subscriber fan-out."""

    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[LogRecord] = deque(maxlen=capacity)
        self._subs: List[Callable[[LogRecord], None]] = []

    def subscribe(self, fn: Callable[[LogRecord], None]) -> None:
        with self._lock:
            self._subs.append(fn)

    def log(self, record: LogRecord) -> None:
        with self._lock:
            self._ring.append(record)
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(record)
            except Exception:  # noqa: BLE001 — log sinks never break enforcement
                pass

    def recent(self, n: int = 100) -> List[LogRecord]:
        with self._lock:
            return list(self._ring)[-n:]
