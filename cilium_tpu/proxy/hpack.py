"""HPACK (RFC 7541) header compression for the external proxy's
HTTP/2 codec.

The reference rides Envoy's nghttp2 codec, so its L7 filter never sees
wire bytes (envoy/cilium_l7policy.cc works on decoded header maps); the
standalone proxy decodes the wire itself. Full decoder (indexed fields,
literals with/without/never indexing, dynamic-table size updates,
Huffman) + a minimal-but-legal encoder (literal-without-indexing, no
Huffman — peers must accept uncompressed literals).

The Huffman code table is the fixed one from RFC 7541 Appendix B.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple


class HpackError(Exception):
    pass


# Ceiling on cumulative DECODED header bytes per block (names + values
# after huffman/table expansion) — the HPACK-bomb guard. Mirrors the
# reference proxy's default max header list size.
MAX_DECODED_HEADER_BYTES = 1 << 16


# RFC 7541 Appendix A: the static table (1-based).
STATIC_TABLE: List[Tuple[bytes, bytes]] = [
    (b":authority", b""),
    (b":method", b"GET"),
    (b":method", b"POST"),
    (b":path", b"/"),
    (b":path", b"/index.html"),
    (b":scheme", b"http"),
    (b":scheme", b"https"),
    (b":status", b"200"),
    (b":status", b"204"),
    (b":status", b"206"),
    (b":status", b"304"),
    (b":status", b"400"),
    (b":status", b"404"),
    (b":status", b"500"),
    (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"),
    (b"accept-language", b""),
    (b"accept-ranges", b""),
    (b"accept", b""),
    (b"access-control-allow-origin", b""),
    (b"age", b""),
    (b"allow", b""),
    (b"authorization", b""),
    (b"cache-control", b""),
    (b"content-disposition", b""),
    (b"content-encoding", b""),
    (b"content-language", b""),
    (b"content-length", b""),
    (b"content-location", b""),
    (b"content-range", b""),
    (b"content-type", b""),
    (b"cookie", b""),
    (b"date", b""),
    (b"etag", b""),
    (b"expect", b""),
    (b"expires", b""),
    (b"from", b""),
    (b"host", b""),
    (b"if-match", b""),
    (b"if-modified-since", b""),
    (b"if-none-match", b""),
    (b"if-range", b""),
    (b"if-unmodified-since", b""),
    (b"last-modified", b""),
    (b"link", b""),
    (b"location", b""),
    (b"max-forwards", b""),
    (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""),
    (b"range", b""),
    (b"referer", b""),
    (b"refresh", b""),
    (b"retry-after", b""),
    (b"server", b""),
    (b"set-cookie", b""),
    (b"strict-transport-security", b""),
    (b"transfer-encoding", b""),
    (b"user-agent", b""),
    (b"vary", b""),
    (b"via", b""),
    (b"www-authenticate", b""),
]

# RFC 7541 Appendix B: (code, bit length) for bytes 0-255 + EOS (256).
HUFFMAN: List[Tuple[int, int]] = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
]


def _build_decode_tree():
    """(left, right) binary trie; leaves hold the symbol int."""
    root: list = [None, None]
    for sym, (code, nbits) in enumerate(HUFFMAN):
        node = root
        for i in range(nbits - 1, -1, -1):
            bit = (code >> i) & 1
            if i == 0:
                node[bit] = sym
            else:
                if node[bit] is None:
                    node[bit] = [None, None]
                node = node[bit]
    return root


_DECODE_TREE = _build_decode_tree()


def huffman_decode(data: bytes) -> bytes:
    """RFC 7541 §5.2. Padding must be the EOS prefix (all 1s) and
    STRICTLY shorter than 8 bits; anything else — a 0 bit, a full EOS
    symbol, or ≥8 all-ones bits — is an error."""
    out = bytearray()
    node = _DECODE_TREE
    pad_ok = True  # only-1s since last symbol boundary
    pad_bits = 0  # bits consumed since last symbol boundary
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            if bit == 0:
                pad_ok = False
            pad_bits += 1
            nxt = node[bit]
            if nxt is None:
                raise HpackError("invalid huffman code")
            if isinstance(nxt, int):
                if nxt == 256:
                    raise HpackError("EOS in huffman data")
                out.append(nxt)
                node = _DECODE_TREE
                pad_ok = True
                pad_bits = 0
            else:
                node = nxt
    if not pad_ok:
        raise HpackError("huffman padding contains 0 bits")
    if node is not _DECODE_TREE and pad_bits >= 8:
        # ≥8 all-ones trailing bits decode as an EOS prefix too, but
        # §5.2 says padding "strictly less than 8 bits" — longer runs
        # MUST be treated as a decoding error (EOS-prefix smuggling)
        raise HpackError("huffman padding of 8 or more bits")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code, n = HUFFMAN[byte]
        acc = (acc << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    """RFC 7541 §5.1 prefix-coded integer; ``flags`` fills the bits
    above the prefix in the first byte."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    """→ (value, next_pos)."""
    if pos >= len(data):
        raise HpackError("truncated integer")
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer overflow")
        if not (b & 0x80):
            return value, pos


class HpackDecoder:
    """One per connection direction (the HPACK dynamic table is
    connection state — RFC 7541 §2.3.2)."""

    def __init__(self, max_table_size: int = 4096) -> None:
        self.max_table_size = max_table_size  # protocol ceiling (SETTINGS)
        self.table_size = max_table_size  # current, ≤ ceiling
        self._dynamic: List[Tuple[bytes, bytes]] = []
        self._dynsize = 0

    def _entry(self, index: int) -> Tuple[bytes, bytes]:
        if index <= 0:
            raise HpackError("index 0")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        di = index - len(STATIC_TABLE) - 1
        if di >= len(self._dynamic):
            raise HpackError(f"index {index} out of range")
        return self._dynamic[di]

    def _add(self, name: bytes, value: bytes) -> None:
        size = len(name) + len(value) + 32  # RFC 7541 §4.1 entry size
        self._dynamic.insert(0, (name, value))
        self._dynsize += size
        while self._dynsize > self.table_size and self._dynamic:
            n, v = self._dynamic.pop()
            self._dynsize -= len(n) + len(v) + 32

    def _read_string(self, data: bytes, pos: int) -> Tuple[bytes, int]:
        if pos >= len(data):
            raise HpackError("truncated string")
        huff = bool(data[pos] & 0x80)
        length, pos = decode_int(data, pos, 7)
        if pos + length > len(data):
            raise HpackError("truncated string data")
        raw = data[pos:pos + length]
        pos += length
        return (huffman_decode(raw) if huff else raw), pos

    def decode(self, data: bytes) -> List[Tuple[bytes, bytes]]:
        headers: List[Tuple[bytes, bytes]] = []
        decoded = 0  # cumulative DECODED bytes (HPACK-bomb guard)
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                index, pos = decode_int(data, pos, 7)
                entry = self._entry(index)
                decoded += len(entry[0]) + len(entry[1])
                headers.append(entry)
            elif b & 0x40:  # literal with incremental indexing
                index, pos = decode_int(data, pos, 6)
                if index:
                    name = self._entry(index)[0]
                else:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                self._add(name, value)
                decoded += len(name) + len(value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = decode_int(data, pos, 5)
                if size > self.max_table_size:
                    raise HpackError("table size above SETTINGS ceiling")
                self.table_size = size
                while self._dynsize > size and self._dynamic:
                    n, v = self._dynamic.pop()
                    self._dynsize -= len(n) + len(v) + 32
            else:  # literal without indexing (0x00) / never indexed (0x10)
                index, pos = decode_int(data, pos, 4)
                if index:
                    name = self._entry(index)[0]
                else:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                decoded += len(name) + len(value)
                headers.append((name, value))
            if decoded > MAX_DECODED_HEADER_BYTES:
                # the wire bytes are small; the EXPANSION is the bomb
                # (huffman + table references amplify ~100×). Checked
                # after every field so the cap bounds peak memory, not
                # just the returned list. → COMPRESSION_ERROR upstream.
                raise HpackError(
                    f"decoded header list exceeds "
                    f"{MAX_DECODED_HEADER_BYTES} bytes"
                )
        return headers


class HpackEncoder:
    """Stateless-by-choice encoder: every field goes out as a literal
    WITHOUT indexing (type 0x00), so no dynamic-table sync is needed
    with the peer's decoder. Static-table name references are used when
    available; values over ~16 bytes ride Huffman."""

    def __init__(self) -> None:
        self._name_index = {}
        for i, (n, _v) in enumerate(STATIC_TABLE):
            self._name_index.setdefault(n, i + 1)

    @staticmethod
    def _string(data: bytes) -> bytes:
        enc = huffman_encode(data)
        if len(enc) < len(data):
            return encode_int(len(enc), 7, 0x80) + enc
        return encode_int(len(data), 7, 0x00) + data

    def encode(self, headers: Iterable[Tuple[bytes, bytes]]) -> bytes:
        out = bytearray()
        for name, value in headers:
            idx = self._name_index.get(name)
            if idx is not None:
                out += encode_int(idx, 4, 0x00)
            else:
                out += encode_int(0, 4, 0x00)
                out += self._string(name)
            out += self._string(value)
        return bytes(out)
